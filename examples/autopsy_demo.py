"""Incident autopsy demo: inject a synthetic latency spike through the demo
stack and watch the diagnosis plane catch it.

What happens:

1. A tiny engine serves behind the OpenAI frontend (frontend → router →
   worker wire path → scheduler) with the incident plane pointed at a
   scratch directory and ring-only tracing armed (no trace file anywhere —
   the in-memory black box is the only trace sink).
2. Calm sequential traffic builds the anomaly detector's trailing
   baselines over the real stats-scrape wire.
3. A concurrency burst against two decode slots injects a queue-wait
   spike; the next scrape fires the detector, which writes ONE debounced
   incident bundle (debug state, step ring, trace ring, digests, thread
   stacks, config, the triggering signal + baseline).
4. ``tools/autopsy.py`` reads the bundle back and attributes the spike —
   queue wait, not prefill/decode/compile — with the signal ratios as
   evidence, then drills into one spiked request from the trace ring.

Run: python examples/autopsy_demo.py
"""

import asyncio
import glob
import json
import os
import sys
import tempfile

import aiohttp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


async def main():
    import autopsy  # tools/autopsy.py

    from dynamo_tpu.engine.engine import EngineArgs, TpuEngine
    from dynamo_tpu.engine.scheduler import SchedulerConfig
    from dynamo_tpu.llm.discovery import ModelManager
    from dynamo_tpu.llm.entrypoint import build_routed_pipeline, register_llm
    from dynamo_tpu.llm.http.service import HttpService
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.llm.tokenizer import ByteTokenizer
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.incidents import DetectorConfig
    from dynamo_tpu.runtime.push_router import PushRouter
    from dynamo_tpu.runtime.tracing import configure_tracing

    incident_dir = tempfile.mkdtemp(prefix="autopsy_demo_")
    configure_tracing(path=None, sample=1.0, ring_size=1024, service="demo")
    drt = await DistributedRuntime.detached()

    print("building engine (2 decode slots — easy to saturate) ...")
    engine = TpuEngine.build(
        EngineArgs(
            model="tiny", dtype="float32", eos_token_ids=[0],
            scheduler=SchedulerConfig(
                num_blocks=128, max_running=2,
                prefill_buckets=[16, 32, 64], decode_buckets=[1, 2, 4],
                enable_mixed_batching=False,
            ),
            warmup_ctx=128,
            incident_dir=incident_dir,
        )
    )
    # Demo-friendly thresholds: fire on a 50 ms / 3x excursion, one bundle.
    engine.incidents.detector.config = DetectorConfig(
        jump_factor=3.0, min_abs_s=0.05, min_window_count=6, baseline_checks=3,
        debounce_s=600.0,
    )

    ep = drt.namespace("demo").component("backend").endpoint("generate")
    card = ModelDeploymentCard(name="tiny-demo", model_type="chat")
    handle, _ = await register_llm(drt, ep, engine, card,
                                   stats_handler=engine.stats_handler)
    drt.local_engines.pop(handle.instance.instance_id)  # full wire path
    client = await ep.client()
    await client.wait_for_instances(1, timeout=5)
    manager = ModelManager()
    manager.add_model(
        "chat", "tiny-demo",
        build_routed_pipeline(ByteTokenizer(), PushRouter(client), card),
    )
    service = HttpService(manager, host="127.0.0.1", port=0)
    await service.start()

    async def post(session, i, tokens):
        body = {"model": "tiny-demo",
                "messages": [{"role": "user", "content": f"request {i}"}],
                "max_tokens": tokens, "temperature": 0}
        async with session.post(
            f"http://127.0.0.1:{service.port}/v1/chat/completions", json=body
        ) as r:
            r.raise_for_status()
            await r.json()

    try:
        async with aiohttp.ClientSession() as session:
            print("calm traffic: 8 sequential requests (baseline builds per scrape)")
            for i in range(8):
                await post(session, i, 4)
                await client.scrape_stats()  # detector check rides the scrape

            print("spike: 24-way burst against 2 decode slots ...")
            await asyncio.gather(*(post(session, 100 + i, 32) for i in range(24)))
            stats = await client.scrape_stats()  # this scrape fires the detector
            w = next(iter(stats.values()))
            print(f"incidents_total={w['incidents_total']} "
                  f"incident_last_age_s={w['incident_last_age_s']}")
    finally:
        await service.stop()
        await engine.stop()
        await drt.shutdown()
        configure_tracing(path=None, sample=0.0, ring_size=0)

    bundles = sorted(glob.glob(os.path.join(incident_dir, "incident_*.json")))
    print(f"\nbundle: {bundles[0] if bundles else '(none — try a slower machine?)'}")
    if not bundles:
        return
    bundle = autopsy.load_bundle(bundles[0])
    report = autopsy.incident_report(bundle)
    print("\n--- incident autopsy ---")
    autopsy.render(report)

    # Drill into the most-queued request from the bundle's trace ring.
    admitted = [r for r in bundle["trace_ring"] if r.get("name") == "admitted"]
    if admitted:
        worst = max(admitted, key=lambda r: (r.get("attrs") or {}).get("queue_s", 0))
        print("\n--- worst request in the black box ---")
        autopsy.render(
            autopsy.request_report(bundle["trace_ring"], worst["trace_id"], bundle=bundle)
        )
    print(f"\nexplore further:\n  python tools/trace_view.py {bundles[0]}\n"
          f"  python tools/autopsy.py {bundles[0]} --json")


if __name__ == "__main__":
    asyncio.run(main())
