"""Structured outputs (guided decoding) against an in-process serving stack.

Runs the full pipeline — OpenAI HTTP frontend → preprocessor → TpuEngine
(tiny model, byte tokenizer) → backend — and exercises the three guided
surfaces: response_format json_schema, a forced tool call, and a choice
list. No checkpoint needed: the token-FSM guarantees grammar-valid output
whatever the (random) weights emit.

    python examples/structured_outputs.py
"""

import asyncio
import json

import aiohttp

from dynamo_tpu.engine.engine import EngineArgs, TpuEngine
from dynamo_tpu.engine.scheduler import SchedulerConfig
from dynamo_tpu.llm.discovery import ModelManager
from dynamo_tpu.llm.entrypoint import build_local_pipeline
from dynamo_tpu.llm.http.service import HttpService
from dynamo_tpu.llm.tokenizer import ByteTokenizer

MODEL = "tiny-chat"

WEATHER_SCHEMA = {
    "type": "object",
    "properties": {
        "city": {"enum": ["SF", "NY", "Tokyo"]},
        "unit": {"enum": ["celsius", "fahrenheit"]},
        "days": {"type": "integer"},
    },
}


async def main() -> None:
    tokenizer = ByteTokenizer()
    engine = TpuEngine.build(
        EngineArgs(
            model="tiny",
            dtype="float32",
            eos_token_ids=[0],
            tokenizer=tokenizer,  # guided decoding lifts grammars against it
            scheduler=SchedulerConfig(num_blocks=64, guided_pool_rows=512),
        )
    )
    manager = ModelManager()
    manager.add_model("chat", MODEL, build_local_pipeline(tokenizer, engine))
    service = HttpService(manager, host="127.0.0.1", port=0)
    await service.start()
    base = f"http://127.0.0.1:{service.port}/v1"

    async with aiohttp.ClientSession() as s:
        # 1) response_format: json_schema — the output IS valid JSON.
        body = {
            "model": MODEL,
            "messages": [{"role": "user", "content": "weather in SF?"}],
            "max_tokens": 64,
            "temperature": 0,
            "response_format": {
                "type": "json_schema",
                "json_schema": {"name": "weather", "schema": WEATHER_SCHEMA},
            },
        }
        async with s.post(f"{base}/chat/completions", json=body) as r:
            data = await r.json()
        content = data["choices"][0]["message"]["content"]
        print("json_schema  ->", content, "| parsed:", json.loads(content))

        # 2) forced tool call — parseable tool_calls, finish 'tool_calls'.
        body = {
            "model": MODEL,
            "messages": [{"role": "user", "content": "look it up"}],
            "max_tokens": 96,
            "temperature": 0,
            "tools": [{"type": "function", "function": {"name": "get_weather", "parameters": WEATHER_SCHEMA}}],
            "tool_choice": {"type": "function", "function": {"name": "get_weather"}},
        }
        async with s.post(f"{base}/chat/completions", json=body) as r:
            data = await r.json()
        call = data["choices"][0]["message"]["tool_calls"][0]["function"]
        print("tool_choice  ->", call["name"], json.loads(call["arguments"]))

        # 3) choice list (nvext extension) on completions.
        body = {
            "model": MODEL,
            "prompt": "pick a color:",
            "max_tokens": 16,
            "temperature": 0,
            "nvext": {"guided_choice": ["red", "green", "blue"]},
        }
        async with s.post(f"{base}/completions", json=body) as r:
            data = await r.json()
        print("guided_choice ->", data["choices"][0]["text"])

    await service.stop()
    await engine.stop()


if __name__ == "__main__":
    asyncio.run(main())
