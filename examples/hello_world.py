"""Hello-world: serve an endpoint, discover it, route a request, stream the
response — the dynamo-tpu equivalent of the reference's
examples/runtime/hello_world (SURVEY.md §3B worker registration flow).

Run: python examples/hello_world.py
"""

import asyncio

from dynamo_tpu.runtime import DistributedRuntime, PushRouter
from dynamo_tpu.runtime.health import SystemHealth, SystemStatusServer, HEALTHY


async def generate(request, context):
    """A toy engine: yields each word of the prompt, uppercased."""
    for word in request["prompt"].split():
        yield {"token": word.upper()}


async def main():
    drt = await DistributedRuntime.detached()

    # Worker side: register + serve.
    endpoint = drt.namespace("hello").component("backend").endpoint("generate")
    handle = await endpoint.serve_endpoint(generate, stats_handler=lambda: {"kv_usage": 0.1})

    # Force the full wire path (pub/sub push + TCP call-home) instead of the
    # in-process fast path, to demonstrate the data plane.
    drt.local_engines.pop(handle.instance.instance_id)

    # Client side: discover + route + stream.
    client = await endpoint.client()
    instances = await client.wait_for_instances(1)
    print(f"discovered instances: {[f'{i.instance_id:x}' for i in instances]}")

    router = PushRouter(client)
    print("response:", end=" ")
    async for item in router.generate({"prompt": "hello distributed tpu world"}):
        print(item.data["token"], end=" ", flush=True)
    print()

    stats = await client.scrape_stats()
    print(f"stats: {stats}")

    # System status server over real HTTP.
    health = SystemHealth()
    health.set_system_ready()
    health.set_endpoint_health(endpoint.path, HEALTHY)
    server = SystemStatusServer(health)
    await server.start()
    import aiohttp

    async with aiohttp.ClientSession() as session:
        async with session.get(f"http://127.0.0.1:{server.port}/health") as resp:
            print(f"GET /health -> {resp.status}: {await resp.text()}")
    await server.stop()
    await drt.shutdown()
    print("clean shutdown")


if __name__ == "__main__":
    asyncio.run(main())
