{{- define "dynamo-tpu.labels" -}}
app.kubernetes.io/part-of: dynamo-tpu
app.kubernetes.io/managed-by: {{ .Release.Service }}
{{- end }}
