"""Benchmark suite: decode, prefill/TTFT, and HTTP end-to-end on the
available device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "detail"}.
The primary metric is decode tok/s/user at the flagship config;
``vs_baseline`` is the **achieved fraction of this chip's HBM roofline** for
that decode step (weights+KV bytes / step time ÷ peak HBM bandwidth) — a
like-for-like bound, unlike cross-hardware comparisons (the reference's
published numbers are for 8B/70B on H100 clusters; see BASELINE.md).
``detail`` carries the full multi-point surface: prefill tok/s + TTFT, HTTP
req/s through the real frontend→scheduler path with SSE, achieved GB/s and
MFU, plus the reference anchor numbers for context.

Ref anchors (BASELINE.md): decode ITL 4.83 ms (51.22 tok/s/user) for
DS-Distill-Llama-8B TP4 on H100; prefill TTFT 48.37 ms @ 3k ISL.
"""

from __future__ import annotations

import json
import os
import time

# Peak HBM bandwidth by chip generation (GB/s, public specs).
HBM_GBPS = {
    "v5 lite": 819.0,  # v5e
    "v5e": 819.0,
    "v5p": 2765.0,
    "v4": 1228.0,
    "v6 lite": 1640.0,  # v6e (Trillium)
    "v6e": 1640.0,
}
# Peak bf16 TFLOP/s by chip generation (public specs).
BF16_TFLOPS = {"v5 lite": 197.0, "v5e": 197.0, "v5p": 459.0, "v4": 275.0, "v6 lite": 918.0, "v6e": 918.0}


def chip_peaks(device_str: str):
    s = device_str.lower()
    for key, bw in HBM_GBPS.items():
        if key in s:
            return bw, BF16_TFLOPS.get(key, 0.0)
    return None, None


def param_bytes_of(params):
    import jax

    return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(params))


def bench_decode(cfg, params, batch, ctx_len, steps, window):
    """Multi-step-window decode (the production num_scheduler_steps path)."""
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine.kv_cache import KvCacheArrays
    from dynamo_tpu.engine.models import llama

    num_blocks = batch * (ctx_len // cfg.block_size + 4) + 8
    cache = KvCacheArrays.create(cfg, num_blocks=num_blocks, dtype=jnp.bfloat16)

    needed = (ctx_len + steps + 1 + cfg.block_size - 1) // cfg.block_size
    round_to = 16
    max_blocks = min((needed + round_to - 1) // round_to * round_to, cfg.max_seq_len // cfg.block_size)
    tables = jnp.tile(jnp.arange(1, max_blocks + 1, dtype=jnp.int32)[None, :], (batch, 1))
    tables = (tables + jnp.arange(batch, dtype=jnp.int32)[:, None] * (ctx_len // cfg.block_size)) % (num_blocks - 1) + 1
    active = jnp.ones((batch,), dtype=bool)
    greedy = jnp.zeros((batch,), jnp.float32)
    top_k = jnp.zeros((batch,), jnp.int32)
    top_p = jnp.ones((batch,), jnp.float32)

    decode_window = jax.jit(
        lambda p, k, v, t, pos, key: llama.decode_multi(
            p, cfg, k, v, t, pos, tables, active, greedy, top_k, top_p, key, window
        ),
        donate_argnums=(1, 2),
    )

    toks = jnp.zeros((batch,), dtype=jnp.int32)
    pos = jnp.full((batch,), ctx_len, dtype=jnp.int32)
    k, v = cache.k, cache.v

    out, k, v = decode_window(params, k, v, toks, pos, jax.random.PRNGKey(0))
    out.block_until_ready()

    n_windows = max(1, steps // window)
    t0 = time.perf_counter()
    for i in range(n_windows):
        out, k, v = decode_window(params, k, v, toks, pos + i * window, jax.random.PRNGKey(i))
    out.block_until_ready()
    dt = time.perf_counter() - t0
    total_steps = n_windows * window
    return dt / total_steps  # seconds per step


def bench_prefill(cfg, params, prompt_len):
    """One full prefill dispatch at the bucketed length → TTFT proxy."""
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine.kv_cache import KvCacheArrays
    from dynamo_tpu.engine.models import llama

    num_blocks = prompt_len // cfg.block_size + 8
    cache = KvCacheArrays.create(cfg, num_blocks=num_blocks, dtype=jnp.bfloat16)
    table = jnp.arange(1, num_blocks, dtype=jnp.int32)

    prefill = jax.jit(
        lambda p, k, v, t: llama.prefill(p, cfg, k, v, t, jnp.int32(prompt_len), jnp.int32(0), table),
        donate_argnums=(1, 2),
    )
    toks = jnp.arange(prompt_len, dtype=jnp.int32) % 1000
    logits, k, v = prefill(params, cache.k, cache.v, toks)
    logits.block_until_ready()

    iters = 8
    t0 = time.perf_counter()
    for _ in range(iters):
        logits, k, v = prefill(params, k, v, toks)
    logits.block_until_ready()
    return (time.perf_counter() - t0) / iters  # seconds per prefill


def bench_http_e2e(n_requests=48, concurrency=12, tokens_out=16):
    """End-to-end serving stack: real HTTP frontend → preprocessor →
    scheduler → detokenize → SSE, tiny model (measures the serving plane,
    not the TPU). Ref: benchmarks/llm/perf.sh genai-perf concurrency sweep."""
    import asyncio

    async def run():
        import aiohttp

        from dynamo_tpu.engine.engine import EngineArgs, TpuEngine
        from dynamo_tpu.engine.scheduler import SchedulerConfig
        from dynamo_tpu.llm.discovery import ModelManager
        from dynamo_tpu.llm.entrypoint import build_local_pipeline
        from dynamo_tpu.llm.http.service import HttpService
        from dynamo_tpu.llm.tokenizer import ByteTokenizer

        engine = TpuEngine.build(
            EngineArgs(
                model="tiny",
                scheduler=SchedulerConfig(num_blocks=1024, max_running=32,
                                          prefill_buckets=[32, 64, 128],
                                          decode_buckets=[1, 2, 4, 8, 16, 32]),
            )
        )
        manager = ModelManager()
        manager.add_model("chat", "bench-tiny", build_local_pipeline(ByteTokenizer(), engine))
        svc = HttpService(manager, host="127.0.0.1", port=0)
        await svc.start()
        url = f"http://127.0.0.1:{svc.port}/v1/chat/completions"

        async def one(session, i):
            body = {
                "model": "bench-tiny",
                "messages": [{"role": "user", "content": f"benchmark request {i} padding padding"}],
                "max_tokens": tokens_out,
                "stream": True,
            }
            t0 = time.perf_counter()
            ttft = None
            async with session.post(url, json=body) as resp:
                async for line in resp.content:
                    if line.startswith(b"data:"):
                        if ttft is None:
                            ttft = time.perf_counter() - t0
                        if b"[DONE]" in line:
                            break
            return ttft

        async with aiohttp.ClientSession() as session:
            await one(session, -1)  # warmup (compiles)
            sem = asyncio.Semaphore(concurrency)

            async def guarded(i):
                async with sem:
                    return await one(session, i)

            t0 = time.perf_counter()
            ttfts = await asyncio.gather(*[guarded(i) for i in range(n_requests)])
            wall = time.perf_counter() - t0

        await svc.stop()
        await engine.stop()
        ttfts = sorted(t for t in ttfts if t is not None)
        p50 = ttfts[len(ttfts) // 2] if ttfts else None
        return {
            "req_s": round(n_requests / wall, 2),
            "tok_s": round(n_requests * tokens_out / wall, 1),
            "ttft_p50_ms": round(p50 * 1000, 1) if p50 else None,
            "concurrency": concurrency,
        }

    return asyncio.run(run())


def main() -> None:
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine.config import get_config
    from dynamo_tpu.engine.models import llama

    model = os.environ.get("BENCH_MODEL", "llama-3.2-1b")
    batch = int(os.environ.get("BENCH_BATCH", "8"))
    steps = int(os.environ.get("BENCH_STEPS", "256"))
    ctx_len = int(os.environ.get("BENCH_CTX", "1024"))
    window = int(os.environ.get("BENCH_WINDOW", "8"))
    prompt_len = int(os.environ.get("BENCH_PREFILL", "2048"))
    attn = os.environ.get("BENCH_ATTN", "auto")
    skip_http = os.environ.get("BENCH_SKIP_HTTP", "") == "1"

    cfg = get_config(model).replace(max_seq_len=max(4096, ctx_len + 512), attention_impl=attn)
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
    device = str(jax.devices()[0])
    hbm_gbps, tflops = chip_peaks(device)

    # --- decode -------------------------------------------------------------
    step_s = bench_decode(cfg, params, batch, ctx_len, steps, window)
    step_ms = step_s * 1000
    tok_s_user = 1.0 / step_s
    tok_s_chip = batch / step_s

    pbytes = param_bytes_of(params)
    kv_bytes = 2 * cfg.num_layers * ctx_len * cfg.num_kv_heads * cfg.head_dim * 2 * batch
    useful_bytes = pbytes + kv_bytes
    achieved_gbps = useful_bytes / step_s / 1e9
    frac_roofline = achieved_gbps / hbm_gbps if hbm_gbps else None

    # --- prefill ------------------------------------------------------------
    prefill_s = bench_prefill(cfg, params, prompt_len)
    prefill_tok_s = prompt_len / prefill_s
    # MFU: 2*P*T flops over the dense params (attention flops excluded — lower bound).
    dense_params = pbytes / 2  # bf16
    prefill_mfu = (2 * dense_params * prompt_len / prefill_s / 1e12 / tflops) if tflops else None

    # --- HTTP e2e (serving stack) -------------------------------------------
    http = None
    if not skip_http:
        try:
            http = bench_http_e2e()
        except Exception as e:  # noqa: BLE001 — e2e bench must not kill the primary metric
            http = {"error": str(e)}

    baseline_tok_s_user = 51.22  # H100 TP4 8B decode (BASELINE.md) — context anchor only
    print(
        json.dumps(
            {
                "metric": f"decode_tok_s_per_user_{model}_b{batch}_ctx{ctx_len}",
                "value": round(tok_s_user, 2),
                "unit": "tok/s/user",
                # Honest like-for-like: fraction of THIS chip's HBM roofline
                # achieved by the decode step (1.0 = bandwidth-bound optimum).
                "vs_baseline": round(frac_roofline, 3) if frac_roofline else None,
                "detail": {
                    "decode": {
                        "step_ms": round(step_ms, 3),
                        "tok_s_per_chip": round(tok_s_chip, 1),
                        "batch": batch,
                        "ctx": ctx_len,
                        "achieved_hbm_gbps": round(achieved_gbps, 1),
                        "hbm_peak_gbps": hbm_gbps,
                        "pct_hbm_roofline": round(100 * frac_roofline, 1) if frac_roofline else None,
                        "attention_impl": attn,
                    },
                    "prefill": {
                        "prompt_len": prompt_len,
                        "ttft_ms": round(prefill_s * 1000, 2),
                        "tok_s": round(prefill_tok_s, 1),
                        "mfu_pct": round(100 * prefill_mfu, 1) if prefill_mfu else None,
                    },
                    "http_e2e": http,
                    "device": device,
                    "ref_anchor": {
                        "decode_tok_s_user_8b_tp4_h100": baseline_tok_s_user,
                        "prefill_ttft_ms_3k_tp4_h100": 48.37,
                        "note": "different model+hardware class; anchors only",
                    },
                },
            }
        )
    )


if __name__ == "__main__":
    main()
