"""Benchmark: decode throughput of the JAX engine on the available device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline anchor (BASELINE.md): the reference's profiling example reports
decode ITL 4.83 ms ⇒ 51.22 tok/s/GPU *per user* for DS-Distill-Llama-8B at
TP4 on H100. Per-chip decode throughput here = batch tokens per step /
step time on one TPU v5e chip (llama-3.2-1b unless overridden). The
comparison is loose (different model/HW class) — it anchors the per-user
decode rate scale until multi-chip 8B/70B configs run.
"""

from __future__ import annotations

import json
import os
import time


def main() -> None:
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine.config import get_config
    from dynamo_tpu.engine.kv_cache import KvCacheArrays
    from dynamo_tpu.engine.models import llama

    model = os.environ.get("BENCH_MODEL", "llama-3.2-1b")
    batch = int(os.environ.get("BENCH_BATCH", "8"))
    steps = int(os.environ.get("BENCH_STEPS", "256"))
    ctx_len = int(os.environ.get("BENCH_CTX", "1024"))

    attn = os.environ.get("BENCH_ATTN", "auto")  # auto|gather|paged_kernel
    cfg = get_config(model).replace(max_seq_len=max(2048, ctx_len + 128), attention_impl=attn)
    num_blocks = batch * (ctx_len // cfg.block_size + 4) + 8

    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
    cache = KvCacheArrays.create(cfg, num_blocks=num_blocks, dtype=jnp.bfloat16)

    # Width bucketed like the scheduler: 16-block granularity over the FULL
    # run's final context (ctx + all generated steps), so every window's
    # positions stay inside the table.
    window_env = int(os.environ.get("BENCH_WINDOW", "8"))
    needed = (ctx_len + steps + 1 + cfg.block_size - 1) // cfg.block_size
    round_to = int(os.environ.get("BENCH_WIDTH_ROUND", "16"))
    max_blocks = min((needed + round_to - 1) // round_to * round_to, cfg.max_seq_len // cfg.block_size)
    tables = jnp.tile(jnp.arange(1, max_blocks + 1, dtype=jnp.int32)[None, :], (batch, 1))
    # Distinct blocks per sequence (wrap within pool to stay allocated).
    tables = (tables + jnp.arange(batch, dtype=jnp.int32)[:, None] * (ctx_len // cfg.block_size)) % (num_blocks - 1) + 1
    active = jnp.ones((batch,), dtype=bool)

    # Multi-step windows (scheduler num_scheduler_steps): the sample→embed
    # feedback loop stays on device, so dispatch overhead amortizes over
    # `window` tokens — the production decode path, not a synthetic loop.
    window = window_env
    greedy = jnp.zeros((batch,), jnp.float32)
    top_k = jnp.zeros((batch,), jnp.int32)
    top_p = jnp.ones((batch,), jnp.float32)

    decode_window = jax.jit(
        lambda p, k, v, t, pos, key: llama.decode_multi(
            p, cfg, k, v, t, pos, tables, active, greedy, top_k, top_p, key, window
        ),
        donate_argnums=(1, 2),
    )

    toks = jnp.zeros((batch,), dtype=jnp.int32)
    pos = jnp.full((batch,), ctx_len, dtype=jnp.int32)
    k, v = cache.k, cache.v

    # Warmup / compile.
    out, k, v = decode_window(params, k, v, toks, pos, jax.random.PRNGKey(0))
    out.block_until_ready()

    n_windows = max(1, steps // window)
    t0 = time.perf_counter()
    for i in range(n_windows):
        out, k, v = decode_window(params, k, v, toks, pos + i * window, jax.random.PRNGKey(i))
    out.block_until_ready()
    dt = time.perf_counter() - t0
    steps = n_windows * window

    step_ms = dt / steps * 1000
    tok_s_per_user = 1.0 / (dt / steps)  # one token per user per step
    tok_s_chip = batch * steps / dt

    baseline_tok_s_user = 51.22  # H100 TP4 8B decode (BASELINE.md)
    print(
        json.dumps(
            {
                "metric": f"decode_tok_s_per_user_{model}_b{batch}_ctx{ctx_len}",
                "value": round(tok_s_per_user, 2),
                "unit": "tok/s/user",
                "vs_baseline": round(tok_s_per_user / baseline_tok_s_user, 3),
                "detail": {
                    "step_ms": round(step_ms, 3),
                    "tok_s_per_chip": round(tok_s_chip, 1),
                    "batch": batch,
                    "device": str(jax.devices()[0]),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
