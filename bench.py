"""Benchmark suite: decode sweep, prefill/TTFT, and HTTP end-to-end.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "detail"}.
The primary metric is decode tok/s/user at the flagship config (best sweep
point); ``vs_baseline`` is the **achieved fraction of this chip's HBM
roofline** for that decode step (weights+KV bytes / step time ÷ peak HBM
bandwidth) — a like-for-like bound, unlike cross-hardware comparisons (the
reference's published numbers are for 8B/70B on H100 clusters; BASELINE.md).

Failure discipline (the round-2 gate produced NO number, rc=1):
- The orchestrator (default entry) never imports jax in-process. It probes
  the backend in a subprocess with a timeout + retry/backoff — a hung TPU
  plugin (observed: bare ``jax.devices()`` hanging minutes) costs a bounded
  probe, not the whole round — then runs the measurement child under the
  remaining wall-clock budget and ALWAYS prints the JSON line.
- The child emits each section's result as a ``BENCH_PARTIAL`` line the
  moment it completes, so a later hang/crash loses only later sections.
- If the real backend is unusable the child re-runs on CPU with a tiny
  config: the line then carries cpu-fallback numbers, an ``errors`` field,
  and a null roofline fraction instead of nothing at all.

Ref anchors (BASELINE.md): decode ITL 4.83 ms (51.22 tok/s/user) for
DS-Distill-Llama-8B TP4 on H100; prefill TTFT 48.37 ms @ 3k ISL.
Ref standard for always-producing profiling flows:
docs/benchmarks/pre_deployment_profiling.md:54-84.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

# Peak HBM bandwidth by chip generation (GB/s, public specs).
HBM_GBPS = {
    "v5 lite": 819.0,  # v5e
    "v5e": 819.0,
    "v5p": 2765.0,
    "v4": 1228.0,
    "v6 lite": 1640.0,  # v6e (Trillium)
    "v6e": 1640.0,
}
# Peak bf16 TFLOP/s by chip generation (public specs).
BF16_TFLOPS = {"v5 lite": 197.0, "v5e": 197.0, "v5p": 459.0, "v4": 275.0, "v6 lite": 918.0, "v6e": 918.0}

PARTIAL_TAG = "BENCH_PARTIAL "


def chip_peaks(device_str: str):
    s = device_str.lower()
    for key, bw in HBM_GBPS.items():
        if key in s:
            return bw, BF16_TFLOPS.get(key, 0.0)
    return None, None


def param_bytes_of(params):
    import jax

    return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(params))


# --------------------------------------------------------------------------
# measurement sections (run inside the child)
# --------------------------------------------------------------------------

def bench_decode(cfg, params, batch, ctx_len, steps, window):
    """Multi-step-window decode (the production num_scheduler_steps path).
    Returns seconds per decode step."""
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine.kv_cache import KvCacheArrays
    from dynamo_tpu.engine.models import llama

    num_blocks = batch * (ctx_len // cfg.block_size + 4) + 8
    cache = KvCacheArrays.create(cfg, num_blocks=num_blocks, dtype=jnp.bfloat16)

    # Production table width: the scheduler's rung bucketing (pow2 and
    # 1.5·pow2) for a sequence ending at ctx_len + steps tokens — the
    # driver's decode number reflects what serving actually gathers.
    from dynamo_tpu.engine.scheduler import width_bucket

    needed = (ctx_len + steps + 1 + cfg.block_size - 1) // cfg.block_size
    max_blocks = width_bucket(needed, cfg.max_seq_len // cfg.block_size)
    tables = jnp.tile(jnp.arange(1, max_blocks + 1, dtype=jnp.int32)[None, :], (batch, 1))
    tables = (tables + jnp.arange(batch, dtype=jnp.int32)[:, None] * (ctx_len // cfg.block_size)) % (num_blocks - 1) + 1
    active = jnp.ones((batch,), dtype=bool)
    greedy = jnp.zeros((batch,), jnp.float32)
    top_k = jnp.zeros((batch,), jnp.int32)
    top_p = jnp.ones((batch,), jnp.float32)

    decode_window = jax.jit(
        lambda p, k, v, t, pos, key: llama.decode_multi(
            p, cfg, k, v, t, pos, tables, active, greedy, top_k, top_p, key, window
        ),
        donate_argnums=(1, 2),
    )

    import numpy as _np

    toks = jnp.zeros((batch,), dtype=jnp.int32)
    pos = jnp.full((batch,), ctx_len, dtype=jnp.int32)
    k, v = cache.k, cache.v

    # Warm until steady state: beyond compile, the FIRST few executions of
    # a fresh executable run slow on tunneled backends (measured: 7.3 vs
    # 4.8 ms/step for the first vs third run of the same jit at b8) — one
    # warmup dispatch is not enough. np.asarray is the real host sync:
    # block_until_ready can return before the device finishes here.
    for i in range(3):
        out, k, v = decode_window(params, k, v, toks, pos, jax.random.PRNGKey(0))
        _np.asarray(out)

    # Best of two timed passes: dispatch→device pipelining on tunneled
    # backends is bimodal run-to-run (measured 4.8 vs 7.3 ms/step for
    # identical loops); the best pass is the reproducible device rate.
    n_windows = max(1, steps // window)
    best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        for i in range(n_windows):
            out, k, v = decode_window(params, k, v, toks, pos + i * window, jax.random.PRNGKey(i))
        _np.asarray(out)
        best = min(best, (time.perf_counter() - t0) / (n_windows * window))
    return best


def _pallas_dispatch_overhead_ms(n: int = 32) -> float:
    """Per-``pallas_call`` dispatch overhead: a jitted chain of ``n``
    dependent no-op kernels, best-of-3, divided by ``n``. This is the tax
    that killed the r4 per-piece paged kernel (1.3-5 ms/launch measured on
    tunneled runtimes) and the number the megakernel amortizes — folded in
    from tools/profile_decode.py so it is tracked every BENCH round."""
    import jax
    import jax.numpy as jnp
    import numpy as _np
    from jax.experimental import pallas as pl

    def nop(x_ref, o_ref):
        o_ref[...] = x_ref[...]

    call = pl.pallas_call(
        nop, out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
        interpret=jax.default_backend() != "tpu",
    )

    @jax.jit
    def chain(x):
        for _ in range(n):
            x = call(x) + 0.0  # dependency: launches serialize
        return x

    x = jnp.zeros((8, 128), jnp.float32)
    _np.asarray(chain(x))  # compile + warm
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        _np.asarray(chain(x))
        best = min(best, time.perf_counter() - t0)
    return best / n * 1000.0


def _decode_attention_cpu_parity() -> dict:
    """CPU half of the decode_attention section (interpreter-mode Pallas):
    megakernel vs gather GREEDY TOKEN PARITY through the real scheduler and
    the one-launch-per-decode-window invariant — the structural guarantees
    CI gates on where no HBM roofline exists."""
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine.config import get_config
    from dynamo_tpu.engine.models import llama
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import Scheduler, SchedulerConfig, StopConditions

    cfg = get_config("tiny")
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)

    def run(impl: str):
        sched = Scheduler(cfg.replace(attention_impl=impl), params, SchedulerConfig(
            num_blocks=128, max_running=4,
            prefill_buckets=[32], decode_buckets=[1, 2, 4],
            num_scheduler_steps=8, enable_prefix_caching=False,
            enable_overlap_decode=False, enable_mixed_batching=False,
        ), dtype=jnp.float32)
        toks: dict = {}
        t0 = time.perf_counter()
        for i in range(3):
            sched.add_request(f"r{i}", list(range(1 + i, 25 + i)),
                              SamplingParams(temperature=0.0),
                              StopConditions(max_tokens=16, ignore_eos=True))
        for _ in range(200):
            if not sched.has_work():
                break
            for s, o in sched.step():
                if o.token_id >= 0:
                    toks.setdefault(s.request_id, []).append(o.token_id)
        wall = time.perf_counter() - t0
        n = sum(len(v) for v in toks.values())
        return sched, toks, round(n / max(wall, 1e-9), 1)

    s_m, t_m, rate_m = run("megakernel")
    s_g, t_g, rate_g = run("gather")
    parity = t_m == t_g
    launches = s_m.flight.fused_window_pallas_launches
    assert parity, "megakernel/gather greedy token streams diverged"
    assert launches == 1, f"fused decode window traced {launches} pallas launches"
    return {
        "cpu_parity_mode": True,
        "token_parity": parity,
        "fused_windows": s_m.flight.fused_windows_total,
        "fused_window_pallas_launches": launches,
        "tok_s_megakernel_interp": rate_m,
        "tok_s_gather": rate_g,
        "note": "CPU: interpreter-mode Pallas — structural asserts (token "
                "parity, 1 launch/window), not speed. TPU rounds report "
                "tok/s + pct_hbm_roofline per impl.",
    }


def bench_decode_attention(cfg=None, params=None, ctx_len=1024, hbm_gbps=None):
    """Decode-attention backend tracking: gather vs megakernel at b∈{8,32}
    — tok/s, achieved HBM GB/s, pct_hbm_roofline, and the per-launch
    dispatch overhead both kernels pay. Folds tools/{ablate_decode,
    bench_decode_impl,profile_decode,profile_decode_split}.py into a
    standing BENCH_r* section so the roofline fraction is tracked every
    round instead of living in one-off tool runs. On CPU it degrades to
    the parity + one-launch-per-window asserts (CI)."""
    import jax

    if jax.default_backend() != "tpu":
        out = _decode_attention_cpu_parity()
        out["pallas_dispatch_ms_per_launch"] = round(_pallas_dispatch_overhead_ms(8), 3)
        return out

    if cfg is None or params is None:
        # Standalone mode (BENCH_DECODE_ATTN_ONLY) builds its own model.
        import jax.numpy as jnp

        from dynamo_tpu.engine.config import get_config
        from dynamo_tpu.engine.models import llama

        cfg = get_config(os.environ.get("BENCH_MODEL", "llama-3.2-1b")).replace(
            max_seq_len=max(4096, ctx_len + 512)
        )
        params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
    if hbm_gbps is None:
        hbm_gbps, _ = chip_peaks(str(jax.devices()[0]))

    points = []
    for batch in (8, 32):
        row = {"batch": batch, "ctx": ctx_len}
        for impl in ("gather", "megakernel"):
            cfg_i = cfg.replace(attention_impl=impl)
            step_s = bench_decode(cfg_i, params, batch, ctx_len, 128, 32)
            pbytes = param_bytes_of(params)
            kv_bytes = 2 * cfg.num_layers * ctx_len * cfg.num_kv_heads * cfg.head_dim * 2 * batch
            gbps = (pbytes + kv_bytes) / step_s / 1e9
            row[impl] = {
                "step_ms": round(step_s * 1000, 3),
                "tok_s_per_chip": round(batch / step_s, 1),
                "achieved_hbm_gbps": round(gbps, 1),
                "pct_hbm_roofline": round(100 * gbps / hbm_gbps, 1) if hbm_gbps else None,
            }
        row["speedup"] = round(
            row["gather"]["step_ms"] / max(row["megakernel"]["step_ms"], 1e-9), 3
        )
        points.append(row)
    return {
        "points": points,
        "pallas_dispatch_ms_per_launch": round(_pallas_dispatch_overhead_ms(), 3),
        "note": "dispatch overhead is per pallas_call on THIS runtime — the "
                "megakernel pays it once per layer (and once per WINDOW on "
                "the fused path), the r4 design paid it per piece.",
    }


def bench_fused_sampling():
    """Fused in-kernel sampling + spec window section
    (BENCH_FUSED_SAMPLE_ONLY): at b∈{8, 32}, sampled-fused (megakernel
    window with the in-kernel top-k/top-p epilogue) vs sampled-multi (the
    sync ``decode_multi`` window) tok/s, plus the fused spec window's
    accepted-tokens/step. On CPU (interpreter-mode Pallas, CI) the numbers
    are structural, not speed — the section's value there is the asserts:
    sampled windows actually dispatch, the launch gauge holds 1 across
    every fused variant, spec parity holds, and ≥2 tokens confirm per
    spec round."""
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine.config import get_config
    from dynamo_tpu.engine.models import llama
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import Scheduler, SchedulerConfig, StopConditions

    cfg = get_config("tiny")
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    on_cpu = jax.default_backend() != "tpu"

    def run(impl: str, batch: int, *, draft: bool, greedy: bool = False,
            max_tokens: int = 12):
        sched = Scheduler(cfg.replace(attention_impl=impl), params, SchedulerConfig(
            num_blocks=4 * batch + 32, max_running=batch,
            prefill_buckets=[32], decode_buckets=[batch],
            num_scheduler_steps=8, enable_prefix_caching=False,
            enable_overlap_decode=False, enable_mixed_batching=False,
        ), dtype=jnp.float32)
        if draft:
            sched.attach_draft(cfg, params, gamma=2)
        sched.warmup(ctx_tokens=64)
        sched.flight.mark_warmup_done(warmed=True)
        toks: dict = {}
        for i in range(batch):
            sp = (SamplingParams(temperature=0.0) if draft or greedy else
                  SamplingParams(temperature=0.8, top_k=20, top_p=0.9, seed=7 + i))
            sched.add_request(f"r{i}", list(range(1 + i % 8, 25 + i % 8)), sp,
                              StopConditions(max_tokens=max_tokens, ignore_eos=True))
        t0 = time.perf_counter()
        steps = 0
        for _ in range(400):
            if not sched.has_work():
                break
            sched_out = sched.step()
            steps += 1
            for s, o in sched_out:
                if o.token_id >= 0:
                    toks.setdefault(s.request_id, []).append(o.token_id)
        wall = time.perf_counter() - t0
        n = sum(len(v) for v in toks.values())
        assert n == batch * max_tokens, f"{impl} b{batch}: {n} tokens"
        assert sched.flight.compiles_after_warmup_total == 0, (
            f"post-warmup compiles: {sched.flight.post_warmup_keys}"
        )
        return sched, toks, round(n / max(wall, 1e-9), 1)

    points = []
    for batch in (8, 32):
        s_f, t_f, rate_f = run("megakernel", batch, draft=False)
        s_m, t_m, rate_m = run("gather", batch, draft=False)
        assert s_f.flight.fused_sampled_windows_total > 0, (
            "sampled traffic never reached the fused window"
        )
        launches = s_f.flight.fused_window_pallas_launches
        assert launches == 1, (
            f"fused sampled window traced {launches} pallas launches"
        )
        # Same request seeds through the fused epilogue and the sync
        # sampler draw from the same (seed, position) threefry keys — the
        # streams only agree where both paths consume identical uniforms,
        # so cross-path we assert shape, and the parity tests
        # (tests/test_megakernel.py) pin bit-identity per path.
        row = {
            "batch": batch,
            "tok_s_sampled_fused": rate_f,
            "tok_s_sampled_multi": rate_m,
            "fused_sampled_windows": s_f.flight.fused_sampled_windows_total,
            "fused_vs_multi": round(rate_f / max(rate_m, 1e-9), 3),
        }

        s_s, t_s, rate_s = run("megakernel", batch, draft=True)
        assert s_s._use_fused_spec, "fused spec gate must engage"
        assert s_s.flight.spec_fused_windows_total > 0
        st = s_s.spec_stats.to_dict()
        assert st["accepted_per_round"] >= 2.0, st
        # Lossless-speculation gate: greedy through the fused spec window
        # must emit the exact token stream plain greedy decoding does.
        _, t_gold, _ = run("gather", batch, draft=False, greedy=True)
        assert t_s == t_gold, "fused spec diverged from plain greedy"
        row["tok_s_spec_fused"] = rate_s
        row["spec_accepted_per_round"] = st["accepted_per_round"]
        row["spec_fused_windows"] = s_s.flight.spec_fused_windows_total
        points.append(row)

    return {
        "cpu_parity_mode": on_cpu,
        "points": points,
        "fused_window_pallas_launches": 1,
        "note": "CPU: interpreter-mode Pallas — structural asserts "
                "(sampled windows dispatch, 1 launch/window across all "
                "fused variants, >=2 accepted tokens/spec round), not "
                "speed. TPU rounds report the real tok/s deltas.",
    }


def bench_prefill(cfg, params, prompt_len):
    """One full prefill dispatch at the bucketed length → TTFT proxy."""
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine.kv_cache import KvCacheArrays
    from dynamo_tpu.engine.models import llama

    num_blocks = prompt_len // cfg.block_size + 8
    cache = KvCacheArrays.create(cfg, num_blocks=num_blocks, dtype=jnp.bfloat16)
    # Power-of-two table width — what Scheduler._prefill_table passes.
    w = 16
    while w < num_blocks - 1:
        w *= 2
    import numpy as _np

    table = jnp.asarray(_np.pad(_np.arange(1, num_blocks, dtype=_np.int32), (0, w - num_blocks + 1)))

    # Same impl choice the Scheduler makes: flash kernel on TPU, XLA else.
    use_flash = jax.default_backend() == "tpu" and cfg.prefill_impl in ("auto", "flash")
    prefill = jax.jit(
        lambda p, k, v, t: llama.prefill(
            p, cfg, k, v, t, jnp.int32(prompt_len), jnp.int32(0), table,
            use_flash=use_flash, has_prefix=False,
        ),
        donate_argnums=(1, 2),
    )
    import numpy as _np

    toks = jnp.arange(prompt_len, dtype=jnp.int32) % 1000
    logits, k, v = prefill(params, cache.k, cache.v, toks)
    _np.asarray(logits[:4])  # real host sync (see bench_decode)

    iters = 8
    t0 = time.perf_counter()
    for _ in range(iters):
        logits, k, v = prefill(params, k, v, toks)
    _np.asarray(logits[:4])
    return (time.perf_counter() - t0) / iters


def bench_tpu_http(n_requests=64, concurrency=32, tokens_out=32, isl=96):
    """Full serving stack with the FLAGSHIP model on the real chip: HTTP →
    preprocess → scheduler (TPU decode windows) → detokenize → SSE. The r4
    artifact measured the engine on TPU and the serving plane on CPU, never
    both — this section carries the combined number (served tok/s vs the
    raw decode rate at the same batch). Shapes are pinned (one prefill
    bucket, one decode bucket) and warmed by live requests so the section
    compiles a handful of executables, not a full warmup grid."""
    import asyncio

    async def run():
        import aiohttp

        from dynamo_tpu.engine.engine import EngineArgs, TpuEngine
        from dynamo_tpu.engine.scheduler import SchedulerConfig
        from dynamo_tpu.llm.discovery import ModelManager
        from dynamo_tpu.llm.entrypoint import build_local_pipeline
        from dynamo_tpu.llm.http.service import HttpService
        from dynamo_tpu.llm.tokenizer import ByteTokenizer

        model = os.environ.get("BENCH_MODEL", "llama-3.2-1b")
        engine = TpuEngine.build(
            EngineArgs(
                model=model,
                scheduler=SchedulerConfig(
                    num_blocks=1024,
                    max_running=concurrency,
                    prefill_buckets=[256],
                    max_prefill_chunk=256,
                    decode_buckets=[concurrency],
                ),
            )
        )
        manager = ModelManager()
        manager.add_model("chat", "bench-1b", build_local_pipeline(ByteTokenizer(), engine))
        svc = HttpService(manager, host="127.0.0.1", port=0)
        await svc.start()
        url = f"http://127.0.0.1:{svc.port}/v1/chat/completions"
        prompt = "x" * isl

        async def one(session, i):
            body = {
                "model": "bench-1b",
                "messages": [{"role": "user", "content": prompt}],
                "max_tokens": tokens_out,
                "stream": True,
            }
            t0 = time.perf_counter()
            ttft = None
            t_last = None
            nchars = 0
            async with session.post(url, json=body) as resp:
                async for line in resp.content:
                    if not line.startswith(b"data:"):
                        continue
                    idx = line.find(b'"content": "')
                    if idx >= 0 and not line.startswith(b'"', idx + 12):
                        now = time.perf_counter()
                        if ttft is None:
                            ttft = now - t0
                        t_last = now
            itl = None
            if ttft is not None and t_last is not None and tokens_out > 1:
                # Approximate per-token latency assuming the request ran to
                # max_tokens (greedy random-weight models essentially never
                # emit EOS early); counting chars breaks on JSON-escaped
                # bytes, so the budget is the honest denominator.
                itl = (t_last - (t0 + ttft)) / (tokens_out - 1)
            return ttft, itl

        async with aiohttp.ClientSession(connector=aiohttp.TCPConnector(limit=0)) as session:
            # Live-request warmup: compiles prefill(256) + the window rungs
            # and single-step decode at this batch bucket (first pass is
            # XLA compile, second is executable steady-state).
            for _ in range(2):
                await asyncio.gather(*[one(session, -i) for i in range(concurrency)])
            sem = asyncio.Semaphore(concurrency)

            async def guarded(i):
                async with sem:
                    return await one(session, i)

            t0 = time.perf_counter()
            results = await asyncio.gather(*[guarded(i) for i in range(n_requests)])
            wall = time.perf_counter() - t0
        await svc.stop()
        await engine.stop()
        ttfts = sorted(t for t, _ in results if t is not None)
        itls = sorted(i for _, i in results if i is not None)
        return {
            "model": model,
            "req_s": round(n_requests / wall, 2),
            "tok_s": round(n_requests * tokens_out / wall, 1),
            "ttft_p50_ms": round(ttfts[len(ttfts) // 2] * 1000, 1) if ttfts else None,
            "itl_p50_ms": round(itls[len(itls) // 2] * 1000, 2) if itls else None,
            "concurrency": concurrency,
            "tokens_out": tokens_out,
            "isl": isl,
        }

    return asyncio.run(run())


def bench_http_e2e(n_requests=48, concurrency=12, tokens_out=16):
    """End-to-end serving stack: real HTTP frontend → preprocessor →
    scheduler → detokenize → SSE, tiny model (measures the serving plane,
    not the TPU). Ref: benchmarks/llm/perf.sh genai-perf concurrency sweep."""
    import asyncio

    async def run():
        import aiohttp

        from dynamo_tpu.engine.engine import EngineArgs, TpuEngine
        from dynamo_tpu.engine.scheduler import SchedulerConfig
        from dynamo_tpu.llm.discovery import ModelManager
        from dynamo_tpu.llm.entrypoint import build_local_pipeline
        from dynamo_tpu.llm.http.service import HttpService
        from dynamo_tpu.llm.tokenizer import ByteTokenizer

        engine = TpuEngine.build(
            EngineArgs(
                model="tiny",
                scheduler=SchedulerConfig(num_blocks=1024, max_running=64,
                                          prefill_buckets=[32, 64, 128],
                                          # max_running/top bucket cover the
                                          # sweep's top concurrency: with 32
                                          # slots the conc-64 level queued
                                          # half its requests a full request
                                          # duration (r05: TTFT p50 242 ms);
                                          # mixed steps + wave admission keep
                                          # the wider batch fed without
                                          # prefill stalls.
                                          decode_buckets=[1, 2, 4, 8, 16, 32, 64],
                                          # Single-step: windows amortize
                                          # DISPATCH cost, which a local CPU
                                          # engine doesn't pay — a 32-step
                                          # window just overshoots 16-token
                                          # requests and serializes the batch
                                          # (measured: 6.1 -> 5.4 req/s).
                                          num_scheduler_steps=1),
                # Precompile: the serving measurement must not time XLA.
                # 160 covers the sweep's real contexts (~70-token templated
                # prompt + 16 out → width rung 6): at 64 the width-6 decode
                # executables compiled mid-traffic and the first high-
                # concurrency level timed XLA, not serving (measured: first
                # b64 level p50 252 ms, second 90 ms).
                warmup_ctx=160,
            )
        )
        manager = ModelManager()
        manager.add_model("chat", "bench-tiny", build_local_pipeline(ByteTokenizer(), engine))
        svc = HttpService(manager, host="127.0.0.1", port=0)
        await svc.start()
        url = f"http://127.0.0.1:{svc.port}/v1/chat/completions"

        async def one(session, i):
            body = {
                "model": "bench-tiny",
                "messages": [{"role": "user", "content": f"benchmark request {i} padding padding"}],
                "max_tokens": tokens_out,
                "stream": True,
            }
            t0 = time.perf_counter()
            ttft = None
            async with session.post(url, json=body) as resp:
                async for line in resp.content:
                    # Client parsing shares the single core with the server
                    # under test — a json.loads per SSE line throttled the
                    # SERVER to ~6 req/s (measured: 6 -> 34 req/s from the
                    # client fix alone). TTFT = first chunk carrying content
                    # (the stream opens with a content-less role chunk);
                    # detect it with a byte scan, parse nothing.
                    if ttft is None and line.startswith(b"data:"):
                        idx = line.find(b'"content": "')
                        # match a NON-EMPTY content delta (the stream opens
                        # with a role chunk whose content is "")
                        if idx >= 0 and not line.startswith(b'"', idx + 12):
                            ttft = time.perf_counter() - t0
            return ttft

        async def level(session, conc, n):
            sem = asyncio.Semaphore(conc)

            async def guarded(i):
                async with sem:
                    return await one(session, i)

            # First-token latency decomposition from the engine's own
            # accounting: queue (arrival→admission) + prefill (admission→
            # first token) sums, and the decode-phase step time from the
            # flight recorder — where a level's TTFT actually goes.
            sched = engine.scheduler
            q0, p0, f0 = sched.queue_wait_s_total, sched.prefill_wait_s_total, sched.first_tokens_total
            dh = sched.flight._hists["decode"]
            d_t0, d_n0 = dh.sum_s, dh.total
            t0 = time.perf_counter()
            ttfts = await asyncio.gather(*[guarded(i) for i in range(n)])
            wall = time.perf_counter() - t0
            firsts = max(sched.first_tokens_total - f0, 1)
            breakdown = {
                "queue_ms_mean": round(1000 * (sched.queue_wait_s_total - q0) / firsts, 2),
                "prefill_ms_mean": round(1000 * (sched.prefill_wait_s_total - p0) / firsts, 2),
                "decode_step_ms_mean": round(
                    1000 * (dh.sum_s - d_t0) / max(dh.total - d_n0, 1), 3
                ),
            }
            ttfts = sorted(t for t in ttfts if t is not None)
            p50 = ttfts[len(ttfts) // 2] if ttfts else None
            return {
                "concurrency": conc,
                "req_s": round(n / wall, 2),
                "tok_s": round(n * tokens_out / wall, 1),
                "ttft_p50_ms": round(p50 * 1000, 1) if p50 else None,
                "breakdown": breakdown,
            }

        # genai-perf-style concurrency sweep (ref: benchmarks/llm/perf.sh):
        # throughput vs concurrency exposes the serving plane's knee.
        async with aiohttp.ClientSession(
            connector=aiohttp.TCPConnector(limit=0)
        ) as session:
            # Warmup: compiles + first-execution costs across the batch
            # buckets the sweep will hit (cold executables polluted the
            # first level by ~6x when warmed with a single request).
            await asyncio.gather(*[one(session, -i) for i in range(1, 17)])
            sweep = []
            for conc in (concurrency, 64):
                if sweep and sweep[-1]["concurrency"] >= conc:
                    continue
                sweep.append(await level(session, conc, max(n_requests, 3 * conc)))

        sched = engine.scheduler
        mixed = {
            "steps": sched.mixed_steps_total,
            "prefill_tokens": sched.mixed_prefill_tokens_total,
            "decode_tokens": sched.mixed_decode_tokens_total,
        }
        await svc.stop()
        await engine.stop()
        best = max(sweep, key=lambda p: p["req_s"])
        return {
            **best, "sweep": sweep, "mixed": mixed,
            "admission_tuning": {
                "note": "per-level breakdown (queue/prefill/decode) drove the "
                        "max_running default 16→32: at conc 64 with 16 slots "
                        "the queue term was 292 ms of a 393 ms TTFT p50 "
                        "(prefill 20 ms); 32 slots measured +53% req/s and "
                        "halved p50; 64 zeroes queueing but shifts 60 ms "
                        "into batched prefill waves — the sweep here runs "
                        "max_running=concurrency for the knee itself",
            },
        }

    return asyncio.run(run())


def bench_mixed_admission():
    """Mixed prefill+decode steps, measured at the scheduler (no HTTP): a
    long prompt arrives while a decode wave runs. Phase-separated
    scheduling dispatches the whole prompt as one stall between decode
    steps; mixed steps carry mixed_prefill_budget-token chunks inside the
    decode dispatch. Reports the decode wave's worst inter-token gap and
    the newcomers' TTFT, mixed on vs off, plus the per-step composition
    counters the scheduler now exports."""
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine.config import get_config
    from dynamo_tpu.engine.models import llama
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import Scheduler, SchedulerConfig, StopConditions

    cfg = get_config("tiny").replace(max_seq_len=4096)
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)

    def run(mixed: bool) -> dict:
        sched = Scheduler(cfg, params, SchedulerConfig(
            num_blocks=768, max_running=16,
            prefill_buckets=[32, 64, 128, 256, 512, 1024],
            decode_buckets=[1, 2, 4, 8, 16],
            num_scheduler_steps=1, enable_prefix_caching=False,
            enable_mixed_batching=mixed,
        ), dtype=jnp.float32)
        for i in range(8):
            sched.add_request(f"d{i}", list(range(1, 33)),
                              SamplingParams(temperature=0.0), StopConditions(max_tokens=400))
        for _ in range(12):  # decode wave warm + executables compiled
            sched.step()
        # Warm the long-prompt shapes too so the gap measures scheduling,
        # not XLA compiles, for both modes.
        sched.add_request("warm", list(range(3, 1027)),
                          SamplingParams(temperature=0.0), StopConditions(max_tokens=2))
        for _ in range(40):
            sched.step()

        t0 = time.perf_counter()
        sched.add_request("long", list(range(5, 1029)),
                          SamplingParams(temperature=0.0), StopConditions(max_tokens=4))
        sched.add_request("short", list(range(7, 39)),
                          SamplingParams(temperature=0.0), StopConditions(max_tokens=4))
        long_ttft = short_ttft = None
        last_decode = t0
        max_gap = 0.0
        for _ in range(400):
            outs = sched.step()
            now = time.perf_counter()
            if any(s.request_id.startswith("d") and o.token_id >= 0 for s, o in outs):
                max_gap = max(max_gap, now - last_decode)
                last_decode = now
            for s, o in outs:
                if o.token_id >= 0 and s.request_id == "long" and long_ttft is None:
                    long_ttft = now - t0
                if o.token_id >= 0 and s.request_id == "short" and short_ttft is None:
                    short_ttft = now - t0
            if long_ttft is not None and short_ttft is not None:
                break
        return {
            "enable_mixed_batching": mixed,
            "long_ttft_ms": round(long_ttft * 1000, 2) if long_ttft else None,
            "short_ttft_ms": round(short_ttft * 1000, 2) if short_ttft else None,
            "decode_max_gap_ms": round(max_gap * 1000, 2),
            "mixed_steps": sched.mixed_steps_total,
            "mixed_prefill_tokens": sched.mixed_prefill_tokens_total,
            "mixed_decode_tokens": sched.mixed_decode_tokens_total,
        }

    on = run(True)
    off = run(False)
    return {
        "mixed_on": on,
        "mixed_off": off,
        "isl": 1024,
        "decode_stall_ratio": round(off["decode_max_gap_ms"] / max(on["decode_max_gap_ms"], 1e-3), 2),
        "note": "tiny model on CPU — scheduling structure, not device speed; "
                "decode_max_gap is the worst stall a 1K prefill injects into "
                "an active 8-wide decode wave",
    }


def bench_decode_overlap():
    """Zero-bubble decode pipeline at the scheduler: steady-state decode
    tok/s and decode_host_gap_ms p50/p99, overlap on vs off, at bucket
    {8, 32}. The overlap path dispatches step N+1 from step N's on-device
    sampled tokens and retires one step behind, so the host gap between
    dispatches — readback + bookkeeping + re-upload on the sync path —
    collapses to the pipeline's own dispatch cost. Greedy token streams are
    asserted identical between the two modes (the acceptance bar's
    token-exact parity)."""
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine.config import get_config
    from dynamo_tpu.engine.models import llama
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import Scheduler, SchedulerConfig, StopConditions

    cfg = get_config("tiny").replace(max_seq_len=4096)
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    out_tokens = 160

    def run(bucket: int, overlap: bool) -> dict:
        sched = Scheduler(cfg, params, SchedulerConfig(
            num_blocks=max(512, bucket * 16), max_running=bucket,
            prefill_buckets=[32, 64],
            decode_buckets=[b for b in (1, 2, 4, 8, 16, 32) if b <= bucket],
            num_scheduler_steps=1, enable_prefix_caching=False,
            enable_overlap_decode=overlap,
        ), dtype=jnp.float32)
        toks: dict = {}
        for i in range(bucket):
            sched.add_request(f"r{i}", list(range(1 + i % 24, 33 + i % 24)),
                              SamplingParams(temperature=0.0),
                              StopConditions(max_tokens=out_tokens, ignore_eos=True))
        while sched.waiting:  # admission (+ executable compiles)
            sched.step()
        for _ in range(12):  # pipeline engaged + shapes warm before measuring
            for s, o in sched.step():
                if o.token_id >= 0:
                    toks.setdefault(s.request_id, []).append(o.token_id)
        t0 = time.perf_counter()
        n0 = sum(len(v) for v in toks.values())
        while len(sched.running) == bucket and sched.has_work():
            for s, o in sched.step():
                if o.token_id >= 0:
                    toks.setdefault(s.request_id, []).append(o.token_id)
        steady_s = time.perf_counter() - t0
        steady_toks = sum(len(v) for v in toks.values()) - n0
        while sched.has_work():  # drain the ramp-down tail unmeasured
            for s, o in sched.step():
                if o.token_id >= 0:
                    toks.setdefault(s.request_id, []).append(o.token_id)
        return {
            "overlap": overlap,
            "tok_s": round(steady_toks / max(steady_s, 1e-9), 1),
            "host_gap_p50_ms": round(sched.flight.gap_percentile(0.50) * 1000, 3),
            "host_gap_p99_ms": round(sched.flight.gap_percentile(0.99) * 1000, 3),
            "overlap_steps": sched.overlap_steps_total,
            "overlap_flushes": sched.overlap_flushes_total,
            "tokens": toks,
        }

    points = []
    for bucket in (8, 32):
        on = run(bucket, True)
        off = run(bucket, False)
        parity = on.pop("tokens") == off.pop("tokens")
        points.append({
            "bucket": bucket,
            "overlap_on": on,
            "overlap_off": off,
            "speedup": round(on["tok_s"] / max(off["tok_s"], 1e-9), 3),
            "token_parity": parity,
        })

    # Static/dynamic cross-validation of the 1-sync/step invariant: the
    # dtlint SYNC001 allowlist DECLARES the overlap path's blocking-sync
    # budget (role=per_step, path=overlap — must be exactly 1 entry), and
    # the measured steady-state count must agree. If someone adds a stray
    # readback, dtlint fails statically; if someone allowlists a second
    # per-step sync, this measurement (and the allowlist shape assert)
    # fails dynamically — the two views cannot drift apart.
    import json as _json
    import os as _os

    import numpy as np

    import dynamo_tpu.engine.scheduler as _sched_mod

    with open(_os.path.join(_os.path.dirname(_os.path.abspath(__file__)),
                            "tools", "dtlint", "sync_allowlist.json")) as f:
        _allow = _json.load(f)
    declared = [e for e in _allow["allowed_syncs"]
                if e["role"] == "per_step" and e["path"] == "overlap"]
    assert len(declared) == 1, (
        f"sync_allowlist declares {len(declared)} per-step overlap syncs; "
        "the zero-bubble budget is exactly 1"
    )
    sched = Scheduler(cfg, params, SchedulerConfig(
        num_blocks=512, max_running=4, prefill_buckets=[32, 64],
        decode_buckets=[1, 2, 4], num_scheduler_steps=1,
        enable_prefix_caching=False, enable_overlap_decode=True,
    ), dtype=jnp.float32)
    for i in range(4):
        sched.add_request(f"s{i}", list(range(3 + i, 27 + i)),
                          SamplingParams(temperature=0.0),
                          StopConditions(max_tokens=120, ignore_eos=True))
    for _ in range(60):
        if sched._pipe is not None:
            break
        sched.step()
    assert sched._pipe is not None, "overlap pipeline never engaged"
    sched.step()
    counter = [0]
    real_asarray, real_device_get = np.asarray, jax.device_get

    def counting_asarray(a, *args, **kw):
        if isinstance(a, jax.Array):
            counter[0] += 1
        return real_asarray(a, *args, **kw)

    def counting_device_get(x, *args, **kw):
        counter[0] += 1
        return real_device_get(x, *args, **kw)

    steps = 10
    _sched_mod.np.asarray = counting_asarray
    _sched_mod.jax.device_get = counting_device_get
    try:
        for _ in range(steps):
            sched.step()
    finally:
        _sched_mod.np.asarray = real_asarray
        _sched_mod.jax.device_get = real_device_get
    while sched.has_work():
        sched.step()
    measured_per_step = counter[0] / steps
    assert measured_per_step <= len(declared), (
        f"measured {measured_per_step} blocking syncs/step vs "
        f"{len(declared)} declared in sync_allowlist.json"
    )

    # Static/dynamic cross-validation of the warmup key space: every
    # executable kind the flight recorder observed compiling during this
    # section must be statically enumerable by dtlint's WARM001 scan, at a
    # statically registered arity. If a new record_exec site appears
    # without a warmup twin, WARM001 fails statically; if the static
    # enumeration drifts from what actually dispatches, this check fails
    # dynamically — the two views of the 0-compile invariant cannot
    # diverge silently.
    from tools.dtlint.rules_warmup import static_warmup_report

    static = static_warmup_report(
        _os.path.dirname(_os.path.abspath(__file__)))
    dynamic_keys = sched.flight.exec_key_summary()
    for kind, arities in dynamic_keys.items():
        assert kind in static["warmed"], (
            f"recorder compiled kind '{kind}' that WARM001's static warmup "
            f"enumeration does not register"
        )
        static_ar = set(static["warmed"][kind])
        assert not static_ar or set(arities) <= static_ar, (
            f"kind '{kind}' compiled at arities {arities} but warmup "
            f"statically registers {sorted(static_ar)}"
        )

    return {
        "points": points,
        "out_tokens": out_tokens,
        # The warmup key space, both views.
        "static_warmed_kinds": sorted(static["warmed"]),
        "dynamic_exec_kinds": sorted(dynamic_keys),
        "static_dynamic_warmup_views_agree": True,
        # The 1-sync/step invariant, both views.
        "sync_allowlist_per_step_overlap": len(declared),
        "measured_blocking_syncs_per_step": round(measured_per_step, 3),
        "static_dynamic_sync_views_agree": measured_per_step <= len(declared),
        "note": "tiny model — on CPU the dispatch gap the pipeline hides is "
                "small, so the tok/s ratio is structural, not the TPU win; "
                "host_gap percentiles + the ≤1-sync bound in "
                "tests/test_overlap_decode.py carry the CPU-fallback "
                "acceptance. On a real chip the sync path's gap includes the "
                "full tunnel round-trip per step.",
    }


def bench_prefix_reuse():
    """Automatic prefix caching, measured at the REAL engine: KV-aware
    routing vs round-robin over two live Schedulers (tiny model). Groups of
    requests share the leading 0.9 of their prompts under cache pressure
    (one worker's pool holds ~half the group prefixes). KV-aware routing
    pins each group to its home worker, where the engine's prefix cache
    turns the hint into SKIPPED prefill FLOPs — the suffix chunk is all
    that computes; round-robin cycles groups across workers, evicting and
    re-prefilling. Reports mean TTFT per policy, the engine-reported
    cached_tokens (asserted equal to the blocks the allocator actually
    served from cache × block_size), and the post-warmup compile count
    (the 0-compile invariant must hold with prefix caching enabled)."""
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine.config import get_config
    from dynamo_tpu.engine.models import llama
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import Scheduler, SchedulerConfig, StopConditions
    from dynamo_tpu.llm.kv_router.indexer import KvIndexer
    from dynamo_tpu.llm.kv_router.scheduler import KvScheduler
    from dynamo_tpu.llm.kv_router.sequence import ActiveSequencesMultiWorker
    from dynamo_tpu.llm.tokens import compute_block_hashes

    cfg = get_config("tiny").replace(max_seq_len=4096)
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    bs = cfg.block_size
    ISL, RATIO, GROUPS, WORKERS, OSL = 1024, 0.9, 4, 2, 2
    # Pool sizing: one worker holds ~2 of the 4 group prefixes (+ working
    # set); all 4 never fit — round-robin's cycling must actually evict.
    num_blocks = 192

    import random as _random

    rng = _random.Random(7)
    shared = [[rng.randrange(1, 30000) for _ in range(int(ISL * RATIO))] for _ in range(GROUPS)]

    def make_prompt(g):
        return shared[g] + [rng.randrange(1, 30000) for _ in range(ISL - len(shared[g]))]

    def run(policy: str) -> dict:
        workers = []
        indexer = KvIndexer(block_size=bs)
        for w in range(WORKERS):
            sched = Scheduler(
                cfg, params,
                SchedulerConfig(
                    # Sequential single-request serving: decode bucket 1
                    # only, mixed/overlap paths off — keeps the warmup grid
                    # (2 workers × every shape) CPU-affordable while the
                    # serving-hot prefill buckets stay real.
                    num_blocks=num_blocks, max_running=8,
                    prefill_buckets=[128, 256, 512, 1024],
                    decode_buckets=[1], num_scheduler_steps=1,
                    enable_mixed_batching=False, enable_overlap_decode=False,
                ),
                dtype=jnp.float32,
                on_kv_event=lambda ev, w=w: indexer.apply_event(w, ev.to_wire()),
            )
            sched.warmup(ISL + 64)
            sched.flight.mark_warmup_done(warmed=True)
            workers.append(sched)
        router = KvScheduler(ActiveSequencesMultiWorker(block_size=bs))

        order = [i % GROUPS for i in range(GROUPS * 6)]
        rng2 = _random.Random(11)
        rng2.shuffle(order)
        ttfts = []
        cached_total = 0
        accounting_exact = True
        for i, g in enumerate(order):
            prompt = make_prompt(g)
            if policy == "kv":
                hashes = compute_block_hashes(prompt, bs)
                decision = router.select_worker(
                    list(range(WORKERS)), (len(prompt) + bs - 1) // bs,
                    indexer.find_matches(hashes),
                )
                w = decision.worker
            else:
                w = i % WORKERS
            sched = workers[w]
            rid = f"{policy}-{i}"
            hits_before = sched.allocator.hit_blocks_total
            sched.add_request(
                rid, prompt, SamplingParams(temperature=0.0),
                StopConditions(max_tokens=OSL, ignore_eos=True),
            )
            t0 = time.perf_counter()
            ttft = None
            cached = 0
            while sched.has_work():
                for s, o in sched.step():
                    if s.request_id == rid and o.token_id >= 0 and ttft is None:
                        ttft = time.perf_counter() - t0
                        cached = o.cached_tokens or 0
            ttfts.append(ttft)
            cached_total += cached
            # Engine-reported cached_tokens must equal the blocks the
            # allocator actually served from cache (full-cover hits report
            # n·bs − 1: one token recomputes to produce logits).
            matched = sched.allocator.hit_blocks_total - hits_before
            if cached not in (matched * bs, max(0, matched * bs - 1)):
                accounting_exact = False
        # Each group's first occurrence is cold establishment (identical per
        # policy); drop them from the mean.
        seen: set = set()
        warm_ttfts = []
        for g, t in zip(order, ttfts):
            if g in seen:
                warm_ttfts.append(t)
            seen.add(g)
        return {
            "ttft_mean_ms": round(1000 * sum(warm_ttfts) / max(len(warm_ttfts), 1), 2),
            "cached_tokens": cached_total,
            "cached_matches_blocks": accounting_exact,
            "compiles_after_warmup": sum(
                s.flight.compiles_after_warmup_total for s in workers
            ),
        }

    kv = run("kv")
    rr = run("rr")
    return {
        "isl": ISL, "prefix_ratio": RATIO, "groups": GROUPS, "workers": WORKERS,
        "worker_blocks": num_blocks,
        "kv": kv, "rr": rr,
        "speedup": round(rr["ttft_mean_ms"] / max(kv["ttft_mean_ms"], 1e-9), 2),
        "note": "tiny model on CPU, sequential requests (no queueing): the "
                "ratio is skipped prefill FLOPs — the engine-level win the "
                "KV router's hint now buys. Real-chip prefill is faster in "
                "absolute terms; the skipped fraction is the same.",
    }


def bench_observability_overhead():
    """Tracing + flight-recorder + telemetry + INCIDENT-PLANE cost at the
    scheduler (no HTTP): steady decode throughput with tracing disabled vs
    fully sampled (sample=1.0, JSONL export live, trace ring + tail keep
    armed). The digests, SLO judge, FLOPs/bytes roofline model, stall
    watchdog, anomaly detector (polled at the production scrape cadence),
    the host stack sampler, and the tenant capacity ledger (every request
    billed to a tenant) are LIVE in both phases — they are always-on in
    production — so the section proves the whole diagnosis plane rides
    inside the budget. The acceptance bar is ≤2%
    token-throughput cost at the bench knee with 0 post-warmup compiles."""
    import tempfile

    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine.config import get_config
    from dynamo_tpu.engine.models import llama
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import Scheduler, SchedulerConfig, StopConditions
    from dynamo_tpu.runtime.incidents import IncidentConfig, IncidentPlane
    from dynamo_tpu.runtime.profiling import (
        ContinuousProfileConfig,
        ContinuousProfiler,
        DeviceProfiler,
        HostStackSampler,
    )
    from dynamo_tpu.runtime.telemetry import StallWatchdog
    from dynamo_tpu.runtime.tracing import configure_tracing, get_tracer

    cfg = get_config("tiny").replace(max_seq_len=4096)
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    rounds = 3

    # One JSONL-exporting tracer for the whole section; the "off" scheduler
    # simply has no per-sequence trace tuples (the production off-path: one
    # None check per event site).
    trace_path = tempfile.mktemp(prefix="bench_trace_", suffix=".jsonl")
    # Incident bundles land in the CI artifact dir when set (failures ship
    # their own black box), else a scratch dir.
    incident_dir = os.environ.get("DYN_INCIDENT_DIR") or tempfile.mkdtemp(
        prefix="bench_incidents_"
    )

    phase_counter = [0]

    def measure(sched, traced: bool) -> float:
        # Each measurement is a FULL identical batch (admission → decode →
        # finish) on the same long-lived scheduler: the per-request trace
        # tuple is the production on/off switch, and reusing one scheduler
        # removes instance-to-instance confounders (allocation layout,
        # build order) while the fixed batch shape removes context-growth
        # drift between phases.
        phase_counter[0] += 1
        p = phase_counter[0]
        tokens = 0
        t0 = time.perf_counter()
        for i in range(8):
            sched.add_request(
                f"p{p}r{i}", list(range(1 + (p + i) % 8, 33 + (p + i) % 8)),
                SamplingParams(temperature=0.0), StopConditions(max_tokens=80),
                trace=(f"{p:016x}{i:016x}", f"{i:016x}") if traced else None,
                # Tenant ledger armed in BOTH phases (it is always-on in
                # production): every request bills to one of two tenants.
                tenant=f"bench-t{i % 2}",
            )
        while sched.has_work():
            tokens += sum(1 for _, o in sched.step() if o.token_id >= 0)
        return tokens / (time.perf_counter() - t0)

    from dynamo_tpu.runtime import faults as _faults

    try:
        # Full plane armed: ring black box + tail keep on top of the live
        # JSONL export (tail is the worst case — every record also lands
        # in the ring).
        configure_tracing(path=trace_path, sample=1.0, service="bench",
                          ring_size=256, tail=True)
        # Chaos plane armed-but-idle: the injector is live (the production
        # posture during a drill window) with a spec that can never match,
        # so every planted site pays its armed-path cost while zero faults
        # fire. The budget + 0-compile assertions below hold regardless.
        _faults.arm(_faults.FaultInjector(
            [{"site": "worker.frame", "kind": "stream_drop",
              "match": {"request_id": "bench-never-matches"}}], seed=0,
        ))
        # SLO targets set so the per-finish judge actually runs; digests +
        # roofline model are unconditionally live in the scheduler.
        sched = Scheduler(cfg, params, SchedulerConfig(
            num_blocks=768, max_running=8,
            prefill_buckets=[32, 64, 128], decode_buckets=[1, 2, 4, 8],
            num_scheduler_steps=1, enable_prefix_caching=False,
            slo_ttft_ms=1000.0, slo_tpot_ms=100.0,
        ), dtype=jnp.float32)
        watchdog = StallWatchdog(
            probe=lambda: (sched.has_work(), sched.flight.last_step_ts),
            stall_after_s=120.0,
        )
        # Incident autopsy plane over the scheduler's own stats surface —
        # detector + recorder polled at the production scrape cadence.
        plane = IncidentPlane(
            IncidentConfig(dir=incident_dir),
            state_probe=sched.debug_state,
            flight_probe=sched.flight.ring_snapshot,
            config_probe=sched.config_snapshot,
        )

        def sched_stats() -> dict:
            s = dict(sched.flight.to_stats())
            s.update(sched.slo.to_stats())
            s["digests"] = sched.telemetry.to_wire()
            return s

        # Host stack sampler armed for the whole measured section at its
        # production period.
        sampler = HostStackSampler(interval_s=0.005)
        sampler.start()
        # Continuous device-truth sampler armed at the DEFAULT duty cycle
        # (0.25 s window / 30 s interval): the production posture. At this
        # cadence it idles through the section — the point is that an armed
        # sampler thread + its due()-polling loop ride inside the same ≤2%
        # budget with zero errors, not that a window fires mid-bench.
        cont = ContinuousProfiler(
            DeviceProfiler(out_dir=tempfile.mkdtemp(prefix="bench_prof_")),
            ContinuousProfileConfig(),
            cost_probe=sched.flight.roofline_totals,
            sink=sched.flight.record_measured_window,
        )
        cont.start()
        measure(sched, False)  # admission-wave + decode executable warmup
        # The warmup measurement compiled every serving shape this section
        # touches: from here, compiles are the 0-post-warmup invariant.
        sched.flight.mark_warmup_done(warmed=True)
        # Round-interleaved best-of-N: warm-up drift hits both modes equally.
        best_off = best_on = 0.0
        for _ in range(rounds):
            best_off = max(best_off, measure(sched, False))
            best_on = max(best_on, measure(sched, True))
            watchdog.check()  # the production poll cadence rides along
            plane.observe(sched_stats())  # detector check per scrape
        sampler_armed = cont.armed
        cont.stop()
        cont_stats = cont.to_stats()
        assert sampler_armed, "continuous profiler thread died mid-section"
        assert cont_stats["device_profile_errors_total"] == 0, (
            f"continuous profiler errored during the bench: {cont_stats}"
        )
        assert cont_stats["device_profile_duty_cycle"] <= 0.02, (
            f"default duty cycle above the 2% clamp: {cont_stats}"
        )
        sampler.stop()
        sampler_report = sampler.report(top=5)
        plane_stats = plane.to_stats()
        tracer = get_tracer()
        ring_records = len(tracer.ring_records())
        tracer.flush()
        off = {"traced": False, "tok_s": round(best_off, 1),
               "rounds": rounds, "trace_records": 0}
        on = {"traced": True, "tok_s": round(best_on, 1),
              "rounds": rounds, "trace_records": tracer.events_written}
        digest_counts = {
            name: sched.telemetry.digest(name).total.count
            for name in sched.telemetry.names()
        }
        compiles_after_warmup = sched.flight.compiles_after_warmup_total
        slo_judged = sched.slo.requests_total
        faults_injected = _faults.get_injector().injected_total
        assert faults_injected == 0, (
            f"armed-but-idle fault injector fired {faults_injected} times"
        )
        # Tenant ledger armed throughout: every request billed, both
        # tenants tracked, and the charged device-seconds conserve (Σ
        # tracked + other = exact total — nothing leaks the sketch).
        ledger_wire = sched.ledger.to_wire()
        assert ledger_wire["bills"] == phase_counter[0] * 8, (
            f"ledger billed {ledger_wire['bills']} of {phase_counter[0] * 8} requests"
        )
        from dynamo_tpu.runtime.ledger import SpaceSaving as _SpaceSaving

        _tracked = {t for t, _, _ in _SpaceSaving.from_wire(
            ledger_wire["sketches"]["device_seconds"]).items()}
        assert _tracked == {"bench-t0", "bench-t1"}, _tracked
        assert ledger_wire["totals"]["device_seconds"] > 0.0
        assert plane.to_stats()["incidents_total"] == 0, (
            "calm bench traffic fired a false incident"
        )
    finally:
        _faults.disarm()
        configure_tracing(path=None, sample=0.0)  # leave the process clean
    overhead_pct = round(100.0 * (off["tok_s"] - on["tok_s"]) / max(off["tok_s"], 1e-9), 2)

    # Static cross-check with the dtlint SYNC001 allowlist: the telemetry/
    # stats plane (metrics, kv_gauges, debug_state — what this section
    # exercises alongside traffic) must declare ZERO sanctioned blocking
    # syncs. A sync sneaking into a stats path shows up twice: dtlint
    # fails statically, and this section's overhead budget pays for it
    # dynamically. The one deliberate exception (the batched MoE aux
    # drain) lives in dtlint_baseline.json, not the allowlist.
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "tools", "dtlint", "sync_allowlist.json")) as f:
        _allow = json.load(f)
    stats_funcs = {"Scheduler.metrics", "Scheduler.kv_gauges", "Scheduler.debug_state"}
    stats_path_syncs = [e for e in _allow["allowed_syncs"] if e["func"] in stats_funcs]
    assert stats_path_syncs == [], (
        f"sync_allowlist sanctions blocking syncs in stats paths: {stats_path_syncs}"
    )
    hot = _allow["hot_paths"].get("dynamo_tpu/engine/scheduler.py", [])
    assert stats_funcs <= set(hot), (
        "scheduler stats paths fell out of the SYNC001 hot-path scope"
    )

    # Static cross-check with dtlint WARM001: the executable keys that
    # compiled during this section (all pre-mark_warmup_done, per the
    # 0-compile assert above) must be inside the statically enumerated
    # warmup key space — the recorder's dynamic view and the linter's
    # static view of "what warmup must cover" stay pinned to each other.
    from tools.dtlint.rules_warmup import static_warmup_report

    _static = static_warmup_report(os.path.dirname(os.path.abspath(__file__)))
    _dynamic = sched.flight.exec_key_summary()
    for _kind, _arities in _dynamic.items():
        assert _kind in _static["warmed"], (
            f"recorder compiled kind '{_kind}' missing from WARM001's "
            f"static warmup enumeration"
        )
        _sar = set(_static["warmed"][_kind])
        assert not _sar or set(_arities) <= _sar, (
            f"kind '{_kind}' compiled at arities {_arities}; static warmup "
            f"registers {sorted(_sar)}"
        )

    return {
        "tracing_off": off,
        "tracing_on": on,
        "overhead_pct": overhead_pct,
        "budget_pct": 2.0,
        "within_budget": overhead_pct <= 2.0,
        # Telemetry-plane proof points: the digests/SLO judge observed real
        # traffic in BOTH phases, the watchdog polled, and none of it
        # dispatched to the device (0 compiles after warmup).
        "digest_counts": digest_counts,
        "slo_judged_requests": slo_judged,
        "compiles_after_warmup": compiles_after_warmup,
        "stats_path_allowed_syncs": 0,
        "warmup_views": {
            "static_warmed_kinds": sorted(_static["warmed"]),
            "dynamic_exec_kinds": sorted(_dynamic),
            "agree": True,
        },
        # Chaos plane armed for the whole measured section with a
        # never-matching scenario: the armed-path site cost rides inside
        # the same ≤2% budget, and zero injections fired (asserted).
        "faults_armed_idle": {"armed": True, "injected": faults_injected},
        # Continuous device-truth sampler armed at the default duty cycle
        # for the whole measured section (asserted above: thread alive,
        # zero errors, duty ≤ 2%).
        "continuous_profiler": {"armed": True, **cont_stats},
        # Tenant capacity ledger armed in both phases: every request billed
        # to one of two tenants, charges conserved, zero extra compiles —
        # attribution is pure host arithmetic riding the same ≤2% budget.
        "tenant_ledger": {
            "armed": True,
            "bills": ledger_wire["bills"],
            "tenants_tracked": sorted(_tracked),
            "device_seconds": round(ledger_wire["totals"]["device_seconds"], 4),
            "kv_block_seconds": round(ledger_wire["totals"]["kv_block_seconds"], 4),
        },
        # Incident autopsy plane armed for the whole section: detector
        # polled per round, trace ring + tail keep live, host stack
        # sampler running at its production period. Calm traffic must not
        # fire (a false positive here is a detector bug worth failing on).
        "incident_plane": {
            "detector_checks": plane.detector.checks_total,
            "incidents": plane_stats["incidents_total"],
            "trace_ring_records": ring_records,
            "host_sampler_samples": sampler_report["samples"],
            "host_sampler_scheduler_share": sampler_report["scheduler_share"],
            "incident_dir": incident_dir,
        },
        "note": "tiny model on CPU, sample=1.0 with live JSONL export, trace "
                "ring + tail keep + anomaly detector + host stack sampler all "
                "armed — the worst case; production sampling (e.g. 0.1) costs "
                "proportionally less. Digests + SLO judge + roofline model "
                "+ watchdog are live in both phases.",
    }


def bench_guided_overhead():
    """Guided decoding cost at the scheduler: steady greedy decode
    throughput with every row unmasked vs every row grammar-masked
    (the fused mask-gather+sample dispatch + the host-side FSM advance).
    Interleaved best-of-N on one long-lived scheduler, same discipline as
    observability_overhead. Budget: ≤5% per-step decode overhead. Also
    reports grammar→token-FSM compile latency for a realistic tool schema
    (the per-first-request cost the LRU cache amortizes away)."""
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine.config import get_config
    from dynamo_tpu.engine.models import llama
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import Scheduler, SchedulerConfig, StopConditions
    from dynamo_tpu.llm.guided.processor import GuidedDecoder
    from dynamo_tpu.llm.tokenizer import ByteTokenizer

    cfg = get_config("tiny").replace(max_seq_len=4096)
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    rounds = 3
    # Never-accepting within the run (500+ chars required, 80 emitted), so
    # masked rows decode the full budget — pure steady-state mask cost.
    pattern = "[ab]{500,}"
    spec = {"kind": "regex", "pattern": pattern}

    sched = Scheduler(cfg, params, SchedulerConfig(
        num_blocks=768, max_running=8,
        prefill_buckets=[32, 64, 128], decode_buckets=[1, 2, 4, 8],
        num_scheduler_steps=1, enable_prefix_caching=False,
        guided_pool_rows=1024,
    ), dtype=jnp.float32)
    sched.attach_guided(ByteTokenizer())

    phase_counter = [0]

    def measure(guided: bool) -> float:
        """Steady-state decode-step throughput from the flight recorder's
        decode-phase histogram: admit all 8 rows first, then measure only
        full-batch decode steps. The subject is the per-STEP cost of the
        fused mask-gather+sample dispatch plus the host FSM advance —
        admission structure (guided rows are wave-ineligible by design)
        and batch ramp-down tails are excluded from both phases alike."""
        phase_counter[0] += 1
        p = phase_counter[0]
        for i in range(8):
            sched.add_request(
                f"p{p}r{i}", list(range(1 + (p + i) % 8, 33 + (p + i) % 8)),
                SamplingParams(temperature=0.0), StopConditions(max_tokens=200),
                guided=spec if guided else None,
            )
        while sched.waiting:
            sched.step()
        h = sched.flight._hists["decode"]
        t_before, n_before = h.sum_s, h.tokens
        while len(sched.running) == 8 and sched.has_work():
            sched.step()
        tok_s = (h.tokens - n_before) / max(h.sum_s - t_before, 1e-9)
        while sched.has_work():  # drain the tail unmeasured
            sched.step()
        return tok_s

    measure(False)  # executable warmup (admission wave + decode)
    measure(True)   # guided-sampler + grammar warmup
    best_off = best_on = 0.0
    for _ in range(rounds):
        best_off = max(best_off, measure(False))
        best_on = max(best_on, measure(True))

    # Grammar→token-FSM compile latency for a realistic tool schema (fresh
    # decoder: no LRU hit), plus the cached re-open cost.
    tool_schema = {
        "type": "object",
        "properties": {
            "location": {"type": "string", "maxLength": 64},
            "unit": {"enum": ["celsius", "fahrenheit"]},
            "days": {"type": "integer"},
            "include_hourly": {"type": "boolean"},
        },
    }
    from dynamo_tpu.llm.guided.grammar import schema_to_regex

    tool_spec = {"kind": "regex", "pattern": schema_to_regex(tool_schema)}
    dec = GuidedDecoder(ByteTokenizer(), eos_ids=[0], vocab_size=cfg.vocab_size)
    t0 = time.perf_counter()
    st = dec.open(tool_spec)
    compile_ms = (time.perf_counter() - t0) * 1000.0
    t0 = time.perf_counter()
    dec.open(tool_spec)
    cached_ms = (time.perf_counter() - t0) * 1000.0

    overhead_pct = round(100.0 * (best_off - best_on) / max(best_off, 1e-9), 2)
    return {
        "unguided": {"tok_s": round(best_off, 1), "rounds": rounds},
        "guided": {"tok_s": round(best_on, 1), "rounds": rounds,
                   "fsm_states": sched.guided.pool._used - 1},
        "overhead_pct": overhead_pct,
        "budget_pct": 5.0,
        "within_budget": overhead_pct <= 5.0,
        "grammar_compile": {
            "tool_schema_ms": round(compile_ms, 2),
            "cached_open_ms": round(cached_ms, 3),
            "fsm_states": st.fsm.num_states,
        },
        "note": "tiny model on CPU, byte tokenizer, every row masked — the "
                "worst case; real batches mix guided/unguided rows through "
                "the same executable",
    }


def bench_device_truth():
    """Measured vs modeled roofline agreement (device-truth plane).

    Runs real decode traffic through a scheduler to accumulate the modeled
    roofline account (FLOPs/bytes/step-seconds), then replays that exact
    span through the trace parser on a synthesized Chrome-trace fixture
    whose device-busy time equals the modeled step seconds — the CPU-CI
    path where the answer is known. Asserts the round trip: the parser's
    per-lane interval union recovers the busy time, the flight recorder's
    ``measured_mfu`` lands on the modeled MFU, ``measured_modeled_mfu_ratio``
    sits at 1.0 within tolerance, and the fused-window launch count
    cross-checks to exactly 1 launch per window from TRACE events. A live
    ``jax.profiler`` window against real device work rides along
    best-effort (real traces vary by backend; reported, not asserted)."""
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine.config import get_config
    from dynamo_tpu.engine.models import llama
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import Scheduler, SchedulerConfig, StopConditions
    from dynamo_tpu.runtime.profiling import (
        ContinuousProfileConfig,
        ContinuousProfiler,
        DeviceProfiler,
        parse_trace_events,
    )

    cfg = get_config("tiny").replace(max_seq_len=4096)
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    sched = Scheduler(cfg, params, SchedulerConfig(
        num_blocks=512, max_running=8,
        prefill_buckets=[32, 64], decode_buckets=[1, 2, 4, 8],
        num_scheduler_steps=1, enable_prefix_caching=False,
    ), dtype=jnp.float32)

    def drive(tag: str, n: int = 6, max_tokens: int = 48) -> None:
        for i in range(n):
            sched.add_request(
                f"{tag}{i}", list(range(1 + i % 8, 33 + i % 8)),
                SamplingParams(temperature=0.0), StopConditions(max_tokens=max_tokens),
            )
        while sched.has_work():
            sched.step()

    # XLA-truth FLOPs: the same cost_analysis calibration warmup() runs,
    # so the modeled side of the comparison is the calibrated model.
    sched._calibrate_cost_model(sched.sc.decode_buckets[0], 1)
    drive("warm")  # compiles every shape this section touches
    sched.flight.mark_warmup_done(warmed=True)
    drive("run")

    flight = sched.flight
    flops, bytes_moved, secs, fused = flight.roofline_totals()
    assert secs > 0 and flops > 0, "no modeled roofline accumulated"
    modeled_stats = flight.to_stats()
    peak_flops = flight.cost_model.peak_flops
    peak_bw = flight.cost_model.peak_bw
    modeled_mfu = flops / secs / peak_flops
    modeled_hbm = bytes_moved / secs / peak_bw

    # --- fixture path: a synthetic trace whose device lane is busy for
    # exactly the modeled step seconds, with one fused-window launch per
    # dispatched window. The parser must recover all of it.
    busy_us = secs * 1e6
    fused_n = max(int(fused), 1)
    fused_us = busy_us * 0.6 / fused_n
    events = [
        {"ph": "M", "pid": 7, "name": "process_name",
         "args": {"name": "/device:TPU:0 (fixture)"}},
        {"ph": "M", "pid": 7, "tid": 1, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        {"ph": "M", "pid": 99, "name": "process_name",
         "args": {"name": "python host"}},
        # Host-lane noise the union must EXCLUDE.
        {"ph": "X", "pid": 99, "tid": 1, "name": "host_busywork",
         "ts": 0.0, "dur": busy_us * 10},
    ]
    t = 0.0
    for _ in range(fused_n):
        events.append({"ph": "X", "pid": 7, "tid": 1,
                       "name": "fused_decode_window(steps=8)",
                       "ts": t, "dur": fused_us})
        t += fused_us + 3.0  # gaps: the union must not bridge them
    other_us = busy_us - fused_us * fused_n
    events.append({"ph": "X", "pid": 7, "tid": 1, "name": "fusion.sample_rows",
                   "ts": t, "dur": other_us})
    summary = parse_trace_events(events)
    assert summary.device_lane_found, "fixture device lane not recognized"
    assert abs(summary.device_time_us - busy_us) <= max(1.0, busy_us * 1e-6), (
        f"interval union lost time: {summary.device_time_us} vs {busy_us}"
    )
    launches = summary.launch_count("fused_decode_window")
    assert launches == fused_n, f"launch count {launches} != {fused_n}"

    record = {
        "status": "ok",
        "wall_s": secs * 1.25,  # device busy 80% of the trace wall window
        "device_time_s": summary.device_time_us / 1e6,
        "flops": flops, "bytes": bytes_moved, "step_seconds": secs,
        "kernel_events": summary.kernel_events,
        "device_lanes": summary.device_lanes,
        "device_lane_found": summary.device_lane_found,
        "truncated": summary.truncated,
        "top_kernels": summary.top(4),
        "top_kernel_share": summary.top_share(),
        "fused_windows": fused_n,
        "fused_kernel_launches": launches,
        "launches_per_fused_window": launches / fused_n,
    }
    flight.record_measured_window(record)
    stats = flight.to_stats()

    # --- the acceptance asserts: measured siblings agree with the model on
    # the span where agreement is the ground truth.
    ratio = stats["measured_modeled_mfu_ratio"]
    measured_mfu = stats["measured_mfu"]
    mfu_rel_err = abs(measured_mfu - modeled_mfu) / max(modeled_mfu, 1e-12)
    assert abs(ratio - 1.0) <= 0.02, (
        f"measured/modeled time ratio {ratio} off the fixture identity"
    )
    assert mfu_rel_err <= 0.05, (
        f"measured_mfu {measured_mfu} vs modeled {modeled_mfu}: {mfu_rel_err:.3%}"
    )
    assert stats["measured_launches_per_fused_window"] == 1.0, (
        "fused-window launch invariant broken on the trace path"
    )
    assert stats["measured_windows_total"] == 1

    # --- live capture (best effort): a real jax.profiler window over real
    # device work, through the same sample_once path the production sampler
    # runs. Reported, not asserted — trace shape varies by backend.
    import threading as _threading

    import tempfile as _tempfile
    stop = _threading.Event()

    def churn() -> None:
        x = jnp.ones((128, 128), jnp.float32)
        while not stop.is_set():
            x = jnp.tanh(x @ x.T / 128.0)
            x.block_until_ready()

    cont = ContinuousProfiler(
        DeviceProfiler(out_dir=_tempfile.mkdtemp(prefix="bench_truth_")),
        ContinuousProfileConfig(window_s=0.1),
        cost_probe=flight.roofline_totals,
        sink=None,  # keep the fixture-path measured stats as the asserted view
    )
    worker = _threading.Thread(target=churn, daemon=True)
    worker.start()
    try:
        live = cont.sample_once(force=True)
    finally:
        stop.set()
        worker.join(timeout=2.0)
    live_report = {
        "status": live.get("status"),
        "kernel_events": live.get("kernel_events"),
        "device_lanes": live.get("device_lanes"),
        "device_lane_found": live.get("device_lane_found"),
        "device_time_ms": round(float(live.get("device_time_s") or 0.0) * 1e3, 3),
        "top_kernels": (live.get("top_kernels") or [])[:3],
    }

    return {
        "modeled": {
            "mfu_overall": round(modeled_mfu, 6),
            "hbm_frac_overall": round(modeled_hbm, 6),
            "mfu_decode": modeled_stats.get("mfu_decode"),
            "hbm_frac_decode": modeled_stats.get("hbm_frac_decode"),
            "step_seconds": round(secs, 6),
            "cost_model_calibrated": stats.get("cost_model_calibrated"),
        },
        "measured": {
            "measured_mfu": measured_mfu,
            "measured_hbm_frac": stats["measured_hbm_frac"],
            "measured_device_frac": stats["measured_device_frac"],
            "measured_top_kernel_share": stats["measured_top_kernel_share"],
            "measured_launches_per_fused_window":
                stats["measured_launches_per_fused_window"],
            "device_seconds": round(summary.device_time_us / 1e6, 6),
        },
        "agreement": {
            "measured_modeled_mfu_ratio": ratio,
            "mfu_rel_err": round(mfu_rel_err, 6),
            "ratio_tolerance": 0.02,
            "mfu_tolerance": 0.05,
            "ok": True,
        },
        "fixture": {
            "kernel_events": summary.kernel_events,
            "device_lanes": summary.device_lanes,
            "fused_windows": fused_n,
            "fused_launches": launches,
        },
        "live_capture": live_report,
        "note": "fixture path is the asserted ground truth (CPU CI); the "
                "live jax.profiler window is reported best-effort. On TPU "
                "the continuous sampler feeds the same record shape from "
                "real traces.",
    }


def bench_autoscale():
    """Closed-loop SLA autoscaling under the million-user traffic harness
    (tools/traffic_harness.py): a seeded diurnal ramp with drifting ISL
    drives a real in-process plane — mocker pools → metrics aggregator
    (multi-endpoint scrape) → Prometheus observer → AutoscaleController →
    fleet launches/drains — with a chaos crash armed the moment the first
    scale event lands. Reports the SLO-attainment + goodput curves across
    the ramp, the scale timeline, and convergence vs the capacity oracle.
    CI asserts: converged (final pools within ±1 of the oracle), SLO
    attainment above the floor, chaos fired, zero token loss."""
    import asyncio

    from tools.traffic_harness import (
        AutoscaleBenchConfig,
        TrafficPattern,
        run_autoscale_bench,
    )

    cfg = AutoscaleBenchConfig(
        pattern=TrafficPattern(
            kind="diurnal", duration_s=float(os.environ.get("BENCH_AUTOSCALE_S", "20")),
            base_rate=1.5, peak_rate=8.0, isl=96, isl_end=144, osl=16,
            prefix_ratio=0.5, seed=0,
        ),
        adjustment_interval_s=1.5,
        scale_cooldown_s=3.0,
        settle_s=5.0,
    )
    report = asyncio.run(run_autoscale_bench(cfg))
    planner = report["planner"]
    report["summary"] = {
        "converged": report["final"]["converged"],
        "final_pools": {"prefill": report["final"]["prefill"],
                        "decode": report["final"]["decode"]},
        "oracle_pools": {"prefill": report["final"]["oracle_prefill"],
                         "decode": report["final"]["oracle_decode"]},
        "slo_attainment": report["slo_attainment"],
        "slo_floor": 0.7,
        "token_loss": report["totals"]["token_loss"],
        "errors": report["totals"]["errors"],
        "chaos_injections": report["chaos"]["injections"],
        "scale_ups": planner["planner_scale_up_total"],
        "scale_downs": planner["planner_scale_down_total"],
    }
    return report


def bench_elastic():
    """Elastic prefill/decode: degrade-vs-queue TTFT/goodput curves under a
    shifting ISL/OSL mix (tools/traffic_harness.py run_elastic_bench). Three
    fleets of identical hardware — pure disagg (static split, queues on
    saturation), pure co-located (mixed everywhere, constant interference),
    elastic (disagg + capacity dial + degradation ladder) — offered the same
    seeded mix flip. CI asserts the elastic fleet strictly dominates both
    static extremes on SLO attainment AND goodput, with zero token loss and
    both degrade directions exercised."""
    import asyncio

    from tools.traffic_harness import ElasticBenchConfig, run_elastic_bench

    cfg = ElasticBenchConfig()
    cfg.pattern.duration_s = float(os.environ.get("BENCH_ELASTIC_S", "16"))
    return asyncio.run(run_elastic_bench(cfg))


# --------------------------------------------------------------------------
# child: run sections against the already-chosen backend, emit partials
# --------------------------------------------------------------------------

def _emit_partial(section: str, payload) -> None:
    print(PARTIAL_TAG + json.dumps({"section": section, "data": payload}), flush=True)


def _run_cpu_subprocess(argv, key, timeout_s, extra_env=None):
    """Run a CPU-pinned helper process and scan stdout for the JSON object
    carrying ``key``. Returns (obj_or_None, error_or_None)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("BENCH_CHILD", None)
    # Helpers under tools/ put THEIR dir on sys.path, not the repo root —
    # make dynamo_tpu importable even without a pip install.
    repo = os.path.dirname(os.path.abspath(__file__))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra_env or {})
    out = subprocess.run(argv, env=env, capture_output=True, text=True, timeout=timeout_s)
    for line in out.stdout.splitlines():
        try:
            obj = json.loads(line)
            if isinstance(obj, dict) and key in obj:
                return obj, None
        except ValueError:
            pass
    return None, f"no result (rc={out.returncode}): {out.stderr.strip()[-200:]}"


def child_main() -> None:
    """Measurement process. Emits BENCH_PARTIAL lines per section and a full
    JSON line at the end; every section is individually fenced so one
    failure cannot empty the round."""
    deadline = float(os.environ["BENCH_DEADLINE"])  # absolute time.time()
    errors: list = []

    def remaining() -> float:
        return deadline - time.time()

    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine.config import get_config
    from dynamo_tpu.engine.models import llama

    cpu_fallback = os.environ.get("BENCH_CPU_FALLBACK") == "1"
    if cpu_fallback:
        model = os.environ.get("BENCH_MODEL_CPU", "tiny")
        batches = [4]
        steps, window, ctx_len, prompt_len = 16, 4, 256, 256
    else:
        model = os.environ.get("BENCH_MODEL", "llama-3.2-1b")
        batches = [int(b) for b in os.environ.get("BENCH_BATCHES", "8,16,32").split(",")]
        steps = int(os.environ.get("BENCH_STEPS", "256"))
        window = int(os.environ.get("BENCH_WINDOW", "32"))
        ctx_len = int(os.environ.get("BENCH_CTX", "1024"))
        prompt_len = int(os.environ.get("BENCH_PREFILL", "2048"))
    attn = os.environ.get("BENCH_ATTN", "auto")
    skip_http = os.environ.get("BENCH_SKIP_HTTP", "") == "1"

    device = str(jax.devices()[0])
    hbm_gbps, tflops = chip_peaks(device)
    _emit_partial("device", {"device": device, "cpu_fallback": cpu_fallback})

    cfg = get_config(model).replace(max_seq_len=max(4096, ctx_len + 512), attention_impl=attn)
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
    pbytes = param_bytes_of(params)

    # --- decode sweep (primary) — smallest batch first so SOME decode
    # number lands before any budget/compile trouble at larger batches.
    decode_points = []
    for batch in batches:
        if decode_points and remaining() < 60:
            errors.append(f"decode sweep truncated before b{batch}: {remaining():.0f}s left")
            break
        try:
            step_s = bench_decode(cfg, params, batch, ctx_len, steps, window)
            kv_bytes = 2 * cfg.num_layers * ctx_len * cfg.num_kv_heads * cfg.head_dim * 2 * batch
            gbps = (pbytes + kv_bytes) / step_s / 1e9
            point = {
                "batch": batch,
                "ctx": ctx_len,
                "step_ms": round(step_s * 1000, 3),
                "tok_s_per_user": round(1.0 / step_s, 2),
                "tok_s_per_chip": round(batch / step_s, 1),
                "achieved_hbm_gbps": round(gbps, 1),
                "pct_hbm_roofline": round(100 * gbps / hbm_gbps, 1) if hbm_gbps else None,
            }
            decode_points.append(point)
            _emit_partial("decode_point", point)
        except Exception as e:  # noqa: BLE001 — a failed point must not kill the sweep
            errors.append(f"decode b{batch}: {type(e).__name__}: {e}")

    # --- int8 KV point (capacity ×2; ON by default, BENCH_INT8=0 opts out —
    # decode latency is at best at parity on current XLA:TPU, the point
    # records the capacity configuration; see models/llama.py:_gather_kv) ---
    if os.environ.get("BENCH_INT8", "1") == "1" and not cpu_fallback and decode_points and remaining() > 90:
        try:
            b8 = batches[0]
            cfg8 = cfg.replace(kv_cache_dtype="int8", attention_impl="gather")
            step_s = bench_decode(cfg8, params, b8, ctx_len, max(64, steps // 4), window)
            kv_bytes = cfg.num_layers * ctx_len * cfg.num_kv_heads * cfg.head_dim * 2 * b8  # int8 k+v
            gbps = (pbytes + kv_bytes) / step_s / 1e9
            point = {
                "batch": b8, "ctx": ctx_len, "kv_dtype": "int8",
                "step_ms": round(step_s * 1000, 3),
                "tok_s_per_user": round(1.0 / step_s, 2),
                "tok_s_per_chip": round(b8 / step_s, 1),
                "achieved_hbm_gbps": round(gbps, 1),
                "pct_hbm_roofline": round(100 * gbps / hbm_gbps, 1) if hbm_gbps else None,
            }
            decode_points.append(point)
            _emit_partial("decode_point", point)
        except Exception as e:  # noqa: BLE001
            errors.append(f"decode int8: {type(e).__name__}: {e}")

    # --- int8-WEIGHT point (weight-only quant speeds decode outright:
    # layer weights stream at half the bytes and XLA fuses the dequant into
    # the matmul reads — measured, see engine/quant.py) ----------------------
    if os.environ.get("BENCH_INT8W", "1") == "1" and not cpu_fallback and decode_points and remaining() > 90:
        params_q = None
        try:
            from dynamo_tpu.engine.quant import quantize_params

            b8 = batches[0]
            # quantize_params mutates in place — hand it a copied layers
            # dict so the bf16 tree stays intact for the prefill section.
            params_q = quantize_params({**params, "layers": dict(params["layers"])})
            step_s = bench_decode(cfg, params_q, b8, ctx_len, max(64, steps // 4), window)
            qbytes = param_bytes_of(params_q)
            kv_bytes = 2 * cfg.num_layers * ctx_len * cfg.num_kv_heads * cfg.head_dim * 2 * b8
            gbps = (qbytes + kv_bytes) / step_s / 1e9
            point = {
                "batch": b8, "ctx": ctx_len, "weight_dtype": "int8",
                "step_ms": round(step_s * 1000, 3),
                "tok_s_per_user": round(1.0 / step_s, 2),
                "tok_s_per_chip": round(b8 / step_s, 1),
                "achieved_hbm_gbps": round(gbps, 1),
                "pct_hbm_roofline": round(100 * gbps / hbm_gbps, 1) if hbm_gbps else None,
            }
            decode_points.append(point)
            _emit_partial("decode_point", point)
        except Exception as e:  # noqa: BLE001
            errors.append(f"decode int8w: {type(e).__name__}: {e}")
        finally:
            # Free on every path: leaked int8 copies push the 8B section
            # over HBM (its own failure-mode comment).
            del params_q

    # --- decode attention backends (gather vs megakernel + dispatch tax) ----
    decode_attention = None
    if remaining() > 90:
        try:
            decode_attention = bench_decode_attention(
                cfg=cfg, params=params if not cpu_fallback else None,
                ctx_len=ctx_len, hbm_gbps=hbm_gbps,
            )
            _emit_partial("decode_attention", decode_attention)
        except Exception as e:  # noqa: BLE001
            errors.append(f"decode_attention: {type(e).__name__}: {e}")
    else:
        errors.append("decode_attention skipped: budget")

    # --- prefill ------------------------------------------------------------
    prefill_detail = None
    if remaining() > 45:
        try:
            prefill_s = bench_prefill(cfg, params, prompt_len)
            dense_params = pbytes / 2  # bf16
            mfu = (2 * dense_params * prompt_len / prefill_s / 1e12 / tflops) if tflops else None
            prefill_detail = {
                "prompt_len": prompt_len,
                "ttft_ms": round(prefill_s * 1000, 2),
                "tok_s": round(prompt_len / prefill_s, 1),
                "mfu_pct": round(100 * mfu, 1) if mfu else None,
            }
            _emit_partial("prefill", prefill_detail)
        except Exception as e:  # noqa: BLE001
            errors.append(f"prefill: {type(e).__name__}: {e}")
    else:
        errors.append("prefill skipped: budget")

    # --- TPU + HTTP combined (flagship model through the full stack) --------
    tpu_http = None
    if not skip_http and not cpu_fallback and remaining() > 120:
        try:
            tpu_http = bench_tpu_http()
            # Served fraction of the raw engine decode rate at the same
            # batch — the serving-plane tax on TPU throughput.
            raw = next((p for p in decode_points if p["batch"] == tpu_http["concurrency"]), None)
            if raw:
                tpu_http["pct_of_raw_decode"] = round(
                    100.0 * tpu_http["tok_s"] / raw["tok_s_per_chip"], 1
                )
            _emit_partial("tpu_http_e2e", tpu_http)
        except Exception as e:  # noqa: BLE001
            errors.append(f"tpu_http_e2e: {type(e).__name__}: {e}")
    elif not skip_http and not cpu_fallback:
        errors.append("tpu_http_e2e skipped: budget")

    # Free the 1B artifacts before the 8B section: the 8.5 GiB int8 model
    # plus resident 1B params/engines exceeds HBM (measured: RESOURCE_
    # EXHAUSTED poisoning every later section).
    try:
        import gc

        del params
        gc.collect()
    except NameError:
        pass

    # --- 8B-class point (int8 weights fit where bf16 cannot) ---------------
    large_detail = None
    if not cpu_fallback and os.environ.get("BENCH_SKIP_8B") != "1" and remaining() > 150:
        try:
            import gc

            from dynamo_tpu.engine.quant import QuantW

            cfg8 = get_config("llama-3-8b").replace(max_seq_len=4096)
            key8 = jax.random.PRNGKey(7)

            def synth_qw(shape):
                nonlocal key8
                key8, k1, k2 = jax.random.split(key8, 3)
                q = jax.random.randint(k1, (cfg8.num_layers,) + shape, -127, 128, jnp.int8)
                s = jax.random.uniform(k2, (cfg8.num_layers, 1, shape[-1]), jnp.float32, 1e-3, 2e-3)
                jnp.asarray(s)[0, 0, 0].block_until_ready()
                return QuantW(q, s)

            def synth_dense(shape, scale=0.02):
                nonlocal key8
                key8, k1 = jax.random.split(key8)
                return jax.random.normal(k1, shape, jnp.bfloat16) * scale

            D8, H8, KVH8, HD8, I8, V8 = (cfg8.hidden_size, cfg8.num_heads, cfg8.num_kv_heads,
                                          cfg8.head_dim, cfg8.intermediate_size, cfg8.vocab_size)
            params8 = {
                "embed": synth_dense((V8, D8)),
                "final_norm": synth_dense((D8,), 1.0),
                "lm_head": synth_dense((D8, V8)),
                "layers": {
                    "wq": synth_qw((D8, H8 * HD8)), "wk": synth_qw((D8, KVH8 * HD8)),
                    "wv": synth_qw((D8, KVH8 * HD8)), "wo": synth_qw((H8 * HD8, D8)),
                    "w_gate": synth_qw((D8, I8)), "w_up": synth_qw((D8, I8)),
                    "w_down": synth_qw((I8, D8)),
                    "attn_norm": synth_dense((cfg8.num_layers, D8), 1.0),
                    "mlp_norm": synth_dense((cfg8.num_layers, D8), 1.0),
                },
            }
            pts = []
            for b8b in (8,):
                if remaining() < 60:
                    errors.append(f"8B point b{b8b} skipped: budget")
                    break
                step_s = bench_decode(cfg8, params8, b8b, ctx_len, 128, 32)
                w_bytes = param_bytes_of(params8)
                kv_b = 2 * cfg8.num_layers * ctx_len * cfg8.num_kv_heads * cfg8.head_dim * 2 * b8b
                gbps = (w_bytes + kv_b) / step_s / 1e9
                pts.append({
                    "batch": b8b, "ctx": ctx_len,
                    "step_ms": round(step_s * 1000, 3),
                    "tok_s_per_user": round(1.0 / step_s, 2),
                    "tok_s_per_chip": round(b8b / step_s, 1),
                    "pct_hbm_roofline": round(100 * gbps / hbm_gbps, 1) if hbm_gbps else None,
                })
            large_detail = {
                "model": "llama-3-8b", "weight_dtype": "int8",
                "note": "bf16 weights are 15.0 GiB and OOM this 16 GiB chip before "
                        "the first decode step (measured); int8 layer weights "
                        "(engine/quant.py) fit with KV headroom. Synthetic codes — "
                        "perf-only; real checkpoints quantize host-side at load.",
                "points": pts,
                "ref_anchor_tok_s_user_8b_tp4_h100": 51.22,
            }
            del params8
            gc.collect()
            _emit_partial("large_model", large_detail)
        except Exception as e:  # noqa: BLE001
            errors.append(f"8B section: {type(e).__name__}: {e}")
    elif not cpu_fallback and os.environ.get("BENCH_SKIP_8B") != "1":
        errors.append("8B section skipped: budget")

    # --- router benefit (mocker fleet, CPU subprocess) ----------------------
    router_prefix = None
    if not skip_http and remaining() > 60:
        try:
            router_prefix, err = _run_cpu_subprocess(
                [sys.executable, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                              "tools", "bench_router_prefix.py"), "--quick"],
                "sweep", max(60, remaining() - 10),
            )
            if router_prefix is not None:
                _emit_partial("router_prefix", router_prefix)
            else:
                errors.append(f"router_prefix: {err}")
        except subprocess.TimeoutExpired:
            errors.append("router_prefix: subprocess timed out")
        except Exception as e:  # noqa: BLE001
            errors.append(f"router_prefix: {type(e).__name__}: {e}")
    elif not skip_http:
        errors.append("router_prefix skipped: budget")

    # --- HTTP e2e (serving stack, tiny model) -------------------------------
    # Runs in a CPU subprocess: the section measures the serving plane
    # (HTTP/preprocess/scheduler-loop/detok overhead), and routing tiny-model
    # dispatches through the TPU tunnel would time the tunnel instead.
    http = None
    if not skip_http and remaining() > 60:
        try:
            http, err = _run_cpu_subprocess(
                [sys.executable, os.path.abspath(__file__)], "tok_s",
                max(60, remaining() - 10), extra_env={"BENCH_HTTP_ONLY": "1"},
            )
            if http is None:
                errors.append(f"http_e2e: {err}")
            else:
                _emit_partial("http_e2e", http)
        except subprocess.TimeoutExpired:
            errors.append("http_e2e: subprocess timed out")
        except Exception as e:  # noqa: BLE001
            errors.append(f"http_e2e: {type(e).__name__}: {e}")
    elif not skip_http:
        errors.append("http_e2e skipped: budget")


    # --- mixed prefill+decode admission (scheduler-level, CPU subprocess) ---
    mixed_admission = None
    if remaining() > 60:
        try:
            mixed_admission, err = _run_cpu_subprocess(
                [sys.executable, os.path.abspath(__file__)], "mixed_on",
                max(60, remaining() - 10), extra_env={"BENCH_MIXED_ONLY": "1"},
            )
            if mixed_admission is None:
                errors.append(f"mixed_admission: {err}")
            else:
                _emit_partial("mixed_admission", mixed_admission)
        except subprocess.TimeoutExpired:
            errors.append("mixed_admission: subprocess timed out")
        except Exception as e:  # noqa: BLE001
            errors.append(f"mixed_admission: {type(e).__name__}: {e}")
    else:
        errors.append("mixed_admission skipped: budget")

    # --- zero-bubble decode overlap (scheduler-level, CPU subprocess) -------
    decode_overlap = None
    if remaining() > 60:
        try:
            decode_overlap, err = _run_cpu_subprocess(
                [sys.executable, os.path.abspath(__file__)], "points",
                max(60, remaining() - 10), extra_env={"BENCH_OVERLAP_ONLY": "1"},
            )
            if decode_overlap is None:
                errors.append(f"decode_overlap: {err}")
            else:
                _emit_partial("decode_overlap", decode_overlap)
        except subprocess.TimeoutExpired:
            errors.append("decode_overlap: subprocess timed out")
        except Exception as e:  # noqa: BLE001
            errors.append(f"decode_overlap: {type(e).__name__}: {e}")
    else:
        errors.append("decode_overlap skipped: budget")

    # --- engine-level prefix reuse (real schedulers, CPU subprocess) --------
    prefix_reuse = None
    if remaining() > 60:
        try:
            prefix_reuse, err = _run_cpu_subprocess(
                [sys.executable, os.path.abspath(__file__)], "speedup",
                max(60, remaining() - 10), extra_env={"BENCH_PREFIX_ONLY": "1"},
            )
            if prefix_reuse is None:
                errors.append(f"prefix_reuse: {err}")
            else:
                _emit_partial("prefix_reuse", prefix_reuse)
        except subprocess.TimeoutExpired:
            errors.append("prefix_reuse: subprocess timed out")
        except Exception as e:  # noqa: BLE001
            errors.append(f"prefix_reuse: {type(e).__name__}: {e}")
    else:
        errors.append("prefix_reuse skipped: budget")

    # --- observability overhead (tracing on vs off, CPU subprocess) ---------
    observability = None
    if remaining() > 45:
        try:
            observability, err = _run_cpu_subprocess(
                [sys.executable, os.path.abspath(__file__)], "overhead_pct",
                max(45, remaining() - 10), extra_env={"BENCH_OBS_ONLY": "1"},
            )
            if observability is None:
                errors.append(f"observability: {err}")
            else:
                _emit_partial("observability", observability)
        except subprocess.TimeoutExpired:
            errors.append("observability: subprocess timed out")
        except Exception as e:  # noqa: BLE001
            errors.append(f"observability: {type(e).__name__}: {e}")
    else:
        errors.append("observability skipped: budget")

    # --- device truth: measured vs modeled roofline (CPU subprocess) --------
    device_truth = None
    if remaining() > 45:
        try:
            device_truth, err = _run_cpu_subprocess(
                [sys.executable, os.path.abspath(__file__)], "agreement",
                max(45, remaining() - 10), extra_env={"BENCH_DEVICE_TRUTH_ONLY": "1"},
            )
            if device_truth is None:
                errors.append(f"device_truth: {err}")
            else:
                _emit_partial("device_truth", device_truth)
        except subprocess.TimeoutExpired:
            errors.append("device_truth: subprocess timed out")
        except Exception as e:  # noqa: BLE001
            errors.append(f"device_truth: {type(e).__name__}: {e}")
    else:
        errors.append("device_truth skipped: budget")

    # --- guided decoding overhead (masked vs unmasked, CPU subprocess) ------
    guided_overhead = None
    if remaining() > 45:
        try:
            guided_overhead, err = _run_cpu_subprocess(
                [sys.executable, os.path.abspath(__file__)], "overhead_pct",
                max(45, remaining() - 10), extra_env={"BENCH_GUIDED_ONLY": "1"},
            )
            if guided_overhead is None:
                errors.append(f"guided_overhead: {err}")
            else:
                _emit_partial("guided_overhead", guided_overhead)
        except subprocess.TimeoutExpired:
            errors.append("guided_overhead: subprocess timed out")
        except Exception as e:  # noqa: BLE001
            errors.append(f"guided_overhead: {type(e).__name__}: {e}")
    else:
        errors.append("guided_overhead skipped: budget")

    # --- fused in-kernel sampling + spec window (CPU subprocess) ------------
    fused_sampling = None
    if remaining() > 60:
        try:
            fused_sampling, err = _run_cpu_subprocess(
                [sys.executable, os.path.abspath(__file__)], "points",
                max(60, remaining() - 10), extra_env={"BENCH_FUSED_SAMPLE_ONLY": "1"},
            )
            if fused_sampling is None:
                errors.append(f"fused_sampling: {err}")
            else:
                _emit_partial("fused_sampling", fused_sampling)
        except subprocess.TimeoutExpired:
            errors.append("fused_sampling: subprocess timed out")
        except Exception as e:  # noqa: BLE001
            errors.append(f"fused_sampling: {type(e).__name__}: {e}")
    else:
        errors.append("fused_sampling skipped: budget")

    # --- closed-loop autoscaling (traffic harness, CPU subprocess) ----------
    autoscale = None
    if remaining() > 60:
        try:
            autoscale, err = _run_cpu_subprocess(
                [sys.executable, os.path.abspath(__file__)], "summary",
                max(60, remaining() - 10), extra_env={"BENCH_AUTOSCALE_ONLY": "1"},
            )
            if autoscale is None:
                errors.append(f"autoscale: {err}")
            else:
                _emit_partial("autoscale", autoscale)
        except subprocess.TimeoutExpired:
            errors.append("autoscale: subprocess timed out")
        except Exception as e:  # noqa: BLE001
            errors.append(f"autoscale: {type(e).__name__}: {e}")
    else:
        errors.append("autoscale skipped: budget")

    # --- elastic prefill/decode (degrade-vs-queue, CPU subprocess) ----------
    elastic = None
    if remaining() > 60:
        try:
            elastic, err = _run_cpu_subprocess(
                [sys.executable, os.path.abspath(__file__)], "summary",
                max(60, remaining() - 10), extra_env={"BENCH_ELASTIC_ONLY": "1"},
            )
            if elastic is None:
                errors.append(f"elastic: {err}")
            else:
                _emit_partial("elastic", elastic)
        except subprocess.TimeoutExpired:
            errors.append("elastic: subprocess timed out")
        except Exception as e:  # noqa: BLE001
            errors.append(f"elastic: {type(e).__name__}: {e}")
    else:
        errors.append("elastic skipped: budget")

    print(json.dumps(assemble(decode_points, prefill_detail, http, device, model,
                              cpu_fallback, errors, tpu_http=tpu_http,
                              router_prefix=router_prefix, large_model=large_detail,
                              mixed_admission=mixed_admission,
                              observability=observability,
                              guided_overhead=guided_overhead,
                              decode_overlap=decode_overlap,
                              prefix_reuse=prefix_reuse,
                              decode_attention=decode_attention,
                              fused_sampling=fused_sampling,
                              autoscale=autoscale, elastic=elastic,
                              device_truth=device_truth)), flush=True)


def assemble(decode_points, prefill_detail, http, device, model, cpu_fallback, errors, tpu_http=None, router_prefix=None, large_model=None, mixed_admission=None, observability=None, guided_overhead=None, decode_overlap=None, prefix_reuse=None, decode_attention=None, fused_sampling=None, autoscale=None, elastic=None, device_truth=None) -> dict:
    """Build the final JSON object from whatever sections completed."""
    hbm_gbps, _ = chip_peaks(device) if device else (None, None)
    best = max(decode_points, key=lambda p: p.get("achieved_hbm_gbps") or 0.0) if decode_points else None
    frac = None
    if best and hbm_gbps:
        frac = round(best["achieved_hbm_gbps"] / hbm_gbps, 3)
    return {
        "metric": (
            f"decode_tok_s_per_user_{model}_b{best['batch']}_ctx{best['ctx']}"
            if best else f"decode_tok_s_per_user_{model}"
        ),
        "value": best["tok_s_per_user"] if best else None,
        "unit": "tok/s/user",
        # Honest like-for-like: fraction of THIS chip's HBM roofline achieved
        # by the best decode point (1.0 = bandwidth-bound optimum). Null on
        # cpu fallback / unknown chip.
        "vs_baseline": frac,
        "detail": {
            "decode_sweep": decode_points,
            "decode_attention": decode_attention,
            "fused_sampling": fused_sampling,
            "prefill": prefill_detail,
            "tpu_http_e2e": tpu_http,
            "http_e2e": http,
            "router_prefix": router_prefix,
            "prefix_reuse": prefix_reuse,
            "large_model": large_model,
            "mixed_admission": mixed_admission,
            "observability": observability,
            "device_truth": device_truth,
            "guided_overhead": guided_overhead,
            "decode_overlap": decode_overlap,
            "autoscale": autoscale,
            "elastic": elastic,
            "device": device,
            "cpu_fallback": cpu_fallback,
            "errors": errors,
            "ref_anchor": {
                "decode_tok_s_user_8b_tp4_h100": 51.22,
                "prefill_ttft_ms_3k_tp4_h100": 48.37,
                "note": "different model+hardware class; anchors only",
            },
            "attention_impls": {
                "prefill": "pallas flash kernel (attention/prefill.py): 40.8 TF/s causal "
                           "at 1B shapes on v5e; 149.8->40.8 ms at 2K ISL (17.1%->63.0% MFU)",
                "decode": "auto = ragged paged-attention megakernel on TPU "
                          "(attention/megakernel.py): one pallas launch per layer "
                          "serves the whole mixed step's ragged batch (chunk rows + "
                          "length-1 decode rows, GQA fold, scalar-prefetched tables, "
                          "pl.when-skipped dead slots, int8 dequant-in-VMEM), and "
                          "greedy decode windows fuse into ONE launch "
                          "(decode_multi_fused, grid = steps x layers, on-chip token "
                          "feedback) where the working set fits VMEM. Off-TPU: XLA "
                          "width-bucketed gather (pow2 + 1.5*pow2 rungs, two-piece "
                          "online-softmax, once-per-window hoist; r5: b32 28.5% -> "
                          "~54% HBM roofline — the 3x gather traffic the megakernel "
                          "removes). The r4/r5 per-piece paged kernel remains "
                          "explicit opt-in; it lost to per-pallas-call dispatch "
                          "overhead, which the decode_attention section now tracks "
                          "per round. Full record: ModelConfig.attention_impl "
                          "docstring.",
            },
        },
    }


# --------------------------------------------------------------------------
# orchestrator: probe → choose backend → run child under budget → ALWAYS
# print the one JSON line
# --------------------------------------------------------------------------

def probe_backend(timeout_s: float, attempts: int = 2, backoff_s: float = 5.0):
    """Initialize the default jax backend in a THROWAWAY subprocess. Returns
    the device string, or None if every attempt fails/hangs. A hung TPU
    plugin costs ``timeout_s`` per attempt here instead of the whole round."""
    code = "import jax; print('PROBE_DEV', jax.devices()[0])"
    last = None
    for i in range(attempts):
        if i:
            time.sleep(backoff_s)
        try:
            out = subprocess.run(
                [sys.executable, "-c", code], capture_output=True, text=True, timeout=timeout_s
            )
            for line in out.stdout.splitlines():
                if line.startswith("PROBE_DEV "):
                    return line[len("PROBE_DEV "):]
            last = f"probe rc={out.returncode}: {out.stderr.strip()[-300:]}"
        except subprocess.TimeoutExpired:
            last = f"probe attempt {i + 1} hung >{timeout_s:.0f}s"
        print(f"bench: {last}", file=sys.stderr, flush=True)
    return None


def main() -> None:
    t_start = time.time()
    budget_s = float(os.environ.get("BENCH_BUDGET_S", "500"))
    errors: list = []

    # Clamp the probe so two attempts + backoff can never eat more than half
    # the total budget — the measurement child must always get wall-clock.
    probe_timeout = min(
        float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "75")), budget_s / 4 - 3
    )
    device = probe_backend(probe_timeout)
    cpu_fallback = device is None
    if cpu_fallback:
        errors.append("real backend unavailable after probe retries; cpu fallback")

    env = dict(os.environ)
    env["BENCH_CHILD"] = "1"
    child_budget = budget_s - (time.time() - t_start) - 5
    env["BENCH_DEADLINE"] = str(time.time() + child_budget)
    if cpu_fallback:
        env["JAX_PLATFORMS"] = "cpu"
        env["BENCH_CPU_FALLBACK"] = "1"

    partials: dict = {"decode_point": []}
    final = None
    try:
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)], env=env,
            stdout=subprocess.PIPE, stderr=sys.stderr, text=True,
        )
        try:
            out, _ = proc.communicate(timeout=child_budget + 30)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, _ = proc.communicate()
            errors.append(f"bench child exceeded {child_budget:.0f}s budget; partial results only")
        for line in (out or "").splitlines():
            if line.startswith(PARTIAL_TAG):
                rec = json.loads(line[len(PARTIAL_TAG):])
                if rec["section"] == "decode_point":
                    partials["decode_point"].append(rec["data"])
                else:
                    partials[rec["section"]] = rec["data"]
            else:
                try:
                    obj = json.loads(line)
                    if isinstance(obj, dict) and "metric" in obj:
                        final = obj
                except ValueError:
                    pass
        if final is None and proc.returncode not in (0, None):
            errors.append(f"bench child rc={proc.returncode}")
    except Exception as e:  # noqa: BLE001 — the orchestrator must always emit
        errors.append(f"orchestrator: {type(e).__name__}: {e}")

    if final is None:
        dev_info = partials.get("device") or {}
        final = assemble(
            partials["decode_point"], partials.get("prefill"), partials.get("http_e2e"),
            dev_info.get("device", device or "unknown"),
            os.environ.get("BENCH_MODEL", "llama-3.2-1b") if not cpu_fallback
            else os.environ.get("BENCH_MODEL_CPU", "tiny"),
            cpu_fallback, [], tpu_http=partials.get("tpu_http_e2e"),
            router_prefix=partials.get("router_prefix"),
            large_model=partials.get("large_model"),
            mixed_admission=partials.get("mixed_admission"),
            observability=partials.get("observability"),
            device_truth=partials.get("device_truth"),
            guided_overhead=partials.get("guided_overhead"),
            decode_overlap=partials.get("decode_overlap"),
            prefix_reuse=partials.get("prefix_reuse"),
            decode_attention=partials.get("decode_attention"),
            fused_sampling=partials.get("fused_sampling"),
            autoscale=partials.get("autoscale"),
        )
    final["detail"]["errors"] = errors + final["detail"].get("errors", [])
    final["detail"]["wall_s"] = round(time.time() - t_start, 1)
    print(json.dumps(final), flush=True)


if __name__ == "__main__":
    if os.environ.get("BENCH_DECODE_ATTN_ONLY") == "1":
        # Standalone decode_attention section (CI uses this on CPU: token
        # parity + one-launch-per-window asserts; on TPU it reports the
        # gather vs megakernel roofline sweep).
        print(json.dumps(bench_decode_attention()), flush=True)
    elif os.environ.get("BENCH_FUSED_SAMPLE_ONLY") == "1":
        # CPU-pinned in CI: the subject is the fused window's in-kernel
        # sampling epilogue + spec variant (structure + counters), not
        # device speed — TPU rounds run it for the real tok/s deltas.
        import jax

        if jax.default_backend() != "tpu":
            jax.config.update("jax_platforms", "cpu")
        print(json.dumps(bench_fused_sampling()), flush=True)
    elif os.environ.get("BENCH_PREFIX_ONLY") == "1":
        # CPU-pinned: the subject is skipped prefill FLOPs vs recompute in
        # the real scheduler, not device speed.
        import jax

        jax.config.update("jax_platforms", "cpu")
        print(json.dumps(bench_prefix_reuse()), flush=True)
    elif os.environ.get("BENCH_OVERLAP_ONLY") == "1":
        # CPU-pinned: the subject is pipeline structure (overlapped vs sync
        # step loop), not device speed.
        import jax

        jax.config.update("jax_platforms", "cpu")
        print(json.dumps(bench_decode_overlap()), flush=True)
    elif os.environ.get("BENCH_MIXED_ONLY") == "1":
        # CPU-pinned like the http section: the subject is scheduler
        # structure (mixed vs phase-separated steps), not the device.
        import jax

        jax.config.update("jax_platforms", "cpu")
        print(json.dumps(bench_mixed_admission()), flush=True)
    elif os.environ.get("BENCH_GUIDED_ONLY") == "1":
        # CPU-pinned: measures the mask-gather + FSM-advance cost in the
        # scheduler step loop, not the device tunnel.
        import jax

        jax.config.update("jax_platforms", "cpu")
        print(json.dumps(bench_guided_overhead()), flush=True)
    elif os.environ.get("BENCH_AUTOSCALE_ONLY") == "1":
        # CPU-pinned: the subject is the closed planner loop over mocker
        # fleets (scheduler/aggregator/controller structure), not a device.
        import jax

        jax.config.update("jax_platforms", "cpu")
        print(json.dumps(bench_autoscale()), flush=True)
    elif os.environ.get("BENCH_ELASTIC_ONLY") == "1":
        # CPU-pinned: the subject is topology policy (dial + degradation
        # ladder vs static extremes) over mocker fleets, not a device.
        import jax

        jax.config.update("jax_platforms", "cpu")
        print(json.dumps(bench_elastic()), flush=True)
    elif os.environ.get("BENCH_DEVICE_TRUTH_ONLY") == "1":
        # CPU-pinned: the asserted path is the trace parser + flight
        # recorder round trip on a known fixture, not device speed.
        import jax

        jax.config.update("jax_platforms", "cpu")
        print(json.dumps(bench_device_truth()), flush=True)
    elif os.environ.get("BENCH_OBS_ONLY") == "1":
        # CPU-pinned: measures the tracing layer's host-side cost, which a
        # device tunnel's dispatch latency would drown out.
        import jax

        jax.config.update("jax_platforms", "cpu")
        print(json.dumps(bench_observability_overhead()), flush=True)
    elif os.environ.get("BENCH_HTTP_ONLY") == "1":
        # Force the CPU backend from inside the process: the axon TPU plugin
        # can override the JAX_PLATFORMS env var (observed), and this section
        # must measure the serving plane, not the device tunnel.
        import jax

        jax.config.update("jax_platforms", "cpu")
        print(json.dumps(bench_http_e2e()), flush=True)
    elif os.environ.get("BENCH_CHILD") == "1":
        child_main()
    else:
        main()
