"""Build the dynamo_tpu_native C++ extension.

Usage: python native/setup.py build_ext --build-lib native/build

No pybind11 in this image — plain CPython C API. The xxhash single-header
implementation is taken from the environment (pyarrow vendors the upstream
header); we do not vendor third-party code into the repo.
"""

import glob
import os
import sys

from setuptools import Extension, setup


def find_xxhash_include() -> str:
    candidates = []
    for site in sys.path:
        if not site or not os.path.isdir(site):
            continue
        candidates += glob.glob(
            os.path.join(site, "pyarrow", "include", "arrow", "vendored", "xxhash")
        )
    for c in candidates:
        if os.path.exists(os.path.join(c, "xxhash.h")):
            return c
    raise SystemExit("xxhash.h not found in environment (need pyarrow include)")


HERE = os.path.dirname(os.path.abspath(__file__))

ext = Extension(
    "dynamo_tpu_native",
    sources=[os.path.join(HERE, "dynamo_tpu_native.cc")],
    include_dirs=[find_xxhash_include()],
    extra_compile_args=["-O2", "-std=c++17", "-fvisibility=hidden"],
    language="c++",
)

setup(name="dynamo_tpu_native", version="0.1.0", ext_modules=[ext], script_args=sys.argv[1:] or ["build_ext", "--build-lib", os.path.join(HERE, "build")])
