// dynamo_tpu_native: C++ hot paths for the router/token layer.
//
// TPU-native equivalents of the reference's native components (SURVEY.md §2):
//   - token block/sequence hashing  (ref: lib/tokens/src/lib.rs, 611 LoC Rust;
//     lib/llm/src/tokens.rs compute_hash_v2 = xxh3_64_with_seed)
//   - radix-tree prefix indexer     (ref: lib/llm/src/kv_router/indexer.rs
//     RadixTree :224 — the router's hottest data structure)
//
// Exposed as a CPython extension (no pybind11 in this image). The Python
// layer (dynamo_tpu.llm.tokens / kv_router.indexer) falls back to pure
// Python when this module is not built; semantics are identical and tested
// for parity in tests/test_native.py.
//
// xxhash: uses the vendored single-header implementation shipped inside the
// environment (XXH3 spec is stable; bit-compatible with the python `xxhash`
// wheel, which the fallback path uses).

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#define XXH_INLINE_ALL
#include <xxhash.h>

#include <algorithm>
#include <cstdint>
#include <memory>
#include <mutex>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// hashing
// ---------------------------------------------------------------------------

// Hash little-endian u32 token ids with a seed (chained from parent block).
static uint64_t hash_u32_span(const uint32_t* data, size_t n, uint64_t seed) {
#if __BYTE_ORDER__ == __ORDER_LITTLE_ENDIAN__
  return XXH3_64bits_withSeed(data, n * 4, seed);
#else
  std::vector<uint8_t> buf(n * 4);
  for (size_t i = 0; i < n; i++) {
    buf[i * 4 + 0] = data[i] & 0xff;
    buf[i * 4 + 1] = (data[i] >> 8) & 0xff;
    buf[i * 4 + 2] = (data[i] >> 16) & 0xff;
    buf[i * 4 + 3] = (data[i] >> 24) & 0xff;
  }
  return XXH3_64bits_withSeed(buf.data(), buf.size(), seed);
#endif
}

static bool tokens_to_u32(PyObject* seq, std::vector<uint32_t>* out) {
  PyObject* fast = PySequence_Fast(seq, "tokens must be a sequence of ints");
  if (!fast) return false;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  out->resize((size_t)n);
  PyObject** items = PySequence_Fast_ITEMS(fast);
  for (Py_ssize_t i = 0; i < n; i++) {
    long long v = PyLong_AsLongLong(items[i]);
    if (v == -1 && PyErr_Occurred()) {
      Py_DECREF(fast);
      return false;
    }
    (*out)[(size_t)i] = (uint32_t)v;
  }
  Py_DECREF(fast);
  return true;
}

// hash_tokens(tokens, seed) -> int (u64)
static PyObject* py_hash_tokens(PyObject*, PyObject* args) {
  PyObject* seq;
  unsigned long long seed;
  if (!PyArg_ParseTuple(args, "OK", &seq, &seed)) return nullptr;
  std::vector<uint32_t> toks;
  if (!tokens_to_u32(seq, &toks)) return nullptr;
  uint64_t h = hash_u32_span(toks.data(), toks.size(), seed);
  return PyLong_FromUnsignedLongLong(h);
}

// hash_token_blocks(tokens, block_size, seed) -> list[u64]  (chained)
static PyObject* py_hash_token_blocks(PyObject*, PyObject* args) {
  PyObject* seq;
  Py_ssize_t block_size;
  unsigned long long seed;
  if (!PyArg_ParseTuple(args, "OnK", &seq, &block_size, &seed)) return nullptr;
  if (block_size <= 0) {
    PyErr_SetString(PyExc_ValueError, "block_size must be > 0");
    return nullptr;
  }
  std::vector<uint32_t> toks;
  if (!tokens_to_u32(seq, &toks)) return nullptr;
  size_t n_full = toks.size() / (size_t)block_size;
  std::vector<uint64_t> hashes(n_full);
  {
    // Pure C++ loop — release the GIL for long sequences.
    Py_BEGIN_ALLOW_THREADS;
    uint64_t s = seed;
    for (size_t i = 0; i < n_full; i++) {
      s = hash_u32_span(toks.data() + i * (size_t)block_size,
                        (size_t)block_size, s);
      hashes[i] = s;
    }
    Py_END_ALLOW_THREADS;
  }
  PyObject* out = PyList_New((Py_ssize_t)n_full);
  if (!out) return nullptr;
  for (size_t i = 0; i < n_full; i++) {
    PyObject* v = PyLong_FromUnsignedLongLong(hashes[i]);
    if (!v) {
      Py_DECREF(out);
      return nullptr;
    }
    PyList_SET_ITEM(out, (Py_ssize_t)i, v);
  }
  return out;
}

// ---------------------------------------------------------------------------
// radix tree (ref: indexer.rs RadixTree :224)
// ---------------------------------------------------------------------------

struct Node {
  uint64_t hash = 0;
  Node* parent = nullptr;
  bool is_root = false;
  std::unordered_set<uint64_t> workers;
  std::unordered_map<uint64_t, Node*> children;
};

struct Tree {
  Node root;
  std::unordered_map<uint64_t, Node*> by_hash;
  std::unordered_map<uint64_t, std::unordered_set<uint64_t>> worker_nodes;

  Tree() { root.is_root = true; }
  ~Tree() { clear(); }

  void clear() {
    for (auto& kv : by_hash) delete kv.second;
    by_hash.clear();
    worker_nodes.clear();
    root.children.clear();
  }

  void apply_stored(uint64_t worker, const std::vector<uint64_t>& hashes,
                    bool has_parent, uint64_t parent_hash) {
    Node* parent = &root;
    if (has_parent) {
      auto it = by_hash.find(parent_hash);
      // Orphan chain (missed parent event): root it so partial matching
      // still works — mirrors the Python fallback and ref behavior.
      if (it != by_hash.end()) parent = it->second;
    }
    Node* node = parent;
    for (uint64_t h : hashes) {
      auto it = by_hash.find(h);
      if (it != by_hash.end()) {
        node = it->second;
      } else {
        auto cit = node->children.find(h);
        Node* child;
        if (cit != node->children.end()) {
          child = cit->second;
        } else {
          child = new Node();
          child->hash = h;
          child->parent = node;
          node->children.emplace(h, child);
          by_hash.emplace(h, child);
        }
        node = child;
      }
      node->workers.insert(worker);
      worker_nodes[worker].insert(h);
    }
  }

  void maybe_prune(Node* node) {
    while (!node->is_root && node->workers.empty() && node->children.empty()) {
      Node* parent = node->parent;
      parent->children.erase(node->hash);
      by_hash.erase(node->hash);
      delete node;
      node = parent;
    }
  }

  void apply_removed(uint64_t worker, const std::vector<uint64_t>& hashes) {
    for (uint64_t h : hashes) {
      auto it = by_hash.find(h);
      if (it == by_hash.end()) continue;
      Node* node = it->second;
      node->workers.erase(worker);
      auto wn = worker_nodes.find(worker);
      if (wn != worker_nodes.end()) wn->second.erase(h);
      maybe_prune(node);
    }
  }

  void remove_worker(uint64_t worker) {
    auto wn = worker_nodes.find(worker);
    if (wn != worker_nodes.end()) {
      // Copy: prune mutates by_hash.
      std::vector<uint64_t> hashes(wn->second.begin(), wn->second.end());
      for (uint64_t h : hashes) {
        auto it = by_hash.find(h);
        if (it == by_hash.end()) continue;
        Node* node = it->second;
        node->workers.erase(worker);
        maybe_prune(node);
      }
      worker_nodes.erase(worker);
    }
  }
};

typedef struct {
  PyObject_HEAD
  Tree* tree;
} RadixTreeObject;

static int RadixTree_init(RadixTreeObject* self, PyObject*, PyObject*) {
  self->tree = new Tree();
  return 0;
}

static void RadixTree_dealloc(RadixTreeObject* self) {
  delete self->tree;
  Py_TYPE(self)->tp_free((PyObject*)self);
}

static bool hashes_to_u64(PyObject* seq, std::vector<uint64_t>* out) {
  PyObject* fast = PySequence_Fast(seq, "block_hashes must be a sequence");
  if (!fast) return false;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  out->resize((size_t)n);
  PyObject** items = PySequence_Fast_ITEMS(fast);
  for (Py_ssize_t i = 0; i < n; i++) {
    uint64_t v = PyLong_AsUnsignedLongLong(items[i]);
    if (v == (uint64_t)-1 && PyErr_Occurred()) {
      Py_DECREF(fast);
      return false;
    }
    (*out)[(size_t)i] = v;
  }
  Py_DECREF(fast);
  return true;
}

// apply_stored(worker, block_hashes, parent_hash_or_None)
static PyObject* RadixTree_apply_stored(RadixTreeObject* self, PyObject* args) {
  unsigned long long worker;
  PyObject* hashes_obj;
  PyObject* parent_obj;
  if (!PyArg_ParseTuple(args, "KOO", &worker, &hashes_obj, &parent_obj))
    return nullptr;
  std::vector<uint64_t> hashes;
  if (!hashes_to_u64(hashes_obj, &hashes)) return nullptr;
  bool has_parent = parent_obj != Py_None;
  uint64_t parent_hash = 0;
  if (has_parent) {
    parent_hash = PyLong_AsUnsignedLongLong(parent_obj);
    if (parent_hash == (uint64_t)-1 && PyErr_Occurred()) return nullptr;
  }
  self->tree->apply_stored(worker, hashes, has_parent, parent_hash);
  Py_RETURN_NONE;
}

static PyObject* RadixTree_apply_removed(RadixTreeObject* self, PyObject* args) {
  unsigned long long worker;
  PyObject* hashes_obj;
  if (!PyArg_ParseTuple(args, "KO", &worker, &hashes_obj)) return nullptr;
  std::vector<uint64_t> hashes;
  if (!hashes_to_u64(hashes_obj, &hashes)) return nullptr;
  self->tree->apply_removed(worker, hashes);
  Py_RETURN_NONE;
}

static PyObject* RadixTree_remove_worker(RadixTreeObject* self, PyObject* args) {
  unsigned long long worker;
  if (!PyArg_ParseTuple(args, "K", &worker)) return nullptr;
  self->tree->remove_worker(worker);
  Py_RETURN_NONE;
}

// find_matches(block_hashes, early_exit=False) -> dict[worker, depth]
static PyObject* RadixTree_find_matches(RadixTreeObject* self, PyObject* args,
                                        PyObject* kwargs) {
  PyObject* hashes_obj;
  int early_exit = 0;
  static const char* kwlist[] = {"block_hashes", "early_exit", nullptr};
  if (!PyArg_ParseTupleAndKeywords(args, kwargs, "O|p", (char**)kwlist,
                                   &hashes_obj, &early_exit))
    return nullptr;
  std::vector<uint64_t> hashes;
  if (!hashes_to_u64(hashes_obj, &hashes)) return nullptr;

  std::unordered_map<uint64_t, int64_t> scores;
  {
    Node* node = &self->tree->root;
    int64_t depth = 0;
    for (uint64_t h : hashes) {
      auto it = node->children.find(h);
      if (it == node->children.end()) break;
      depth++;
      node = it->second;
      for (uint64_t w : node->workers) scores[w] = depth;
      if (early_exit && node->children.empty()) break;
    }
  }
  PyObject* out = PyDict_New();
  if (!out) return nullptr;
  for (auto& kv : scores) {
    PyObject* k = PyLong_FromUnsignedLongLong(kv.first);
    PyObject* v = PyLong_FromLongLong(kv.second);
    if (!k || !v || PyDict_SetItem(out, k, v) < 0) {
      Py_XDECREF(k);
      Py_XDECREF(v);
      Py_DECREF(out);
      return nullptr;
    }
    Py_DECREF(k);
    Py_DECREF(v);
  }
  return out;
}

static PyObject* RadixTree_size(RadixTreeObject* self, PyObject*) {
  return PyLong_FromSize_t(self->tree->by_hash.size());
}

static PyObject* RadixTree_workers(RadixTreeObject* self, PyObject*) {
  std::vector<uint64_t> ws;
  ws.reserve(self->tree->worker_nodes.size());
  for (auto& kv : self->tree->worker_nodes) ws.push_back(kv.first);
  std::sort(ws.begin(), ws.end());
  PyObject* out = PyList_New((Py_ssize_t)ws.size());
  if (!out) return nullptr;
  for (size_t i = 0; i < ws.size(); i++) {
    PyObject* v = PyLong_FromUnsignedLongLong(ws[i]);
    if (!v) {
      Py_DECREF(out);
      return nullptr;
    }
    PyList_SET_ITEM(out, (Py_ssize_t)i, v);
  }
  return out;
}

// dump_records() -> list[(hash, parent_hash_or_None, sorted_workers)]
// BFS order so parents restore before children (snapshot format matches the
// Python tree's dump()).
static PyObject* RadixTree_dump_records(RadixTreeObject* self, PyObject*) {
  PyObject* out = PyList_New(0);
  if (!out) return nullptr;
  std::vector<Node*> stack{&self->tree->root};
  while (!stack.empty()) {
    Node* node = stack.back();
    stack.pop_back();
    for (auto& kv : node->children) {
      Node* child = kv.second;
      PyObject* parent = node->is_root
                             ? Py_NewRef(Py_None)
                             : PyLong_FromUnsignedLongLong(node->hash);
      std::vector<uint64_t> ws(child->workers.begin(), child->workers.end());
      std::sort(ws.begin(), ws.end());
      PyObject* wlist = PyList_New((Py_ssize_t)ws.size());
      if (!parent || !wlist) {
        Py_XDECREF(parent);
        Py_XDECREF(wlist);
        Py_DECREF(out);
        return nullptr;
      }
      for (size_t i = 0; i < ws.size(); i++)
        PyList_SET_ITEM(wlist, (Py_ssize_t)i,
                        PyLong_FromUnsignedLongLong(ws[i]));
      PyObject* rec = Py_BuildValue("(KNN)", (unsigned long long)child->hash,
                                    parent, wlist);
      if (!rec || PyList_Append(out, rec) < 0) {
        Py_XDECREF(rec);
        Py_DECREF(out);
        return nullptr;
      }
      Py_DECREF(rec);
      stack.push_back(child);
    }
  }
  return out;
}

static PyObject* RadixTree_clear(RadixTreeObject* self, PyObject*) {
  self->tree->clear();
  Py_RETURN_NONE;
}

static PyMethodDef RadixTree_methods[] = {
    {"apply_stored", (PyCFunction)RadixTree_apply_stored, METH_VARARGS,
     "apply_stored(worker, block_hashes, parent_hash_or_None)"},
    {"apply_removed", (PyCFunction)RadixTree_apply_removed, METH_VARARGS,
     "apply_removed(worker, block_hashes)"},
    {"remove_worker", (PyCFunction)RadixTree_remove_worker, METH_VARARGS,
     "remove_worker(worker)"},
    {"find_matches", (PyCFunction)RadixTree_find_matches,
     METH_VARARGS | METH_KEYWORDS,
     "find_matches(block_hashes, early_exit=False) -> {worker: depth}"},
    {"size", (PyCFunction)RadixTree_size, METH_NOARGS, "node count"},
    {"workers", (PyCFunction)RadixTree_workers, METH_NOARGS,
     "sorted worker ids"},
    {"dump_records", (PyCFunction)RadixTree_dump_records, METH_NOARGS,
     "snapshot records (hash, parent, workers) in BFS order"},
    {"clear", (PyCFunction)RadixTree_clear, METH_NOARGS, "drop all state"},
    {nullptr, nullptr, 0, nullptr},
};

static PyTypeObject RadixTreeType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
};

}  // namespace

// ---------------------------------------------------------------------------
// C ABI: KV event publishing for external native runtimes
// (ref: lib/bindings/c/src/lib.rs — dynamo_llm_init/shutdown + KV event
// publish FFI used by the TRT-LLM C++ runtime). A native component (data
// loader, custom engine runtime) calls these extern "C" functions WITHOUT
// holding the GIL; events land in a mutex-guarded queue the Python
// KvEventPublisher drains (drain_kv_events below).
// ---------------------------------------------------------------------------

struct CKvEvent {
  uint64_t worker_id;
  int kind;  // 0 = stored, 1 = removed
  std::vector<uint64_t> hashes;
  uint64_t parent;  // meaningful iff has_parent
  bool has_parent;
};

static std::mutex g_kv_events_mu;
static std::vector<CKvEvent> g_kv_events;
static bool g_kv_initialized = false;
// Bounded: if the Python drainer is not running, publishes are dropped (and
// counted) instead of growing the queue without limit.
static const size_t kKvEventQueueCap = 65536;
static uint64_t g_kv_events_dropped = 0;

extern "C" {

#define DYN_EXPORT __attribute__((visibility("default")))

// Returns 0 on success. Idempotent.
DYN_EXPORT int dynamo_tpu_llm_init(void) {
  std::lock_guard<std::mutex> lock(g_kv_events_mu);
  g_kv_initialized = true;
  return 0;
}

DYN_EXPORT int dynamo_tpu_llm_shutdown(void) {
  std::lock_guard<std::mutex> lock(g_kv_events_mu);
  g_kv_initialized = false;
  g_kv_events.clear();
  return 0;
}

// hashes: array of n chained block hashes; parent: hash of the block
// preceding hashes[0], or pass has_parent=0 for a sequence head.
DYN_EXPORT int dynamo_tpu_kv_event_publish_stored(uint64_t worker_id, const uint64_t* hashes,
                                       size_t n, uint64_t parent, int has_parent) {
  std::lock_guard<std::mutex> lock(g_kv_events_mu);
  if (!g_kv_initialized) return -1;
  if (g_kv_events.size() >= kKvEventQueueCap) {
    g_kv_events_dropped++;
    return -2;
  }
  CKvEvent ev;
  ev.worker_id = worker_id;
  ev.kind = 0;
  ev.hashes.assign(hashes, hashes + n);
  ev.parent = parent;
  ev.has_parent = has_parent != 0;
  g_kv_events.push_back(std::move(ev));
  return 0;
}

DYN_EXPORT int dynamo_tpu_kv_event_publish_removed(uint64_t worker_id, const uint64_t* hashes,
                                        size_t n) {
  std::lock_guard<std::mutex> lock(g_kv_events_mu);
  if (!g_kv_initialized) return -1;
  if (g_kv_events.size() >= kKvEventQueueCap) {
    g_kv_events_dropped++;
    return -2;
  }
  CKvEvent ev;
  ev.worker_id = worker_id;
  ev.kind = 1;
  ev.hashes.assign(hashes, hashes + n);
  ev.parent = 0;
  ev.has_parent = false;
  g_kv_events.push_back(std::move(ev));
  return 0;
}

}  // extern "C"

// drain_kv_events() -> list[dict] — Python-side pump into KvEventPublisher.
static PyObject* py_drain_kv_events(PyObject*, PyObject*) {
  std::vector<CKvEvent> drained;
  {
    std::lock_guard<std::mutex> lock(g_kv_events_mu);
    drained.swap(g_kv_events);
  }
  PyObject* out = PyList_New((Py_ssize_t)drained.size());
  if (!out) return nullptr;
  for (size_t i = 0; i < drained.size(); i++) {
    const CKvEvent& ev = drained[i];
    PyObject* hashes = PyList_New((Py_ssize_t)ev.hashes.size());
    if (!hashes) { Py_DECREF(out); return nullptr; }
    for (size_t j = 0; j < ev.hashes.size(); j++) {
      PyList_SET_ITEM(hashes, (Py_ssize_t)j,
                      PyLong_FromUnsignedLongLong(ev.hashes[j]));
    }
    PyObject* parent = ev.has_parent
        ? PyLong_FromUnsignedLongLong(ev.parent)
        : (Py_INCREF(Py_None), Py_None);
    PyObject* d = Py_BuildValue(
        "{s:K, s:s, s:N, s:N}",
        "worker_id", (unsigned long long)ev.worker_id,
        "kind", ev.kind == 0 ? "stored" : "removed",
        "block_hashes", hashes,
        "parent_hash", parent);
    if (!d) { Py_DECREF(out); return nullptr; }
    PyList_SET_ITEM(out, (Py_ssize_t)i, d);
  }
  return out;
}

static PyObject* py_kv_events_dropped(PyObject*, PyObject*) {
  std::lock_guard<std::mutex> lock(g_kv_events_mu);
  return PyLong_FromUnsignedLongLong(g_kv_events_dropped);
}

static PyMethodDef module_methods[] = {
    {"hash_tokens", py_hash_tokens, METH_VARARGS,
     "hash_tokens(tokens, seed) -> u64 (xxh3_64 over LE u32 ids)"},
    {"hash_token_blocks", py_hash_token_blocks, METH_VARARGS,
     "hash_token_blocks(tokens, block_size, seed) -> list[u64] (chained)"},
    {"drain_kv_events", py_drain_kv_events, METH_NOARGS,
     "drain_kv_events() -> list[dict] — pop events queued via the C ABI"},
    {"kv_events_dropped", py_kv_events_dropped, METH_NOARGS,
     "kv_events_dropped() -> int — publishes rejected because the queue was full"},
    {nullptr, nullptr, 0, nullptr},
};

static struct PyModuleDef native_module = {
    PyModuleDef_HEAD_INIT,
    "dynamo_tpu_native",
    "C++ hot paths: token hashing + radix-tree prefix indexer + KV event C ABI",
    -1,
    module_methods,
};

PyMODINIT_FUNC PyInit_dynamo_tpu_native(void) {
  RadixTreeType.tp_name = "dynamo_tpu_native.RadixTree";
  RadixTreeType.tp_basicsize = sizeof(RadixTreeObject);
  RadixTreeType.tp_flags = Py_TPFLAGS_DEFAULT;
  RadixTreeType.tp_doc = "C++ radix tree over chained block hashes";
  RadixTreeType.tp_new = PyType_GenericNew;
  RadixTreeType.tp_init = (initproc)RadixTree_init;
  RadixTreeType.tp_dealloc = (destructor)RadixTree_dealloc;
  RadixTreeType.tp_methods = RadixTree_methods;
  if (PyType_Ready(&RadixTreeType) < 0) return nullptr;

  PyObject* m = PyModule_Create(&native_module);
  if (!m) return nullptr;
  Py_INCREF(&RadixTreeType);
  if (PyModule_AddObject(m, "RadixTree", (PyObject*)&RadixTreeType) < 0) {
    Py_DECREF(&RadixTreeType);
    Py_DECREF(m);
    return nullptr;
  }
  return m;
}
