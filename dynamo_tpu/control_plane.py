"""Run the built-in control-plane broker: ``python -m dynamo_tpu.control_plane``.

Plays the roles etcd + NATS play for the reference (discovery/leases +
messaging/streams/object store) as a single zero-dependency process.
"""

from __future__ import annotations

import argparse
import asyncio

from dynamo_tpu.runtime.logging import init_logging
from dynamo_tpu.runtime.transports.tcp_control import ControlPlaneServer


async def amain(host: str, port: int) -> None:
    server = ControlPlaneServer(host=host, port=port)
    await server.start()
    print(f"control plane ready on {server.host}:{server.port}", flush=True)
    try:
        await server.serve_forever()
    except asyncio.CancelledError:
        pass
    finally:
        await server.close()


def main() -> None:
    init_logging()
    parser = argparse.ArgumentParser(description="dynamo-tpu built-in control plane (etcd+NATS role)")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=6650)
    args = parser.parse_args()
    try:
        asyncio.run(amain(args.host, args.port))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
