"""Logits processing: per-request processors applied before sampling.

Ref: lib/bindings/python src/dynamo/logits_processing — ``BaseLogitsProcessor``
protocol + example processors that engine adapters pass through to the
engine. TPU twist: processors come in two flavors —

- **Jit processors** (subclass :class:`JitLogitsProcessor`): pure functions
  of (logits, generated-token history) that the scheduler folds into the
  compiled sampling step. They must be shape-polymorphic-free jnp code.
- **Host processors** (plain :class:`BaseLogitsProcessor`): arbitrary Python
  run on the host between device steps (one device↔host sync per step —
  fine for debugging/constrained decoding prototypes, not for the hot path).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Protocol, Sequence, runtime_checkable

import jax
import jax.numpy as jnp


@runtime_checkable
class BaseLogitsProcessor(Protocol):
    """Protocol: called with the running token history and current logits,
    returns adjusted logits (host-side, numpy/jax array in/out)."""

    def __call__(self, token_ids: Sequence[int], logits: jax.Array) -> jax.Array:
        ...


class JitLogitsProcessor:
    """A processor expressible in pure jnp over fixed shapes; the scheduler
    can fuse it into the compiled decode step.

    ``apply(logits, history, history_len)``: logits [V] f32, history [H] i32
    (rolling window of generated ids, -1 padded), history_len scalar."""

    def apply(self, logits: jax.Array, history: jax.Array, history_len: jax.Array) -> jax.Array:
        raise NotImplementedError


# --- example / stock processors --------------------------------------------


@dataclass
class TemperatureProcessor(JitLogitsProcessor):
    temperature: float = 1.0

    def apply(self, logits, history, history_len):
        t = jnp.maximum(self.temperature, 1e-6)
        return logits / t

    def __call__(self, token_ids, logits):
        return self.apply(logits, None, None)


@dataclass
class RepetitionPenaltyProcessor(JitLogitsProcessor):
    """HF-style repetition penalty over the generated-token window:
    seen tokens' logits are divided (if >0) or multiplied (if <0) by
    ``penalty``."""

    penalty: float = 1.1

    def apply(self, logits, history, history_len):
        V = logits.shape[-1]
        hist = jnp.where(history >= 0, history, V)  # pad → out-of-range bucket
        seen = jnp.zeros((V + 1,), dtype=bool).at[hist].set(True)[:V]
        penalized = jnp.where(logits > 0, logits / self.penalty, logits * self.penalty)
        return jnp.where(seen, penalized, logits)

    def __call__(self, token_ids, logits):
        hist = jnp.asarray(list(token_ids) or [-1], dtype=jnp.int32)
        return self.apply(logits, hist, jnp.int32(len(token_ids)))


@dataclass
class MinPProcessor(JitLogitsProcessor):
    """min-p: drop tokens whose probability < min_p * max_prob."""

    min_p: float = 0.05

    def apply(self, logits, history, history_len):
        probs = jax.nn.softmax(logits, axis=-1)
        cutoff = self.min_p * jnp.max(probs, axis=-1, keepdims=True)
        return jnp.where(probs >= cutoff, logits, -jnp.inf)

    def __call__(self, token_ids, logits):
        return self.apply(logits, None, None)


class LogitBiasProcessor(JitLogitsProcessor):
    """OpenAI ``logit_bias``: add a per-token additive bias to the logits
    before sampling (−100 effectively bans a token, +100 effectively forces
    it among the biased set). The bias arrays are built once per request;
    apply is a two-gather jnp add, so the host path costs one fused op."""

    def __init__(self, bias: dict):
        # {token_id: bias} — accept str keys (raw OpenAI JSON) defensively.
        ids = [int(k) for k in bias.keys()]
        vals = [float(v) for v in bias.values()]
        self.ids = jnp.asarray(ids or [0], dtype=jnp.int32)
        self.vals = jnp.asarray(vals or [0.0], dtype=jnp.float32)
        self.empty = not ids

    def apply(self, logits, history, history_len):
        if self.empty:
            return logits
        V = logits.shape[-1]
        ids = jnp.clip(self.ids, 0, V - 1)
        keep = (self.ids >= 0) & (self.ids < V)
        return logits.at[ids].add(jnp.where(keep, self.vals, 0.0))

    def __call__(self, token_ids, logits):
        return self.apply(logits, None, None)


@dataclass
class AllowedTokensProcessor(JitLogitsProcessor):
    """Constrain sampling to an allow-list (the building block for
    constrained/JSON decoding — the reference exposes the same example)."""

    allowed: Sequence[int] = ()

    def apply(self, logits, history, history_len):
        V = logits.shape[-1]
        mask = jnp.zeros((V,), dtype=bool).at[jnp.asarray(list(self.allowed), dtype=jnp.int32)].set(True)
        return jnp.where(mask, logits, -jnp.inf)

    def __call__(self, token_ids, logits):
        return self.apply(logits, None, None)


def apply_chain(
    processors: List[BaseLogitsProcessor],
    token_ids: Sequence[int],
    logits: jax.Array,
) -> jax.Array:
    for proc in processors:
        logits = proc(token_ids, logits)
    return logits
