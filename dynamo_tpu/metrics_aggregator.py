"""Metrics aggregator service: scrape worker stats → Prometheus.

Ref: components/metrics/src/{main.rs,lib.rs} (863 LoC Rust) — polls
component service stats and exposes cluster-level Prometheus gauges (plus the
KV-hit-rate event consumer). Run:
``python -m dynamo_tpu.metrics_aggregator --endpoint ns/comp/ep``.
"""

from __future__ import annotations

import argparse
import asyncio
from typing import Optional

from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.health import SystemHealth, SystemStatusServer, HEALTHY
from dynamo_tpu.runtime.logging import get_logger, init_logging
from dynamo_tpu.runtime.metrics import MetricsRegistry

logger = get_logger(__name__)


class MetricsAggregator:
    def __init__(self, drt: DistributedRuntime, namespace: str, component: str, endpoint: str, interval_s: float = 2.0):
        self.drt = drt
        self.namespace = namespace
        self.component = component
        self.endpoint_name = endpoint
        self.interval_s = interval_s
        self.registry = MetricsRegistry(labels={"namespace": namespace, "component": component})
        self._task: Optional[asyncio.Task] = None
        self.client = None

    async def start(self) -> None:
        ep = self.drt.namespace(self.namespace).component(self.component).endpoint(self.endpoint_name)
        self.client = await ep.client()
        self._task = asyncio.get_running_loop().create_task(self._loop())

    async def _loop(self) -> None:
        g_workers = self.registry.gauge("workers", "live worker instances")
        try:
            while True:
                stats = await self.client.scrape_stats()
                g_workers.set(len(stats))
                for wid, s in stats.items():
                    labels = {"worker": f"{wid:x}"}
                    for key in ("kv_usage", "num_running", "num_waiting", "in_flight",
                                "remote_prefills", "local_prefills",
                                "moe_dropped_total", "moe_assignments_total",
                                "mixed_steps_total", "mixed_prefill_tokens_total",
                                "mixed_decode_tokens_total"):
                        if key in s:
                            self.registry.gauge(f"worker_{key}", f"worker {key}", **labels).set(float(s[key]))
                await asyncio.sleep(self.interval_s)
        except asyncio.CancelledError:
            pass

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass


async def amain(args) -> None:
    drt = await DistributedRuntime.from_settings()
    ns, comp, ep = args.endpoint.split("/")
    agg = MetricsAggregator(drt, ns, comp, ep, interval_s=args.interval)
    await agg.start()
    health = SystemHealth()
    health.set_system_ready()
    server = SystemStatusServer(health, metrics=agg.registry)
    server.config.port = args.port
    await server.start()
    logger.info("metrics aggregator serving :%d/metrics for %s", server.port, args.endpoint)
    await asyncio.Event().wait()


def main() -> None:
    init_logging()
    p = argparse.ArgumentParser(description="dynamo-tpu metrics aggregator")
    p.add_argument("--endpoint", required=True, help="ns/component/endpoint to scrape")
    p.add_argument("--port", type=int, default=9090)
    p.add_argument("--interval", type=float, default=2.0)
    try:
        asyncio.run(amain(p.parse_args()))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
