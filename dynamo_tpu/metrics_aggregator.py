"""Metrics aggregator service: scrape worker stats → Prometheus.

Ref: components/metrics/src/{main.rs,lib.rs} (863 LoC Rust) — polls
component service stats and exposes cluster-level Prometheus gauges (plus the
KV-hit-rate event consumer). Run:
``python -m dynamo_tpu.metrics_aggregator --endpoint ns/comp/ep``.
"""

from __future__ import annotations

import argparse
import asyncio
from typing import Optional, Sequence

from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.health import SystemHealth, SystemStatusServer, HEALTHY
from dynamo_tpu.runtime.logging import get_logger, init_logging
from dynamo_tpu.runtime.metrics import MetricsRegistry
from dynamo_tpu.runtime.telemetry import DigestCollector

logger = get_logger(__name__)


# Point-in-time worker stats → Gauges.
GAUGE_KEYS = (
    "kv_usage", "kv_total_blocks", "kv_active_blocks",
    "num_running", "num_waiting", "in_flight",
    "remote_prefills", "local_prefills",
    # KV-pool utilization (free/cached depth, internal fragmentation) and
    # the prefix-cache hit rate — the load-skew signals elastic
    # prefill/decode rebalancing observes.
    "kv_free_blocks", "kv_cached_blocks", "kv_fragmentation", "prefix_hit_rate",
    # SLO attainment + live goodput rates (the SloJudge rolling window).
    "slo_attainment", "goodput_req_per_s", "goodput_tok_per_s",
    # Live roofline estimates per phase (flight-recorder FLOPs+bytes model).
    "mfu_prefill", "mfu_decode", "mfu_mixed", "mfu_wave", "mfu_spec",
    "hbm_frac_prefill", "hbm_frac_decode", "hbm_frac_mixed",
    "hbm_frac_wave", "hbm_frac_spec",
    # Stall watchdog: 1.0 = step loop wedged with work queued.
    "engine_stalled", "last_step_age_s",
    # Drain lifecycle: 1.0 while the worker is deregistered and finishing
    # (or migrating) its in-flight work.
    "draining",
    # KV warmth: fraction of the worker's KV pool holding registered
    # (reusable) prefix blocks — the engine-side half of the planner's
    # coldest-worker scale-down ranking.
    "kv_warmth",
    # Planner (autoscale controller) targets + mode, scraped from the
    # planner's own stats endpoint (planner/fleet.py serve_planner).
    "planner_prefill_target", "planner_decode_target", "planner_dry_run",
    # Incident autopsy plane: seconds since the last black-box capture
    # (-1 = never) — the "is anything firing / did we capture it" gauge.
    "incident_last_age_s",
    # Pallas launch sites traced into one fused decode-window executable
    # (must be exactly 1; CI asserts — see flight_recorder).
    "fused_window_pallas_launches",
    # Elastic capacity dial: the live prefill:decode split each worker is
    # running (fraction ∈ [0,1]; 0.5 = configured identity) and the budget /
    # slot values it resolves to, plus the planner's fleet-wide ratio target.
    "elastic_prefill_fraction", "elastic_prefill_budget", "elastic_decode_slots",
    "planner_elastic_ratio",
    # Device-truth profiling plane (ISSUE 15): the continuous sampler's live
    # duty cycle, the measured (trace-derived) siblings of the modeled
    # roofline gauges, the measured÷modeled cross-check ratio, and whether
    # the cost model was calibrated from XLA cost_analysis.
    "device_profile_duty_cycle",
    "measured_mfu", "measured_hbm_frac", "measured_device_frac",
    "measured_modeled_mfu_ratio", "measured_top_kernel_share",
    "measured_launches_per_fused_window",
    "cost_model_calibrated",
    # Profile-derived capacity: EMA of measured per-worker tok/s the
    # autoscale controller is currently steering on (0 until warm).
    "planner_measured_prefill_tok_s", "planner_measured_decode_tok_s",
    # Tenant capacity ledger (runtime/ledger.py): tenants currently tracked
    # by the worker's device-seconds heavy-hitter sketch (≤ top_k).
    "tenant_tracked",
)

# Fleet-level digest families the aggregator re-exports (merged across
# workers): each becomes ``dynamo_component_fleet_<name>_seconds`` (native
# histogram, cumulative) + ``..._seconds_quantile`` (windowed p50/p90/p99
# gauges). Workers may export any subset; unknown names flow through too.
DIGEST_KEYS = (
    "ttft", "tpot", "itl", "queue_wait",
    "prefill_step", "decode_step", "mixed_step", "wave_step", "spec_step",
)
FLEET_DIGEST_PREFIX = "dynamo_component_fleet_"

# Monotonic worker stats → Counters (``rate()``-able; a Gauge here breaks
# PromQL rate/increase semantics). The scrape sees running totals, so the
# aggregator exports per-scrape deltas; a total going backwards means the
# worker restarted and the new total is counted from zero.
COUNTER_KEYS = (
    "request_total", "preemptions_total",
    "moe_dropped_total", "moe_assignments_total",
    "mixed_steps_total", "mixed_prefill_tokens_total", "mixed_decode_tokens_total",
    "overlap_steps_total", "overlap_flushes_total",
    "cached_tokens_total",
    "prefix_hit_blocks_total", "prefix_miss_blocks_total",
    "prefix_evicted_blocks_total", "prefix_onboard_total",
    "queue_wait_seconds_total", "prefill_wait_seconds_total", "first_tokens_total",
    "decode_host_gap_events_total", "decode_host_gap_seconds_total",
    "compiles_total", "compiles_after_warmup_total",
    "guided_requests_total", "guided_grammar_compiles_total",
    "guided_grammar_compile_seconds_total",
    "step_prefill_steps_total", "step_prefill_time_seconds_total", "step_prefill_tokens_total",
    "step_decode_steps_total", "step_decode_time_seconds_total", "step_decode_tokens_total",
    "step_mixed_steps_total", "step_mixed_time_seconds_total", "step_mixed_tokens_total",
    "step_wave_steps_total", "step_wave_time_seconds_total", "step_wave_tokens_total",
    "step_spec_steps_total", "step_spec_time_seconds_total", "step_spec_tokens_total",
    # SLO attainment + goodput (SLO-attained requests/tokens; rate() gives
    # goodput req/s and tok/s over any window).
    "slo_ttft_attained_total", "slo_ttft_violated_total",
    "slo_tpot_attained_total", "slo_tpot_violated_total",
    "goodput_requests_total", "goodput_tokens_total",
    # Per-phase FLOPs/bytes from the flight-recorder cost model: rate()
    # against the chip peaks gives MFU / HBM-roofline fraction in PromQL.
    "step_prefill_flops_total", "step_prefill_bytes_total",
    "step_decode_flops_total", "step_decode_bytes_total",
    "step_mixed_flops_total", "step_mixed_bytes_total",
    "step_wave_flops_total", "step_wave_bytes_total",
    "step_spec_flops_total", "step_spec_bytes_total",
    # Stall watchdog transitions (each is one wedged-engine incident).
    "engine_stalls_total",
    # Fused megakernel decode windows dispatched (one pallas launch each),
    # plus the sampled-epilogue and speculative variants of that window.
    "fused_windows_total", "fused_sampled_windows_total",
    "spec_fused_windows_total", "spec_fused_accepted_tokens_total",
    # Incident autopsy plane (runtime/incidents.py): anomaly-triggered
    # black-box captures, total and per trigger reason, plus on-demand /
    # per-incident device-profile captures.
    "incidents_total",
    "incidents_ttft_p99_total", "incidents_tpot_p99_total",
    "incidents_queue_wait_p99_total", "incidents_slo_violation_total",
    "incidents_post_warmup_compile_total", "incidents_engine_stall_total",
    "incidents_host_gap_total", "incidents_worker_lost_total",
    "profiler_captures_total",
    # Failure lifecycle (chaos plane, runtime/faults.py + hardened paths):
    # deadline evictions, completed drains, and injected faults total /
    # per kind (keys only present on chaos-armed workers).
    "request_timeouts_total", "worker_drains_total",
    # Traffic shape (mocker fleets / frontend-less stacks): the planner's
    # observer derives request rate and avg ISL/OSL from these deltas.
    "input_tokens_total", "output_tokens_total", "disagg_prefill_done_total",
    # Autoscale controller decisions (planner/controller.py to_stats):
    # actions taken and the anti-flap gates that suppressed them.
    "planner_decisions_total",
    "planner_scale_up_total", "planner_scale_down_total",
    "planner_hysteresis_suppressed_total", "planner_cooldown_suppressed_total",
    "planner_drain_debounced_total",
    "faults_injected_total",
    "faults_crash_total", "faults_hang_total", "faults_stream_drop_total",
    "faults_delay_total", "faults_partition_total", "faults_lease_drop_total",
    "faults_stats_blackout_total", "faults_slow_total",
    # Elastic prefill/decode (ISSUE 14): dial moves, degradation-ladder
    # transitions in both directions, and token-boundary prefill splits.
    "elastic_dial_changes_total",
    "degrade_disagg_to_colocated_total", "degrade_colocated_to_disagg_total",
    "split_prefills_total", "planner_dial_total",
    # Device-truth profiling plane (ISSUE 15): continuous-sampler window
    # accounting (attempted windows, trace seconds, yields to on-demand
    # captures, parse/capture errors), the flight-recorder fold of parsed
    # windows, and capture-lock contention on the shared DeviceProfiler.
    "device_profile_windows_total", "device_profile_window_seconds_total",
    "device_profile_skipped_busy_total", "device_profile_errors_total",
    "measured_windows_total", "measured_device_seconds_total",
    "measured_wall_seconds_total",
    "profiler_capture_conflicts_total",
    # Tenant capacity ledger: per-worker exact billed totals (unlabeled —
    # the labeled per-tenant families are fleet-side, built from the merged
    # sketch wire in _export_tenant_families).
    "tenant_billed_device_seconds_total", "tenant_billed_kv_block_seconds_total",
    "tenant_billed_queue_seconds_total", "tenant_billed_output_tokens_total",
    "tenant_bills_total", "tenant_slo_attained_total", "tenant_slo_violated_total",
)

# Fleet-merged per-tenant counter families: top-K tenants by label plus an
# ``other`` bucket so Σ labeled series ≈ the fleet's exact billed total
# (the SpaceSaving over-count bias lands in the clamped ``other``).
TENANT_FAMILY_BY_DIM = {
    "device_seconds": "tenant_device_seconds_total",
    "kv_block_seconds": "tenant_kv_block_seconds_total",
    "queue_seconds": "tenant_queue_seconds_total",
}


class MetricsAggregator:
    def __init__(self, drt: DistributedRuntime, namespace: str, component: str, endpoint: str, interval_s: float = 2.0,
                 incident_dir: Optional[str] = None, extra_endpoints: Sequence[str] = ()):
        self.drt = drt
        self.namespace = namespace
        self.component = component
        self.endpoint_name = endpoint
        # Additional ``ns/component/endpoint`` paths scraped into the same
        # registry — a disaggregated deployment's prefill + decode pools
        # (plus the planner's stats endpoint) aggregate in one process.
        self.extra_endpoints = list(extra_endpoints)
        self.interval_s = interval_s
        self.registry = MetricsRegistry(labels={"namespace": namespace, "component": component})
        # Fleet-level incident plane: the aggregator is the one process that
        # sees the whole instance set, so the ``worker_lost`` detector (set
        # shrink between scrapes — a crash or lease lapse, since drains move
        # worker_drains_total instead) lives here. Bundles attach the
        # process's registered evidence probes — in single-process demo
        # stacks that includes the router's routing-decision ring.
        import os as _os

        from dynamo_tpu.runtime.incidents import (
            INCIDENT_DIR_ENV,
            IncidentConfig,
            IncidentPlane,
        )

        self.incidents = IncidentPlane(
            IncidentConfig(dir=incident_dir or _os.environ.get(INCIDENT_DIR_ENV)),
            config_probe=lambda: {
                "role": "metrics_aggregator",
                "endpoint": f"{namespace}/{component}/{endpoint}",
            },
        )
        self._last_scrape: dict = {}
        # Fleet-merged latency digests: per-worker wire sketches merge
        # bucket-wise into TRUE fleet quantiles (averaging per-worker p99s
        # does not compose), re-exported as native Prometheus histograms +
        # quantile gauges under dynamo_component_fleet_*.
        self.digests = DigestCollector(FLEET_DIGEST_PREFIX, registry=self.registry.registry)
        self._task: Optional[asyncio.Task] = None
        self.client = None
        # Last-seen totals per (worker, key) for Counter delta export.
        self._last: dict = {}
        # Latest tenant-ledger wire per worker (kept across scrapes so a
        # briefly-missed worker doesn't re-count its history when it
        # reappears); merged fleet-wide each scrape.
        self._tenant_wires: dict = {}

    async def start(self) -> None:
        ep = self.drt.namespace(self.namespace).component(self.component).endpoint(self.endpoint_name)
        self.client = await ep.client()
        self.extra_clients = []
        for path in self.extra_endpoints:
            ns, comp, name = path.split("/")
            extra = self.drt.namespace(ns).component(comp).endpoint(name)
            self.extra_clients.append(await extra.client())
        self._task = asyncio.get_running_loop().create_task(self._loop())

    def export_stats(self, stats: dict) -> None:
        """Fold one scrape ({worker_id: stats_dict}) into the registry.
        Separated from the poll loop so tests (and the metrics-hygiene
        check) can drive it without a control plane."""
        self.registry.gauge("workers", "live worker instances").set(len(stats))
        for wid, s in stats.items():
            labels = {"worker": f"{wid:x}"}
            for key in GAUGE_KEYS:
                if key in s:
                    self.registry.gauge(f"worker_{key}", f"worker {key}", **labels).set(float(s[key]))
            for key in COUNTER_KEYS:
                if key not in s:
                    continue
                c = self.registry.counter(f"worker_{key}", f"worker {key} (monotonic)", **labels)
                cur = float(s[key])
                prev = self._last.get((wid, key))
                if prev is None or cur < prev:
                    c.inc(cur)  # first sight, or worker restarted
                else:
                    c.inc(cur - prev)
                self._last[(wid, key)] = cur
        self.digests.update_from_wire(
            s.get("digests") for s in stats.values() if isinstance(s.get("digests"), dict)
        )
        # Tenant ledger: fold each worker's sketch wire and export the
        # fleet-merged labeled families (delta-per-scrape, like counters).
        for wid, s in stats.items():
            if isinstance(s.get("tenant_ledger"), dict):
                self._tenant_wires[wid] = s["tenant_ledger"]
        self._export_tenant_families()
        # Fleet-level anomaly check: a shrinking instance set fires
        # worker_lost and captures a bundle with the per-worker scrape
        # summary + registered evidence (router decisions) attached.
        self._last_scrape = {
            f"{wid:x}": {
                k: s.get(k)
                for k in ("num_running", "num_waiting", "kv_usage", "in_flight", "draining")
                if k in s
            }
            for wid, s in stats.items()
        }
        self.incidents.state_probe = lambda: {"last_scrape": self._last_scrape}
        self.incidents.observe({"worker_instance_count": len(stats)})
        plane = self.incidents.to_stats()
        for key, help_ in (
            ("incidents_total", "fleet-level incident captures (worker_lost et al)"),
            ("incidents_worker_lost_total", "instance-set shrink incidents"),
        ):
            c = self.registry.counter(f"fleet_{key}", help_)
            cur = float(plane[key])
            prev = self._last.get(("fleet", key))
            c.inc(cur if prev is None else max(cur - prev, 0.0))
            self._last[("fleet", key)] = cur

    def _export_tenant_families(self) -> None:
        """Merge per-worker tenant-ledger wires into fleet-true top-K
        sketches and export labeled counter families: per-tenant
        device/KV-block/queue seconds (plus ``other`` so totals conserve)
        and per-tenant/per-phase SLO verdicts. Cumulative merged values
        diff against the last scrape (clamped ≥ 0 — sketch estimates may
        wobble when the merged top-K set shifts)."""
        from dynamo_tpu.runtime.ledger import TenantFleet, attribute

        merged = TenantFleet().merge(self._tenant_wires.values())
        if not merged:
            return

        def inc_delta(family: str, value: float, **labels) -> None:
            c = self.registry.counter(family, f"fleet per-tenant {family}", **labels)
            key = ("tenant", family, tuple(sorted(labels.items())))
            prev = self._last.get(key)
            c.inc(float(value) if prev is None else max(float(value) - prev, 0.0))
            self._last[key] = float(value)

        att = attribute(merged)
        for dim, family in TENANT_FAMILY_BY_DIM.items():
            d = att.get(dim) or {}
            for row in d.get("tenants") or []:
                inc_delta(family, row["value"], tenant=row["tenant"])
            inc_delta(family, d.get("other") or 0.0, tenant="other")
        for tenant, counts in (merged.get("slo") or {}).items():
            for kind, family in (("violated", "tenant_slo_violated_total"),
                                 ("attained", "tenant_slo_attained_total")):
                for phase, n in (counts.get(kind) or {}).items():
                    inc_delta(family, n, tenant=tenant, phase=phase)

    async def scrape_once(self) -> dict:
        """One merged scrape across the primary + extra endpoints (worker
        ids are lease ids, unique across components)."""
        stats = await self.client.scrape_stats()
        for client in getattr(self, "extra_clients", ()):
            stats.update(await client.scrape_stats())
        return stats

    async def _loop(self) -> None:
        try:
            while True:
                self.export_stats(await self.scrape_once())
                await asyncio.sleep(self.interval_s)
        except asyncio.CancelledError:
            pass

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass


async def amain(args) -> None:
    drt = await DistributedRuntime.from_settings()
    primary, *extra = args.endpoint
    ns, comp, ep = primary.split("/")
    agg = MetricsAggregator(drt, ns, comp, ep, interval_s=args.interval,
                            incident_dir=args.incident_dir,
                            extra_endpoints=extra)
    await agg.start()
    health = SystemHealth()
    health.set_system_ready()
    server = SystemStatusServer(health, metrics=agg.registry)
    server.config.port = args.port
    await server.start()
    logger.info("metrics aggregator serving :%d/metrics for %s", server.port, args.endpoint)
    await asyncio.Event().wait()


def main() -> None:
    init_logging()
    p = argparse.ArgumentParser(description="dynamo-tpu metrics aggregator")
    p.add_argument("--endpoint", action="append", required=True,
                   help="ns/component/endpoint to scrape (repeatable: a "
                        "disagg deployment names its prefill, decode, and "
                        "planner endpoints)")
    p.add_argument("--port", type=int, default=9090)
    p.add_argument("--interval", type=float, default=2.0)
    p.add_argument("--incident-dir", default=None,
                   help="write fleet-level (worker_lost) incident bundles here "
                        "(default DYN_INCIDENT_DIR)")
    try:
        asyncio.run(amain(p.parse_args()))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
