"""SLA + load planner: autoscaling prefill/decode worker fleets.

Ref: components/planner/src/dynamo/planner (SURVEY.md §3F) — observe
frontend metrics each adjustment interval, predict load, invert profiling
interpolators against TTFT/ITL SLAs, scale replicas through a connector
(Kubernetes in production; virtual/local here for sim + tests).
"""

from dynamo_tpu.planner.load_predictor import (
    ARIMAPredictor,
    ConstantPredictor,
    LoadPredictor,
    SeasonalNaivePredictor,
    SeasonalTrendPredictor,
    TrendPredictor,
    make_predictor,
)
from dynamo_tpu.planner.interpolator import PrefillInterpolator, DecodeInterpolator
from dynamo_tpu.planner.planner_core import Planner, PlannerConfig, SlaTargets
from dynamo_tpu.planner.connectors import LocalConnector, VirtualConnector
from dynamo_tpu.planner.controller import (
    AutoscaleController,
    CapacityModel,
    ControllerConfig,
    Decision,
    FleetView,
    MockerCapacityModel,
    StaticCapacityModel,
    WorkerView,
    rank_coldest,
)
from dynamo_tpu.planner.fleet import AutoscaleLoop, MockerFleet
