"""Closed-loop SLA autoscaler: the planner's decision layer.

Ref: ROADMAP item 1 and "Taming the Chaos: Coordinated Autoscaling for
Heterogeneous and Disaggregated LLM Inference" (arXiv 2508.19559) — the
prefill and decode pools of a disaggregated deployment saturate on
*different* signals (prefill on input-token rate, decode on output-token
rate × batch residency), so one shared replica count always over- or
under-provisions one side. This controller scales the pools independently
but **coordinately**: both desired sizes derive from one predicted load
(rate/ISL/OSL from the same observation window), a shared chip budget
clamps them together preserving their ratio, and the SLA feedback
corrections read the same fleet-merged quantiles.

Design, per decision interval:

  observe → predict → desire → gate → act

- **desire**: per-pool target from a :class:`CapacityModel` (tokens/s a
  worker sustains at the predicted ISL/OSL) plus reactive SLA feedback —
  TTFT/queue-wait pressure bumps prefill, TPOT/KV pressure bumps decode —
  so the loop stays closed even when the feed-forward model is miscalibrated.
- **gate** (the anti-flap machinery, in order):
  *hysteresis* — a pool only moves after the demand signal has agreed for
  ``scale_up_stable_intervals`` / ``scale_down_stable_intervals``
  consecutive windows (quantile noise never flips a single window into a
  fleet change); *cooldown* — after any action a pool holds for
  ``scale_cooldown_s`` (launch/drain transients would otherwise echo into
  the next observation and flap); *drain debounce* — a scale-down is never
  issued while a previous drain is still in flight (DynaServe's "one
  elastic step at a time": capacity accounting during an unfinished drain
  is a lie, and stacking drains can hollow a pool).
- **act**: slice-granular (``max_step`` workers per decision per pool);
  scale-down names explicit *victims* — the **coldest** workers by the KV
  warmth signal (the router's actual-reuse accounting from PR 5 merged
  with the engine-side cached-block fraction and KV utilization), so a
  shrink erodes the fleet's prefix cache as little as possible.

The controller is a pure decision function over
``(ObservedLoad, FleetView, now)`` — no I/O, no clocks of its own — so the
decision table is exactly replayable in tests. Actuation lives in
:mod:`dynamo_tpu.planner.fleet`; every decision lands in counters/gauges
(``planner_*`` keys on the stats wire → aggregator → Grafana "Planner"
row) and as a ``planner_decision`` trace event in the tracer ring.
"""

from __future__ import annotations

import math
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from dynamo_tpu.planner.load_predictor import LoadPredictor, make_predictor
from dynamo_tpu.planner.planner_core import ObservedLoad
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.tracing import get_tracer

logger = get_logger(__name__)

PREFILL = "prefill"
DECODE = "decode"
POOLS = (PREFILL, DECODE)


# --- capacity models ----------------------------------------------------------
class CapacityModel:
    """Per-worker sustained throughput as a function of the offered shape.

    The controller inverts this into pool sizes; the ``autoscale`` bench's
    oracle applies the same inversion to the *true* offered load, so
    "converged" means the controller recovered the oracle sizes from noisy
    observed signals alone."""

    utilization: float = 0.8  # headroom target: size pools to this fraction

    def prefill_tokens_per_s(self, isl: float) -> float:
        raise NotImplementedError

    def decode_tokens_per_s(self, isl: float, osl: float) -> float:
        raise NotImplementedError

    def required(self, rate: float, isl: float, osl: float) -> Dict[str, int]:
        """Workers each pool needs for ``rate`` req/s at this shape."""
        isl = max(isl, 1.0)
        osl = max(osl, 1.0)
        rate = max(rate, 0.0)
        pre = rate * isl / max(self.prefill_tokens_per_s(isl) * self.utilization, 1e-9)
        dec = rate * osl / max(self.decode_tokens_per_s(isl, osl) * self.utilization, 1e-9)
        return {PREFILL: max(1, math.ceil(pre)), DECODE: max(1, math.ceil(dec))}


class MockerCapacityModel(CapacityModel):
    """Capacity derived from the mocker's own timing model (llm/mocker.py):
    the traffic harness and the controller then agree on what a worker can
    do, and any gap between plan and attainment is *queueing*, not model
    drift."""

    def __init__(self, args, decode_args=None, utilization: float = 0.8):
        # Heterogeneous pools: the prefill pool's timing args size prefill
        # capacity, the decode pool's size decode capacity.
        self.args = args
        self.decode_args = decode_args if decode_args is not None else args
        self.utilization = utilization

    def prefill_tokens_per_s(self, isl: float) -> float:
        a = self.args
        chunk = min(max(isl, 1.0), a.max_prefill_chunk)
        return chunk / (a.prefill_ms(chunk) / 1000.0) * a.speedup_ratio

    def decode_tokens_per_s(self, isl: float, osl: float) -> float:
        a = self.decode_args
        b = a.max_batch
        step_ms = a.decode_ms(b, int(b * (isl + osl)))
        return b / (step_ms / 1000.0) * a.speedup_ratio


class StaticCapacityModel(CapacityModel):
    """Fixed per-worker token rates (profiled offline, e.g. from the
    planner interpolators' measured surfaces)."""

    def __init__(self, prefill_tok_s: float, decode_tok_s: float, utilization: float = 0.8):
        self._pre = prefill_tok_s
        self._dec = decode_tok_s
        self.utilization = utilization

    def prefill_tokens_per_s(self, isl: float) -> float:
        return self._pre

    def decode_tokens_per_s(self, isl: float, osl: float) -> float:
        return self._dec


class ProfiledCapacityModel(CapacityModel):
    """Measured per-worker capacity with a declared-rate prior.

    Wraps any prior :class:`CapacityModel`. Every decision interval the
    controller feeds it the window's MEASURED per-worker token rates
    (``ObservedLoad.measured_*_tok_s`` — fleet Δstep_tokens/Δstep_busy_time
    from the flight recorder's counters, device-truth-audited by the
    profiling plane); they fold into a per-phase EMA, and once
    ``min_windows`` real observations exist the measured rate replaces the
    prior's declared one in the capacity inversion. Declared rates drift
    from reality (quantization, interference, chip revisions, model
    changes); measurement closes the loop — the coordinated-autoscaling
    ground paper's point (arXiv 2508.19559) — and the replay test shows the
    decision table converging to the true-rate oracle from a wrong prior.
    """

    def __init__(self, prior: CapacityModel, alpha: float = 0.4,
                 min_windows: int = 2, utilization: Optional[float] = None):
        self.prior = prior
        self.utilization = prior.utilization if utilization is None else utilization
        self.alpha = alpha
        self.min_windows = min_windows
        self._pre_ema = 0.0
        self._pre_n = 0
        self._dec_ema = 0.0
        self._dec_n = 0
        self.observations_total = 0

    def observe(self, load: ObservedLoad) -> None:
        """Fold one observation window's measured rates in (zeros — no step
        traffic that window — are skipped, never averaged in)."""
        seen = False
        if load.measured_prefill_tok_s > 0:
            self._pre_n += 1
            self._pre_ema = (
                load.measured_prefill_tok_s if self._pre_n == 1
                else self._pre_ema + self.alpha * (load.measured_prefill_tok_s - self._pre_ema)
            )
            seen = True
        if load.measured_decode_tok_s > 0:
            self._dec_n += 1
            self._dec_ema = (
                load.measured_decode_tok_s if self._dec_n == 1
                else self._dec_ema + self.alpha * (load.measured_decode_tok_s - self._dec_ema)
            )
            seen = True
        if seen:
            self.observations_total += 1

    def measured_rates(self) -> tuple:
        """(prefill_tok_s, decode_tok_s) actually in use — 0.0 while a phase
        still rides the prior (stats-gauge surface)."""
        return (
            self._pre_ema if self._pre_n >= self.min_windows else 0.0,
            self._dec_ema if self._dec_n >= self.min_windows else 0.0,
        )

    def prefill_tokens_per_s(self, isl: float) -> float:
        if self._pre_n >= self.min_windows:
            return self._pre_ema
        return self.prior.prefill_tokens_per_s(isl)

    def decode_tokens_per_s(self, isl: float, osl: float) -> float:
        if self._dec_n >= self.min_windows:
            return self._dec_ema
        return self.prior.decode_tokens_per_s(isl, osl)


# --- fleet view (what the controller sees) ------------------------------------
@dataclass
class WorkerView:
    """One worker of one pool, as the decision layer sees it."""

    worker_id: int
    kv_util: float = 0.0  # allocator usage 0..1 (live load)
    kv_warmth: float = 0.0  # cached-block fraction 0..1 (reusable prefix KV)
    cached_tokens_total: int = 0  # router-accounted ACTUAL reuse served here
    draining: bool = False

    def warmth_score(self, max_cached: int) -> float:
        """Composite KV warmth: router-proven reuse dominates (a worker the
        router keeps hitting is the one whose prefixes traffic actually
        wants), engine-side cached depth and live utilization break ties."""
        reuse = self.cached_tokens_total / max_cached if max_cached > 0 else 0.0
        return 2.0 * reuse + 1.0 * self.kv_warmth + 0.5 * self.kv_util


@dataclass
class FleetView:
    """Point-in-time fleet state handed to ``decide``."""

    pools: Dict[str, List[WorkerView]] = field(default_factory=lambda: {PREFILL: [], DECODE: []})
    drains_in_flight: Dict[str, int] = field(default_factory=dict)

    def size(self, pool: str) -> int:
        return len(self.pools.get(pool, ()))


def rank_coldest(workers: Sequence[WorkerView], n: int) -> List[int]:
    """The ``n`` coldest drain candidates by the composite warmth score.
    Already-draining workers are never candidates (they are leaving)."""
    live = [w for w in workers if not w.draining]
    max_cached = max((w.cached_tokens_total for w in live), default=0)
    ranked = sorted(live, key=lambda w: (w.warmth_score(max_cached), w.worker_id))
    return [w.worker_id for w in ranked[:n]]


# --- decisions ----------------------------------------------------------------
@dataclass
class Decision:
    pool: str
    action: str  # "add" | "drain" | "hold" | "dial"
    count: int  # workers added/drained (0 for hold/dial)
    target: int  # desired size after gating
    current: int
    victims: List[int] = field(default_factory=list)  # drain: coldest-first ids
    reason: str = ""
    # "dial" only: the commanded fleet-wide prefill fraction (every worker's
    # set_capacity_dial argument — the elastic ratio actuator's payload).
    fraction: float = 0.5


@dataclass
class ControllerConfig:
    min_prefill: int = 1
    max_prefill: int = 8
    min_decode: int = 1
    max_decode: int = 8
    max_total: int = 0  # shared chip budget; 0 = min/max bounds only
    scale_cooldown_s: float = 60.0
    scale_up_stable_intervals: int = 1  # react fast to pressure...
    scale_down_stable_intervals: int = 3  # ...but shrink only on sustained calm
    max_step: int = 2  # slice granularity: workers per decision per pool
    # Reactive SLA feedback (closed loop even under model miscalibration).
    slo_floor: float = 0.9  # attainment below this bumps the pressured pool
    ttft_sla_s: float = 0.0  # 0 = judge from slo_attainment + queue signals only
    tpot_sla_s: float = 0.0
    kv_pressure: float = 0.9  # mean decode kv_util above this bumps decode
    load_predictor: str = "trend"
    dry_run: bool = False  # log + count decisions, actuator skips them
    # Elastic ratio actuator: between scale events the fleet-wide
    # prefill:decode capacity split tracks the observed ISL/OSL mix via the
    # per-worker dial (set_capacity_dial) — far cheaper than a scale event
    # (no launch/drain transient). A deadband + min-interval keep the dial
    # from chattering on quantile noise.
    dial_deadband: float = 0.05
    dial_min_interval_s: float = 30.0

    def bounds(self, pool: str) -> tuple:
        if pool == PREFILL:
            return self.min_prefill, self.max_prefill
        return self.min_decode, self.max_decode


class AutoscaleController:
    """The decision layer. Call :meth:`decide` once per adjustment interval
    with a fresh ``ObservedLoad`` and ``FleetView``; apply the returned
    decisions through :class:`dynamo_tpu.planner.fleet.MockerFleet` (or any
    actuator honoring add/drain + victims)."""

    def __init__(self, config: ControllerConfig, capacity: CapacityModel):
        self.config = config
        self.capacity = capacity
        self.rate_predictor: LoadPredictor = make_predictor(config.load_predictor)
        self.isl_predictor: LoadPredictor = make_predictor(config.load_predictor)
        self.osl_predictor: LoadPredictor = make_predictor(config.load_predictor)
        # Gating state, per pool.
        self._over: Dict[str, int] = {p: 0 for p in POOLS}
        self._under: Dict[str, int] = {p: 0 for p in POOLS}
        self._last_action_ts: Dict[str, float] = {}
        # Decision counters/gauges (→ to_stats → aggregator → Grafana).
        self.decisions_total = 0
        self.scale_up_total = 0
        self.scale_down_total = 0
        self.hysteresis_suppressed_total = 0
        self.cooldown_suppressed_total = 0
        self.drain_debounced_total = 0
        # Ratio actuator state: last commanded fleet-wide prefill fraction.
        self.dial_total = 0
        self._elastic_ratio = 0.5
        self._last_dial_ts: Optional[float] = None
        self._targets: Dict[str, int] = {PREFILL: 0, DECODE: 0}
        self._trace_id = uuid.uuid4().hex

    # --- desire ------------------------------------------------------------
    def desired_sizes(self, load: ObservedLoad) -> Dict[str, int]:
        """Feed-forward capacity inversion + reactive SLA feedback, clamped
        to bounds and the shared budget."""
        c = self.config
        want = self.capacity.required(load.request_rate, load.avg_isl, load.avg_osl)

        # Closed-loop corrections: attribute an SLO breach to the pool whose
        # signal is pressured. Queue-wait/TTFT pressure is prefill-side
        # (admission starved), TPOT/KV pressure is decode-side (batch too
        # deep or pool too hot). Only bump on real traffic — an idle fleet
        # reports attainment 1.0 and zero quantiles.
        breach = load.slo_attainment < c.slo_floor
        ttft_hot = c.ttft_sla_s > 0 and load.ttft_p99 > c.ttft_sla_s
        tpot_hot = c.tpot_sla_s > 0 and load.tpot_p99 > c.tpot_sla_s
        if (breach or ttft_hot) and load.request_rate > 0 and (
            ttft_hot or load.queue_wait_p99 >= load.tpot_p99
        ):
            want[PREFILL] += 1
        if (breach and tpot_hot) or (tpot_hot and load.request_rate > 0):
            want[DECODE] += 1
        if load.kv_util > c.kv_pressure:
            want[DECODE] += 1

        for pool in POOLS:
            lo, hi = c.bounds(pool)
            want[pool] = max(lo, min(hi, want[pool]))
        # Coordinated budget clamp, preserving the prefill:decode ratio
        # (ref planner_core.compute_replicas :339-352).
        if c.max_total and want[PREFILL] + want[DECODE] > c.max_total:
            scale = c.max_total / (want[PREFILL] + want[DECODE])
            for pool in POOLS:
                lo, _ = c.bounds(pool)
                want[pool] = max(lo, math.floor(want[pool] * scale))
        return want

    # --- the decision function --------------------------------------------
    def decide(self, load: ObservedLoad, view: FleetView, now: float) -> List[Decision]:
        c = self.config
        self.decisions_total += 1
        # Measured-capacity feedback: a ProfiledCapacityModel folds this
        # window's measured tok/s in before the inversion below uses it.
        # Stateful like the predictors — replays stay exactly reproducible.
        observe = getattr(self.capacity, "observe", None)
        if observe is not None:
            observe(load)
        self.rate_predictor.observe(load.request_rate)
        self.isl_predictor.observe(load.avg_isl)
        self.osl_predictor.observe(load.avg_osl)
        predicted = ObservedLoad(
            request_rate=self.rate_predictor.predict(),
            avg_isl=self.isl_predictor.predict(),
            avg_osl=self.osl_predictor.predict(),
            ttft_p99=load.ttft_p99,
            tpot_p99=load.tpot_p99,
            queue_wait_p99=load.queue_wait_p99,
            slo_attainment=load.slo_attainment,
            kv_util=load.kv_util,
        )
        want = self.desired_sizes(predicted)
        self._targets = dict(want)

        out: List[Decision] = []
        for pool in POOLS:
            current = view.size(pool)
            target = want[pool]
            decision = self._gate(pool, current, target, view, now)
            out.append(decision)
            self._trace(decision, predicted)
            if decision.action != "hold":
                logger.info(
                    "planner %s: %s %d -> %d (%s)%s",
                    pool, decision.action, current, decision.target, decision.reason,
                    " [dry-run]" if c.dry_run else "",
                )
        return out

    # --- elastic ratio actuator --------------------------------------------
    def decide_dial(self, load: ObservedLoad, now: float) -> Optional[Decision]:
        """Track the observed ISL/OSL mix with the per-worker capacity dial
        *between* scale events: the fraction of fleet work that is prefill
        (per-token prefill cost × ISL vs per-token decode cost × OSL, from
        the same CapacityModel ``decide`` inverts) becomes every worker's
        commanded prefill fraction. Pure like ``decide`` — the actuation
        (MockerFleet.apply / the ``set_dial`` control op) lives elsewhere."""
        c = self.config
        if load.request_rate <= 0:
            return None  # idle fleet: nothing to track, hold the dial
        isl = max(load.avg_isl, 1.0)
        osl = max(load.avg_osl, 1.0)
        pre = isl / max(self.capacity.prefill_tokens_per_s(isl), 1e-9)
        dec = osl / max(self.capacity.decode_tokens_per_s(isl, osl), 1e-9)
        f = pre / (pre + dec) if (pre + dec) > 0 else 0.5
        f = min(1.0, max(0.0, f))
        if abs(f - self._elastic_ratio) < c.dial_deadband:
            return None
        if self._last_dial_ts is not None and now - self._last_dial_ts < c.dial_min_interval_s:
            return None
        prev = self._elastic_ratio
        self._last_dial_ts = now
        self._elastic_ratio = f
        self.dial_total += 1
        d = Decision(
            "fleet", "dial", 0, 0, 0, fraction=f,
            reason=f"isl/osl mix: prefill_fraction {prev:.2f} -> {f:.2f}",
        )
        self._trace(d, load)
        logger.info("planner dial: %s%s", d.reason, " [dry-run]" if c.dry_run else "")
        return d

    def _gate(self, pool: str, current: int, target: int, view: FleetView, now: float) -> Decision:
        c = self.config
        hold = Decision(pool, "hold", 0, current, current)

        # Hysteresis bookkeeping: consecutive windows of agreement.
        if target > current:
            self._over[pool] += 1
            self._under[pool] = 0
        elif target < current:
            self._under[pool] += 1
            self._over[pool] = 0
        else:
            self._over[pool] = self._under[pool] = 0
            return hold

        up = target > current
        needed = c.scale_up_stable_intervals if up else c.scale_down_stable_intervals
        streak = self._over[pool] if up else self._under[pool]
        if streak < needed:
            self.hysteresis_suppressed_total += 1
            hold.reason = f"hysteresis {streak}/{needed}"
            return hold

        last = self._last_action_ts.get(pool)
        if last is not None and now - last < c.scale_cooldown_s:
            self.cooldown_suppressed_total += 1
            hold.reason = f"cooldown {now - last:.1f}s/{c.scale_cooldown_s:.0f}s"
            return hold

        if not up and view.drains_in_flight.get(pool, 0) > 0:
            # Debounce: the previous drain has not landed; the pool's true
            # capacity is already below ``current`` and shrinking again
            # would double-count the same decision.
            self.drain_debounced_total += 1
            hold.reason = f"drain in flight ({view.drains_in_flight[pool]})"
            return hold

        count = min(abs(target - current), c.max_step)
        stepped = current + count if up else current - count
        self._last_action_ts[pool] = now
        self._over[pool] = self._under[pool] = 0
        if up:
            self.scale_up_total += 1
            return Decision(pool, "add", count, stepped, current,
                            reason=f"demand {target} > {current}")
        self.scale_down_total += 1
        victims = rank_coldest(view.pools.get(pool, ()), count)
        return Decision(pool, "drain", len(victims), stepped, current, victims=victims,
                        reason=f"demand {target} < {current}, coldest={['%x' % v for v in victims]}")

    # --- observability -----------------------------------------------------
    def _trace(self, d: Decision, predicted: ObservedLoad) -> None:
        get_tracer().event(
            "planner_decision", self._trace_id, service="planner",
            pool=d.pool, action=d.action, count=d.count, target=d.target,
            current=d.current, victims=[f"{v:x}" for v in d.victims],
            reason=d.reason, rate=round(predicted.request_rate, 3),
            isl=round(predicted.avg_isl, 1), osl=round(predicted.avg_osl, 1),
            dry_run=self.config.dry_run,
        )

    def to_stats(self) -> dict:
        """Planner decision counters/gauges on the stats-scrape wire (same
        shape the aggregator's COUNTER_KEYS/GAUGE_KEYS registries expect;
        the fleet serves this on a scraped ``planner`` endpoint)."""
        rates_fn = getattr(self.capacity, "measured_rates", None)
        rates = rates_fn() if rates_fn is not None else (0.0, 0.0)
        return {
            "planner_decisions_total": self.decisions_total,
            "planner_scale_up_total": self.scale_up_total,
            "planner_scale_down_total": self.scale_down_total,
            "planner_hysteresis_suppressed_total": self.hysteresis_suppressed_total,
            "planner_cooldown_suppressed_total": self.cooldown_suppressed_total,
            "planner_drain_debounced_total": self.drain_debounced_total,
            "planner_prefill_target": float(self._targets.get(PREFILL, 0)),
            "planner_decode_target": float(self._targets.get(DECODE, 0)),
            "planner_dry_run": 1.0 if self.config.dry_run else 0.0,
            "planner_dial_total": self.dial_total,
            "planner_elastic_ratio": self._elastic_ratio,
            # Measured per-worker capacity in use (0.0 = riding the prior /
            # not a ProfiledCapacityModel): the Grafana "Device truth" row
            # shows when the planner switched from declared to measured.
            "planner_measured_prefill_tok_s": round(rates[0], 3),
            "planner_measured_decode_tok_s": round(rates[1], 3),
        }
