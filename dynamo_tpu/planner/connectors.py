"""Scaling connectors: how the planner actually changes replica counts.

Ref: components/planner — ``KubernetesConnector`` (scales
DynamoGraphDeployment CRDs) and ``VirtualConnector`` (simulation,
virtual_connector.py). Here:

- :class:`VirtualConnector` — records targets (planner unit tests / sims).
- :class:`LocalConnector` — actually spawns/retires in-process workers via
  factory coroutines (TPU-host single-node autoscaling; also how the
  planner e2e test runs a real scaling loop without a cluster).
- :class:`KubernetesConnector` — kubectl-based scale for k8s deployments.
"""

from __future__ import annotations

import asyncio
import json
import shutil
import subprocess
from typing import Awaitable, Callable, Dict, List, Optional

from dynamo_tpu.runtime.logging import get_logger

logger = get_logger(__name__)


class Connector:
    async def set_replicas(self, component: str, replicas: int) -> None:
        raise NotImplementedError

    async def get_replicas(self, component: str) -> int:
        raise NotImplementedError


class VirtualConnector(Connector):
    def __init__(self):
        self.targets: Dict[str, int] = {}
        self.history: List[tuple] = []

    async def set_replicas(self, component: str, replicas: int) -> None:
        self.targets[component] = replicas
        self.history.append((component, replicas))

    async def get_replicas(self, component: str) -> int:
        return self.targets.get(component, 0)


class LocalConnector(Connector):
    """Scales real in-process workers. ``factory(component) -> handle`` must
    return an object with an async ``stop()`` (e.g. ServeHandle wrapper)."""

    def __init__(self, factory: Callable[[str], Awaitable[object]]):
        self.factory = factory
        self.workers: Dict[str, List[object]] = {}

    async def set_replicas(self, component: str, replicas: int) -> None:
        current = self.workers.setdefault(component, [])
        while len(current) < replicas:
            current.append(await self.factory(component))
            logger.info("scaled up %s -> %d", component, len(current))
        while len(current) > replicas:
            worker = current.pop()
            await worker.stop()
            logger.info("scaled down %s -> %d", component, len(current))

    async def get_replicas(self, component: str) -> int:
        return len(self.workers.get(component, []))

    async def shutdown(self) -> None:
        for component in list(self.workers):
            await self.set_replicas(component, 0)


class KubernetesConnector(Connector):
    """kubectl connector (ref: kubernetes_connector.py → kube.py).

    Two modes:
    - ``graph`` set: scales the DynamoGraphDeployment CR's per-service
      replicas (``kubectl patch dgd/<graph> --type=merge``) — an
      in-cluster controller reconciles (deploy/crd.py schema).
    - otherwise: scales rendered Deployments directly
      (``kubectl scale deployment/<fmt>``) — the controller-less
      manifests.py path.

    ``kubectl_cmd`` injects the binary (tests use a stub; ``--dry-run``
    flows through to validate apply-ability without a cluster)."""

    def __init__(
        self,
        namespace: str = "default",
        deployment_fmt: str = "dynamo-{component}",
        *,
        graph: Optional[str] = None,
        kubectl_cmd: Optional[List[str]] = None,
        extra_args: Optional[List[str]] = None,
    ):
        self.kubectl = list(kubectl_cmd) if kubectl_cmd else ["kubectl"]
        if kubectl_cmd is None and shutil.which("kubectl") is None:
            raise RuntimeError("kubectl not found in PATH")
        self.namespace = namespace
        self.deployment_fmt = deployment_fmt
        self.graph = graph
        self.extra_args = list(extra_args or [])

    def _name(self, component: str) -> str:
        return self.deployment_fmt.format(component=component)

    async def _kubectl(self, *args: str) -> str:
        cmd = [*self.kubectl, "-n", self.namespace, *args, *self.extra_args]
        proc = await asyncio.create_subprocess_exec(
            *cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE
        )
        out, err = await proc.communicate()
        if proc.returncode != 0:
            raise RuntimeError(f"{' '.join(cmd[:3])}… failed: {err.decode().strip()}")
        return out.decode()

    async def set_replicas(self, component: str, replicas: int) -> None:
        if self.graph:
            patch = json.dumps({"spec": {"services": {component: {"replicas": replicas}}}})
            await self._kubectl(
                "patch", f"dynamographdeployments.dynamo.tpu.io/{self.graph}",
                "--type=merge", "-p", patch,
            )
        else:
            await self._kubectl(
                "scale", f"deployment/{self._name(component)}", f"--replicas={replicas}"
            )

    async def get_replicas(self, component: str) -> int:
        if self.graph:
            out = await self._kubectl(
                "get", f"dynamographdeployments.dynamo.tpu.io/{self.graph}",
                "-o", f"jsonpath={{.spec.services.{component}.replicas}}",
            )
        else:
            out = await self._kubectl(
                "get", f"deployment/{self._name(component)}", "-o", "jsonpath={.spec.replicas}"
            )
        return int(out.strip() or 0)
