"""Pre-deployment profiling: measure the engine's TTFT/ITL surfaces and save
interpolator inputs.

Ref: benchmarks/profiler/profile_sla.py — sweeps engine configs offline and
writes npz files the SLA planner loads (pre_deployment_profiling.md:60-84).
Run: ``python -m dynamo_tpu.planner.profiler --model tiny --out profiles/``.
"""

from __future__ import annotations

import argparse
import os
import time
from typing import List, Sequence

import numpy as np


def profile_prefill(model: str, isls: List[int], dtype: str = "bfloat16") -> dict:
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine.config import get_config
    from dynamo_tpu.engine.kv_cache import KvCacheArrays
    from dynamo_tpu.engine.models import llama

    cfg = get_config(model)
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16 if dtype == "bfloat16" else jnp.float32)
    rows = {"isl": [], "ttft_ms": [], "thpt_per_chip": []}
    for isl in isls:
        isl = min(isl, cfg.max_seq_len - cfg.block_size)
        num_blocks = isl // cfg.block_size + 4
        cache = KvCacheArrays.create(cfg, num_blocks=num_blocks + 1)
        table = jnp.arange(1, num_blocks + 1, dtype=jnp.int32)
        tokens = jnp.zeros((isl,), dtype=jnp.int32)

        fn = jax.jit(lambda p, k, v, t: llama.prefill(p, cfg, k, v, t, jnp.int32(isl), jnp.int32(0), table))
        logits, k, v = fn(params, cache.k, cache.v, tokens)  # compile
        logits.block_until_ready()
        t0 = time.perf_counter()
        n = 3
        for _ in range(n):
            logits, k, v = fn(params, k, v, tokens)
        logits.block_until_ready()
        dt = (time.perf_counter() - t0) / n
        rows["isl"].append(isl)
        rows["ttft_ms"].append(dt * 1000)
        rows["thpt_per_chip"].append(isl / dt)
    return rows


def profile_decode(model: str, batches: List[int], ctxs: Sequence[int] = (1024,), dtype: str = "bfloat16") -> dict:
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine.config import get_config
    from dynamo_tpu.engine.kv_cache import KvCacheArrays
    from dynamo_tpu.engine.models import llama

    cfg = get_config(model)
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16 if dtype == "bfloat16" else jnp.float32)
    rows = {"active_kv": [], "context_len": [], "itl_ms": [], "thpt_per_chip": []}
    # GRID over (batch, context): the ITL surface the SLA math inverts is
    # two-dimensional (ref profile_sla.py sweeps both; a single-ctx line
    # cannot price long-context decode).
    # Dedup after clamping: on short-context models several requested ctxs
    # clamp to the same value and would write duplicate noisy grid points
    # (DecodeInterpolator's exact-match branch then picks one arbitrarily).
    for ctx in sorted({min(int(c), cfg.max_seq_len - cfg.block_size) for c in ctxs}):
      for B in batches:
          blocks_per_seq = ctx // cfg.block_size + 2
          num_blocks = B * blocks_per_seq + 1
          cache = KvCacheArrays.create(cfg, num_blocks=num_blocks)
          tables = jnp.stack(
              [jnp.arange(1 + i * blocks_per_seq, 1 + (i + 1) * blocks_per_seq, dtype=jnp.int32) for i in range(B)]
          )
          toks = jnp.zeros((B,), dtype=jnp.int32)
          pos = jnp.full((B,), ctx, dtype=jnp.int32)
          active = jnp.ones((B,), dtype=bool)
          fn = jax.jit(lambda p, k, v, t: llama.decode(p, cfg, k, v, t, pos, tables, active), donate_argnums=(1, 2))
          logits, k, v = fn(params, cache.k, cache.v, toks)
          logits.block_until_ready()
          t0 = time.perf_counter()
          n = 8
          for _ in range(n):
              logits, k, v = fn(params, k, v, toks)
          logits.block_until_ready()
          dt = (time.perf_counter() - t0) / n
          rows["active_kv"].append(B * blocks_per_seq)
          rows["context_len"].append(ctx)
          rows["itl_ms"].append(dt * 1000)
          rows["thpt_per_chip"].append(B / dt)
    return rows


def main() -> None:
    p = argparse.ArgumentParser(description="dynamo-tpu SLA profiler")
    p.add_argument("--model", default="tiny")
    p.add_argument("--out", default="profiles")
    p.add_argument("--isls", type=int, nargs="+", default=[128, 256, 512, 1024])
    p.add_argument("--batches", type=int, nargs="+", default=[1, 2, 4, 8])
    p.add_argument("--ctxs", type=int, nargs="+", default=[512, 1024, 2048])
    args = p.parse_args()
    os.makedirs(args.out, exist_ok=True)
    pre = profile_prefill(args.model, args.isls)
    np.savez(os.path.join(args.out, f"prefill_{args.model}.npz"), **{k: np.asarray(v) for k, v in pre.items()})
    dec = profile_decode(args.model, args.batches, args.ctxs)
    np.savez(os.path.join(args.out, f"decode_{args.model}.npz"), **{k: np.asarray(v) for k, v in dec.items()})
    print(f"profiles written to {args.out}/: prefill {pre['ttft_ms']} ms, decode {dec['itl_ms']} ms")


if __name__ == "__main__":
    main()
