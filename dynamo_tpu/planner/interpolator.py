"""Profiling interpolators: performance surfaces feeding the SLA planner.

Ref: benchmarks/profiler/profile_sla.py + docs/benchmarks/
pre_deployment_profiling.md:60-84 — offline profiling produces (a) TTFT vs
ISL points per prefill config (quadratic fit) and (b) an ITL surface vs
(active KV blocks, context length) per decode config; the planner inverts
these against SLA targets to size fleets.

Profiles load from npz (keys ``isl``, ``ttft_ms``, ``thpt_per_chip`` /
``active_kv``, ``context_len``, ``itl_ms``, ``thpt_per_chip``) or from dict
points recorded by ``dynamo_tpu.planner.profiler``.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


class PrefillInterpolator:
    """TTFT(isl) quadratic fit + throughput/chip lookup."""

    def __init__(self, isl: Sequence[float], ttft_ms: Sequence[float], thpt_per_chip: Sequence[float]):
        isl = np.asarray(isl, dtype=np.float64)
        self._ttft_coef = np.polyfit(isl, np.asarray(ttft_ms, dtype=np.float64), deg=min(2, len(isl) - 1))
        self._thpt_coef = np.polyfit(isl, np.asarray(thpt_per_chip, dtype=np.float64), deg=min(2, len(isl) - 1))
        self._isl_range = (float(isl.min()), float(isl.max()))

    @classmethod
    def from_npz(cls, path: str) -> "PrefillInterpolator":
        z = np.load(path)
        return cls(z["isl"], z["ttft_ms"], z["thpt_per_chip"])

    def ttft_ms(self, isl: float) -> float:
        return float(np.polyval(self._ttft_coef, np.clip(isl, *self._isl_range)))

    def throughput_per_chip(self, isl: float) -> float:
        return max(1e-9, float(np.polyval(self._thpt_coef, np.clip(isl, *self._isl_range))))


class DecodeInterpolator:
    """ITL surface over (active_kv_usage, context_len) via inverse-distance
    interpolation on profiled points; inverted to find the max
    throughput/chip that still meets the ITL SLA (ref:
    find_best_throughput_per_gpu)."""

    def __init__(
        self,
        active_kv: Sequence[float],
        context_len: Sequence[float],
        itl_ms: Sequence[float],
        thpt_per_chip: Sequence[float],
    ):
        self.pts = np.stack(
            [np.asarray(active_kv, dtype=np.float64), np.asarray(context_len, dtype=np.float64)], axis=1
        )
        self.itl = np.asarray(itl_ms, dtype=np.float64)
        self.thpt = np.asarray(thpt_per_chip, dtype=np.float64)
        self._scale = self.pts.max(axis=0)
        self._scale[self._scale == 0] = 1.0

    @classmethod
    def from_npz(cls, path: str) -> "DecodeInterpolator":
        z = np.load(path)
        return cls(z["active_kv"], z["context_len"], z["itl_ms"], z["thpt_per_chip"])

    def _idw(self, values: np.ndarray, active_kv: float, context_len: float) -> float:
        q = np.array([active_kv, context_len], dtype=np.float64) / self._scale
        d = np.linalg.norm(self.pts / self._scale - q, axis=1)
        if d.min() < 1e-12:
            return float(values[d.argmin()])
        w = 1.0 / (d**2)
        return float((values * w).sum() / w.sum())

    def itl_ms(self, active_kv: float, context_len: float) -> float:
        return self._idw(self.itl, active_kv, context_len)

    def find_best_throughput_per_chip(self, itl_sla_ms: float, context_len: float) -> float:
        """Max profiled throughput whose interpolated ITL meets the SLA at
        this context length (binary search over the kv-usage axis)."""
        best = 0.0
        for kv, thpt in sorted(zip(self.pts[:, 0], self.thpt)):
            if self.itl_ms(kv, context_len) <= itl_sla_ms:
                best = max(best, float(thpt))
        if best == 0.0:
            best = float(self.thpt.min())  # SLA unattainable: size by the floor
        return best
