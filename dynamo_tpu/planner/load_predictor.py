"""Load predictors for the SLA planner.

Ref: components/planner/src/dynamo/planner/utils/load_predictor.py:66-158 —
constant / ARIMA / Prophet. Prophet isn't in this image; a seasonal-naive
predictor covers the periodic-traffic case it served.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, Optional

import numpy as np


class LoadPredictor:
    def __init__(self, window: int = 64):
        self.history: Deque[float] = deque(maxlen=window)

    def observe(self, value: float) -> None:
        self.history.append(float(value))

    def predict(self) -> float:
        raise NotImplementedError


class ConstantPredictor(LoadPredictor):
    """Next load = last observed (ref: constant predictor)."""

    def predict(self) -> float:
        return self.history[-1] if self.history else 0.0


class ARIMAPredictor(LoadPredictor):
    """AR(p) via least squares on the differenced series — the workhorse of
    the reference's ARIMA mode without statsmodels."""

    def __init__(self, window: int = 64, order: int = 4):
        super().__init__(window)
        self.order = order

    def predict(self) -> float:
        h = np.asarray(self.history, dtype=np.float64)
        if len(h) < self.order + 2:
            return h[-1] if len(h) else 0.0
        d = np.diff(h)
        p = self.order
        X = np.stack([d[i : len(d) - p + i] for i in range(p)], axis=1)
        y = d[p:]
        try:
            coef, *_ = np.linalg.lstsq(X, y, rcond=None)
            next_diff = float(np.dot(d[-p:], coef))
        except np.linalg.LinAlgError:
            next_diff = 0.0
        return max(0.0, h[-1] + next_diff)


class SeasonalNaivePredictor(LoadPredictor):
    """Next load = value one period ago (periodic traffic; the Prophet
    role for daily/hourly sine-like load)."""

    def __init__(self, window: int = 256, period: int = 24):
        super().__init__(window)
        self.period = period

    def predict(self) -> float:
        if len(self.history) >= self.period:
            return self.history[-self.period]
        return self.history[-1] if self.history else 0.0


class TrendPredictor(LoadPredictor):
    """Trailing-window linear trend, extrapolated one interval ahead.

    Fixes the constant predictor's structural ramp bias: "next = last
    observed" is exactly one adjustment interval behind any monotone ramp,
    so a planner steering on it scales for the load of the *previous*
    window, permanently. A least-squares slope over the trailing window
    projects ``last + slope`` instead — zero-lag on a linear ramp, and the
    window averaging keeps single-sample noise from whipping the estimate
    (validated against the traffic harness's diurnal ramp in
    tests/test_autoscale.py)."""

    def __init__(self, window: int = 8):
        super().__init__(window)

    def predict(self) -> float:
        h = np.asarray(self.history, dtype=np.float64)
        if len(h) == 0:
            return 0.0
        if len(h) < 3:
            return float(h[-1])
        x = np.arange(len(h), dtype=np.float64)
        slope, intercept = np.polyfit(x, h, 1)
        return max(0.0, float(slope * len(h) + intercept))


class SeasonalTrendPredictor(LoadPredictor):
    """Seasonality-aware mode (ARIMA-lite): seasonal-naive base plus the
    trailing linear trend of the seasonal residual. Tracks a diurnal sine
    through its turning points — where a pure trend overshoots the crest
    and the seasonal-naive alone lags by however much the day has grown."""

    def __init__(self, window: int = 256, period: int = 24, trend_window: int = 8):
        super().__init__(window)
        self.period = period
        self._trend = TrendPredictor(window=trend_window)

    def observe(self, value: float) -> None:
        super().observe(value)
        if len(self.history) > self.period:
            # Residual vs one period ago: how much this cycle differs from
            # the last (the day-over-day growth the naive term misses).
            self._trend.observe(value - self.history[-1 - self.period])

    def predict(self) -> float:
        if len(self.history) <= self.period:
            # No full period yet: fall back to trend-on-levels.
            t = TrendPredictor(window=min(8, max(3, len(self.history))))
            for v in self.history:
                t.observe(v)
            return t.predict()
        return max(0.0, self.history[-self.period] + self._trend.predict())


def make_predictor(kind: str, **kwargs) -> LoadPredictor:
    kinds = {
        "constant": ConstantPredictor,
        "arima": ARIMAPredictor,
        "trend": TrendPredictor,
        "seasonal": SeasonalNaivePredictor,
        "seasonal_trend": SeasonalTrendPredictor,
        "prophet": SeasonalTrendPredictor,  # alias: closest available model
    }
    if kind not in kinds:
        raise ValueError(f"unknown predictor {kind!r} (have {sorted(kinds)})")
    return kinds[kind](**kwargs)
