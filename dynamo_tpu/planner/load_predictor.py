"""Load predictors for the SLA planner.

Ref: components/planner/src/dynamo/planner/utils/load_predictor.py:66-158 —
constant / ARIMA / Prophet. Prophet isn't in this image; a seasonal-naive
predictor covers the periodic-traffic case it served.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, Optional

import numpy as np


class LoadPredictor:
    def __init__(self, window: int = 64):
        self.history: Deque[float] = deque(maxlen=window)

    def observe(self, value: float) -> None:
        self.history.append(float(value))

    def predict(self) -> float:
        raise NotImplementedError


class ConstantPredictor(LoadPredictor):
    """Next load = last observed (ref: constant predictor)."""

    def predict(self) -> float:
        return self.history[-1] if self.history else 0.0


class ARIMAPredictor(LoadPredictor):
    """AR(p) via least squares on the differenced series — the workhorse of
    the reference's ARIMA mode without statsmodels."""

    def __init__(self, window: int = 64, order: int = 4):
        super().__init__(window)
        self.order = order

    def predict(self) -> float:
        h = np.asarray(self.history, dtype=np.float64)
        if len(h) < self.order + 2:
            return h[-1] if len(h) else 0.0
        d = np.diff(h)
        p = self.order
        X = np.stack([d[i : len(d) - p + i] for i in range(p)], axis=1)
        y = d[p:]
        try:
            coef, *_ = np.linalg.lstsq(X, y, rcond=None)
            next_diff = float(np.dot(d[-p:], coef))
        except np.linalg.LinAlgError:
            next_diff = 0.0
        return max(0.0, h[-1] + next_diff)


class SeasonalNaivePredictor(LoadPredictor):
    """Next load = value one period ago (periodic traffic; the Prophet
    role for daily/hourly sine-like load)."""

    def __init__(self, window: int = 256, period: int = 24):
        super().__init__(window)
        self.period = period

    def predict(self) -> float:
        if len(self.history) >= self.period:
            return self.history[-self.period]
        return self.history[-1] if self.history else 0.0


def make_predictor(kind: str, **kwargs) -> LoadPredictor:
    kinds = {
        "constant": ConstantPredictor,
        "arima": ARIMAPredictor,
        "seasonal": SeasonalNaivePredictor,
        "prophet": SeasonalNaivePredictor,  # alias: closest available model
    }
    if kind not in kinds:
        raise ValueError(f"unknown predictor {kind!r} (have {sorted(kinds)})")
    return kinds[kind](**kwargs)
