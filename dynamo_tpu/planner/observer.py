"""Frontend metrics observation for the planner.

Ref: planner_core.py ``observe_metrics`` (:193) — reads the frontend's
Prometheus endpoint and derives per-interval request rate, average ISL, and
average OSL from counter deltas.
"""

from __future__ import annotations

import re
import time
from typing import Dict, Optional

import aiohttp

from dynamo_tpu.planner.planner_core import ObservedLoad

_METRIC_RE = re.compile(r"^(\w+)(?:\{([^}]*)\})?\s+([0-9.eE+-]+)$")


def parse_prometheus(text: str) -> Dict[str, float]:
    """Sum metric families across label sets (model-agnostic totals)."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        m = _METRIC_RE.match(line.strip())
        if m:
            name, _, value = m.groups()
            out[name] = out.get(name, 0.0) + float(value)
    return out


class PrometheusObserver:
    """Polls the frontend /metrics and yields ObservedLoad deltas."""

    def __init__(self, metrics_url: str):
        self.metrics_url = metrics_url
        self._last: Optional[Dict[str, float]] = None
        self._last_ts: Optional[float] = None

    async def observe(self) -> ObservedLoad:
        async with aiohttp.ClientSession() as session:
            async with session.get(self.metrics_url) as resp:
                text = await resp.text()
        now = time.monotonic()
        cur = parse_prometheus(text)
        load = ObservedLoad()
        if self._last is not None and self._last_ts is not None:
            dt = max(now - self._last_ts, 1e-6)

            def delta(name: str) -> float:
                return max(0.0, cur.get(name, 0.0) - self._last.get(name, 0.0))

            d_req = delta("dynamo_frontend_requests_total")
            d_in = delta("dynamo_frontend_input_tokens_total")
            d_out = delta("dynamo_frontend_output_tokens_total")
            load = ObservedLoad(
                request_rate=d_req / dt,
                avg_isl=d_in / d_req if d_req > 0 else 0.0,
                avg_osl=d_out / d_req if d_req > 0 else 0.0,
            )
        self._last = cur
        self._last_ts = now
        return load
