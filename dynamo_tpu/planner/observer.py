"""Metrics observation for the planner.

Ref: planner_core.py ``observe_metrics`` (:193) — reads Prometheus
endpoints and derives the planner's control inputs. Two layers:

- **Counters → per-window rates**: request rate, average ISL/OSL, SLO
  attainment and goodput from counter deltas between polls.
- **Digest quantile gauges → latency distributions**: the frontend and the
  metrics aggregator export fleet-merged digest quantiles
  (``*_seconds_quantile{quantile="0.99"}``); the observer lifts them into
  ``ObservedLoad.ttft_p99`` etc. — the signals SLA-driven autoscaling
  actually inverts, rather than averages.

``parse_prometheus_samples`` is a real text-exposition parser: labeled
series, histogram/summary sample families (``_bucket``/``_sum``/
``_count``/``quantile``), escaped label values, exponent/NaN/Inf values.
The old regex silently dropped anything it did not match, which is how a
planner ends up steering on zeros.
"""

from __future__ import annotations

import math
import re
import time
from typing import Dict, Iterable, List, NamedTuple, Optional, Sequence, Tuple

import aiohttp

from dynamo_tpu.planner.planner_core import ObservedLoad

# name, optional {labels}, value, optional timestamp. Value is \S+ so
# exponents, NaN, +Inf/-Inf all parse (float() handles every Prometheus
# value literal: "NaN", "+Inf", "1e+05", ...).
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)(?:\s+-?\d+)?$"
)
# label="value" with \" \\ \n escapes (the exposition-format escape set).
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


class Sample(NamedTuple):
    name: str
    labels: Dict[str, str]
    value: float


def _unescape(v: str) -> str:
    return v.replace(r"\"", '"').replace(r"\n", "\n").replace("\\\\", "\\")


def parse_prometheus_samples(text: str) -> List[Sample]:
    """Every sample line in the exposition, labels preserved. Histogram and
    summary children appear under their sample names (``x_bucket``,
    ``x_sum``, ``x_count``, ``x{quantile=...}``)."""
    out: List[Sample] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        name, raw_labels, raw_value = m.groups()
        try:
            value = float(raw_value)
        except ValueError:
            continue
        labels: Dict[str, str] = {}
        if raw_labels:
            for lm in _LABEL_RE.finditer(raw_labels):
                labels[lm.group(1)] = _unescape(lm.group(2))
        out.append(Sample(name, labels, value))
    return out


def parse_prometheus(text: str) -> Dict[str, float]:
    """Sum metric families across label sets (model-agnostic totals). NaN
    samples are skipped — one uninitialized gauge must not poison a sum."""
    out: Dict[str, float] = {}
    for s in parse_prometheus_samples(text):
        if math.isnan(s.value):
            continue
        out[s.name] = out.get(s.name, 0.0) + s.value
    return out


def _finite(samples: Iterable[Sample]) -> List[Sample]:
    return [s for s in samples if math.isfinite(s.value)]


class PrometheusObserver:
    """Polls one or more Prometheus endpoints and yields ObservedLoad.

    Typically two URLs: the frontend ``/metrics`` (request counters + its
    own e2e digest quantiles/SLO account) and the metrics aggregator
    (fleet-merged engine digests, KV utilization). One URL works when that
    endpoint exports everything."""

    def __init__(self, metrics_url: str, extra_urls: Sequence[str] = ()):
        self.urls = [metrics_url, *extra_urls]
        self._last: Optional[Dict[str, float]] = None
        self._last_ts: Optional[float] = None

    @property
    def metrics_url(self) -> str:
        return self.urls[0]

    async def _fetch(self) -> str:
        parts = []
        async with aiohttp.ClientSession() as session:
            for url in self.urls:
                async with session.get(url) as resp:
                    parts.append(await resp.text())
        return "\n".join(parts)

    # --- signal extraction (separated so tests can drive from text) ---------
    @staticmethod
    def _quantile(samples: List[Sample], stream: str, q: str) -> float:
        """Max across sources of ``*<stream>_seconds_quantile{quantile=q}``
        — with one merged fleet gauge this is that gauge; with several
        sources (frontend e2e + engine fleet), the planner should react to
        the worst."""
        suffix = f"{stream}_seconds_quantile"
        vals = [
            s.value for s in _finite(samples)
            if s.name.endswith(suffix) and s.labels.get("quantile") == q
        ]
        return max(vals) if vals else 0.0

    @staticmethod
    def _gauge_mean(samples: List[Sample], suffix: str) -> float:
        vals = [s.value for s in _finite(samples) if s.name.endswith(suffix)]
        return sum(vals) / len(vals) if vals else 0.0

    def load_from_text(self, text: str, now: Optional[float] = None) -> ObservedLoad:
        """Fold one scrape into the delta state and derive the load. The
        first call establishes the baseline and returns a default load."""
        now = time.monotonic() if now is None else now
        samples = parse_prometheus_samples(text)
        cur: Dict[str, float] = {}
        for s in samples:
            if math.isnan(s.value):
                continue
            cur[s.name] = cur.get(s.name, 0.0) + s.value

        load = ObservedLoad()
        if self._last is not None and self._last_ts is not None:
            dt = max(now - self._last_ts, 1e-6)
            last = self._last

            def delta(name: str) -> float:
                return max(0.0, cur.get(name, 0.0) - last.get(name, 0.0))

            def delta_suffix(suffix: str) -> float:
                return sum(
                    max(0.0, v - last.get(name, 0.0))
                    for name, v in cur.items() if name.endswith(suffix)
                )

            d_req = delta("dynamo_frontend_requests_total")
            d_in = delta("dynamo_frontend_input_tokens_total")
            d_out = delta("dynamo_frontend_output_tokens_total")
            if d_req == 0.0:
                # Frontend-less stacks (mocker fleets under the traffic
                # harness, engine-only deployments): derive the traffic
                # shape from the engine-side counters the aggregator
                # forwards (worker_request_total / worker_*_tokens_total).
                d_req = delta_suffix("worker_request_total")
                d_in = delta_suffix("worker_input_tokens_total")
                d_out = delta_suffix("worker_output_tokens_total")
            # SLO attainment over THIS window (counter deltas, all sources:
            # frontend phase-labeled + worker flat keys both end in
            # slo_*attained_total / slo_*violated_total).
            d_att = delta_suffix("slo_attained_total") + delta_suffix("slo_ttft_attained_total") \
                + delta_suffix("slo_tpot_attained_total")
            d_vio = delta_suffix("slo_violated_total") + delta_suffix("slo_ttft_violated_total") \
                + delta_suffix("slo_tpot_violated_total")
            # Measured per-worker capacity: tokens ÷ busy step time over the
            # window (flight-recorder / mocker step_* families via the
            # aggregator). Feeds ProfiledCapacityModel so the controller's
            # inversion uses what workers DID, not what a model declared.
            d_pre_tok = delta_suffix("step_prefill_tokens_total")
            d_pre_s = delta_suffix("step_prefill_time_seconds_total")
            d_dec_tok = delta_suffix("step_decode_tokens_total")
            d_dec_s = delta_suffix("step_decode_time_seconds_total")
            load = ObservedLoad(
                request_rate=d_req / dt,
                avg_isl=d_in / d_req if d_req > 0 else 0.0,
                avg_osl=d_out / d_req if d_req > 0 else 0.0,
                ttft_p50=self._quantile(samples, "ttft", "0.5"),
                ttft_p90=self._quantile(samples, "ttft", "0.9"),
                ttft_p99=self._quantile(samples, "ttft", "0.99"),
                tpot_p99=self._quantile(samples, "tpot", "0.99"),
                queue_wait_p99=self._quantile(samples, "queue_wait", "0.99"),
                slo_attainment=(d_att / (d_att + d_vio)) if (d_att + d_vio) > 0 else 1.0,
                goodput_req_s=delta_suffix("goodput_requests_total") / dt,
                goodput_tok_s=delta_suffix("goodput_tokens_total") / dt,
                kv_util=self._gauge_mean(samples, "_kv_usage"),
                measured_prefill_tok_s=d_pre_tok / d_pre_s if d_pre_s > 0 else 0.0,
                measured_decode_tok_s=d_dec_tok / d_dec_s if d_dec_s > 0 else 0.0,
            )
        self._last = cur
        self._last_ts = now
        return load

    async def observe(self) -> ObservedLoad:
        return self.load_from_text(await self._fetch())
