"""Planner CLI: ``python -m dynamo_tpu.planner.main`` (ref:
``python -m dynamo.planner`` — start_sla_planner planner_core.py:552)."""

from __future__ import annotations

import argparse
import asyncio

from dynamo_tpu.planner.connectors import KubernetesConnector, VirtualConnector
from dynamo_tpu.planner.interpolator import DecodeInterpolator, PrefillInterpolator
from dynamo_tpu.planner.observer import PrometheusObserver
from dynamo_tpu.planner.planner_core import Planner, PlannerConfig, SlaTargets
from dynamo_tpu.runtime.logging import get_logger, init_logging

logger = get_logger(__name__)


def main() -> None:
    init_logging()
    p = argparse.ArgumentParser(description="dynamo-tpu SLA planner")
    p.add_argument("--frontend-metrics-url", default="http://127.0.0.1:8000/metrics")
    p.add_argument("--prefill-profile", required=True, help="npz from dynamo_tpu.planner.profiler")
    p.add_argument("--decode-profile", required=True)
    p.add_argument("--adjustment-interval", type=float, default=30.0,
                   help="seconds between observe→predict→decide→act passes")
    p.add_argument("--ttft-sla-ms", type=float, default=200.0)
    p.add_argument("--itl-sla-ms", type=float, default=20.0)
    p.add_argument("--max-chip-budget", type=int, default=8)
    p.add_argument("--min-prefill", type=int, default=1,
                   help="prefill pool floor (replicas)")
    p.add_argument("--max-prefill", type=int, default=0,
                   help="prefill pool ceiling (0 = chip budget only)")
    p.add_argument("--min-decode", type=int, default=1,
                   help="decode pool floor (replicas)")
    p.add_argument("--max-decode", type=int, default=0,
                   help="decode pool ceiling (0 = chip budget only)")
    p.add_argument("--scale-cooldown-s", type=float, default=0.0,
                   help="hold this long after any applied scale change "
                        "(suppresses flapping on launch/drain transients)")
    p.add_argument("--dry-run", action="store_true",
                   help="log scaling decisions without driving the connector")
    p.add_argument("--load-predictor",
                   choices=["constant", "arima", "trend", "seasonal",
                            "seasonal_trend", "prophet"],
                   default="arima")
    p.add_argument("--connector", choices=["virtual", "kubernetes"], default="virtual")
    p.add_argument("--k8s-namespace", default="default")
    args = p.parse_args()

    config = PlannerConfig(
        adjustment_interval_s=args.adjustment_interval,
        load_predictor=args.load_predictor,
        max_chip_budget=args.max_chip_budget,
        min_prefill_replicas=args.min_prefill,
        max_prefill_replicas=args.max_prefill,
        min_decode_replicas=args.min_decode,
        max_decode_replicas=args.max_decode,
        scale_cooldown_s=args.scale_cooldown_s,
        dry_run=args.dry_run,
        sla=SlaTargets(ttft_ms=args.ttft_sla_ms, itl_ms=args.itl_sla_ms),
    )
    connector = (
        KubernetesConnector(namespace=args.k8s_namespace) if args.connector == "kubernetes" else VirtualConnector()
    )
    observer = PrometheusObserver(args.frontend_metrics_url)
    planner = Planner(
        config,
        connector,
        PrefillInterpolator.from_npz(args.prefill_profile),
        DecodeInterpolator.from_npz(args.decode_profile),
        observer.observe,
    )

    async def run():
        logger.info("planner started: interval=%.0fs sla=%s", config.adjustment_interval_s, config.sla)
        await planner.run()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
