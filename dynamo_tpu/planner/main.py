"""Planner CLI: ``python -m dynamo_tpu.planner.main`` (ref:
``python -m dynamo.planner`` — start_sla_planner planner_core.py:552)."""

from __future__ import annotations

import argparse
import asyncio

from dynamo_tpu.planner.connectors import KubernetesConnector, VirtualConnector
from dynamo_tpu.planner.interpolator import DecodeInterpolator, PrefillInterpolator
from dynamo_tpu.planner.observer import PrometheusObserver
from dynamo_tpu.planner.planner_core import Planner, PlannerConfig, SlaTargets
from dynamo_tpu.runtime.logging import get_logger, init_logging

logger = get_logger(__name__)


def main() -> None:
    init_logging()
    p = argparse.ArgumentParser(description="dynamo-tpu SLA planner")
    p.add_argument("--frontend-metrics-url", default="http://127.0.0.1:8000/metrics")
    p.add_argument("--prefill-profile", required=True, help="npz from dynamo_tpu.planner.profiler")
    p.add_argument("--decode-profile", required=True)
    p.add_argument("--adjustment-interval", type=float, default=30.0)
    p.add_argument("--ttft-sla-ms", type=float, default=200.0)
    p.add_argument("--itl-sla-ms", type=float, default=20.0)
    p.add_argument("--max-chip-budget", type=int, default=8)
    p.add_argument("--load-predictor", choices=["constant", "arima", "seasonal", "prophet"], default="arima")
    p.add_argument("--connector", choices=["virtual", "kubernetes"], default="virtual")
    p.add_argument("--k8s-namespace", default="default")
    args = p.parse_args()

    config = PlannerConfig(
        adjustment_interval_s=args.adjustment_interval,
        load_predictor=args.load_predictor,
        max_chip_budget=args.max_chip_budget,
        sla=SlaTargets(ttft_ms=args.ttft_sla_ms, itl_ms=args.itl_sla_ms),
    )
    connector = (
        KubernetesConnector(namespace=args.k8s_namespace) if args.connector == "kubernetes" else VirtualConnector()
    )
    observer = PrometheusObserver(args.frontend_metrics_url)
    planner = Planner(
        config,
        connector,
        PrefillInterpolator.from_npz(args.prefill_profile),
        DecodeInterpolator.from_npz(args.decode_profile),
        observer.observe,
    )

    async def run():
        logger.info("planner started: interval=%.0fs sla=%s", config.adjustment_interval_s, config.sla)
        await planner.run()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
