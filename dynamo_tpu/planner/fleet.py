"""Fleet actuation for the autoscale controller: real in-process workers.

The decision layer (:mod:`dynamo_tpu.planner.controller`) is pure; this
module makes its decisions *real capacity changes*: ``add`` launches a
mocker worker — served endpoint, KV-event + metrics publishers, forced
wire path — and ``drain`` retires one through the PR 10 drain lifecycle
(deregister → reject-new-to-migration → finish/sever in-flight → revoke),
so routers, the aggregator, and live requests observe exactly what a
production scale event looks like, process-free.

Drains run as background tasks and are *tracked*: ``drains_in_flight`` is
the controller's debounce signal (never a second scale-down while one is
still landing). The planner itself is scrape-observable — ``serve_planner``
registers a ``planner`` endpoint whose stats handler is the controller's
counter/gauge dict, so the metrics aggregator exports planner decisions
next to worker stats and the Grafana "Planner" row stays MET001-pinned.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Awaitable, Callable, Dict, List, Optional

from dynamo_tpu.planner.controller import (
    DECODE,
    POOLS,
    PREFILL,
    AutoscaleController,
    Decision,
    FleetView,
    WorkerView,
    rank_coldest,
)
from dynamo_tpu.planner.planner_core import ObservedLoad
from dynamo_tpu.runtime.logging import get_logger

logger = get_logger(__name__)


@dataclass
class FleetWorker:
    component: str
    worker_id: int
    engine: object
    handle: object
    publishers: List[object] = field(default_factory=list)


class MockerFleet:
    """Launch/drain in-process mocker workers per pool (prefill/decode).

    ``make_args(component) -> MockEngineArgs`` parameterizes each pool's
    engine (heterogeneous pools: prefill-tuned vs decode-tuned timing).
    """

    def __init__(
        self,
        drt,
        namespace: str = "autoscale",
        *,
        make_args: Optional[Callable[[str], object]] = None,
        endpoint_name: str = "generate",
        drain_timeout_s: float = 10.0,
        publish_kv_events: bool = True,
        wire_path: bool = True,
    ):
        self.drt = drt
        self.namespace = namespace
        self.endpoint_name = endpoint_name
        self.drain_timeout_s = drain_timeout_s
        self.publish_kv_events = publish_kv_events
        self.wire_path = wire_path
        self.make_args = make_args or (lambda component: None)
        self.pools: Dict[str, List[FleetWorker]] = {p: [] for p in POOLS}
        self._drains: Dict[str, set] = {p: set() for p in POOLS}
        self.launches_total = 0
        self.drains_total = 0
        self._planner_handle = None

    def endpoint(self, component: str):
        return self.drt.namespace(self.namespace).component(component).endpoint(self.endpoint_name)

    def scrape_endpoints(self) -> List[str]:
        """``ns/component/endpoint`` strings the metrics aggregator should
        scrape to see the whole autoscaling plane (both pools + planner)."""
        eps = [f"{self.namespace}/{c}/{self.endpoint_name}" for c in POOLS]
        if self._planner_handle is not None:
            eps.append(f"{self.namespace}/planner/control")
        return eps

    # --- launch -------------------------------------------------------------
    async def add_worker(self, component: str) -> FleetWorker:
        from dynamo_tpu.llm.kv_router import KvEventPublisher, WorkerMetricsPublisher
        from dynamo_tpu.llm.mocker import MockEngineArgs, MockTpuEngine

        args = self.make_args(component) or MockEngineArgs()
        engine = MockTpuEngine(args)
        ep = self.endpoint(component)
        handle = await ep.serve_endpoint(engine.generate, stats_handler=engine.stats_handler)
        worker_id = handle.instance.instance_id
        publishers: List[object] = []
        if self.publish_kv_events:
            kv_pub = KvEventPublisher(self.drt, ep.namespace, ep.component, worker_id)
            kv_pub.start()
            engine.set_kv_event_sink(kv_pub.publish)
            m_pub = WorkerMetricsPublisher(
                self.drt, ep.namespace, ep.component, worker_id, engine.metrics, interval_s=0.25
            )
            m_pub.start()
            publishers = [kv_pub, m_pub]
        if self.wire_path:
            # Real deployments cross the pub/sub + TCP wire; the local
            # fast path would hide drain/migration semantics.
            self.drt.local_engines.pop(worker_id, None)
        worker = FleetWorker(component, worker_id, engine, handle, publishers)
        self.pools[component].append(worker)
        self.launches_total += 1
        logger.info("fleet: launched %s worker %x (pool=%d)",
                    component, worker_id, len(self.pools[component]))
        return worker

    # --- drain --------------------------------------------------------------
    def drain_worker(self, component: str, worker_id: int) -> Optional[asyncio.Task]:
        """Start a tracked background drain of one worker; returns the task
        (None if the id is not live in the pool)."""
        pool = self.pools[component]
        worker = next((w for w in pool if w.worker_id == worker_id), None)
        if worker is None:
            return None
        # Out of the pool immediately: capacity accounting must not count a
        # leaving worker, and the controller's view stops offering it as a
        # victim. The drain itself completes in the background.
        pool.remove(worker)

        async def _drain() -> None:
            try:
                await worker.handle.stop(drain=True, timeout_s=self.drain_timeout_s)
            finally:
                for pub in worker.publishers:
                    try:
                        await pub.stop()
                    except Exception:  # noqa: BLE001 — cleanup must not leak a drain slot
                        logger.exception("fleet: publisher stop failed for %x", worker_id)
                self.drains_total += 1
                logger.info("fleet: drained %s worker %x (pool=%d)",
                            component, worker_id, len(pool))

        task = asyncio.get_running_loop().create_task(_drain())
        self._drains[component].add(task)
        task.add_done_callback(self._drains[component].discard)
        return task

    def drains_in_flight(self, component: str) -> int:
        return sum(1 for t in self._drains[component] if not t.done())

    async def wait_drains(self, timeout: float = 30.0) -> bool:
        pending = [t for drains in self._drains.values() for t in drains]
        if not pending:
            return True
        done, not_done = await asyncio.wait(pending, timeout=timeout)
        return not not_done

    # --- view ---------------------------------------------------------------
    def view(self, router_stats: Optional[dict] = None) -> FleetView:
        """The controller's input: live pool membership + per-worker KV
        warmth. ``router_stats`` is ``KvPushRouter.stats()`` — its
        ``cached_tokens_by_worker`` (ACTUAL engine-reported reuse per
        worker, PR 5) is the strongest warmth signal."""
        by_worker = (router_stats or {}).get("cached_tokens_by_worker", {})
        pools: Dict[str, List[WorkerView]] = {}
        for component, workers in self.pools.items():
            views = []
            for w in workers:
                alloc = w.engine.allocator
                views.append(WorkerView(
                    worker_id=w.worker_id,
                    kv_util=alloc.usage(),
                    kv_warmth=alloc.num_cached / alloc.num_blocks if alloc.num_blocks else 0.0,
                    cached_tokens_total=int(by_worker.get(w.worker_id, 0)),
                    draining=bool(getattr(w.handle, "draining", False)),
                ))
            pools[component] = views
        return FleetView(
            pools=pools,
            drains_in_flight={c: self.drains_in_flight(c) for c in POOLS},
        )

    def size(self, component: str) -> int:
        return len(self.pools[component])

    # --- actuation ----------------------------------------------------------
    async def apply(self, decisions: List[Decision]) -> None:
        for d in decisions:
            if d.action == "add":
                for _ in range(d.count):
                    await self.add_worker(d.pool)
            elif d.action == "drain":
                victims = list(d.victims)
                if not victims and d.count:
                    victims = rank_coldest(self.view().pools.get(d.pool, ()), d.count)
                for v in victims:
                    self.drain_worker(d.pool, v)
            elif d.action == "dial":
                self.set_dial(d.fraction)

    def set_dial(self, prefill_fraction: float) -> int:
        """Apply the ratio actuator's commanded prefill fraction to every
        live worker (the in-process mirror of broadcasting the ``set_dial``
        control op); returns how many workers took the dial. New workers
        launched later start at their configured split — the next dial
        decision re-aligns them."""
        applied = 0
        for workers in self.pools.values():
            for w in workers:
                dial = getattr(w.engine, "set_capacity_dial", None)
                if dial is None:
                    continue
                try:
                    dial(prefill_fraction)
                    applied += 1
                except Exception:  # noqa: BLE001 — one bad worker must not stop the sweep
                    logger.exception("fleet: set_capacity_dial failed on %x", w.worker_id)
        logger.info("fleet: dial %.3f applied to %d worker(s)", prefill_fraction, applied)
        return applied

    # --- planner observability ----------------------------------------------
    async def serve_planner(self, controller: AutoscaleController):
        """Expose the controller's decision counters on the stats-scrape
        wire: a ``planner`` pseudo-worker whose scrape dict is
        ``controller.to_stats()`` (aggregator → ``planner_*`` families)."""

        async def _control(request, context):
            yield {"planner": True, **controller.to_stats()}

        ep = self.drt.namespace(self.namespace).component("planner").endpoint("control")
        self._planner_handle = await ep.serve_endpoint(_control, stats_handler=controller.to_stats)
        return self._planner_handle

    async def shutdown(self) -> None:
        await self.wait_drains(timeout=self.drain_timeout_s + 5.0)
        for component in list(self.pools):
            for worker in list(self.pools[component]):
                self.drain_worker(component, worker.worker_id)
        await self.wait_drains(timeout=self.drain_timeout_s + 5.0)
        if self._planner_handle is not None:
            await self._planner_handle.stop(drain=False)
            self._planner_handle = None

    def summary(self) -> dict:
        return {
            "launches": self.launches_total,
            "drains": self.drains_total,
            "pools": {c: [f"{w.worker_id:x}" for w in ws] for c, ws in self.pools.items()},
        }


class AutoscaleLoop:
    """observe → decide → act on a fixed adjustment interval.

    ``observe_fn`` yields :class:`ObservedLoad` (typically
    ``PrometheusObserver.observe`` over the aggregator's /metrics);
    ``router_stats_fn`` feeds the warmth ranking. ``step()`` is public so
    harnesses can drive compressed time deterministically."""

    def __init__(
        self,
        controller: AutoscaleController,
        fleet: MockerFleet,
        observe_fn: Callable[[], Awaitable[ObservedLoad]],
        *,
        interval_s: float = 10.0,
        router_stats_fn: Optional[Callable[[], dict]] = None,
    ):
        self.controller = controller
        self.fleet = fleet
        self.observe_fn = observe_fn
        self.interval_s = interval_s
        self.router_stats_fn = router_stats_fn
        self.decision_log: List[Decision] = []
        self._task: Optional[asyncio.Task] = None

    async def step(self, now: Optional[float] = None) -> List[Decision]:
        load = await self.observe_fn()
        router_stats = self.router_stats_fn() if self.router_stats_fn else None
        view = self.fleet.view(router_stats)
        ts = time.monotonic() if now is None else now
        decisions = self.controller.decide(load, view, ts)
        # Ratio actuator: between scale events, the per-worker capacity dial
        # tracks the observed ISL/OSL mix (no launch/drain transient).
        dial = self.controller.decide_dial(load, ts)
        if dial is not None:
            decisions.append(dial)
        self.decision_log.extend(d for d in decisions if d.action != "hold")
        if not self.controller.config.dry_run:
            await self.fleet.apply(decisions)
        return decisions

    async def run(self) -> None:
        while True:
            try:
                await self.step()
            except Exception:
                logger.exception("autoscale step failed")
            await asyncio.sleep(self.interval_s)

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(self.run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
