"""The SLA planner adjustment loop.

Ref: components/planner/src/dynamo/planner/utils/planner_core.py —
``start_sla_planner`` (:552), ``Planner.run`` (:414): every
``adjustment_interval``: observe frontend metrics (:193), predict load
(:240), ``_compute_replica_requirements`` (:259):

  prefill_replicas = ceil(req_rate * isl / interval / prefill_thpt_per_chip
                          / chips_per_prefill_engine)
  decode_replicas  = ceil(req_rate * osl / interval /
                          itl_sla_inverted_thpt / chips_per_decode_engine)
  clamp to max_chip_budget (:339-352)

then ``make_adjustments`` (:355) through a connector.
"""

from __future__ import annotations

import asyncio
import math
import time
from dataclasses import dataclass, field
from typing import Awaitable, Callable, Optional

from dynamo_tpu.planner.connectors import Connector
from dynamo_tpu.planner.interpolator import DecodeInterpolator, PrefillInterpolator
from dynamo_tpu.planner.load_predictor import LoadPredictor, make_predictor
from dynamo_tpu.runtime.logging import get_logger

logger = get_logger(__name__)

PREFILL_COMPONENT = "prefill"
DECODE_COMPONENT = "decode"


@dataclass
class SlaTargets:
    ttft_ms: float = 200.0
    itl_ms: float = 20.0


@dataclass
class ObservedLoad:
    """One observation window from the frontend + aggregator metrics
    (ref: observe_metrics planner_core.py:193).

    Beyond the rate/shape deltas, the load now carries the distribution
    signals SLA-driven scaling actually consumes (arXiv:2508.19559): TTFT/
    TPOT/queue-wait quantiles from the fleet-merged digests, the SLO
    attainment + goodput account, and KV utilization (the warmth signal
    that makes scale-down decisions KV-cache-aware)."""

    request_rate: float = 0.0  # req/s
    avg_isl: float = 0.0  # input tokens per request
    avg_osl: float = 0.0  # output tokens per request
    # Latency quantiles (seconds) from digest quantile gauges; 0.0 = no data.
    ttft_p50: float = 0.0
    ttft_p90: float = 0.0
    ttft_p99: float = 0.0
    tpot_p99: float = 0.0
    queue_wait_p99: float = 0.0
    # SLO attainment over the window (judged phase checks that met target);
    # 1.0 with no data so an idle fleet never looks like an SLO breach.
    slo_attainment: float = 1.0
    # Goodput: SLO-attained requests/tokens per second over the window.
    goodput_req_s: float = 0.0
    goodput_tok_s: float = 0.0
    # Mean KV-pool usage across workers (0..1).
    kv_util: float = 0.0
    # MEASURED per-worker sustained token rates over the window: fleet-wide
    # Δstep_{phase}_tokens / Δstep_{phase}_time_seconds (step time is
    # per-worker busy time, so the quotient is tok/s per busy worker —
    # exactly the capacity quantity declared rates approximate). 0.0 = no
    # step traffic this window; ProfiledCapacityModel ignores zeros.
    measured_prefill_tok_s: float = 0.0
    measured_decode_tok_s: float = 0.0


@dataclass
class PlannerConfig:
    adjustment_interval_s: float = 30.0
    load_predictor: str = "arima"
    chips_per_prefill_engine: int = 1
    chips_per_decode_engine: int = 1
    min_prefill_replicas: int = 1
    min_decode_replicas: int = 1
    # Per-pool ceilings (0 = bounded by max_chip_budget only).
    max_prefill_replicas: int = 0
    max_decode_replicas: int = 0
    max_chip_budget: int = 8
    # Hold after any applied change (0 = act every interval). Launch/drain
    # transients echo into the next observation window; acting on that echo
    # flaps the fleet.
    scale_cooldown_s: float = 0.0
    # Log decisions without driving the connector.
    dry_run: bool = False
    sla: SlaTargets = field(default_factory=SlaTargets)


@dataclass
class ReplicaPlan:
    prefill: int
    decode: int


class Planner:
    def __init__(
        self,
        config: PlannerConfig,
        connector: Connector,
        prefill_interp: PrefillInterpolator,
        decode_interp: DecodeInterpolator,
        observe_fn: Callable[[], Awaitable[ObservedLoad]],
    ):
        self.config = config
        self.connector = connector
        self.prefill_interp = prefill_interp
        self.decode_interp = decode_interp
        self.observe_fn = observe_fn
        self.rate_predictor: LoadPredictor = make_predictor(config.load_predictor)
        self.isl_predictor: LoadPredictor = make_predictor("constant")
        self.osl_predictor: LoadPredictor = make_predictor("constant")
        self._task: Optional[asyncio.Task] = None
        self.last_plan: Optional[ReplicaPlan] = None
        self._last_change_ts: Optional[float] = None
        self.cooldown_holds_total = 0
        self.dry_run_decisions_total = 0

    # --- the math (ref: _compute_replica_requirements :259) -----------------
    def compute_replicas(self, load: ObservedLoad) -> ReplicaPlan:
        c = self.config
        isl = max(load.avg_isl, 1.0)
        osl = max(load.avg_osl, 1.0)
        rate = max(load.request_rate, 0.0)

        # Prefill: token demand / per-chip prefill throughput at this ISL.
        prefill_thpt = self.prefill_interp.throughput_per_chip(isl)
        prefill_chips = rate * isl / prefill_thpt
        prefill = max(c.min_prefill_replicas, math.ceil(prefill_chips / c.chips_per_prefill_engine))

        # Decode: invert the ITL SLA into a max safe per-chip token rate.
        decode_thpt = self.decode_interp.find_best_throughput_per_chip(c.sla.itl_ms, isl + osl)
        decode_chips = rate * osl / max(decode_thpt, 1e-9)
        decode = max(c.min_decode_replicas, math.ceil(decode_chips / c.chips_per_decode_engine))

        # Per-pool ceilings, then the budget clamp preserving the
        # prefill:decode ratio (ref :339-352).
        if c.max_prefill_replicas > 0:
            prefill = min(prefill, c.max_prefill_replicas)
        if c.max_decode_replicas > 0:
            decode = min(decode, c.max_decode_replicas)
        total_chips = prefill * c.chips_per_prefill_engine + decode * c.chips_per_decode_engine
        if total_chips > c.max_chip_budget:
            scale = c.max_chip_budget / total_chips
            prefill = max(c.min_prefill_replicas, math.floor(prefill * scale))
            decode = max(c.min_decode_replicas, math.floor(decode * scale))
        return ReplicaPlan(prefill=prefill, decode=decode)

    # --- loop (ref: Planner.run :414) ---------------------------------------
    async def step(self) -> ReplicaPlan:
        load = await self.observe_fn()
        self.rate_predictor.observe(load.request_rate)
        self.isl_predictor.observe(load.avg_isl)
        self.osl_predictor.observe(load.avg_osl)
        predicted = ObservedLoad(
            request_rate=self.rate_predictor.predict(),
            avg_isl=self.isl_predictor.predict(),
            avg_osl=self.osl_predictor.predict(),
        )
        plan = self.compute_replicas(predicted)
        if self.last_plan is None or plan != self.last_plan:
            now = time.monotonic()
            if (
                self.last_plan is not None
                and self.config.scale_cooldown_s > 0
                and self._last_change_ts is not None
                and now - self._last_change_ts < self.config.scale_cooldown_s
            ):
                # Cooldown: hold the applied plan; the demand re-evaluates
                # next interval with the transient settled.
                self.cooldown_holds_total += 1
                return self.last_plan
            logger.info(
                "planner%s: rate=%.2f isl=%.0f osl=%.0f ttft_p99=%.3fs tpot_p99=%.4fs "
                "slo=%.2f goodput=%.2freq/s kv=%.2f -> prefill=%d decode=%d",
                " [dry-run]" if self.config.dry_run else "",
                predicted.request_rate, predicted.avg_isl, predicted.avg_osl,
                load.ttft_p99, load.tpot_p99, load.slo_attainment,
                load.goodput_req_s, load.kv_util, plan.prefill, plan.decode,
            )
            if self.config.dry_run:
                self.dry_run_decisions_total += 1
                return plan
            await self.connector.set_replicas(PREFILL_COMPONENT, plan.prefill)
            await self.connector.set_replicas(DECODE_COMPONENT, plan.decode)
            self.last_plan = plan
            self._last_change_ts = now
        return plan

    async def run(self) -> None:
        while True:
            try:
                await self.step()
            except Exception:
                logger.exception("planner step failed")
            await asyncio.sleep(self.config.adjustment_interval_s)

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(self.run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
