"""dynamo-run equivalent: single-command launcher.

Ref: launch/dynamo-run (SURVEY.md §3E) — ``dynamo-run in=X out=Y``:
- in:  http | text | batch:<prompts.jsonl>
- out: <model-preset> | mocker | dyn://<ns>.<component>.<endpoint>

Examples:
  python -m dynamo_tpu.run in=http out=tiny
  python -m dynamo_tpu.run in=text out=tiny
  python -m dynamo_tpu.run in=batch:prompts.jsonl out=tiny --output results.jsonl
  python -m dynamo_tpu.run in=http out=dyn://dynamo.backend.generate
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

from dynamo_tpu.engine.engine import EngineArgs, TpuEngine
from dynamo_tpu.engine.scheduler import SchedulerConfig
from dynamo_tpu.llm.discovery import ModelManager
from dynamo_tpu.llm.entrypoint import RouterEngine, build_local_pipeline
from dynamo_tpu.llm.http.service import HttpService
from dynamo_tpu.llm.mocker import MockEngineArgs, MockTpuEngine
from dynamo_tpu.llm.tokenizer import load_tokenizer
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.logging import get_logger, init_logging
from dynamo_tpu.runtime.push_router import PushRouter

logger = get_logger(__name__)


async def make_engine(out_spec: str, args, drt):
    """Resolve out= to (engine, needs_drt)."""
    if out_spec == "mocker":
        return MockTpuEngine(MockEngineArgs()), None
    if out_spec.startswith("dyn://"):
        path = out_spec[6:]
        ns, comp, ep_name = path.split(".")
        ep = drt.namespace(ns).component(comp).endpoint(ep_name)
        client = await ep.client()
        await client.wait_for_instances(1, timeout=args.timeout)
        return RouterEngine(PushRouter(client)), None
    engine = TpuEngine.build(
        EngineArgs(
            model=out_spec,
            dtype=args.dtype,
            checkpoint_path=args.checkpoint,
            scheduler=SchedulerConfig(num_blocks=args.num_blocks),
        )
    )
    return engine, None


async def amain(args) -> None:
    drt = await DistributedRuntime.from_settings()
    engine, _ = await make_engine(args.out, args, drt)
    tokenizer = load_tokenizer(args.tokenizer)
    pipeline = build_local_pipeline(tokenizer, engine)
    model_name = args.model_name or args.out

    if args.mode == "http":
        manager = ModelManager()
        manager.add_model("chat", model_name, pipeline)
        if isinstance(engine, TpuEngine):
            from dynamo_tpu.engine.embeddings import EmbeddingEngine
            from dynamo_tpu.llm.entrypoint import build_embeddings_pipeline

            sched = engine.scheduler
            manager.add_model(
                "embeddings",
                model_name,
                build_embeddings_pipeline(tokenizer, EmbeddingEngine(sched.mc, sched.params)),
            )
        service = HttpService(manager, host="0.0.0.0", port=args.http_port)
        await service.start()
        print(f"serving {model_name} on :{service.port} (POST /v1/chat/completions)", flush=True)
        drt.runtime.install_signal_handlers()
        await drt.runtime.cancellation.cancelled()
        await service.stop()
    elif args.mode == "text":
        print(f"interactive chat with {model_name}; ctrl-d to exit")
        loop = asyncio.get_running_loop()
        while True:
            try:
                line = await loop.run_in_executor(None, lambda: input("> "))
            except (EOFError, KeyboardInterrupt):
                break
            body = {
                "model": model_name,
                "messages": [{"role": "user", "content": line}],
                "max_tokens": args.max_tokens,
            }
            async for item in pipeline.generate(body, Context()):
                data = item.data if hasattr(item, "data") else item
                if data and data.get("text"):
                    print(data["text"], end="", flush=True)
            print()
    elif args.mode.startswith("batch"):
        path = args.mode.split(":", 1)[1]
        out_path = args.output or "results.jsonl"
        with open(path) as f, open(out_path, "w") as out_f:
            for line in f:
                if not line.strip():
                    continue
                rec = json.loads(line)
                body = {
                    "model": model_name,
                    "prompt": rec.get("prompt") or rec.get("text", ""),
                    "max_tokens": rec.get("max_tokens", args.max_tokens),
                }
                text_parts = []
                async for item in pipeline.generate(body, Context()):
                    data = item.data if hasattr(item, "data") else item
                    if data and data.get("text"):
                        text_parts.append(data["text"])
                out_f.write(json.dumps({"prompt": body["prompt"], "output": "".join(text_parts)}) + "\n")
        print(f"batch results written to {out_path}")
    if hasattr(engine, "stop"):
        await engine.stop()
    await drt.shutdown()


def main() -> None:
    init_logging()
    p = argparse.ArgumentParser(description="dynamo-run for TPU", allow_abbrev=False)
    p.add_argument("io", nargs=2, help="in=http|text|batch:<file> out=<model>|mocker|dyn://ns.comp.ep")
    p.add_argument("--http-port", type=int, default=8080)
    p.add_argument("--model-name", default=None)
    p.add_argument("--tokenizer", default=None)
    p.add_argument("--checkpoint", default=None)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--num-blocks", type=int, default=512)
    p.add_argument("--max-tokens", type=int, default=128)
    p.add_argument("--output", default=None)
    p.add_argument("--timeout", type=float, default=30.0)
    args = p.parse_args()
    spec = {}
    for part in args.io:
        key, _, value = part.partition("=")
        spec[key] = value
    if "in" not in spec or "out" not in spec:
        p.error("expected in=... out=...")
    args.mode = spec["in"]
    args.out = spec["out"]
    try:
        asyncio.run(amain(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
