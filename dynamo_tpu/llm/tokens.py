"""Token sequences, block partitioning, and chained block hashing.

Ref: lib/tokens/src/lib.rs (611 LoC) and lib/llm/src/tokens.rs —
``compute_hash_v2`` = xxh3_64_with_seed (tokens.rs:36), ``SequenceHash``
(:33). Block hashes chain: each block's hash seeds from its parent's, so a
block hash identifies the *entire prefix* ending at that block. Router
overlap matching and engine prefix caching both key on these.

Python fallback uses the ``xxhash`` wheel; the C++ native extension
(``native/tokenhash``) replaces the hot loop when built.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import List, Optional, Sequence

import xxhash

# Seed for the first block in a sequence (no parent). The reference uses its
# own constant; any fixed seed works as long as engine + router agree.
ROOT_SEED = 0x6462_6C6B  # "dblk"

from dynamo_tpu.native import get_native

_native = get_native()
_native_hash_blocks = _native.hash_token_blocks if _native is not None else None

BlockHash = int
SequenceHash = int


def hash_tokens(tokens: Sequence[int], seed: int = ROOT_SEED) -> int:
    """xxh3_64 over little-endian u32 token ids, seeded (ref: tokens.rs:36)."""
    buf = struct.pack(f"<{len(tokens)}I", *tokens)
    return xxhash.xxh3_64_intdigest(buf, seed=seed)


def compute_block_hashes(tokens: Sequence[int], block_size: int) -> List[BlockHash]:
    """Chained hashes for each *complete* block of the token sequence.

    block_hash[i] = xxh3(tokens[i*bs:(i+1)*bs], seed=block_hash[i-1])
    Partial trailing blocks get no hash (they are not reusable).
    """
    n_full = len(tokens) // block_size
    if _native_hash_blocks is not None:
        return _native_hash_blocks(list(tokens), block_size, ROOT_SEED)
    hashes: List[BlockHash] = []
    seed = ROOT_SEED
    for i in range(n_full):
        h = hash_tokens(tokens[i * block_size : (i + 1) * block_size], seed)
        hashes.append(h)
        seed = h
    return hashes


def extend_block_hashes(
    prev_hashes: List[BlockHash], tokens: Sequence[int], block_size: int
) -> List[BlockHash]:
    """Incrementally extend: hash only blocks beyond len(prev_hashes)."""
    n_full = len(tokens) // block_size
    hashes = list(prev_hashes)
    seed = hashes[-1] if hashes else ROOT_SEED
    for i in range(len(hashes), n_full):
        h = hash_tokens(tokens[i * block_size : (i + 1) * block_size], seed)
        hashes.append(h)
        seed = h
    return hashes


@dataclass
class TokenBlock:
    """A fixed-size block of tokens with its chained hash."""

    tokens: List[int]
    block_hash: BlockHash
    parent_hash: Optional[BlockHash]


def to_blocks(tokens: Sequence[int], block_size: int) -> List[TokenBlock]:
    hashes = compute_block_hashes(tokens, block_size)
    blocks = []
    parent: Optional[BlockHash] = None
    for i, h in enumerate(hashes):
        blocks.append(
            TokenBlock(tokens=list(tokens[i * block_size : (i + 1) * block_size]), block_hash=h, parent_hash=parent)
        )
        parent = h
    return blocks
