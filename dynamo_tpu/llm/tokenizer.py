"""Tokenizer abstraction + incremental detokenization.

Ref: lib/llm/src/tokenizers.rs (HF tokenizers wrapper + ``DecodeStream``).
Backends:
- :class:`HFTokenizer` — a local ``tokenizer.json`` via the ``tokenizers``
  wheel (no network; the reference downloads from the hub, we resolve local
  paths only).
- :class:`ByteTokenizer` — UTF-8 byte-level fallback (vocab 256) so the full
  serving stack runs hermetically in tests and demos (pairs with the ``tiny``
  model config).

:class:`DecodeStream` implements incremental detokenization with the
prefix-diff technique: hold back output while the decoded tail ends in an
incomplete UTF-8/byte-fallback sequence (U+FFFD).
"""

from __future__ import annotations

import json
import os
from typing import List, Optional, Protocol, Sequence


class Tokenizer(Protocol):
    def encode(self, text: str) -> List[int]: ...

    def decode(self, ids: Sequence[int]) -> str: ...

    @property
    def eos_token_ids(self) -> List[int]: ...

    @property
    def vocab_size(self) -> int: ...


class ByteTokenizer:
    """UTF-8 bytes as tokens; id 0 reserved as EOS/pad."""

    EOS = 0

    def encode(self, text: str) -> List[int]:
        return [b if b != 0 else 1 for b in text.encode("utf-8")]

    def decode(self, ids: Sequence[int]) -> str:
        return bytes(i & 0xFF for i in ids if i != self.EOS).decode("utf-8", errors="replace")

    @property
    def eos_token_ids(self) -> List[int]:
        return [self.EOS]

    @property
    def vocab_size(self) -> int:
        return 256


class HFTokenizer:
    def __init__(self, path: str):
        from tokenizers import Tokenizer as _Tok

        tokenizer_file = path if path.endswith(".json") else os.path.join(path, "tokenizer.json")
        self._tok = _Tok.from_file(tokenizer_file)
        self._eos_ids: List[int] = []
        self.chat_template: Optional[str] = None
        self.bos_token: Optional[str] = None
        self.eos_token: Optional[str] = None
        cfg_path = os.path.join(os.path.dirname(tokenizer_file), "tokenizer_config.json")
        if os.path.exists(cfg_path):
            with open(cfg_path) as f:
                cfg = json.load(f)
            self.chat_template = cfg.get("chat_template")
            for key in ("eos_token", "bos_token"):
                tok = cfg.get(key)
                if isinstance(tok, dict):
                    tok = tok.get("content")
                setattr(self, key.replace("_token", "_token"), tok)
                if key == "eos_token" and tok:
                    tid = self._tok.token_to_id(tok)
                    if tid is not None:
                        self._eos_ids.append(tid)

    def encode(self, text: str) -> List[int]:
        return self._tok.encode(text, add_special_tokens=False).ids

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=True)

    @property
    def eos_token_ids(self) -> List[int]:
        return self._eos_ids

    @property
    def vocab_size(self) -> int:
        return self._tok.get_vocab_size()


class DecodeStream:
    """Incremental detokenizer: feed token ids, get text deltas
    (ref: tokenizers.rs DecodeStream)."""

    def __init__(self, tokenizer: Tokenizer, skip_token_ids: Optional[Sequence[int]] = None):
        self.tokenizer = tokenizer
        self.ids: List[int] = []
        self._emitted = 0  # chars already emitted
        self._skip = set(skip_token_ids or [])

    def step(self, token_ids: Sequence[int]) -> str:
        self.ids.extend(t for t in token_ids if t not in self._skip)
        text = self.tokenizer.decode(self.ids)
        # Hold back while the tail is an incomplete sequence.
        while text.endswith("�") and len(text) > self._emitted:
            text = text[:-1]
        delta = text[self._emitted :]
        self._emitted += len(delta)
        return delta

    def flush(self) -> str:
        text = self.tokenizer.decode(self.ids)
        delta = text[self._emitted :]
        self._emitted = len(text)
        return delta


def load_tokenizer(path_or_name: Optional[str]) -> Tokenizer:
    """Local tokenizer.json dir/file → HFTokenizer; otherwise ByteTokenizer."""
    if path_or_name:
        candidate = path_or_name if path_or_name.endswith(".json") else os.path.join(path_or_name, "tokenizer.json")
        if os.path.exists(candidate):
            return HFTokenizer(path_or_name)
    return ByteTokenizer()
