"""Internal protocol types shared across the pipeline.

Ref: lib/llm/src/protocols/common/* — ``PreprocessedRequest`` (the
tokenized, template-rendered form that crosses the wire to workers),
``LLMEngineOutput`` (per-step engine emission), StopConditions,
SamplingOptions. Kept as plain dicts on the wire (msgpack/json friendly);
these dataclasses are the typed construction/validation layer.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from dynamo_tpu.runtime.engine import Annotated


@dataclass
class SamplingOptions:
    temperature: Optional[float] = None
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    seed: Optional[int] = None
    frequency_penalty: Optional[float] = None
    presence_penalty: Optional[float] = None

    def to_wire(self) -> dict:
        return {k: v for k, v in self.__dict__.items() if v is not None}


@dataclass
class StopConditionsSpec:
    max_tokens: Optional[int] = None
    min_tokens: Optional[int] = None
    stop: List[str] = field(default_factory=list)  # stop strings (backend-jailed)
    stop_token_ids: List[int] = field(default_factory=list)
    ignore_eos: bool = False

    def to_wire(self) -> dict:
        return {
            "max_tokens": self.max_tokens,
            "min_tokens": self.min_tokens,
            "stop": self.stop,
            "stop_token_ids": self.stop_token_ids,
            "ignore_eos": self.ignore_eos,
        }


@dataclass
class PreprocessedRequest:
    """What the frontend sends to workers (ref: protocols/common
    PreprocessedRequest): token ids + sampling + stop conditions +
    annotations. ``router_overrides`` mirrors nvext per-request router
    config (kv_router.rs:86 RouterConfigOverride)."""

    token_ids: List[int]
    sampling_options: Dict[str, Any] = field(default_factory=dict)
    stop_conditions: Dict[str, Any] = field(default_factory=dict)
    annotations: List[str] = field(default_factory=list)
    model: str = ""
    router_overrides: Dict[str, Any] = field(default_factory=dict)
    # Disaggregation: set by the decode worker when forwarding to prefill.
    disagg_params: Dict[str, Any] = field(default_factory=dict)
    # Multimodal: image data URLs extracted from chat content parts; the
    # EncodeOperator (multimodal.py) turns them into embedding features.
    image_urls: List[str] = field(default_factory=list)
    # Guided decoding: normalized constraint spec ({"kind": "regex",
    # "pattern": ...}) the worker's engine compiles to a token FSM
    # (llm/guided). Built by the preprocessor from response_format /
    # tool_choice / nvext guided_* — the wire stays text-free.
    guided_decoding: Optional[Dict[str, Any]] = None
    # Capacity-ledger attribution: resolved by the frontend (`user` field →
    # x-dynamo-tenant header → API-key hash → "anon") and billed by the
    # worker scheduler (runtime/ledger.py).
    tenant: str = "anon"

    def to_wire(self) -> dict:
        d = {
            "token_ids": self.token_ids,
            "sampling_options": self.sampling_options,
            "stop_conditions": self.stop_conditions,
            "annotations": self.annotations,
            "model": self.model,
            "router_overrides": self.router_overrides,
            "disagg_params": self.disagg_params,
            "tenant": self.tenant,
        }
        if self.image_urls:
            d["_mm_image_urls"] = self.image_urls
        if self.guided_decoding:
            d["guided_decoding"] = self.guided_decoding
        return d

    @classmethod
    def from_wire(cls, d: dict) -> "PreprocessedRequest":
        return cls(
            token_ids=list(d.get("token_ids") or []),
            sampling_options=d.get("sampling_options") or {},
            stop_conditions=d.get("stop_conditions") or {},
            annotations=list(d.get("annotations") or []),
            model=d.get("model", ""),
            router_overrides=d.get("router_overrides") or {},
            disagg_params=d.get("disagg_params") or {},
            guided_decoding=d.get("guided_decoding"),
            tenant=d.get("tenant") or "anon",
        )


@dataclass
class LLMEngineOutput:
    """Per-step engine emission (ref: protocols/common LLMEngineOutput)."""

    token_ids: List[int] = field(default_factory=list)
    text: Optional[str] = None  # set by the Backend detokenizer
    finish_reason: Optional[str] = None
    cum_log_probs: Optional[float] = None
    logprobs: Optional[List[float]] = None  # per-token chosen logprobs (aligned with token_ids)
    # Per-token top-k alternatives (OpenAI ``top_logprobs``): one
    # [[alt_token_id, logprob], ...] list per token_ids entry.
    top_logprobs: Optional[List[list]] = None
    index: int = 0
    # Set by the Backend parser stage on the final frame (OpenAI wire shape).
    tool_calls: Optional[List[dict]] = None
    reasoning: Optional[str] = None  # reasoning_content delta

    def to_wire(self) -> dict:
        d: Dict[str, Any] = {"token_ids": self.token_ids, "index": self.index}
        if self.text is not None:
            d["text"] = self.text
        if self.finish_reason is not None:
            d["finish_reason"] = self.finish_reason
        if self.cum_log_probs is not None:
            d["cum_log_probs"] = self.cum_log_probs
        if self.logprobs is not None:
            d["logprobs"] = self.logprobs
        if self.top_logprobs is not None:
            d["top_logprobs"] = self.top_logprobs
        if self.tool_calls is not None:
            d["tool_calls"] = self.tool_calls
        if self.reasoning is not None:
            d["reasoning"] = self.reasoning
        return d

    @classmethod
    def from_wire(cls, d: dict) -> "LLMEngineOutput":
        return cls(
            token_ids=list(d.get("token_ids") or []),
            text=d.get("text"),
            finish_reason=d.get("finish_reason"),
            cum_log_probs=d.get("cum_log_probs"),
            logprobs=d.get("logprobs"),
            top_logprobs=d.get("top_logprobs"),
            index=d.get("index", 0),
            tool_calls=d.get("tool_calls"),
            reasoning=d.get("reasoning"),
        )


def as_engine_output(item) -> Optional[LLMEngineOutput]:
    """Normalize a stream item (Annotated wrapper or wire dict) into an
    LLMEngineOutput; None for pure annotations. Shared by the HTTP and gRPC
    frontends so the stream-item convention lives in one place."""
    if isinstance(item, Annotated):
        if item.data is None:
            return None
        return LLMEngineOutput.from_wire(item.data)
    if isinstance(item, dict):
        return LLMEngineOutput.from_wire(item)
    return None
