"""OpenAI API protocol: request validation + response/chunk builders.

Ref: lib/llm/src/protocols/openai/{chat_completions,completions}/* and the
async-openai fork (lib/async-openai, SURVEY.md N5) — here the wire format is
handled as validated dicts (BYOT-style) rather than a type-per-field fork;
``validate.rs`` checks are mirrored in :func:`validate_chat_request`.

``nvext`` (protocols/openai/nvext.rs) per-request extensions are accepted
under the same key: ``{"nvext": {"annotations": [...], "router": {...}}}``.
"""

from __future__ import annotations

import re
import time
import uuid
from typing import Any, Dict, List, Optional


class RequestError(ValueError):
    """400-class protocol violation."""


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise RequestError(msg)


# Tenant ids feed the capacity ledger's heavy-hitter sketches and come back
# out as Prometheus label values and Grafana legends — cap length and
# charset so an abusive `user` field can't explode label cardinality per
# byte or smuggle control characters into dashboards.
TENANT_MAX_LEN = 64
_TENANT_RE = re.compile(r"^[A-Za-z0-9._:-]+$")


def validate_tenant(value: Any, source: str = "user") -> str:
    """Validate a client-supplied tenant id (OpenAI ``user`` field or the
    ``x-dynamo-tenant`` header). Returns the id; raises a structured 400
    on abuse."""
    _require(isinstance(value, str) and bool(value), f"{source} must be a non-empty string")
    _require(
        len(value) <= TENANT_MAX_LEN,
        f"{source} must be at most {TENANT_MAX_LEN} characters",
    )
    _require(
        _TENANT_RE.match(value) is not None,
        f"{source} may only contain [A-Za-z0-9._:-]",
    )
    return value


def validate_chat_request(body: dict) -> dict:
    _require(isinstance(body, dict), "body must be a JSON object")
    _require(bool(body.get("model")), "missing required field: model")
    messages = body.get("messages")
    _require(isinstance(messages, list) and len(messages) > 0, "messages must be a non-empty array")
    for m in messages:
        _require(isinstance(m, dict) and "role" in m, "each message needs a role")
        _require(m["role"] in ("system", "user", "assistant", "tool", "developer"), f"invalid role {m['role']!r}")
    for key in ("temperature", "top_p", "frequency_penalty", "presence_penalty"):
        v = body.get(key)
        _require(v is None or isinstance(v, (int, float)), f"{key} must be a number")
    t = body.get("temperature")
    _require(t is None or 0.0 <= t <= 2.0, "temperature must be in [0, 2]")
    tp = body.get("top_p")
    _require(tp is None or 0.0 < tp <= 1.0, "top_p must be in (0, 1]")
    mt = body.get("max_tokens") or body.get("max_completion_tokens")
    _require(mt is None or (isinstance(mt, int) and mt > 0), "max_tokens must be a positive integer")
    _validate_common_sampling(body)
    lp = body.get("logprobs")
    _require(lp is None or isinstance(lp, bool), "logprobs must be a boolean")
    tlp = body.get("top_logprobs")
    _require(
        tlp is None or (isinstance(tlp, int) and 0 <= tlp <= 20),
        "top_logprobs must be an integer in [0, 20]",
    )
    _require(tlp is None or bool(lp), "top_logprobs requires logprobs: true")
    stop = body.get("stop")
    _require(
        stop is None or isinstance(stop, str) or (isinstance(stop, list) and all(isinstance(s, str) for s in stop)),
        "stop must be a string or array of strings",
    )
    _validate_response_format(body)
    _validate_tools(body)
    _validate_tool_choice(body)
    return body


RESPONSE_FORMAT_TYPES = ("text", "json_object", "json_schema")


def _validate_response_format(body: dict) -> None:
    """Structural response_format checks (ref: validate.rs response_format).
    Schema *compilability* is checked by the preprocessor's grammar build —
    both layers raise RequestError, so malformed constraints are always a
    structured 400, never a 500."""
    rf = body.get("response_format")
    if rf is None:
        return
    _require(
        isinstance(rf, dict) and isinstance(rf.get("type"), str),
        "response_format must be an object with a string 'type'",
    )
    _require(
        rf["type"] in RESPONSE_FORMAT_TYPES,
        f"response_format.type must be one of {list(RESPONSE_FORMAT_TYPES)}",
    )
    if rf["type"] == "json_schema":
        js = rf.get("json_schema")
        _require(isinstance(js, dict), "response_format.json_schema must be an object")
        _require(
            isinstance(js.get("schema"), dict),
            "response_format.json_schema.schema is required and must be an object",
        )
        name = js.get("name")
        _require(name is None or isinstance(name, str), "json_schema.name must be a string")


def _validate_tools(body: dict) -> None:
    tools = body.get("tools")
    if tools is None:
        return
    _require(isinstance(tools, list), "tools must be an array")
    for t in tools:
        _require(
            isinstance(t, dict) and t.get("type") == "function" and isinstance(t.get("function"), dict),
            "each tool must be {type: 'function', function: {...}}",
        )
        fn = t["function"]
        _require(isinstance(fn.get("name"), str) and bool(fn["name"]), "tool function.name is required")
        params = fn.get("parameters")
        _require(params is None or isinstance(params, dict), "tool function.parameters must be an object")


def _tool_names(body: dict) -> List[str]:
    return [
        (t.get("function") or {}).get("name")
        for t in (body.get("tools") or [])
        if isinstance(t, dict)
    ]


def _validate_tool_choice(body: dict) -> None:
    tc = body.get("tool_choice")
    if tc is None:
        return
    if isinstance(tc, str):
        _require(
            tc in ("none", "auto", "required"),
            "tool_choice must be 'none', 'auto', 'required', or {type:'function',function:{name}}",
        )
        _require(
            tc != "required" or bool(body.get("tools")),
            "tool_choice 'required' needs a non-empty tools array",
        )
        return
    _require(
        isinstance(tc, dict)
        and tc.get("type") == "function"
        and isinstance(tc.get("function"), dict)
        and isinstance(tc["function"].get("name"), str),
        "named tool_choice must be {type: 'function', function: {name: ...}}",
    )
    name = tc["function"]["name"]
    _require(
        name in _tool_names(body),
        f"tool_choice names unknown tool {name!r}",
    )


MAX_N = 8  # per-request choice fan-out cap (each choice is a full generation)


def _validate_common_sampling(body: dict) -> None:
    _validate_guided_ext(body)
    # Per-request deadline in seconds: the frontend turns it into a wire
    # deadline budget (stop_conditions.deadline_ms) that the scheduler
    # enforces by evicting past-deadline rows — expiry is a 504, not a hang.
    to = body.get("timeout")
    _require(
        to is None or (isinstance(to, (int, float)) and not isinstance(to, bool) and 0 < to <= 3600),
        "timeout must be a number of seconds in (0, 3600]",
    )
    n = body.get("n")
    _require(
        n is None or (isinstance(n, int) and 1 <= n <= MAX_N),
        f"n must be an integer in [1, {MAX_N}]",
    )
    seed = body.get("seed")
    _require(seed is None or isinstance(seed, int), "seed must be an integer")
    user = body.get("user")
    if user is not None:
        validate_tenant(user, "user")
    lb = body.get("logit_bias")
    if lb is not None:
        _require(isinstance(lb, dict), "logit_bias must be an object mapping token ids to bias")
        for k, v in lb.items():
            _require(
                isinstance(k, (str, int)) and str(k).lstrip("-").isdigit(),
                "logit_bias keys must be token ids",
            )
            _require(
                isinstance(v, (int, float)) and not isinstance(v, bool) and -100.0 <= v <= 100.0,
                "logit_bias values must be numbers in [-100, 100]",
            )


def _validate_guided_ext(body: dict) -> None:
    """nvext guided-decoding extensions (guided_regex / guided_choice /
    guided_json) — structural checks; at most one constraint per request."""
    nv = body.get("nvext") or {}
    gr = nv.get("guided_regex")
    _require(gr is None or (isinstance(gr, str) and bool(gr)), "nvext.guided_regex must be a non-empty string")
    gc = nv.get("guided_choice")
    _require(
        gc is None
        or (isinstance(gc, list) and len(gc) > 0 and all(isinstance(c, str) and c for c in gc)),
        "nvext.guided_choice must be a non-empty array of strings",
    )
    gj = nv.get("guided_json")
    _require(gj is None or isinstance(gj, dict), "nvext.guided_json must be a schema object")
    _require(
        sum(x is not None for x in (gr, gc, gj)) <= 1,
        "at most one nvext guided_* constraint per request",
    )


def validate_completion_request(body: dict) -> dict:
    _require(isinstance(body, dict), "body must be a JSON object")
    _require(bool(body.get("model")), "missing required field: model")
    prompt = body.get("prompt")
    _require(
        isinstance(prompt, str)
        or (isinstance(prompt, list) and all(isinstance(p, (str, int)) for p in prompt)),
        "prompt must be a string, array of strings, or array of token ids",
    )
    _validate_common_sampling(body)
    lp = body.get("logprobs")
    _require(
        lp is None or (isinstance(lp, int) and 0 <= lp <= 5),
        "logprobs must be an integer in [0, 5]",
    )
    return body


def sampling_from_request(body: dict) -> Dict[str, Any]:
    out = {
        k: body.get(k)
        for k in ("temperature", "top_p", "top_k", "seed", "frequency_penalty", "presence_penalty")
        if body.get(k) is not None
    }
    # Chat uses a boolean, completions an int count; either turns on
    # chosen-token logprobs engine-side. Completions ``logprobs: 0`` still
    # returns chosen-token logprobs (OpenAI semantics) — only absent/False
    # means off.
    lp = body.get("logprobs")
    if lp is not None and lp is not False:
        out["logprobs"] = True
    tlp = body.get("top_logprobs")
    if tlp:
        out["top_logprobs"] = int(tlp)
    elif isinstance(lp, int) and not isinstance(lp, bool) and lp > 0:
        # Completions: the logprobs int doubles as the top-k alternatives
        # count (OpenAI legacy semantics).
        out["top_logprobs"] = int(lp)
    lb = body.get("logit_bias")
    if lb:
        # Normalize keys to ints for the wire (OpenAI clients send strings).
        out["logit_bias"] = {int(k): float(v) for k, v in lb.items()}
    return out


def stop_conditions_from_request(body: dict, eos_token_ids: Optional[List[int]] = None) -> Dict[str, Any]:
    stop = body.get("stop")
    if isinstance(stop, str):
        stop = [stop]
    return {
        "max_tokens": body.get("max_tokens") or body.get("max_completion_tokens"),
        "min_tokens": body.get("min_tokens"),
        "stop": stop or [],
        "stop_token_ids": body.get("stop_token_ids") or [],
        "ignore_eos": bool((body.get("nvext") or {}).get("ignore_eos", False)),
    }


# --- response builders ------------------------------------------------------


def make_id(prefix: str = "chatcmpl") -> str:
    return f"{prefix}-{uuid.uuid4().hex[:24]}"


def _top_entries(alts: Optional[list]) -> List[dict]:
    """Alternative-token entries for one position from the wire shape
    [[alt_token_id, logprob], ...]. Alternatives are identified by token id
    (``token_id:<n>``): per-alternative detokenization is not meaningful for
    tokens that were never generated into the stream, and the id form is
    lossless where a context-free decode of a lone id is not."""
    if not alts:
        return []
    return [
        {"token": f"token_id:{int(tid)}", "logprob": float(lp), "bytes": None}
        for tid, lp in alts
    ]


def chat_logprobs_content(
    text: Optional[str], logprobs: List[float], top_logprobs: Optional[List[list]] = None
) -> dict:
    """Chat logprobs block for one delta/message: one entry per generated
    token (chosen-token logprob; ``top_logprobs`` alternatives populated when
    the engine computed them — wire shape [[alt_token_id, logprob], ...] per
    token, aligned with ``logprobs``)."""
    toks = [text] if (text and len(logprobs) == 1) else [""] * len(logprobs)
    tops = top_logprobs or []
    return {
        "content": [
            {
                "token": t,
                "logprob": lp,
                "bytes": list(t.encode()) if t else None,
                "top_logprobs": _top_entries(tops[i] if i < len(tops) else None),
            }
            for i, (t, lp) in enumerate(zip(toks, logprobs))
        ]
    }


def completion_logprobs_block(
    texts: List[str], logprobs: List[float], top_logprobs: Optional[List[list]] = None
) -> dict:
    """Completions-style logprobs arrays (tokens / token_logprobs).
    ``text_offset`` is omitted: per-token character offsets are not tracked
    through streaming detokenization, and an empty array misaligned with
    ``tokens`` is worse for zip/index consumers than absence.
    ``top_logprobs`` is the legacy per-position dict-of-alternatives form
    when the engine computed them, else None."""
    tops = None
    if top_logprobs:
        tops = [
            {e["token"]: e["logprob"] for e in _top_entries(alts)}
            for alts in top_logprobs
        ]
        # Pad to alignment with tokens if the engine emitted fewer positions.
        while len(tops) < len(logprobs):
            tops.append({})
    return {
        "tokens": texts,
        "token_logprobs": logprobs,
        "top_logprobs": tops,
    }


def chat_chunk(
    rid: str,
    model: str,
    delta: dict,
    finish_reason: Optional[str] = None,
    usage: Optional[dict] = None,
    index: int = 0,
    logprobs: Optional[dict] = None,
) -> dict:
    choice = {"index": index, "delta": delta, "finish_reason": finish_reason}
    if logprobs is not None:
        choice["logprobs"] = logprobs
    out = {
        "id": rid,
        "object": "chat.completion.chunk",
        "created": int(time.time()),
        "model": model,
        "choices": [choice],
    }
    if usage is not None:
        out["usage"] = usage
    return out


def chat_choice(
    index: int,
    text: str,
    finish_reason: str,
    tool_calls: Optional[list] = None,
    reasoning: Optional[str] = None,
    logprobs: Optional[dict] = None,
) -> dict:
    message: dict = {"role": "assistant", "content": text}
    if tool_calls:
        message["tool_calls"] = tool_calls
        message["content"] = text or None
    if reasoning:
        message["reasoning_content"] = reasoning
    choice = {"index": index, "message": message, "finish_reason": finish_reason}
    if logprobs is not None:
        choice["logprobs"] = logprobs
    return choice


def chat_response_multi(rid: str, model: str, choices: List[dict], usage: dict) -> dict:
    return {
        "id": rid,
        "object": "chat.completion",
        "created": int(time.time()),
        "model": model,
        "choices": choices,
        "usage": usage,
    }


def chat_response(
    rid: str,
    model: str,
    text: str,
    finish_reason: str,
    usage: dict,
    tool_calls: Optional[list] = None,
    reasoning: Optional[str] = None,
    logprobs: Optional[dict] = None,
) -> dict:
    return chat_response_multi(
        rid, model,
        [chat_choice(0, text, finish_reason, tool_calls, reasoning, logprobs)],
        usage,
    )


def completion_chunk(
    rid: str,
    model: str,
    text: str,
    finish_reason: Optional[str] = None,
    index: int = 0,
    logprobs: Optional[dict] = None,
) -> dict:
    choice = {"index": index, "text": text, "finish_reason": finish_reason}
    if logprobs is not None:
        choice["logprobs"] = logprobs
    return {
        "id": rid,
        "object": "text_completion",
        "created": int(time.time()),
        "model": model,
        "choices": [choice],
    }


def completion_choice(
    index: int, text: str, finish_reason: str, logprobs: Optional[dict] = None
) -> dict:
    choice = {"index": index, "text": text, "finish_reason": finish_reason}
    if logprobs is not None:
        choice["logprobs"] = logprobs
    return choice


def completion_response_multi(rid: str, model: str, choices: List[dict], usage: dict) -> dict:
    return {
        "id": rid,
        "object": "text_completion",
        "created": int(time.time()),
        "model": model,
        "choices": choices,
        "usage": usage,
    }


def completion_response(
    rid: str, model: str, text: str, finish_reason: str, usage: dict,
    logprobs: Optional[dict] = None,
) -> dict:
    return completion_response_multi(
        rid, model, [completion_choice(0, text, finish_reason, logprobs)], usage
    )


def usage_dict(
    prompt_tokens: int,
    completion_tokens: int,
    cached_tokens: Optional[int] = None,
    tenant: Optional[str] = None,
) -> dict:
    """OpenAI usage block. ``cached_tokens`` (engine-reported prefix-cache
    reuse) renders as ``prompt_tokens_details.cached_tokens`` when known —
    the OpenAI prompt-caching wire shape. ``tenant`` echoes the resolved
    tenant id the capacity ledger billed this request under."""
    out = {
        "prompt_tokens": prompt_tokens,
        "completion_tokens": completion_tokens,
        "total_tokens": prompt_tokens + completion_tokens,
    }
    if cached_tokens is not None:
        out["prompt_tokens_details"] = {"cached_tokens": int(cached_tokens)}
    if tenant is not None:
        out["tenant"] = tenant
    return out


def error_body(message: str, err_type: str = "invalid_request_error", code: int = 400) -> dict:
    return {"error": {"message": message, "type": err_type, "code": code}}


# --- embeddings (ref: openai.rs:369, protocols/openai/embeddings) -----------


def validate_embedding_request(body: dict) -> dict:
    _require(isinstance(body, dict), "body must be a JSON object")
    _require(bool(body.get("model")), "missing required field: model")
    inp = body.get("input")
    ok = isinstance(inp, str) or (
        isinstance(inp, list)
        and len(inp) > 0
        and (
            all(isinstance(x, str) for x in inp)
            or all(isinstance(x, int) for x in inp)
            or all(isinstance(x, list) and all(isinstance(t, int) for t in x) for x in inp)
        )
    )
    _require(ok, "input must be a string, array of strings, or array(s) of token ids")
    return body


def embedding_response(rid: str, model: str, vectors: list, usage: dict) -> dict:
    return {
        "id": rid,
        "object": "list",
        "model": model,
        "data": [
            {"object": "embedding", "index": i, "embedding": v} for i, v in enumerate(vectors)
        ],
        "usage": usage,
    }


# --- responses API (ref: openai.rs:714, protocols/openai/responses.rs) ------


def validate_responses_request(body: dict) -> dict:
    _require(isinstance(body, dict), "body must be a JSON object")
    _require(bool(body.get("model")), "missing required field: model")
    inp = body.get("input")
    _require(
        isinstance(inp, str) or (isinstance(inp, list) and len(inp) > 0),
        "input must be a string or a non-empty array",
    )
    return body


def responses_input_to_messages(body: dict) -> list:
    """Convert Responses-API input (+ optional instructions) to chat
    messages. Raises RequestError on malformed input items."""
    messages = []
    if body.get("instructions"):
        messages.append({"role": "system", "content": body["instructions"]})
    inp = body.get("input")
    if isinstance(inp, str):
        messages.append({"role": "user", "content": inp})
        return messages
    for item in inp:
        if isinstance(item, str):
            messages.append({"role": "user", "content": item})
            continue
        _require(isinstance(item, dict), "input items must be strings or objects")
        role = item.get("role", "user")
        content = item.get("content", "")
        if isinstance(content, list):  # content parts → concatenated text
            content = "".join(
                p.get("text", "") for p in content if isinstance(p, dict) and p.get("type") in ("input_text", "output_text", "text")
            )
        messages.append({"role": role, "content": content})
    return messages


def responses_text_format_to_response_format(body: dict) -> Optional[dict]:
    """Responses-API structured outputs → chat ``response_format``. The
    Responses API nests the format flat under ``text.format``
    (``{type: 'json_schema', name, schema}``); chat nests it under
    ``response_format.json_schema``. A chat-shaped ``response_format`` on
    the body passes through unchanged."""
    txt = body.get("text")
    fmt = txt.get("format") if isinstance(txt, dict) else None
    if isinstance(fmt, dict) and fmt.get("type"):
        if fmt["type"] == "json_schema":
            return {
                "type": "json_schema",
                "json_schema": {k: fmt[k] for k in ("name", "schema", "strict") if k in fmt},
            }
        return {"type": fmt["type"]}
    rf = body.get("response_format")
    return rf if isinstance(rf, dict) else None


def responses_tool_choice_to_chat(tc):
    """Responses-API flat named tool_choice (``{type:'function', name}``) →
    chat shape; strings and chat-shaped dicts pass through."""
    if isinstance(tc, dict) and tc.get("type") == "function" and "function" not in tc and tc.get("name"):
        return {"type": "function", "function": {"name": tc["name"]}}
    return tc


def responses_tools_to_chat(tools: Optional[list]) -> list:
    """Responses-API tool definitions (flat ``{type:'function', name, ...}``)
    → chat-completions shape (``{type, function:{...}}``). Chat-shaped items
    pass through unchanged."""
    out = []
    for t in tools or []:
        if not isinstance(t, dict):
            continue
        if isinstance(t.get("function"), dict):
            out.append(t)
        elif t.get("type") == "function" and t.get("name"):
            fn = {k: t[k] for k in ("name", "description", "parameters", "strict") if k in t}
            out.append({"type": "function", "function": fn})
    return out


def responses_message_item(rid: str, text: str, status: str = "completed") -> dict:
    return {
        "type": "message",
        "id": f"msg-{rid}",
        "role": "assistant",
        "status": status,
        "content": [{"type": "output_text", "text": text, "annotations": []}],
    }


def responses_function_call_item(rid: str, idx: int, call: dict) -> dict:
    """Chat tool_call dict → Responses function_call output item."""
    fn = call.get("function") or {}
    return {
        "type": "function_call",
        "id": f"fc-{rid}-{idx}",
        "call_id": call.get("id") or f"call-{rid}-{idx}",
        "name": fn.get("name", ""),
        "arguments": fn.get("arguments", ""),
        "status": "completed",
    }


def responses_envelope(
    rid: str, model: str, output: list, usage: Optional[dict] = None, status: str = "completed"
) -> dict:
    usage = usage or {}
    return {
        "id": rid,
        "object": "response",
        "created_at": int(time.time()),
        "model": model,
        "status": status,
        "output": output,
        "usage": {
            "input_tokens": usage.get("prompt_tokens", 0),
            "output_tokens": usage.get("completion_tokens", 0),
            "total_tokens": usage.get("total_tokens", 0),
        },
    }


def responses_response(
    rid: str, model: str, text: str, usage: dict, status: str = "completed",
    tool_calls: Optional[list] = None,
) -> dict:
    output = []
    if text or not tool_calls:
        output.append(responses_message_item(rid, text, status))
    for i, call in enumerate(tool_calls or []):
        output.append(responses_function_call_item(rid, i, call))
    return responses_envelope(rid, model, output, usage, status)
