"""Wire protocols: OpenAI API types + internal request/response shapes
(ref: lib/llm/src/protocols — SURVEY.md §2b)."""
