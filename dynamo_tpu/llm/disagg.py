"""Disaggregated prefill/decode serving + worker-to-worker KV transfer.

Ref: SURVEY.md §3C — the decode worker receives the request, forwards a
``max_tokens=1`` prefill request (``do_remote_decode``) to a prefill worker,
the KV blocks move worker→worker, and decode continues from the transferred
KV. In the reference the transfer is NIXL RDMA under vLLM connectors; here
it is the TCP response plane carrying raw block bytes (same call-home
machinery as response streams), with the descriptor exchange
(``kv_transfer_params``) riding the normal response stream — the
``RdmaMetadata`` role (lib/bindings nixl_connect:1417). On multi-host TPU
slices the byte transport swaps for ICI/DCN device-to-device transfer
without changing this protocol.

Conditional disaggregation: prompts shorter than
``max_local_prefill_length`` prefill locally (ref: disagg_router.rs:13-250
``DisaggRouterConf`` watched from the store — dynamic config plane).
"""

from __future__ import annotations

import asyncio
import json
import time
from dataclasses import asdict, dataclass
from typing import Any, AsyncIterator, List, Optional, Tuple

import msgpack
import numpy as np

from dynamo_tpu.runtime.client import Client
from dynamo_tpu.runtime.component import Instance
from dynamo_tpu.runtime.engine import Annotated, Context
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.push_router import NoInstancesError, PushRouter, RouterMode
from dynamo_tpu.runtime.transports.tcp import ConnectionInfo, TcpCallHome
from dynamo_tpu.runtime.work_queue import WorkQueue

logger = get_logger(__name__)

DISAGG_CONF_PREFIX = "public/components/disagg_router/models"


@dataclass
class DisaggRouterConf:
    """Dynamic conditional-disagg config (ref: disagg_router.rs
    DisaggRouterConf{max_local_prefill_length})."""

    max_local_prefill_length: int = 0  # 0 ⇒ always remote when prefill pool exists

    @staticmethod
    def store_key(model_type: str, model: str) -> str:
        return f"{DISAGG_CONF_PREFIX}/{model_type}/{model}"


class DisaggRouter:
    """Local-vs-remote prefill decision, hot-reloaded from the store."""

    def __init__(self, drt, model: str, model_type: str = "chat", conf: Optional[DisaggRouterConf] = None):
        self.drt = drt
        self.key = DisaggRouterConf.store_key(model_type, model)
        self.conf = conf or DisaggRouterConf()
        self._task: Optional[asyncio.Task] = None

    async def start(self) -> None:
        entry = await self.drt.store.get(self.key)
        if entry is not None:
            self._apply(entry.value)
        _, watch = await self.drt.store.get_and_watch_prefix(self.key)
        self._watch = watch

        async def loop():
            async for ev in watch:
                if ev.value is not None:
                    self._apply(ev.value)

        self._task = asyncio.get_running_loop().create_task(loop())

    def _apply(self, raw: bytes) -> None:
        try:
            d = json.loads(raw)
            self.conf = DisaggRouterConf(max_local_prefill_length=int(d.get("max_local_prefill_length", 0)))
            logger.info("disagg conf updated: %s", self.conf)
        except (ValueError, TypeError):
            logger.warning("bad disagg conf at %s", self.key)

    def prefill_remote(self, prompt_len: int, prefill_available: bool) -> bool:
        if not prefill_available:
            return False
        return prompt_len > self.conf.max_local_prefill_length

    async def stop(self) -> None:
        if self._task is not None:
            await self._watch.cancel()
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass


# ---------------------------------------------------------------------------
# KV transfer plane
# ---------------------------------------------------------------------------


def kvx_subject(instance: Instance) -> str:
    return f"kvx.{instance.subject[3:]}"  # rq.<rest> → kvx.<rest>


# Process-local exporter registry: a decode worker colocated with the
# prefill worker hands KV over entirely on device, skipping wire + transfer
# server (the NIXL same-node NVLink role).
_LOCAL_EXPORTERS: dict = {}


class KvExportService:
    """Prefill-worker side: serves KV pull requests over the data plane."""

    def __init__(self, drt, engine, instance: Instance):
        self.drt = drt
        self.engine = engine
        self.subject = kvx_subject(instance)
        self._task: Optional[asyncio.Task] = None
        self._reap_tasks: set = set()

    async def start(self) -> None:
        _LOCAL_EXPORTERS[self.subject] = self
        sub = await self.drt.bus.subscribe(self.subject)

        async def loop():
            async for msg in sub:
                try:
                    req = msgpack.unpackb(msg.data, raw=False)
                except Exception:
                    continue
                asyncio.get_running_loop().create_task(self._serve_pull(req))

        self._sub = sub
        self._task = asyncio.get_running_loop().create_task(loop())

    async def _serve_pull(self, req: dict) -> None:
        call_home = TcpCallHome(ConnectionInfo.from_dict(req["conn"]))
        try:
            if not await call_home.connect():
                return
            if req.get("mode") == "device":
                await self._serve_pull_device(req, call_home)
                return
            export = await self.engine.take_export(req["request_id"])
            if export is None:
                await call_home.error(f"no export for {req['request_id']}")
                return
            blocks, hashes, prompt_len = export
            for i, (k_np, v_np) in enumerate(blocks):
                header = {
                    "seq": i,
                    "total": len(blocks),
                    "shape": list(k_np.shape),
                    "dtype": str(k_np.dtype),
                    "prompt_len": prompt_len,
                }
                body = k_np.tobytes() + v_np.tobytes()
                await call_home.send(header, body)
            await call_home.complete()
        except ConnectionError:
            logger.warning("kv export pull dropped for %s", req.get("request_id"))
        finally:
            await call_home.close()

    async def _serve_pull_device(self, req: dict, call_home: TcpCallHome) -> None:
        """Device-native pull: blocks stay on the accelerator. We offer the
        stacked export on the transfer plane and send only the rendezvous
        metadata down the wire; the decode worker pulls device-to-device
        (ref: NIXL one-sided GET under vllm handlers.py:153-204)."""
        from dynamo_tpu.llm.block_manager.device_transfer import get_plane

        rid = req["request_id"]
        export = await self.engine.take_export_device(rid)
        if export is None:
            await call_home.error(f"no export for {rid}")
            return
        (k_stack, v_stack), _hashes, prompt_len = export
        plane = get_plane()
        arrays = [k_stack] if v_stack is None else [k_stack, v_stack]
        meta = await asyncio.to_thread(plane.offer, rid, arrays)
        ack_sub = await self.drt.bus.subscribe(f"kvx_ack.{rid}")
        await call_home.send(
            {"seq": 0, "total": 1, "mode": "device", "meta": meta,
             "prompt_len": prompt_len, "has_v": v_stack is not None},
            b"",
        )
        await call_home.complete()

        async def reap():
            # Hold the offered buffers until the consumer acks the pull (or
            # a TTL passes — consumer died mid-pull).
            try:
                await ack_sub.next(timeout=60.0)
            finally:
                plane.release_offer(rid)
                await ack_sub.unsubscribe()

        # Keep a strong reference: the loop holds only weak refs to tasks, so
        # an un-referenced reap task can be GC'd mid-await, leaking the
        # offered device buffers and the ack subscription.
        task = asyncio.get_running_loop().create_task(reap())
        self._reap_tasks.add(task)
        task.add_done_callback(self._reap_tasks.discard)

    async def stop(self) -> None:
        _LOCAL_EXPORTERS.pop(self.subject, None)
        if self._task is not None:
            await self._sub.unsubscribe()
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        # Cancel pending reaps so offered buffers / ack subscriptions don't
        # outlive the service by the reap TTL (their finally blocks release).
        for task in list(self._reap_tasks):
            task.cancel()
        if self._reap_tasks:
            await asyncio.gather(*self._reap_tasks, return_exceptions=True)


async def pull_kv_blocks(drt, instance: Instance, request_id: str) -> List[Tuple[np.ndarray, np.ndarray]]:
    """Decode-worker side: pull the prefilled KV blocks for ``request_id``
    from the prefill worker that computed them (host-numpy wire path)."""
    conn_info, pending = drt.tcp_server_handle().register()
    await drt.bus.publish(
        kvx_subject(instance),
        msgpack.packb({"request_id": request_id, "conn": conn_info.to_dict()}, use_bin_type=True),
    )
    blocks: List[Tuple[np.ndarray, np.ndarray]] = []
    try:
        async for frame in pending.frames():
            if frame.kind == "data":
                shape = tuple(frame.header["shape"])
                dtype = np.dtype(frame.header["dtype"])
                half = len(frame.body) // 2
                k = np.frombuffer(frame.body[:half], dtype=dtype).reshape(shape)
                v = np.frombuffer(frame.body[half:], dtype=dtype).reshape(shape)
                blocks.append((k, v))
            elif frame.kind == "error":
                raise RuntimeError(frame.header.get("message", "kv pull failed"))
    finally:
        drt.tcp_server_handle().unregister(conn_info.stream_id)
    return blocks


async def pull_kv_blocks_device(drt, instance: Instance, request_id: str):
    """Device-native pull: request rendezvous metadata over the control
    wire, then one-sided device-to-device pull via the transfer plane.
    Returns (k_stack, v_stack|None) device arrays."""
    from dynamo_tpu.llm.block_manager.device_transfer import get_plane

    # Same-process exporter: hand the stacked device arrays over directly —
    # no wire, no transfer server, zero host bytes.
    svc = _LOCAL_EXPORTERS.get(kvx_subject(instance))
    if svc is not None:
        export = await svc.engine.take_export_device(request_id)
        if export is None:
            raise RuntimeError(f"no export for {request_id}")
        (k_stack, v_stack), _hashes, _plen = export
        return k_stack, v_stack

    conn_info, pending = drt.tcp_server_handle().register()
    await drt.bus.publish(
        kvx_subject(instance),
        msgpack.packb(
            {"request_id": request_id, "conn": conn_info.to_dict(), "mode": "device"},
            use_bin_type=True,
        ),
    )
    meta = None
    has_v = True
    try:
        async for frame in pending.frames():
            if frame.kind == "data":
                meta = frame.header["meta"]
                has_v = bool(frame.header.get("has_v", True))
            elif frame.kind == "error":
                raise RuntimeError(frame.header.get("message", "kv pull failed"))
    finally:
        drt.tcp_server_handle().unregister(conn_info.stream_id)
    if meta is None:
        raise RuntimeError("device kv pull: no rendezvous metadata received")
    plane = get_plane()
    arrays = await asyncio.to_thread(plane.pull, meta)
    await drt.bus.publish(f"kvx_ack.{request_id}", b"1")
    if has_v:
        return arrays[0], arrays[1]
    return arrays[0], None


# ---------------------------------------------------------------------------
# Decode-worker handler
# ---------------------------------------------------------------------------


PREFILL_QUEUE = "prefill"


async def _first_token_of(stream) -> int:
    """Consume a prefill response stream; return its first emitted token.

    The prefill role emits exactly one token (max_tokens=1); shared by the
    push and queue strategies so the output convention lives in one place."""
    first: Optional[int] = None
    async for item in stream:
        data = item.data if isinstance(item, Annotated) else item
        if first is None and data and data.get("token_ids"):
            first = data["token_ids"][0]
    if first is None:
        raise RuntimeError("prefill returned no token")
    return first


class PrefillQueueWorker:
    """Prefill-first strategy, worker side (ref: trtllm
    request_handlers/handler_base.py:42-55 ``DisaggregationStrategy``
    prefill_first + the NatsQueue prefill-queue path, _core.pyi:894): pull
    prefill jobs from the shared durable queue, run them on the local
    engine, and reply to the decode worker's inbox subject. The KV blocks
    stay registered for pull under the job's request id, exactly as in the
    push path."""

    def __init__(self, drt, engine, instance: Instance, queue_name: str = PREFILL_QUEUE,
                 lease_id: Optional[int] = None):
        self.drt = drt
        self.engine = engine
        self.instance = instance
        self.queue_name = queue_name
        self.lease_id = lease_id
        self.queue = WorkQueue(drt.store, drt.bus, queue_name, lease_id=lease_id)
        self.jobs_served = 0
        self._task: Optional[asyncio.Task] = None
        self._stop = asyncio.Event()

    async def start(self) -> None:
        # Advertise liveness so decode workers only enqueue when someone can
        # pull (leased ⇒ the registration dies with us).
        await self.drt.store.put(
            f"wq/{self.queue_name}/workers/{self.instance.instance_id:x}",
            b"",
            lease_id=self.lease_id,
        )
        self._task = asyncio.get_running_loop().create_task(self._loop())

    async def _loop(self) -> None:
        while not self._stop.is_set():
            item = await self.queue.dequeue(timeout=0.2)
            if item is None:
                continue
            try:
                await self._serve_job(item.data)
            except Exception:  # noqa: BLE001 — one bad job must not kill the loop
                logger.exception("prefill queue job failed")
            finally:
                await item.ack()

    async def _serve_job(self, raw: bytes) -> None:
        job = json.loads(raw)
        reply_subject = job["reply_subject"]
        reply = {"request_id": job.get("request_id")}
        # The decode worker gave up at expires_at (wall clock): running the
        # job after that would prefill into the void and pin KV blocks until
        # the export TTL reclaims them — skip instead.
        expires_at = job.get("expires_at")
        if expires_at is not None and time.time() > expires_at:
            logger.warning("dropping expired prefill job %s", reply["request_id"])
            return
        try:
            ctx = Context(id=job["request_id"])
            first_token = await _first_token_of(self.engine.generate(job["request"], ctx))
            reply.update(first_token=first_token, instance=asdict(self.instance))
            self.jobs_served += 1
        except Exception as e:  # noqa: BLE001 — error crosses the wire
            reply["error"] = str(e)
        await self.drt.bus.publish(reply_subject, json.dumps(reply).encode())

    async def stop(self) -> None:
        self._stop.set()
        if self._task is not None:
            await self._task


class DisaggDecodeHandler:
    """The decode worker's endpoint handler (ref: vllm handlers.py:135):
    conditionally forwards prefill to the prefill pool, pulls KV, then runs
    local decode from the injected cache.

    ``strategy`` picks how prefill work reaches the pool (ref: trtllm
    handler_base.py:42-55): ``decode_first`` pushes directly to a chosen
    prefill instance; ``prefill_first`` enqueues on the shared durable queue
    and lets any prefill worker pull it."""

    def __init__(
        self,
        drt,
        engine,
        prefill_client: Optional[Client] = None,
        disagg_router: Optional[DisaggRouter] = None,
        strategy: str = "decode_first",
        prefill_queue_name: str = PREFILL_QUEUE,
        queue_reply_timeout_s: float = 30.0,
        kv_transfer: str = "device",
        pool_load_probe: Optional[Any] = None,
        block_size: int = 16,
    ):
        if strategy not in ("decode_first", "prefill_first"):
            raise ValueError(f"unknown disagg strategy: {strategy}")
        if kv_transfer not in ("device", "host"):
            raise ValueError(f"unknown kv_transfer mode: {kv_transfer}")
        # "device": blocks move accelerator-to-accelerator (in-process direct
        # handoff, else jax transfer server — the NIXL path). "host": numpy
        # over the TCP response plane (debug / heterogeneous fallback).
        self.kv_transfer = kv_transfer
        self.drt = drt
        self.engine = engine
        self.prefill_client = prefill_client
        self.prefill_router = PushRouter(prefill_client, RouterMode.ROUND_ROBIN) if prefill_client else None
        self.disagg_router = disagg_router
        self.strategy = strategy
        self.queue = (
            WorkQueue(drt.store, drt.bus, prefill_queue_name) if strategy == "prefill_first" else None
        )
        self.queue_reply_timeout_s = queue_reply_timeout_s
        self.prefill_queue_name = prefill_queue_name
        self.remote_prefills = 0
        self.local_prefills = 0
        # Elastic degradation ladder: an optional load probe (sync or async
        # callable returning {"prefill_saturated": bool, "local_saturated":
        # bool, "max_prefill_tokens": int|None}) lets the handler degrade
        # PROACTIVELY — a saturated prefill pool routes to the co-located
        # mixed batch (and a saturated local engine offloads to the pool)
        # instead of queueing. None ⇒ reactive-only (pre-elastic behavior).
        self.pool_load_probe = pool_load_probe
        self.block_size = max(1, int(block_size))
        self.degrade_disagg_to_colocated_total = 0
        self.degrade_colocated_to_disagg_total = 0
        # Token-boundary splits: prefill leg truncated to N tokens on the
        # pool, remainder prefilled on the decode worker (partial KV inject).
        self.split_prefills_total = 0
        # prefill_first liveness: cached queue-worker presence + timeout
        # backoff, so a pool with zero pull workers doesn't cost every request
        # the full queue_reply_timeout_s of TTFT before local fallback.
        self._liveness_cache: Tuple[float, bool] = (0.0, False)
        self._liveness_ttl_s = 2.0
        self._backoff_until = 0.0
        self.queue_backoff_s = 15.0

    async def _pool_load(self) -> dict:
        if self.pool_load_probe is None:
            return {}
        try:
            res = self.pool_load_probe()
            if asyncio.iscoroutine(res) or isinstance(res, asyncio.Future):
                res = await res
            return res or {}
        except Exception:  # noqa: BLE001 — a broken probe must not fail serving
            logger.exception("pool load probe failed; treating as no signal")
            return {}

    def _mode_transition(self, context: Context, direction: str, reason: str, **kw) -> None:
        """Trace a degradation-ladder step (observable mode transitions are
        part of the elastic contract — chaos asserts them, Grafana counts
        the paired degrade_*_total counters)."""
        tp = context.traceparent
        if tp is None:
            return
        from dynamo_tpu.runtime.tracing import get_tracer

        get_tracer().event(
            "mode_transition", tp.trace_id, parent_id=tp.parent_id,
            service="worker", request_id=context.id, direction=direction,
            reason=reason, **kw,
        )

    async def can_prefill_remote(self) -> bool:
        if self.strategy == "prefill_first":
            now = time.monotonic()
            if now < self._backoff_until:
                return False
            ts, alive = self._liveness_cache
            if now - ts > self._liveness_ttl_s:
                workers = await self.drt.store.get_prefix(f"wq/{self.prefill_queue_name}/workers/")
                alive = bool(workers)
                self._liveness_cache = (now, alive)
            return alive
        return self.prefill_router is not None and bool(self.prefill_client.instances)

    async def _prefill_via_push(self, prefill_req: dict, prefill_ctx: Context) -> Tuple[int, Instance]:
        instance_id = self.prefill_router.select()
        instance = self.prefill_client.instances[instance_id]
        first_token = await _first_token_of(
            self.prefill_router.generate(prefill_req, prefill_ctx, instance_id=instance_id)
        )
        return first_token, instance

    async def _prefill_via_queue(self, prefill_req: dict, prefill_ctx: Context) -> Tuple[int, Instance]:
        reply_subject = f"prefill_reply.{prefill_ctx.id}"
        sub = await self.drt.bus.subscribe(reply_subject)
        try:
            await self.queue.enqueue(json.dumps({
                "request": prefill_req,
                "request_id": prefill_ctx.id,
                "reply_subject": reply_subject,
                "expires_at": time.time() + self.queue_reply_timeout_s,
            }).encode())
            msg = await sub.next(timeout=self.queue_reply_timeout_s)
        finally:
            await sub.unsubscribe()
        if msg is None:
            raise RuntimeError("prefill queue reply timed out")
        reply = json.loads(msg.data)
        if reply.get("error"):
            raise RuntimeError(f"queued prefill failed: {reply['error']}")
        return reply["first_token"], Instance(**reply["instance"])

    async def generate(self, request: Any, context: Context) -> AsyncIterator[dict]:
        tokens = list(request.get("token_ids") or [])
        can_remote = await self.can_prefill_remote()
        remote = (
            self.disagg_router.prefill_remote(len(tokens), can_remote)
            if self.disagg_router is not None
            else can_remote
        )
        # Elastic degradation ladder (proactive rungs): the load probe can
        # override the length rule in BOTH directions before any wire hop —
        # a saturated prefill pool sends this request to the co-located
        # mixed batch instead of queueing behind the pool; a saturated local
        # engine offloads its prefill to an idle pool. Every flip is counted
        # and traced so chaos/bench can assert the ladder, not infer it.
        load = await self._pool_load()
        split_at = 0
        if remote and load.get("prefill_saturated"):
            remote = False
            self.degrade_disagg_to_colocated_total += 1
            self._mode_transition(context, "disagg_to_colocated", "prefill_pool_saturated",
                                  prompt_tokens=len(tokens))
        elif not remote and can_remote and load.get("local_saturated"):
            remote = True
            self.degrade_colocated_to_disagg_total += 1
            self._mode_transition(context, "colocated_to_disagg", "local_saturated",
                                  prompt_tokens=len(tokens))
        if remote:
            # Token-boundary split: the pool takes only the first N tokens
            # (request-pinned split_at, else the probe's remaining prefill
            # headroom rounded down to a block boundary); the decode worker
            # finishes the remainder via partial KV injection + chunked
            # prefill. N ≥ block_size so the transferred KV is non-empty.
            dp = request.get("disagg_params") or {}
            split_at = int(dp.get("split_at") or 0)
            cap = load.get("max_prefill_tokens")
            if split_at <= 0 and cap is not None and 0 < int(cap) < len(tokens):
                split_at = (int(cap) // self.block_size) * self.block_size
            if split_at < self.block_size or split_at >= len(tokens):
                split_at = 0

        if not remote:
            self.local_prefills += 1
            async for item in self.engine.generate(request, context):
                yield item
            return

        self.remote_prefills += 1
        leg_start = time.monotonic()
        # 1) Forward prefill (max_tokens=1, keep blocks) to the prefill pool.
        prefill_req = dict(request)
        if split_at:
            prefill_req["token_ids"] = tokens[:split_at]
            self.split_prefills_total += 1
        prefill_req["stop_conditions"] = {**(request.get("stop_conditions") or {}), "max_tokens": 1, "ignore_eos": True}
        prefill_req["disagg_params"] = {"do_remote_decode": True}
        prefill_ctx = context.child()  # same request id crosses the wire
        tp = context.traceparent
        if tp is not None:
            from dynamo_tpu.runtime.tracing import get_tracer

            get_tracer().event(
                "disagg_hop", tp.trace_id, parent_id=tp.parent_id, service="worker",
                request_id=context.id, prompt_tokens=len(tokens),
                strategy=self.strategy, kv_transfer=self.kv_transfer,
                split_at=split_at,
            )

        try:
            if self.strategy == "prefill_first":
                first_token, instance = await self._prefill_via_queue(prefill_req, prefill_ctx)
            else:
                first_token, instance = await self._prefill_via_push(prefill_req, prefill_ctx)
            # 2) Pull the KV blocks (the NIXL-transfer step).
            if self.kv_transfer == "device":
                device_blocks = await pull_kv_blocks_device(self.drt, instance, prefill_ctx.id)
                blocks = None
            else:
                blocks = await pull_kv_blocks(self.drt, instance, prefill_ctx.id)
        except (NoInstancesError, ConnectionError, RuntimeError) as e:
            # Prefill pool failed — degrade to local prefill (availability
            # over disagg, matching the reference's fallback). A queue-reply
            # timeout means registered workers aren't actually pulling: back
            # off so subsequent requests skip straight to local.
            if self.strategy == "prefill_first" and "timed out" in str(e):
                self._backoff_until = time.monotonic() + self.queue_backoff_s
            logger.warning("remote prefill failed (%s); running locally", e)
            self.degrade_disagg_to_colocated_total += 1
            self._mode_transition(context, "disagg_to_colocated", f"remote_prefill_failed:{e}",
                                  prompt_tokens=len(tokens))
            self.local_prefills += 1
            async for item in self.engine.generate(request, context):
                yield item
            return

        # 3) Continue decode locally from the injected KV.
        local_req = dict(request)
        prefilled = {"first_token": first_token}
        if blocks is not None:
            prefilled["blocks"] = blocks
        else:
            prefilled["device_blocks"] = device_blocks
        if split_at:
            # Partial leg: the scheduler resumes chunked prefill at split_at
            # and samples its OWN first token there — the pool leg's token
            # (sampled from a truncated prompt) is discarded by the injector.
            prefilled["prefill_len"] = split_at
        local_req["_prefilled"] = prefilled
        # Deadline folding: deadline_ms is the REMAINING budget at arrival,
        # and the decode leg re-arrives at its local engine after the prefill
        # hop + KV pull — without folding, a split/remote request would be
        # granted the hop time twice over a single-worker serve.
        stop = dict(request.get("stop_conditions") or {})
        if stop.get("deadline_ms"):
            elapsed_ms = (time.monotonic() - leg_start) * 1000.0
            stop["deadline_ms"] = max(1.0, float(stop["deadline_ms"]) - elapsed_ms)
            local_req["stop_conditions"] = stop
        async for item in self.engine.generate(local_req, context):
            yield item

    def stats_handler(self) -> dict:
        base = self.engine.stats_handler() if hasattr(self.engine, "stats_handler") else {}
        return {
            **base,
            "remote_prefills": self.remote_prefills,
            "local_prefills": self.local_prefills,
            "degrade_disagg_to_colocated_total": self.degrade_disagg_to_colocated_total,
            "degrade_colocated_to_disagg_total": self.degrade_colocated_to_disagg_total,
            "split_prefills_total": self.split_prefills_total,
        }
