"""GGUF metadata parsing (pure Python, read-only).

Ref: lib/llm/src/gguf/ (~900 LoC) — the reference parses GGUF container
metadata to build ModelDeploymentCards for llama.cpp models (context length,
tokenizer, architecture). Same role here: read the header, metadata KV table
and tensor directory without loading tensor data.

Format (gguf v2/v3, little-endian):
  magic "GGUF" | version u32 | tensor_count u64 | metadata_kv_count u64
  kv: key(string) type(u32) value          string: len u64 + utf8 bytes
  tensor: name(string) n_dims(u32) dims(u64 × n) ggml_type(u32) offset(u64)
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Any, BinaryIO, Dict, List, Optional

GGUF_MAGIC = b"GGUF"

# Metadata value types.
T_UINT8, T_INT8, T_UINT16, T_INT16, T_UINT32, T_INT32 = 0, 1, 2, 3, 4, 5
T_FLOAT32, T_BOOL, T_STRING, T_ARRAY, T_UINT64, T_INT64, T_FLOAT64 = 6, 7, 8, 9, 10, 11, 12

_SCALAR_FMT = {
    T_UINT8: "<B", T_INT8: "<b", T_UINT16: "<H", T_INT16: "<h",
    T_UINT32: "<I", T_INT32: "<i", T_FLOAT32: "<f",
    T_UINT64: "<Q", T_INT64: "<q", T_FLOAT64: "<d",
}

# ggml tensor dtypes we care to name (subset; unknown ids stay numeric).
GGML_TYPE_NAMES = {
    0: "f32", 1: "f16", 2: "q4_0", 3: "q4_1", 6: "q5_0", 7: "q5_1",
    8: "q8_0", 9: "q8_1", 10: "q2_k", 11: "q3_k", 12: "q4_k", 13: "q5_k",
    14: "q6_k", 15: "q8_k", 16: "iq2_xxs", 17: "iq2_xs", 18: "iq3_xxs",
    24: "i8", 25: "i16", 26: "i32", 27: "i64", 28: "f64", 30: "bf16",
}


class GgufError(ValueError):
    pass


@dataclass
class GgufTensorInfo:
    name: str
    shape: List[int]
    ggml_type: int
    offset: int

    @property
    def dtype_name(self) -> str:
        return GGML_TYPE_NAMES.get(self.ggml_type, f"ggml_{self.ggml_type}")


@dataclass
class GgufMetadata:
    version: int
    metadata: Dict[str, Any] = field(default_factory=dict)
    tensors: List[GgufTensorInfo] = field(default_factory=list)
    # Absolute file offset of the (aligned) tensor-data section; tensor
    # offsets are relative to this.
    data_start: int = 0

    # --- convenience accessors the MDC builder uses -------------------------
    @property
    def architecture(self) -> Optional[str]:
        return self.metadata.get("general.architecture")

    @property
    def model_name(self) -> Optional[str]:
        return self.metadata.get("general.name")

    def arch_field(self, suffix: str) -> Any:
        """Read ``{arch}.{suffix}`` (e.g. context_length, block_count)."""
        arch = self.architecture
        return self.metadata.get(f"{arch}.{suffix}") if arch else None

    @property
    def context_length(self) -> Optional[int]:
        return self.arch_field("context_length")

    @property
    def num_layers(self) -> Optional[int]:
        return self.arch_field("block_count")

    @property
    def tokenizer_model(self) -> Optional[str]:
        return self.metadata.get("tokenizer.ggml.model")

    @property
    def tokens(self) -> Optional[list]:
        return self.metadata.get("tokenizer.ggml.tokens")

    @property
    def chat_template(self) -> Optional[str]:
        return self.metadata.get("tokenizer.chat_template")


def _read(f: BinaryIO, n: int) -> bytes:
    data = f.read(n)
    if len(data) != n:
        raise GgufError(f"truncated GGUF file: wanted {n} bytes, got {len(data)}")
    return data


def _read_scalar(f: BinaryIO, fmt: str):
    return struct.unpack(fmt, _read(f, struct.calcsize(fmt)))[0]


def _read_string(f: BinaryIO) -> str:
    n = _read_scalar(f, "<Q")
    if n > 1 << 32:
        raise GgufError(f"implausible string length {n}")
    return _read(f, n).decode("utf-8", errors="replace")


def _read_value(f: BinaryIO, vtype: int, *, max_array: int):
    if vtype in _SCALAR_FMT:
        return _read_scalar(f, _SCALAR_FMT[vtype])
    if vtype == T_BOOL:
        return _read_scalar(f, "<B") != 0
    if vtype == T_STRING:
        return _read_string(f)
    if vtype == T_ARRAY:
        etype = _read_scalar(f, "<I")
        count = _read_scalar(f, "<Q")
        if count > max_array:
            raise GgufError(f"array too large ({count} > {max_array})")
        return [_read_value(f, etype, max_array=max_array) for _ in range(count)]
    raise GgufError(f"unknown GGUF value type {vtype}")


def parse_gguf(path: str, *, max_array: int = 1 << 24) -> GgufMetadata:
    """Parse header + metadata + tensor directory (no tensor data reads)."""
    with open(path, "rb") as f:
        if _read(f, 4) != GGUF_MAGIC:
            raise GgufError(f"{path}: not a GGUF file")
        version = _read_scalar(f, "<I")
        if version not in (2, 3):
            raise GgufError(f"unsupported GGUF version {version}")
        tensor_count = _read_scalar(f, "<Q")
        kv_count = _read_scalar(f, "<Q")
        meta = GgufMetadata(version=version)
        for _ in range(kv_count):
            key = _read_string(f)
            vtype = _read_scalar(f, "<I")
            meta.metadata[key] = _read_value(f, vtype, max_array=max_array)
        for _ in range(tensor_count):
            name = _read_string(f)
            n_dims = _read_scalar(f, "<I")
            if n_dims > 8:
                raise GgufError(f"implausible tensor rank {n_dims}")
            shape = [_read_scalar(f, "<Q") for _ in range(n_dims)]
            ggml_type = _read_scalar(f, "<I")
            offset = _read_scalar(f, "<Q")
            meta.tensors.append(GgufTensorInfo(name=name, shape=shape, ggml_type=ggml_type, offset=offset))
        align = int(meta.metadata.get("general.alignment", 32) or 32)
        pos = f.tell()
        meta.data_start = (pos + align - 1) // align * align
        return meta


# --- tensor data loading ----------------------------------------------------
# Real-valued + q8_0 + k-quant coverage (q4_k/q5_k/q6_k are what most
# published GGUF checkpoints actually ship as — ref: lib/llm/src/gguf/ +
# lib/engines/llamacpp serve the full llama.cpp range). Remaining exotic
# quants (iq*, q2/q3_k) raise — convert externally.

GGML_F32, GGML_F16, GGML_Q8_0, GGML_BF16 = 0, 1, 8, 30
GGML_Q4_K, GGML_Q5_K, GGML_Q6_K = 12, 13, 14
QK_K = 256  # k-quant super-block size

# Bytes per QK_K super-block: q4_k = d,dmin(2×f16) + scales(12) + qs(128);
# q5_k adds qh(32); q6_k = ql(128) + qh(64) + scales(16×i8) + d(f16).
_KQUANT_BLOCK_BYTES = {GGML_Q4_K: 144, GGML_Q5_K: 176, GGML_Q6_K: 210}


def _tensor_nbytes(info: GgufTensorInfo) -> int:
    import math

    n = math.prod(info.shape) if info.shape else 1
    if info.ggml_type in (GGML_F16, GGML_BF16):
        return n * 2
    if info.ggml_type == GGML_F32:
        return n * 4
    if info.ggml_type == GGML_Q8_0:
        if n % 32:
            raise GgufError(f"{info.name}: q8_0 needs multiple-of-32 elements")
        return (n // 32) * 34  # f16 scale + 32 int8 codes per block
    if info.ggml_type in _KQUANT_BLOCK_BYTES:
        if n % QK_K:
            raise GgufError(f"{info.name}: k-quants need multiple-of-{QK_K} elements")
        return (n // QK_K) * _KQUANT_BLOCK_BYTES[info.ggml_type]
    raise GgufError(
        f"{info.name}: unsupported tensor dtype {info.dtype_name} "
        "(supported: f32, f16, bf16, q8_0, q4_k, q5_k, q6_k)"
    )


def _scale_min_k4(scales):
    """Unpack q4_k/q5_k packed 6-bit (scale, min) pairs: [nb, 12] uint8 →
    two [nb, 8] float32 arrays (llama.cpp get_scale_min_k4 layout)."""
    import numpy as np

    s = scales.astype(np.uint8)
    sc = np.empty(s.shape[:-1] + (8,), np.float32)
    mn = np.empty_like(sc)
    sc[..., :4] = (s[..., 0:4] & 63).astype(np.float32)
    mn[..., :4] = (s[..., 4:8] & 63).astype(np.float32)
    sc[..., 4:] = ((s[..., 8:12] & 0xF) | ((s[..., 0:4] >> 6) << 4)).astype(np.float32)
    mn[..., 4:] = ((s[..., 8:12] >> 4) | ((s[..., 4:8] >> 6) << 4)).astype(np.float32)
    return sc, mn


def _dequant_q4_k(raw):
    import numpy as np

    b = np.frombuffer(raw, dtype=np.uint8).reshape(-1, 144)
    d = b[:, 0:2].copy().view(np.float16).astype(np.float32)  # [nb, 1]
    dmin = b[:, 2:4].copy().view(np.float16).astype(np.float32)
    sc, mn = _scale_min_k4(b[:, 4:16])  # [nb, 8]
    qs = b[:, 16:144]  # [nb, 128] — nibbles for 8 sub-blocks of 32
    lo = (qs & 0xF).astype(np.float32).reshape(-1, 4, 32)  # sub-blocks 0,2,4,6
    hi = (qs >> 4).astype(np.float32).reshape(-1, 4, 32)  # sub-blocks 1,3,5,7
    out = np.empty((b.shape[0], 8, 32), np.float32)
    out[:, 0::2] = d[:, :, None] * sc[:, 0::2, None] * lo - dmin[:, :, None] * mn[:, 0::2, None]
    out[:, 1::2] = d[:, :, None] * sc[:, 1::2, None] * hi - dmin[:, :, None] * mn[:, 1::2, None]
    return out.reshape(-1)


def _dequant_q5_k(raw):
    import numpy as np

    b = np.frombuffer(raw, dtype=np.uint8).reshape(-1, 176)
    d = b[:, 0:2].copy().view(np.float16).astype(np.float32)
    dmin = b[:, 2:4].copy().view(np.float16).astype(np.float32)
    sc, mn = _scale_min_k4(b[:, 4:16])
    qh = b[:, 16:48]  # [nb, 32] — one high bit per element per 32-lane
    qs = b[:, 48:176]  # [nb, 128]
    lo = (qs & 0xF).astype(np.uint8).reshape(-1, 4, 32)
    hi = (qs >> 4).astype(np.uint8).reshape(-1, 4, 32)
    out = np.empty((b.shape[0], 8, 32), np.float32)
    for j in range(4):  # 64-element chunks; qh bit pairs (2j, 2j+1)
        h1 = ((qh >> (2 * j)) & 1).astype(np.uint8)  # [nb, 32]
        h2 = ((qh >> (2 * j + 1)) & 1).astype(np.uint8)
        q1 = (lo[:, j] | (h1 << 4)).astype(np.float32)
        q2 = (hi[:, j] | (h2 << 4)).astype(np.float32)
        out[:, 2 * j] = d * sc[:, 2 * j : 2 * j + 1] * q1 - dmin * mn[:, 2 * j : 2 * j + 1]
        out[:, 2 * j + 1] = d * sc[:, 2 * j + 1 : 2 * j + 2] * q2 - dmin * mn[:, 2 * j + 1 : 2 * j + 2]
    return out.reshape(-1)


def _dequant_q6_k(raw):
    import numpy as np

    b = np.frombuffer(raw, dtype=np.uint8).reshape(-1, 210)
    ql = b[:, 0:128].reshape(-1, 2, 64)  # two 128-element halves
    qh = b[:, 128:192].reshape(-1, 2, 32)
    sc = b[:, 192:208].copy().view(np.int8).astype(np.float32).reshape(-1, 2, 8)
    d = b[:, 208:210].copy().view(np.float16).astype(np.float32)  # [nb, 1]
    out = np.empty((b.shape[0], 2, 4, 32), np.float32)
    for half in range(2):
        l_lo = (ql[:, half, :32] & 0xF).astype(np.int16)
        l2_lo = (ql[:, half, 32:] & 0xF).astype(np.int16)
        l_hi = (ql[:, half, :32] >> 4).astype(np.int16)
        l2_hi = (ql[:, half, 32:] >> 4).astype(np.int16)
        h = qh[:, half].astype(np.int16)
        q1 = (l_lo | ((h & 3) << 4)) - 32
        q2 = (l2_lo | (((h >> 2) & 3) << 4)) - 32
        q3 = (l_hi | (((h >> 4) & 3) << 4)) - 32
        q4 = (l2_hi | (((h >> 6) & 3) << 4)) - 32
        # scale index: l//16 + {0,2,4,6} over the 8 per-half scales
        s = sc[:, half]  # [nb, 8]
        for qi, (q, off) in enumerate(((q1, 0), (q2, 2), (q3, 4), (q4, 6))):
            scale = np.repeat(s[:, off : off + 2], 16, axis=1)  # [nb, 32]
            out[:, half, qi] = d * scale * q.astype(np.float32)
    return out.reshape(-1)


def read_tensor(f: BinaryIO, meta: GgufMetadata, info: GgufTensorInfo):
    """Read one tensor as float32 numpy, shaped with ggml's ne reversed
    (ne[0] is the contiguous dim), i.e. matrices come out HF-style
    ``[out, in]``."""
    import numpy as np

    f.seek(meta.data_start + info.offset)
    raw = _read(f, _tensor_nbytes(info))
    shape = tuple(reversed(info.shape)) if info.shape else ()
    if info.ggml_type == GGML_F32:
        arr = np.frombuffer(raw, dtype=np.float32)
    elif info.ggml_type == GGML_F16:
        arr = np.frombuffer(raw, dtype=np.float16).astype(np.float32)
    elif info.ggml_type == GGML_BF16:
        u = np.frombuffer(raw, dtype=np.uint16).astype(np.uint32) << 16
        arr = u.view(np.float32)
    elif info.ggml_type == GGML_Q4_K:
        arr = _dequant_q4_k(raw)
    elif info.ggml_type == GGML_Q5_K:
        arr = _dequant_q5_k(raw)
    elif info.ggml_type == GGML_Q6_K:
        arr = _dequant_q6_k(raw)
    else:  # q8_0
        blocks = np.frombuffer(raw, dtype=np.uint8).reshape(-1, 34)
        scales = blocks[:, :2].copy().view(np.float16).astype(np.float32)  # [nb, 1]
        codes = blocks[:, 2:].copy().view(np.int8).astype(np.float32)  # [nb, 32]
        arr = (codes * scales).reshape(-1)
    return arr.reshape(shape)


def load_tensors(path: str, names: Optional[List[str]] = None) -> Dict[str, Any]:
    """Load (a subset of) a GGUF file's tensors as f32 numpy arrays."""
    meta = parse_gguf(path)
    want = set(names) if names is not None else None
    out: Dict[str, Any] = {}
    with open(path, "rb") as f:
        for info in meta.tensors:
            if want is not None and info.name not in want:
                continue
            out[info.name] = read_tensor(f, meta, info)
    return out
