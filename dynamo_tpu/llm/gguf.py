"""GGUF metadata parsing (pure Python, read-only).

Ref: lib/llm/src/gguf/ (~900 LoC) — the reference parses GGUF container
metadata to build ModelDeploymentCards for llama.cpp models (context length,
tokenizer, architecture). Same role here: read the header, metadata KV table
and tensor directory without loading tensor data.

Format (gguf v2/v3, little-endian):
  magic "GGUF" | version u32 | tensor_count u64 | metadata_kv_count u64
  kv: key(string) type(u32) value          string: len u64 + utf8 bytes
  tensor: name(string) n_dims(u32) dims(u64 × n) ggml_type(u32) offset(u64)
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Any, BinaryIO, Dict, List, Optional

GGUF_MAGIC = b"GGUF"

# Metadata value types.
T_UINT8, T_INT8, T_UINT16, T_INT16, T_UINT32, T_INT32 = 0, 1, 2, 3, 4, 5
T_FLOAT32, T_BOOL, T_STRING, T_ARRAY, T_UINT64, T_INT64, T_FLOAT64 = 6, 7, 8, 9, 10, 11, 12

_SCALAR_FMT = {
    T_UINT8: "<B", T_INT8: "<b", T_UINT16: "<H", T_INT16: "<h",
    T_UINT32: "<I", T_INT32: "<i", T_FLOAT32: "<f",
    T_UINT64: "<Q", T_INT64: "<q", T_FLOAT64: "<d",
}

# ggml tensor dtypes we care to name (subset; unknown ids stay numeric).
GGML_TYPE_NAMES = {
    0: "f32", 1: "f16", 2: "q4_0", 3: "q4_1", 6: "q5_0", 7: "q5_1",
    8: "q8_0", 9: "q8_1", 10: "q2_k", 11: "q3_k", 12: "q4_k", 13: "q5_k",
    14: "q6_k", 15: "q8_k", 16: "iq2_xxs", 17: "iq2_xs", 18: "iq3_xxs",
    24: "i8", 25: "i16", 26: "i32", 27: "i64", 28: "f64", 30: "bf16",
}


class GgufError(ValueError):
    pass


@dataclass
class GgufTensorInfo:
    name: str
    shape: List[int]
    ggml_type: int
    offset: int

    @property
    def dtype_name(self) -> str:
        return GGML_TYPE_NAMES.get(self.ggml_type, f"ggml_{self.ggml_type}")


@dataclass
class GgufMetadata:
    version: int
    metadata: Dict[str, Any] = field(default_factory=dict)
    tensors: List[GgufTensorInfo] = field(default_factory=list)
    # Absolute file offset of the (aligned) tensor-data section; tensor
    # offsets are relative to this.
    data_start: int = 0

    # --- convenience accessors the MDC builder uses -------------------------
    @property
    def architecture(self) -> Optional[str]:
        return self.metadata.get("general.architecture")

    @property
    def model_name(self) -> Optional[str]:
        return self.metadata.get("general.name")

    def arch_field(self, suffix: str) -> Any:
        """Read ``{arch}.{suffix}`` (e.g. context_length, block_count)."""
        arch = self.architecture
        return self.metadata.get(f"{arch}.{suffix}") if arch else None

    @property
    def context_length(self) -> Optional[int]:
        return self.arch_field("context_length")

    @property
    def num_layers(self) -> Optional[int]:
        return self.arch_field("block_count")

    @property
    def tokenizer_model(self) -> Optional[str]:
        return self.metadata.get("tokenizer.ggml.model")

    @property
    def tokens(self) -> Optional[list]:
        return self.metadata.get("tokenizer.ggml.tokens")

    @property
    def chat_template(self) -> Optional[str]:
        return self.metadata.get("tokenizer.chat_template")


def _read(f: BinaryIO, n: int) -> bytes:
    data = f.read(n)
    if len(data) != n:
        raise GgufError(f"truncated GGUF file: wanted {n} bytes, got {len(data)}")
    return data


def _read_scalar(f: BinaryIO, fmt: str):
    return struct.unpack(fmt, _read(f, struct.calcsize(fmt)))[0]


def _read_string(f: BinaryIO) -> str:
    n = _read_scalar(f, "<Q")
    if n > 1 << 32:
        raise GgufError(f"implausible string length {n}")
    return _read(f, n).decode("utf-8", errors="replace")


def _read_value(f: BinaryIO, vtype: int, *, max_array: int):
    if vtype in _SCALAR_FMT:
        return _read_scalar(f, _SCALAR_FMT[vtype])
    if vtype == T_BOOL:
        return _read_scalar(f, "<B") != 0
    if vtype == T_STRING:
        return _read_string(f)
    if vtype == T_ARRAY:
        etype = _read_scalar(f, "<I")
        count = _read_scalar(f, "<Q")
        if count > max_array:
            raise GgufError(f"array too large ({count} > {max_array})")
        return [_read_value(f, etype, max_array=max_array) for _ in range(count)]
    raise GgufError(f"unknown GGUF value type {vtype}")


def parse_gguf(path: str, *, max_array: int = 1 << 24) -> GgufMetadata:
    """Parse header + metadata + tensor directory (no tensor data reads)."""
    with open(path, "rb") as f:
        if _read(f, 4) != GGUF_MAGIC:
            raise GgufError(f"{path}: not a GGUF file")
        version = _read_scalar(f, "<I")
        if version not in (2, 3):
            raise GgufError(f"unsupported GGUF version {version}")
        tensor_count = _read_scalar(f, "<Q")
        kv_count = _read_scalar(f, "<Q")
        meta = GgufMetadata(version=version)
        for _ in range(kv_count):
            key = _read_string(f)
            vtype = _read_scalar(f, "<I")
            meta.metadata[key] = _read_value(f, vtype, max_array=max_array)
        for _ in range(tensor_count):
            name = _read_string(f)
            n_dims = _read_scalar(f, "<I")
            if n_dims > 8:
                raise GgufError(f"implausible tensor rank {n_dims}")
            shape = [_read_scalar(f, "<Q") for _ in range(n_dims)]
            ggml_type = _read_scalar(f, "<I")
            offset = _read_scalar(f, "<Q")
            meta.tensors.append(GgufTensorInfo(name=name, shape=shape, ggml_type=ggml_type, offset=offset))
        align = int(meta.metadata.get("general.alignment", 32) or 32)
        pos = f.tell()
        meta.data_start = (pos + align - 1) // align * align
        return meta


# --- tensor data loading ----------------------------------------------------
# Real-valued + q8_0 coverage: what llama.cpp emits for f32/f16/bf16 exports
# and the simplest quantized format. Other quants raise (convert externally).

GGML_F32, GGML_F16, GGML_Q8_0, GGML_BF16 = 0, 1, 8, 30


def _tensor_nbytes(info: GgufTensorInfo) -> int:
    import math

    n = math.prod(info.shape) if info.shape else 1
    if info.ggml_type in (GGML_F16, GGML_BF16):
        return n * 2
    if info.ggml_type == GGML_F32:
        return n * 4
    if info.ggml_type == GGML_Q8_0:
        if n % 32:
            raise GgufError(f"{info.name}: q8_0 needs multiple-of-32 elements")
        return (n // 32) * 34  # f16 scale + 32 int8 codes per block
    raise GgufError(
        f"{info.name}: unsupported tensor dtype {info.dtype_name} "
        "(supported: f32, f16, bf16, q8_0)"
    )


def read_tensor(f: BinaryIO, meta: GgufMetadata, info: GgufTensorInfo):
    """Read one tensor as float32 numpy, shaped with ggml's ne reversed
    (ne[0] is the contiguous dim), i.e. matrices come out HF-style
    ``[out, in]``."""
    import numpy as np

    f.seek(meta.data_start + info.offset)
    raw = _read(f, _tensor_nbytes(info))
    shape = tuple(reversed(info.shape)) if info.shape else ()
    if info.ggml_type == GGML_F32:
        arr = np.frombuffer(raw, dtype=np.float32)
    elif info.ggml_type == GGML_F16:
        arr = np.frombuffer(raw, dtype=np.float16).astype(np.float32)
    elif info.ggml_type == GGML_BF16:
        u = np.frombuffer(raw, dtype=np.uint16).astype(np.uint32) << 16
        arr = u.view(np.float32)
    else:  # q8_0
        blocks = np.frombuffer(raw, dtype=np.uint8).reshape(-1, 34)
        scales = blocks[:, :2].copy().view(np.float16).astype(np.float32)  # [nb, 1]
        codes = blocks[:, 2:].copy().view(np.int8).astype(np.float32)  # [nb, 32]
        arr = (codes * scales).reshape(-1)
    return arr.reshape(shape)


def load_tensors(path: str, names: Optional[List[str]] = None) -> Dict[str, Any]:
    """Load (a subset of) a GGUF file's tensors as f32 numpy arrays."""
    meta = parse_gguf(path)
    want = set(names) if names is not None else None
    out: Dict[str, Any] = {}
    with open(path, "rb") as f:
        for info in meta.tensors:
            if want is not None and info.name not in want:
                continue
            out[info.name] = read_tensor(f, meta, info)
    return out
