"""LLM serving library: protocols, preprocessing, routing, KV block
management, disaggregation (ref: lib/llm — SURVEY.md §2b)."""
