"""C ABI bridge: native-runtime KV event publishing.

Ref: lib/bindings/c/src/lib.rs (326 LoC) — `dynamo_llm_init/shutdown` and
the KV-event publish FFI the reference exposes so TRT-LLM's C++ runtime can
feed the KV router without crossing into Rust-managed async. Here the same
role: a C++ component (custom data loader, native engine runtime) calls the
``extern "C"`` functions in the dynamo_tpu_native extension —

    int dynamo_tpu_llm_init(void);
    int dynamo_tpu_llm_shutdown(void);
    int dynamo_tpu_kv_event_publish_stored(uint64_t worker_id,
        const uint64_t* hashes, size_t n, uint64_t parent, int has_parent);
    int dynamo_tpu_kv_event_publish_removed(uint64_t worker_id,
        const uint64_t* hashes, size_t n);

— without holding the GIL; events land in a mutex-guarded queue inside the
extension, and :class:`NativeKvEventSource` pumps them into the normal
``KvEventPublisher`` → router path.

``load_c_abi()`` returns a ctypes handle to the same functions (what an
out-of-process C client would dlopen), used by tests and as API reference.
"""

from __future__ import annotations

import asyncio
import ctypes
from typing import Optional

from dynamo_tpu.engine.kv_cache import KvEvent
from dynamo_tpu.native import get_native
from dynamo_tpu.runtime.logging import get_logger

logger = get_logger(__name__)


def load_c_abi() -> ctypes.CDLL:
    """ctypes handle to the extension's C ABI (raises if not built)."""
    native = get_native()
    if native is None:
        raise RuntimeError("dynamo_tpu_native extension is not available")
    lib = ctypes.CDLL(native.__file__)
    lib.dynamo_tpu_llm_init.restype = ctypes.c_int
    lib.dynamo_tpu_llm_shutdown.restype = ctypes.c_int
    lib.dynamo_tpu_kv_event_publish_stored.restype = ctypes.c_int
    lib.dynamo_tpu_kv_event_publish_stored.argtypes = [
        ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint64), ctypes.c_size_t,
        ctypes.c_uint64, ctypes.c_int,
    ]
    lib.dynamo_tpu_kv_event_publish_removed.restype = ctypes.c_int
    lib.dynamo_tpu_kv_event_publish_removed.argtypes = [
        ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint64), ctypes.c_size_t,
    ]
    return lib


class NativeKvEventSource:
    """Pump C-ABI-queued KV events into a KvEventPublisher."""

    def __init__(self, publisher, poll_interval_s: float = 0.05):
        self.publisher = publisher
        self.poll_interval_s = poll_interval_s
        self._task: Optional[asyncio.Task] = None
        self._stop = asyncio.Event()
        self.events_pumped = 0

    def start(self) -> None:
        native = get_native()
        if native is None or not hasattr(native, "drain_kv_events"):
            raise RuntimeError("dynamo_tpu_native extension with KV event ABI not available")
        self._native = native
        self._task = asyncio.get_running_loop().create_task(self._loop())

    async def _loop(self) -> None:
        while not self._stop.is_set():
            for ev in self._native.drain_kv_events():
                self.publisher.publish(
                    KvEvent(
                        kind=ev["kind"],
                        block_hashes=ev["block_hashes"],
                        parent_hash=ev["parent_hash"],
                    )
                )
                self.events_pumped += 1
            try:
                await asyncio.wait_for(self._stop.wait(), timeout=self.poll_interval_s)
            except asyncio.TimeoutError:
                pass

    async def stop(self) -> None:
        self._stop.set()
        if self._task is not None:
            await self._task
            self._task = None
