"""Router-side background: consume KV events into the indexer; snapshot +
purge for bounded replay.

Ref: lib/llm/src/kv_router/subscriber.rs:71 ``start_kv_router_background`` —
on startup download the radix snapshot from the object store
(``radix-bucket``, kv_router.rs:69), then consume the durable stream; past
``router_snapshot_threshold`` events, upload a fresh snapshot under the
store lock (``router-snapshot-lock``) and purge the stream so replicas
resync cheaply.
"""

from __future__ import annotations

import asyncio
import json
from typing import Optional

from dynamo_tpu.llm.kv_router.indexer import KvIndexer
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.transports.kvstore import KeyExists

logger = get_logger(__name__)

RADIX_STATE_BUCKET = "radix-bucket"
ROUTER_SNAPSHOT_LOCK = "locks/router-snapshot"


class KvRouterSubscriber:
    def __init__(
        self,
        drt,
        indexer: KvIndexer,
        stream_name: str,
        *,
        snapshot_threshold: int = 1_000_000,
        reset_states: bool = False,
    ):
        self.drt = drt
        self.indexer = indexer
        self.stream_name = stream_name
        self.snapshot_threshold = snapshot_threshold
        self.reset_states = reset_states
        self._task: Optional[asyncio.Task] = None
        self._events_since_snapshot = 0
        self._consumed_seq = 0

    async def start(self) -> None:
        bucket = await self.drt.bus.object_store(RADIX_STATE_BUCKET)
        if self.reset_states:
            await bucket.delete(self.stream_name)
            stream = await self.drt.bus.stream(self.stream_name)
            await stream.purge()
        else:
            snap = await bucket.get(self.stream_name)
            if snap is not None:
                try:
                    self.indexer.load_snapshot(snap)
                    logger.info("restored radix snapshot: %d nodes", self.indexer.size())
                except Exception:
                    logger.exception("radix snapshot restore failed; starting empty")
        self._task = asyncio.get_running_loop().create_task(self._consume())

    async def _consume(self) -> None:
        stream = await self.drt.bus.stream(self.stream_name)
        try:
            async for msg in stream.consume(from_seq=1):
                try:
                    event = json.loads(msg.data)
                    self.indexer.apply_event(int(event["worker_id"]), event)
                except (ValueError, KeyError):
                    logger.warning("malformed kv event on %s", self.stream_name)
                self._consumed_seq = msg.seq
                self._events_since_snapshot += 1
                if self._events_since_snapshot >= self.snapshot_threshold:
                    await self._snapshot(stream)
        except asyncio.CancelledError:
            pass

    async def _snapshot(self, stream) -> None:
        """Upload snapshot + purge, single-writer via a store lock
        (ref: ROUTER_SNAPSHOT_LOCK kv_router.rs:71)."""
        self._events_since_snapshot = 0
        try:
            await self.drt.store.put(ROUTER_SNAPSHOT_LOCK, b"1", create_only=True)
        except KeyExists:
            return  # another replica is snapshotting
        try:
            # Quiesce async appliers (sharded indexer) so the snapshot holds
            # everything up to _consumed_seq before the stream is purged.
            self.indexer.flush()
            bucket = await self.drt.bus.object_store(RADIX_STATE_BUCKET)
            await bucket.put(self.stream_name, self.indexer.dump())
            await stream.purge(up_to_seq=self._consumed_seq)
            logger.info("radix snapshot uploaded (%d nodes), stream purged to %d",
                        self.indexer.size(), self._consumed_seq)
        finally:
            await self.drt.store.delete(ROUTER_SNAPSHOT_LOCK)

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
