"""Radix tree over chained block hashes: which workers hold which prefixes.

Ref: lib/llm/src/kv_router/indexer.rs (2,152 LoC) — ``RadixTree`` (:224),
``KvIndexer`` (:738 single-threaded event applier), ``OverlapScores``,
snapshot/replay (``dump_events``).

Because block hashes chain (each block's hash seeds from its parent's —
``dynamo_tpu.llm.tokens``), a block hash uniquely identifies its whole
prefix. That gives the tree a flat global index (hash → node) for O(1) event
application while ``find_matches`` walks parent→child links for the longest
shared prefix per worker.
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set

WorkerId = int
BlockHash = int


@dataclass
class OverlapScores:
    """Per-worker count of matched prefix blocks (ref: indexer.rs
    OverlapScores)."""

    scores: Dict[WorkerId, int] = field(default_factory=dict)

    def best(self) -> int:
        return max(self.scores.values(), default=0)


class _Node:
    __slots__ = ("block_hash", "workers", "children", "parent", "last_access")

    def __init__(self, block_hash: Optional[BlockHash], parent: Optional["_Node"]):
        self.block_hash = block_hash
        self.workers: Set[WorkerId] = set()
        self.children: Dict[BlockHash, "_Node"] = {}
        self.parent = parent
        self.last_access = time.monotonic()


class RadixTree:
    """The prefix index (ref: indexer.rs:224)."""

    def __init__(self):
        self.root = _Node(None, None)
        self._by_hash: Dict[BlockHash, _Node] = {}
        # Per-worker membership for O(worker) removal on instance death.
        self._worker_nodes: Dict[WorkerId, Set[BlockHash]] = {}

    # --- queries ------------------------------------------------------------
    def find_matches(self, block_hashes: Sequence[BlockHash], early_exit: bool = False) -> OverlapScores:
        """Walk the chain; each worker's score is the depth of the deepest
        node on the path that it holds (contiguous from root by construction)."""
        scores: Dict[WorkerId, int] = {}
        node = self.root
        depth = 0
        for h in block_hashes:
            child = node.children.get(h)
            if child is None:
                break
            depth += 1
            child.last_access = time.monotonic()
            for w in child.workers:
                scores[w] = depth
            node = child
            if early_exit and len(node.children) == 0:
                break
        return OverlapScores(scores=scores)

    def size(self) -> int:
        return len(self._by_hash)

    def workers(self) -> List[WorkerId]:
        return sorted(self._worker_nodes)

    # --- mutation (event application) --------------------------------------
    def apply_stored(
        self, worker: WorkerId, block_hashes: Sequence[BlockHash], parent_hash: Optional[BlockHash]
    ) -> None:
        parent = self.root if parent_hash is None else self._by_hash.get(parent_hash)
        if parent is None:
            # Orphan chain (we missed the parent's event — e.g. joined after
            # snapshot purge): root it so partial matching still works.
            parent = self.root
        node = parent
        for h in block_hashes:
            existing = self._by_hash.get(h)
            if existing is not None:
                node = existing
            else:
                child = node.children.get(h)
                if child is None:
                    child = _Node(h, node)
                    node.children[h] = child
                    self._by_hash[h] = child
                node = child
            node.workers.add(worker)
            self._worker_nodes.setdefault(worker, set()).add(h)

    def apply_removed(self, worker: WorkerId, block_hashes: Sequence[BlockHash]) -> None:
        for h in block_hashes:
            node = self._by_hash.get(h)
            if node is None:
                continue
            node.workers.discard(worker)
            wn = self._worker_nodes.get(worker)
            if wn is not None:
                wn.discard(h)
            self._maybe_prune(node)

    def remove_worker(self, worker: WorkerId) -> None:
        for h in list(self._worker_nodes.get(worker, ())):
            node = self._by_hash.get(h)
            if node is not None:
                node.workers.discard(worker)
                self._maybe_prune(node)
        self._worker_nodes.pop(worker, None)

    def _maybe_prune(self, node: _Node) -> None:
        """Remove leaf nodes no worker holds (cascade toward root)."""
        while node is not self.root and not node.workers and not node.children:
            parent = node.parent
            if parent is not None and node.block_hash is not None:
                parent.children.pop(node.block_hash, None)
            if node.block_hash is not None:
                self._by_hash.pop(node.block_hash, None)
            node = parent if parent is not None else self.root

    # --- snapshot (ref: subscriber.rs radix snapshot to object store) -------
    def dump(self) -> bytes:
        """Serialize as (worker, parent, hashes) chains, BFS order so parents
        restore before children."""
        out = []
        stack = [self.root]
        while stack:
            node = stack.pop()
            for child in node.children.values():
                out.append(
                    {
                        "h": child.block_hash,
                        "p": node.block_hash,
                        "w": sorted(child.workers),
                    }
                )
                stack.append(child)
        return json.dumps(out).encode()

    @classmethod
    def load(cls, raw: bytes) -> "RadixTree":
        return _load_into(cls(), raw)


class NativeRadixTree:
    """Same interface as :class:`RadixTree`, backed by the C++ extension
    (``native/dynamo_tpu_native.cc`` — the equivalent of the reference's
    native indexer.rs hot path)."""

    def __init__(self, _impl=None):
        from dynamo_tpu.native import get_native

        self._impl = _impl if _impl is not None else get_native().RadixTree()

    def find_matches(self, block_hashes: Sequence[BlockHash], early_exit: bool = False) -> OverlapScores:
        return OverlapScores(scores=self._impl.find_matches(list(block_hashes), early_exit=early_exit))

    def size(self) -> int:
        return self._impl.size()

    def workers(self) -> List[WorkerId]:
        return self._impl.workers()

    def apply_stored(
        self, worker: WorkerId, block_hashes: Sequence[BlockHash], parent_hash: Optional[BlockHash]
    ) -> None:
        self._impl.apply_stored(worker, list(block_hashes), parent_hash)

    def apply_removed(self, worker: WorkerId, block_hashes: Sequence[BlockHash]) -> None:
        self._impl.apply_removed(worker, list(block_hashes))

    def remove_worker(self, worker: WorkerId) -> None:
        self._impl.remove_worker(worker)

    def dump(self) -> bytes:
        out = [{"h": h, "p": p, "w": ws} for h, p, ws in self._impl.dump_records()]
        return json.dumps(out).encode()

    @classmethod
    def load(cls, raw: bytes) -> "NativeRadixTree":
        return _load_into(cls(), raw)


def _load_into(tree, raw: bytes):
    """Restore snapshot records (BFS order: parents before children) into any
    tree implementation. One place owns the {"h","p","w"} record schema."""
    for rec in json.loads(raw):
        for w in rec["w"]:
            tree.apply_stored(w, [rec["h"]], rec["p"])
    return tree


def make_radix_tree():
    """Native C++ tree when built, pure-Python fallback otherwise."""
    from dynamo_tpu.native import available

    return NativeRadixTree() if available() else RadixTree()


def load_radix(raw: bytes):
    """Restore a snapshot into whichever tree implementation is active."""
    from dynamo_tpu.native import available

    return NativeRadixTree.load(raw) if available() else RadixTree.load(raw)


class KvIndexer:
    """Single-consumer event applier over a RadixTree (ref: indexer.rs:738).
    All events for one worker must arrive in order; cross-worker order is
    irrelevant (per-worker state is independent)."""

    def __init__(self, block_size: int = 16):
        self.tree = make_radix_tree()
        self.block_size = block_size
        self.events_applied = 0

    def apply_event(self, worker: WorkerId, event: dict) -> None:
        kind = event.get("kind")
        if kind == "stored":
            self.tree.apply_stored(worker, event.get("block_hashes") or [], event.get("parent_hash"))
        elif kind == "removed":
            self.tree.apply_removed(worker, event.get("block_hashes") or [])
        elif kind == "cleared":
            self.tree.remove_worker(worker)
        self.events_applied += 1

    def find_matches(self, block_hashes: Sequence[BlockHash]) -> OverlapScores:
        return self.tree.find_matches(block_hashes)

    def find_matches_for_tokens(self, token_ids: Sequence[int]) -> OverlapScores:
        from dynamo_tpu.llm.tokens import compute_block_hashes

        return self.find_matches(compute_block_hashes(token_ids, self.block_size))

    def remove_worker(self, worker: WorkerId) -> None:
        self.tree.remove_worker(worker)

    # Snapshot surface shared with KvIndexerSharded (subscriber.py calls
    # these so either indexer flavor can sit under the event stream).
    def dump(self) -> bytes:
        return self.tree.dump()

    def load_snapshot(self, raw: bytes) -> None:
        self.tree = load_radix(raw)

    def size(self) -> int:
        return self.tree.size()

    def flush(self) -> None:
        pass  # synchronous applier: nothing queued
