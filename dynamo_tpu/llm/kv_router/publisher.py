"""Worker-side publishers: KV cache events + load metrics.

Ref: lib/llm/src/kv_router/publisher.rs — ``KvEventPublisher`` (:90: engine
KV events → durable stream ``kv_events``) and ``WorkerMetricsPublisher``
(:483: ForwardPassMetrics → ``kv_metrics`` subject + Prometheus).

Subjects/streams (mirroring kv_router.rs:60):
- stream  ``kv_events.{ns}.{component}``   — durable, replayable, snapshotted
- subject ``kv_metrics.{ns}.{component}``  — fire-and-forget load gossip
"""

from __future__ import annotations

import asyncio
import json
from typing import Optional

from dynamo_tpu.engine.kv_cache import KvEvent
from dynamo_tpu.runtime.logging import get_logger

logger = get_logger(__name__)


def kv_events_stream_name(namespace: str, component: str) -> str:
    return f"kv_events.{namespace}.{component}"


def kv_metrics_subject(namespace: str, component: str) -> str:
    return f"kv_metrics.{namespace}.{component}"


class KvEventPublisher:
    """Forwards engine KV events onto the durable stream, stamped with the
    worker id (lease id). Events are queued synchronously (the engine step
    loop must not await) and drained by a background task."""

    def __init__(self, drt, namespace: str, component: str, worker_id: int):
        self.drt = drt
        self.stream_name = kv_events_stream_name(namespace, component)
        self.worker_id = worker_id
        self._queue: "asyncio.Queue[Optional[dict]]" = asyncio.Queue()
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(self._drain())

    def publish(self, event: KvEvent) -> None:
        """Synchronous enqueue — safe to call from the scheduler thread via
        loop.call_soon_threadsafe."""
        self._queue.put_nowait({"worker_id": self.worker_id, **event.to_wire()})

    def publish_threadsafe(self, loop: asyncio.AbstractEventLoop, event: KvEvent) -> None:
        loop.call_soon_threadsafe(self.publish, event)

    async def _drain(self) -> None:
        stream = await self.drt.bus.stream(self.stream_name)
        while True:
            item = await self._queue.get()
            if item is None:
                return
            try:
                await stream.publish(self.stream_name, json.dumps(item).encode())
            except Exception:
                logger.exception("kv event publish failed")

    async def stop(self) -> None:
        if self._task is not None:
            self._queue.put_nowait(None)
            await self._task
            self._task = None


class WorkerMetricsPublisher:
    """Periodically publishes ForwardPassMetrics for scheduler load input +
    busy-threshold gating (ref: publisher.rs:483)."""

    def __init__(self, drt, namespace: str, component: str, worker_id: int, metrics_fn, interval_s: float = 1.0):
        self.drt = drt
        self.subject = kv_metrics_subject(namespace, component)
        self.worker_id = worker_id
        self.metrics_fn = metrics_fn
        self.interval_s = interval_s
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(self._loop())

    async def _loop(self) -> None:
        try:
            while True:
                try:
                    m = self.metrics_fn()
                    payload = {"worker_id": self.worker_id, **(m.to_wire() if hasattr(m, "to_wire") else dict(m))}
                    await self.drt.bus.publish(self.subject, json.dumps(payload).encode())
                except Exception:
                    logger.exception("metrics publish failed")
                await asyncio.sleep(self.interval_s)
        except asyncio.CancelledError:
            pass

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
