"""Per-worker in-flight load tracking: the load terms of the routing cost.

Ref: lib/llm/src/kv_router/sequence.rs — ``ActiveSequences`` (:53) /
``ActiveSequencesMultiWorker`` (:268): per worker, the sum of in-flight
prefill tokens (not yet prefilled) and active decode blocks. These feed
``KvScheduler``'s cost function; they are the router's *predicted* load,
updated optimistically at scheduling time and corrected on completion.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, Optional

WorkerId = int


@dataclass
class _ActiveSeq:
    worker: WorkerId
    prefill_tokens: int  # tokens still needing prefill when scheduled
    decode_blocks: int
    prefill_done: bool = False
    started: float = field(default_factory=time.monotonic)


class ActiveSequencesMultiWorker:
    def __init__(self, block_size: int = 16):
        self.block_size = block_size
        self._seqs: Dict[str, _ActiveSeq] = {}
        self._prefill_tokens: Dict[WorkerId, int] = {}
        self._decode_blocks: Dict[WorkerId, int] = {}

    # --- worker set maintenance --------------------------------------------
    def ensure_worker(self, worker: WorkerId) -> None:
        self._prefill_tokens.setdefault(worker, 0)
        self._decode_blocks.setdefault(worker, 0)

    def remove_worker(self, worker: WorkerId) -> None:
        self._prefill_tokens.pop(worker, None)
        self._decode_blocks.pop(worker, None)
        for rid in [r for r, s in self._seqs.items() if s.worker == worker]:
            del self._seqs[rid]

    # --- request lifecycle --------------------------------------------------
    def add_request(
        self,
        request_id: str,
        worker: WorkerId,
        prompt_tokens: int,
        overlap_blocks: int,
    ) -> None:
        """Register a scheduled request: prefill need = tokens beyond the
        worker's cached prefix; decode load = the NEW blocks this request
        adds. Overlapped blocks are shared with the resident prefix — they
        cost the worker no extra HBM and no extra write bandwidth, so
        counting them at full weight made the cost model route high-overlap
        requests AWAY from their warm worker the moment it had one request
        in flight (the engine's prefix-cache hit then never happened —
        measured as the 1.1× router-benefit plateau in
        tools/bench_router_prefix.py)."""
        self.ensure_worker(worker)
        prefill = max(0, prompt_tokens - overlap_blocks * self.block_size)
        blocks = (prompt_tokens + self.block_size - 1) // self.block_size
        blocks = max(0, blocks - overlap_blocks)
        seq = _ActiveSeq(worker=worker, prefill_tokens=prefill, decode_blocks=blocks)
        self._seqs[request_id] = seq
        self._prefill_tokens[worker] += prefill
        self._decode_blocks[worker] += blocks

    def mark_prefill_done(self, request_id: str) -> None:
        seq = self._seqs.get(request_id)
        if seq is not None and not seq.prefill_done:
            seq.prefill_done = True
            self._prefill_tokens[seq.worker] = max(0, self._prefill_tokens.get(seq.worker, 0) - seq.prefill_tokens)

    def free(self, request_id: str) -> Optional[WorkerId]:
        seq = self._seqs.pop(request_id, None)
        if seq is None:
            return None
        if not seq.prefill_done:
            self._prefill_tokens[seq.worker] = max(0, self._prefill_tokens.get(seq.worker, 0) - seq.prefill_tokens)
        self._decode_blocks[seq.worker] = max(0, self._decode_blocks.get(seq.worker, 0) - seq.decode_blocks)
        return seq.worker

    # --- load queries -------------------------------------------------------
    def prefill_tokens(self, worker: WorkerId) -> int:
        return self._prefill_tokens.get(worker, 0)

    def decode_blocks(self, worker: WorkerId) -> int:
        return self._decode_blocks.get(worker, 0)

    def active_requests(self, worker: WorkerId) -> int:
        return sum(1 for s in self._seqs.values() if s.worker == worker)
