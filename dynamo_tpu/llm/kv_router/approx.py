"""ApproxKvIndexer: KV-awareness without engine events.

Ref: lib/llm/src/kv_router/approx.rs:165 — when engines don't publish KV
events, assume the blocks of a routed request live on the chosen worker for a
TTL (reference default 120 s), indexed in the same radix tree so the
scheduler code path is identical.
"""

from __future__ import annotations

import heapq
import time
from typing import List, Optional, Sequence, Tuple

from dynamo_tpu.llm.kv_router.indexer import KvIndexer, OverlapScores, WorkerId
from dynamo_tpu.llm.tokens import compute_block_hashes

DEFAULT_TTL_S = 120.0


class ApproxKvIndexer(KvIndexer):
    def __init__(self, block_size: int = 16, ttl_s: float = DEFAULT_TTL_S):
        super().__init__(block_size)
        self.ttl_s = ttl_s
        # Min-heap of (expiry, worker, hashes) pending removal.
        self._expiry: List[Tuple[float, WorkerId, tuple]] = []

    def process_routing_decision(self, worker: WorkerId, token_ids: Sequence[int]) -> None:
        """Assume the chosen worker now caches this prompt's blocks."""
        hashes = compute_block_hashes(token_ids, self.block_size)
        if not hashes:
            return
        self.tree.apply_stored(worker, hashes, None)
        heapq.heappush(self._expiry, (time.monotonic() + self.ttl_s, worker, tuple(hashes)))

    def expire(self, now: Optional[float] = None) -> int:
        now = time.monotonic() if now is None else now
        n = 0
        while self._expiry and self._expiry[0][0] <= now:
            _, worker, hashes = heapq.heappop(self._expiry)
            self.tree.apply_removed(worker, list(hashes))
            n += 1
        return n

    def find_matches(self, block_hashes) -> OverlapScores:
        self.expire()
        return super().find_matches(block_hashes)
