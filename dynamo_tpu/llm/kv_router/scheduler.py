"""KV-aware worker selection: the routing cost function.

Ref: lib/llm/src/kv_router/scheduler.rs — ``KvScheduler`` (:86),
``DefaultWorkerSelector::select_worker`` (:461):

    potential_prefill_blocks = prompt_blocks - overlap_blocks(worker)
    logit = overlap_score_weight * potential_prefill_blocks + decode_blocks
    → softmax-sample over -logit with ``temperature`` (:375);
      temperature 0 ⇒ argmin (deterministic best).

Lower logit = cheaper: the worker either already holds the prefix (small
prefill term) or is lightly loaded (small decode term).
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from dynamo_tpu.llm.kv_router.indexer import OverlapScores
from dynamo_tpu.llm.kv_router.sequence import ActiveSequencesMultiWorker

WorkerId = int


@dataclass
class SchedulingDecision:
    worker: WorkerId
    overlap_blocks: int
    cost: float


class KvScheduler:
    def __init__(
        self,
        sequences: ActiveSequencesMultiWorker,
        *,
        overlap_score_weight: float = 1.0,
        temperature: float = 0.0,
        rng: Optional[random.Random] = None,
    ):
        self.sequences = sequences
        self.overlap_score_weight = overlap_score_weight
        self.temperature = temperature
        self.rng = rng or random.Random(0)

    def select_worker(
        self,
        workers: Sequence[WorkerId],
        prompt_blocks: int,
        overlaps: OverlapScores,
        *,
        overlap_score_weight: Optional[float] = None,
        temperature: Optional[float] = None,
        external_prefill_tokens: Optional[Dict[WorkerId, int]] = None,
        prefill_fractions: Optional[Dict[WorkerId, float]] = None,
    ) -> SchedulingDecision:
        if not workers:
            raise ValueError("no workers to select from")
        w_weight = self.overlap_score_weight if overlap_score_weight is None else overlap_score_weight
        temp = self.temperature if temperature is None else temperature
        external = external_prefill_tokens or {}
        fractions = prefill_fractions or {}

        costs: List[Tuple[WorkerId, float, int]] = []
        for w in workers:
            overlap = min(overlaps.scores.get(w, 0), prompt_blocks)
            potential_prefill_blocks = prompt_blocks - overlap
            decode_blocks = self.sequences.decode_blocks(w)
            # Pending prefill tokens keep the cost honest between metric
            # updates (same term the reference folds in via ActiveSequences),
            # plus other routers' gossiped pending prefills
            # (ref: prefill_counter.rs PrefillCountersMultiWorker).
            pending = self.sequences.prefill_tokens(w) + external.get(w, 0)
            pending_prefill_blocks = pending / max(self.sequences.block_size, 1)
            # Elastic capacity dial: a worker dialed toward prefill
            # (fraction > 0.5) clears prefill blocks proportionally faster,
            # so its prefill cost shrinks by the same 2·f factor the dial
            # scales mixed_prefill_budget by (f = 0.5 ⇒ exact pre-elastic
            # cost; gossiped via ForwardPassMetrics.elastic_prefill_fraction).
            pf_scale = 1.0 / max(2.0 * fractions.get(w, 0.5), 0.1)
            cost = w_weight * (potential_prefill_blocks + pending_prefill_blocks) * pf_scale + decode_blocks
            costs.append((w, cost, overlap))

        chosen = self._softmax_sample(costs, temp)
        return SchedulingDecision(worker=chosen[0], overlap_blocks=chosen[2], cost=chosen[1])

    def _softmax_sample(self, costs: List[Tuple[WorkerId, float, int]], temperature: float):
        if temperature <= 0.0:
            # Deterministic best; EXACT ties break randomly — id-ordered
            # tie-breaking concentrated every cold request onto one worker
            # (measured: a serial warm pass put 8 prefix groups on a single
            # mocker, evicting two of them, and KV routing then LOST to
            # round-robin in tools/bench_router_prefix.py).
            best = min(c[1] for c in costs)
            return self.rng.choice([c for c in costs if c[1] == best])
        # softmax over -cost/temperature (ref: softmax_sample scheduler.rs:375)
        mx = max(-c[1] / temperature for c in costs)
        weights = [math.exp(-c[1] / temperature - mx) for c in costs]
        total = sum(weights)
        r = self.rng.random() * total
        acc = 0.0
        for c, wgt in zip(costs, weights):
            acc += wgt
            if r <= acc:
                return c
        return costs[-1]
