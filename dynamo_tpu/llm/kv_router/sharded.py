"""Sharded KV indexer: parallel event application for high event rates.

Ref: lib/llm/src/kv_router/indexer.rs:970 ``KvIndexerSharded`` — the
reference scales the router index by sharding the radix tree per *worker
assignment*:

- every worker is pinned to exactly one shard (the shard with the fewest
  workers at registration — load balancing);
- KV events route to the owning shard only, so shards apply events with no
  cross-shard synchronization (per-worker event order is preserved because
  one worker's events all land on one single-consumer shard);
- match requests scatter-gather across all shards and merge their
  ``OverlapScores`` (a worker's blocks exist only in its shard, so the merge
  is a disjoint union).

Here each shard owns a radix tree (native C++ when built) behind a lock and
a dedicated applier thread draining a per-shard event queue. Lookups take
the shard locks in the caller's thread (cheap reads, no cross-thread
round-trip); writes scale with the shard count because the expensive
``apply_stored`` work happens in per-shard threads.
"""

from __future__ import annotations

import queue
import threading
from typing import Dict, List, Optional, Sequence

from dynamo_tpu.llm.kv_router.indexer import OverlapScores, make_radix_tree
from dynamo_tpu.runtime.logging import get_logger

logger = get_logger(__name__)

WorkerId = int
BlockHash = int

_STOP = object()


class _Shard:
    def __init__(self, idx: int):
        self.idx = idx
        self.tree = make_radix_tree()
        self.lock = threading.Lock()
        self.queue: "queue.Queue" = queue.Queue()
        self.thread = threading.Thread(target=self._run, name=f"kv-indexer-shard-{idx}", daemon=True)
        self.thread.start()

    def _run(self) -> None:
        while True:
            item = self.queue.get()
            if item is _STOP:
                # Release any flush fences enqueued behind the stop marker so
                # a flush() racing close() returns instead of timing out.
                while not self.queue.empty():
                    trailing = self.queue.get_nowait()
                    if trailing is not _STOP and trailing[0] == "flush":
                        trailing[2].set()
                return
            kind, worker, payload = item
            try:
                if kind == "flush":
                    payload.set()  # all prior items fully applied (FIFO queue)
                    continue
                with self.lock:
                    if kind == "stored":
                        self.tree.apply_stored(worker, payload[0], payload[1])
                    elif kind == "removed":
                        self.tree.apply_removed(worker, payload)
                    elif kind == "remove_worker":
                        self.tree.remove_worker(worker)
            except Exception:  # noqa: BLE001 — a bad event must not kill the shard
                logger.exception("shard %d: event application failed", self.idx)

    def stop(self) -> None:
        self.queue.put(_STOP)
        self.thread.join(timeout=5.0)


class KvIndexerSharded:
    """Drop-in for :class:`KvIndexer` with ``num_shards`` parallel appliers.

    ``flush()`` drains all shard queues — tests and snapshot capture use it
    to observe a consistent point; the serving path never needs to.
    """

    def __init__(self, block_size: int = 16, num_shards: int = 4):
        if num_shards < 1:
            raise ValueError("num_shards must be >= 1")
        self.block_size = block_size
        self.shards = [_Shard(i) for i in range(num_shards)]
        self._assignment: Dict[WorkerId, int] = {}
        self._counts = [0] * num_shards
        self._assign_lock = threading.Lock()
        self.events_applied = 0

    # --- worker→shard assignment -------------------------------------------
    def _shard_of(self, worker: WorkerId) -> _Shard:
        with self._assign_lock:
            idx = self._assignment.get(worker)
            if idx is None:
                idx = min(range(len(self.shards)), key=lambda i: self._counts[i])
                self._assignment[worker] = idx
                self._counts[idx] += 1
            return self.shards[idx]

    # --- event application (async, per-shard ordered) -----------------------
    def apply_event(self, worker: WorkerId, event: dict) -> None:
        kind = event.get("kind")
        shard = self._shard_of(worker)
        if kind == "stored":
            shard.queue.put(("stored", worker, (event.get("block_hashes") or [], event.get("parent_hash"))))
        elif kind == "removed":
            shard.queue.put(("removed", worker, event.get("block_hashes") or []))
        elif kind == "cleared":
            shard.queue.put(("remove_worker", worker, None))
        self.events_applied += 1

    def remove_worker(self, worker: WorkerId) -> None:
        with self._assign_lock:
            idx = self._assignment.pop(worker, None)
            if idx is not None:
                self._counts[idx] -= 1
        shard = self.shards[idx] if idx is not None else None
        if shard is not None:
            shard.queue.put(("remove_worker", worker, None))

    # --- queries (scatter-gather) ------------------------------------------
    def find_matches(self, block_hashes: Sequence[BlockHash]) -> OverlapScores:
        merged: Dict[WorkerId, int] = {}
        for shard in self.shards:
            with shard.lock:
                scores = shard.tree.find_matches(block_hashes).scores
            merged.update(scores)  # disjoint by construction (worker→one shard)
        return OverlapScores(scores=merged)

    def find_matches_for_tokens(self, token_ids: Sequence[int]) -> OverlapScores:
        from dynamo_tpu.llm.tokens import compute_block_hashes

        return self.find_matches(compute_block_hashes(token_ids, self.block_size))

    # --- maintenance --------------------------------------------------------
    def flush(self, timeout: float = 10.0) -> None:
        """Block until every event enqueued before this call has been fully
        *applied* (quiesce point). Queue emptiness is not enough — the
        applier pops an item before applying it, so an empty queue can
        coexist with an event mid-apply; a per-shard sentinel processed
        in FIFO order cannot."""
        import time

        deadline = time.monotonic() + timeout
        fences = []
        for shard in self.shards:
            ev = threading.Event()
            shard.queue.put(("flush", None, ev))
            fences.append(ev)
        for shard, ev in zip(self.shards, fences):
            while not ev.wait(0.05):
                if not shard.thread.is_alive():
                    break  # shard closed: applier gone, nothing in flight
                if time.monotonic() > deadline:
                    raise TimeoutError("shard queues did not drain")

    def size(self) -> int:
        total = 0
        for shard in self.shards:
            with shard.lock:
                total += shard.tree.size()
        return total

    def dump(self) -> bytes:
        """Merged snapshot across shards (shard-disjoint record union)."""
        import json

        records: List[dict] = []
        for shard in self.shards:
            with shard.lock:
                records.extend(json.loads(shard.tree.dump()))
        return json.dumps(records).encode()

    def load_snapshot(self, raw: bytes) -> None:
        """Restore a snapshot, routing each record to its worker's shard."""
        import json

        for rec in json.loads(raw):
            for w in rec["w"]:
                self.apply_event(w, {"kind": "stored", "block_hashes": [rec["h"]], "parent_hash": rec["p"]})
        self.flush()

    def close(self) -> None:
        for shard in self.shards:
            shard.stop()
