"""Inter-router pending-prefill accounting.

Ref: lib/llm/src/kv_router/prefill_counter.rs (545 LoC) — with replicated
routers, each router only sees its *own* in-flight prefills, so two routers
can stampede the same worker. The reference fixes this by gossiping prefill
events on a shared subject: every router publishes ``NewPrefill(request_id,
worker_id, tokens)`` when it routes and ``CompletePrefill(request_id)`` when
the first token arrives; every router applies *other* routers' events
(skipping its own by ``router_id``) into per-worker counters. The scheduler
then folds the global pending-prefill token sum per worker into its cost.

Wire shape (JSON on ``prefill_events.{ns}.{component}``):
``{"router_id": ..., "request_id": ..., "worker_id": ..., "kind":
"new"|"complete", "tokens": N}``
"""

from __future__ import annotations

import asyncio
import json
import uuid
from typing import Dict, Optional

from dynamo_tpu.runtime.logging import get_logger

logger = get_logger(__name__)

WorkerId = int


def prefill_events_subject(namespace: str, component: str) -> str:
    return f"prefill_events.{namespace}.{component}"


class PrefillCounter:
    """Pending prefill tokens for one worker, keyed by request id
    (ref: prefill_counter.rs PrefillCounterState — map + running sum)."""

    def __init__(self):
        self._tokens: Dict[str, int] = {}
        self._sum = 0

    def insert(self, request_id: str, tokens: int) -> None:
        old = self._tokens.get(request_id)
        if old is not None:
            self._sum -= old
        self._tokens[request_id] = tokens
        self._sum += tokens

    def remove(self, request_id: str) -> Optional[int]:
        tokens = self._tokens.pop(request_id, None)
        if tokens is not None:
            self._sum -= tokens
        return tokens

    @property
    def running_sum(self) -> int:
        return self._sum

    def __len__(self) -> int:
        return len(self._tokens)


class PrefillCountersMultiWorker:
    """All workers' counters + the cross-router gossip loop
    (ref: prefill_counter.rs PrefillCountersMultiWorker)."""

    def __init__(self, drt, namespace: str, component: str):
        self.drt = drt
        self.subject = prefill_events_subject(namespace, component)
        self.router_id = uuid.uuid4().hex
        self.counters: Dict[WorkerId, PrefillCounter] = {}
        self._request_worker: Dict[str, WorkerId] = {}
        self._task: Optional[asyncio.Task] = None
        self._sub = None

    # --- local publish ------------------------------------------------------
    # Own routing decisions are NOT applied to the local counters: the local
    # ActiveSequencesMultiWorker already carries them in the scheduler cost,
    # so the counters hold only *other* routers' pending prefills and the two
    # terms add without double counting.
    async def new_prefill(self, request_id: str, worker: WorkerId, tokens: int) -> None:
        await self._publish({"kind": "new", "request_id": request_id, "worker_id": worker, "tokens": tokens})

    async def complete_prefill(self, request_id: str, worker: Optional[WorkerId] = None) -> None:
        await self._publish({"kind": "complete", "request_id": request_id, "worker_id": worker})

    async def _publish(self, body: dict) -> None:
        body["router_id"] = self.router_id
        try:
            await self.drt.bus.publish(self.subject, json.dumps(body).encode())
        except (ConnectionError, OSError) as e:
            logger.warning("prefill event publish failed: %s", e)

    def _apply_new(self, request_id: str, worker: WorkerId, tokens: int) -> None:
        existing = self._request_worker.get(request_id)
        if existing is not None and existing != worker:
            logger.warning("request %s already tracked on worker %x", request_id, existing)
        self._request_worker[request_id] = worker
        self.counters.setdefault(worker, PrefillCounter()).insert(request_id, tokens)

    def _apply_complete(self, request_id: str, worker_hint: Optional[WorkerId] = None) -> None:
        worker = self._request_worker.pop(request_id, None)
        if worker is None:
            worker = worker_hint  # "complete" seen without its "new" (e.g. joined late)
        if worker is None:
            return
        counter = self.counters.get(worker)
        if counter is not None:
            counter.remove(request_id)

    # --- queries ------------------------------------------------------------
    def pending_tokens(self, worker: WorkerId) -> int:
        c = self.counters.get(worker)
        return c.running_sum if c is not None else 0

    def remove_worker(self, worker: WorkerId) -> None:
        self.counters.pop(worker, None)
        self._request_worker = {r: w for r, w in self._request_worker.items() if w != worker}

    # --- gossip loop --------------------------------------------------------
    async def start(self) -> None:
        self._sub = await self.drt.bus.subscribe(self.subject)
        self._task = asyncio.get_running_loop().create_task(self._consume())

    async def _consume(self) -> None:
        try:
            async for msg in self._sub:
                try:
                    ev = json.loads(msg.data)
                except ValueError:
                    continue
                if ev.get("router_id") == self.router_id:
                    continue  # own events already applied locally
                if ev.get("kind") == "new":
                    self._apply_new(ev["request_id"], int(ev["worker_id"]), int(ev.get("tokens", 0)))
                elif ev.get("kind") == "complete":
                    hint = ev.get("worker_id")
                    self._apply_complete(ev["request_id"], None if hint is None else int(hint))
        except asyncio.CancelledError:
            pass

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        if self._sub is not None:
            await self._sub.unsubscribe()
            self._sub = None
