"""KV-aware request routing (ref: lib/llm/src/kv_router — SURVEY.md §2b).

``KvPushRouter`` wraps the plain PushRouter with KV-aware worker selection:
prompt block hashes → radix-tree overlap per worker → cost function over
(prefill need, decode load) → softmax/argmin choice → direct-routed push.
State maintenance: durable KV-event stream feeds the indexer (exact mode) or
routing decisions feed a TTL index (approx mode); worker metrics gossip
corrects load; instance death prunes both.
"""

from __future__ import annotations

import asyncio
import json
from dataclasses import dataclass
from typing import Any, AsyncIterator, Optional

from dynamo_tpu.llm.kv_router.approx import ApproxKvIndexer
from dynamo_tpu.llm.kv_router.indexer import KvIndexer, OverlapScores, RadixTree
from dynamo_tpu.llm.kv_router.prefill_counter import PrefillCountersMultiWorker
from dynamo_tpu.llm.kv_router.publisher import (
    KvEventPublisher,
    WorkerMetricsPublisher,
    kv_events_stream_name,
    kv_metrics_subject,
)
from dynamo_tpu.llm.kv_router.scheduler import KvScheduler, SchedulingDecision
from dynamo_tpu.llm.kv_router.sequence import ActiveSequencesMultiWorker
from dynamo_tpu.llm.kv_router.sharded import KvIndexerSharded
from dynamo_tpu.llm.kv_router.subscriber import KvRouterSubscriber
from dynamo_tpu.llm.tokens import compute_block_hashes
from dynamo_tpu.runtime.client import Client
from dynamo_tpu.runtime.engine import Annotated, Context
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.push_router import PushRouter, RouterMode

logger = get_logger(__name__)

__all__ = [
    "KvRouterConfig",
    "KvPushRouter",
    "KvIndexer",
    "KvIndexerSharded",
    "PrefillCountersMultiWorker",
    "ApproxKvIndexer",
    "RadixTree",
    "OverlapScores",
    "KvScheduler",
    "ActiveSequencesMultiWorker",
    "KvEventPublisher",
    "WorkerMetricsPublisher",
    "KvRouterSubscriber",
    "kv_events_stream_name",
    "kv_metrics_subject",
]


@dataclass
class KvRouterConfig:
    """Ref: kv_router.rs:96 KvRouterConfig + per-request overrides (:86)."""

    overlap_score_weight: float = 1.0
    temperature: float = 0.0
    block_size: int = 16
    use_kv_events: bool = True  # False → ApproxKvIndexer
    approx_ttl_s: float = 120.0
    snapshot_threshold: int = 1_000_000
    reset_states: bool = False
    # >1 ⇒ KvIndexerSharded: parallel event appliers, worker-pinned shards
    # (ref: indexer.rs:970 KvIndexerSharded).
    num_indexer_shards: int = 1
    # Gossip pending prefills between replicated routers so they don't
    # stampede one worker (ref: prefill_counter.rs).
    track_prefill_counters: bool = False
    # In-flight prefix awareness (exact mode): routed-but-not-yet-registered
    # prompts count as overlap on their chosen worker for this long, so a
    # burst of same-prefix requests CONCENTRATES on one worker instead of
    # spreading its prefix across the fleet (each spread copy prefills cold
    # AND pollutes another worker's cache). The engine registers blocks at
    # prompt completion and the exact index takes over well inside the TTL.
    # 0 disables.
    pending_overlap_ttl_s: float = 10.0


class KvPushRouter:
    """AsyncEngine-shaped KV router (ref: kv_router.rs KvPushRouter)."""

    def __init__(self, client: Client, config: KvRouterConfig):
        self.client = client
        self.config = config
        self.push = PushRouter(client, RouterMode.DIRECT)
        self.sequences = ActiveSequencesMultiWorker(block_size=config.block_size)
        self.scheduler = KvScheduler(
            self.sequences,
            overlap_score_weight=config.overlap_score_weight,
            temperature=config.temperature,
        )
        if not config.use_kv_events:
            self.indexer = ApproxKvIndexer(block_size=config.block_size, ttl_s=config.approx_ttl_s)
        elif config.num_indexer_shards > 1:
            self.indexer = KvIndexerSharded(
                block_size=config.block_size, num_shards=config.num_indexer_shards
            )
        else:
            self.indexer: KvIndexer = KvIndexer(block_size=config.block_size)
        # Exact mode: a second, TTL'd radix tree over in-flight routing
        # decisions (approx mode already feeds decisions into its main
        # index). find_matches merges both, taking the max per worker.
        self.pending_index: Optional[ApproxKvIndexer] = (
            ApproxKvIndexer(block_size=config.block_size, ttl_s=config.pending_overlap_ttl_s)
            if config.use_kv_events and config.pending_overlap_ttl_s > 0
            else None
        )
        self.prefill_counters: Optional[PrefillCountersMultiWorker] = None
        self.subscriber: Optional[KvRouterSubscriber] = None
        self._metrics_task: Optional[asyncio.Task] = None
        # Reuse accounting: predicted overlap (scheduling time) vs the
        # engine's ACTUAL cached_tokens report (first response frame). A
        # persistent gap means the index is stale or the engine is evicting
        # under pressure — the router is steering to cold workers either way.
        self.predicted_cached_tokens_total = 0
        self.cached_tokens_total = 0
        self.cached_tokens_by_worker: dict = {}
        # Elastic capacity dial (gossiped ForwardPassMetrics): per-worker
        # prefill fraction feeds the cost model so routing follows the
        # fleet's live prefill:decode shape, not just its KV state.
        self.elastic_fraction_by_worker: dict = {}

    @classmethod
    async def create(cls, client: Client, config: Optional[KvRouterConfig] = None) -> "KvPushRouter":
        config = config or KvRouterConfig()
        router = cls(client, config)
        ep = client.endpoint
        if config.use_kv_events:
            router.subscriber = KvRouterSubscriber(
                client.drt,
                router.indexer,
                kv_events_stream_name(ep.namespace, ep.component),
                snapshot_threshold=config.snapshot_threshold,
                reset_states=config.reset_states,
            )
            await router.subscriber.start()
        if config.track_prefill_counters:
            ep = client.endpoint
            router.prefill_counters = PrefillCountersMultiWorker(client.drt, ep.namespace, ep.component)
            await router.prefill_counters.start()
        router._metrics_task = asyncio.get_running_loop().create_task(router._consume_metrics())
        return router

    async def _consume_metrics(self) -> None:
        """Worker load gossip → busy-threshold monitor (ref: scheduler.rs
        watch channels + worker_monitor.rs)."""
        ep = self.client.endpoint
        sub = await self.client.drt.bus.subscribe(kv_metrics_subject(ep.namespace, ep.component))
        try:
            async for msg in sub:
                try:
                    m = json.loads(msg.data)
                    wid = int(m["worker_id"])
                    self.push.monitor.update(wid, float(m.get("kv_usage", 0.0)))
                    self.elastic_fraction_by_worker[wid] = float(
                        m.get("elastic_prefill_fraction", 0.5) or 0.5
                    )
                except (ValueError, KeyError):
                    continue
        except asyncio.CancelledError:
            pass
        finally:
            await sub.unsubscribe()

    def _sync_workers(self) -> list:
        """Reconcile tracked state with the live instance set."""
        live = self.client.instance_ids()
        live_set = set(live)
        for w in list(self.sequences._prefill_tokens):
            if w not in live_set:
                self.sequences.remove_worker(w)
                self.indexer.remove_worker(w)
                if self.pending_index is not None:
                    self.pending_index.remove_worker(w)
                if self.prefill_counters is not None:
                    self.prefill_counters.remove_worker(w)
                self.elastic_fraction_by_worker.pop(w, None)
        for w in live:
            self.sequences.ensure_worker(w)
        return live

    async def schedule(self, token_ids, router_overrides: Optional[dict] = None) -> SchedulingDecision:
        workers = self._sync_workers()
        # Circuit breaker (push router): skip workers with an OPEN circuit
        # unless that would leave nobody — availability beats purity.
        blocked = self.push.breaker.blocked_instances()
        if blocked:
            unblocked = [w for w in workers if w not in blocked]
            workers = unblocked or workers
        hashes = compute_block_hashes(token_ids, self.config.block_size)
        prompt_blocks = max(1, (len(token_ids) + self.config.block_size - 1) // self.config.block_size)
        overlaps = self.indexer.find_matches(hashes)
        if self.pending_index is not None:
            # Merge in-flight decisions: a prefix mid-prefill on a worker is
            # (about to be) cached there even though no KV event says so yet.
            for w, s in self.pending_index.find_matches(hashes).scores.items():
                overlaps.scores[w] = max(overlaps.scores.get(w, 0), s)
        overrides = router_overrides or {}
        external = (
            {w: self.prefill_counters.pending_tokens(w) for w in workers}
            if self.prefill_counters is not None
            else None
        )
        return self.scheduler.select_worker(
            workers,
            prompt_blocks,
            overlaps,
            overlap_score_weight=overrides.get("overlap_score_weight"),
            temperature=overrides.get("temperature"),
            external_prefill_tokens=external,
            prefill_fractions=self.elastic_fraction_by_worker,
        )

    async def generate(self, request: Any, context: Optional[Context] = None) -> AsyncIterator[Annotated]:
        ctx = context or Context()
        token_ids = list(request.get("token_ids") or [])
        decision = await self.schedule(token_ids, request.get("router_overrides"))
        rid = ctx.id
        self.sequences.add_request(rid, decision.worker, len(token_ids), decision.overlap_blocks)
        if isinstance(self.indexer, ApproxKvIndexer):
            self.indexer.process_routing_decision(decision.worker, token_ids)
        elif self.pending_index is not None:
            self.pending_index.process_routing_decision(decision.worker, token_ids)
        if self.prefill_counters is not None:
            await self.prefill_counters.new_prefill(rid, decision.worker, len(token_ids))
        logger.debug(
            "kv-routed %s -> %x (overlap=%d blocks, cost=%.1f)", rid, decision.worker, decision.overlap_blocks, decision.cost
        )
        self.predicted_cached_tokens_total += decision.overlap_blocks * self.config.block_size
        first = True
        try:
            async for item in self.push.generate(request, ctx, instance_id=decision.worker):
                if first and (not isinstance(item, Annotated) or not item.is_annotation()):
                    self.sequences.mark_prefill_done(rid)
                    if self.prefill_counters is not None:
                        await self.prefill_counters.complete_prefill(rid, decision.worker)
                    first = False
                    # Engine-reported reuse (first frame): close the loop on
                    # the predicted overlap so the router's accounting
                    # reflects blocks actually skipped, not hoped for.
                    data = item.data if isinstance(item, Annotated) else item
                    if isinstance(data, dict) and data.get("cached_tokens") is not None:
                        n = int(data["cached_tokens"])
                        self.cached_tokens_total += n
                        self.cached_tokens_by_worker[decision.worker] = (
                            self.cached_tokens_by_worker.get(decision.worker, 0) + n
                        )
                yield item
        finally:
            self.sequences.free(rid)
            if first and self.prefill_counters is not None:
                # Stream ended before the first token (abort/error): retract
                # the pending-prefill gossip too.
                await self.prefill_counters.complete_prefill(rid, decision.worker)

    def stats(self) -> dict:
        """Router-side reuse accounting: predicted (index overlap at
        scheduling time) vs actual (engine-reported cached_tokens)."""
        return {
            "predicted_cached_tokens_total": self.predicted_cached_tokens_total,
            "cached_tokens_total": self.cached_tokens_total,
            "cached_tokens_by_worker": dict(self.cached_tokens_by_worker),
        }

    async def close(self) -> None:
        if self.subscriber is not None:
            await self.subscriber.stop()
        if self.prefill_counters is not None:
            await self.prefill_counters.stop()
        if isinstance(self.indexer, KvIndexerSharded):
            self.indexer.close()
        if self._metrics_task is not None:
            self._metrics_task.cancel()
            try:
                await self._metrics_task
            except asyncio.CancelledError:
                pass
