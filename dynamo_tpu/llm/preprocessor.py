"""OpenAI→internal preprocessing: chat template render + tokenization +
sampling/stop extraction.

Ref: lib/llm/src/preprocessor.rs — ``OpenAIPreprocessor`` :143,
``preprocess_request`` :194, ``apply_template`` :258 (minijinja; here
jinja2), annotation emission (``formatted_prompt``, ``token_ids``).

Runs as a pipeline Operator on the frontend so workers only ever see
token ids (PreprocessedRequest) — the wire stays text-free.
"""

from __future__ import annotations

from typing import Any, AsyncIterator, List, Optional

import jinja2

from dynamo_tpu.llm.protocols.common import PreprocessedRequest
from dynamo_tpu.llm.protocols.openai import (
    sampling_from_request,
    stop_conditions_from_request,
)
from dynamo_tpu.llm.tokenizer import Tokenizer
from dynamo_tpu.runtime.engine import Annotated, Context
from dynamo_tpu.runtime.pipeline import Operator

# Generic fallback template (model-specific templates come from
# tokenizer_config.json via the MDC).
DEFAULT_CHAT_TEMPLATE = (
    "{% for message in messages %}"
    "<|{{ message.role }}|>\n{{ message.content }}\n"
    "{% endfor %}"
    "{% if add_generation_prompt %}<|assistant|>\n{% endif %}"
)

ANNOTATION_FORMATTED_PROMPT = "formatted_prompt"
ANNOTATION_TOKEN_IDS = "token_ids"


class PromptFormatter:
    """Jinja chat-template renderer (ref: preprocessor/prompt/*)."""

    def __init__(self, template: Optional[str] = None, bos_token: str = "", eos_token: str = ""):
        self.env = jinja2.Environment(keep_trailing_newline=True)
        self.env.globals["raise_exception"] = self._raise
        self.template = self.env.from_string(template or DEFAULT_CHAT_TEMPLATE)
        self.bos_token = bos_token
        self.eos_token = eos_token

    @staticmethod
    def _raise(msg: str):
        raise ValueError(msg)

    def render(self, messages: List[dict], add_generation_prompt: bool = True, **extra: Any) -> str:
        return self.template.render(
            messages=messages,
            add_generation_prompt=add_generation_prompt,
            bos_token=self.bos_token,
            eos_token=self.eos_token,
            **extra,
        )


class OpenAIPreprocessor(Operator):
    """Chat/completion request → PreprocessedRequest (wire dict)."""

    def __init__(
        self,
        tokenizer: Tokenizer,
        formatter: Optional[PromptFormatter] = None,
        *,
        default_max_tokens: int = 512,
        tool_call_parser: Optional[str] = None,
        reasoning_parser: Optional[str] = None,
    ):
        self.tokenizer = tokenizer
        self.formatter = formatter or PromptFormatter(getattr(tokenizer, "chat_template", None))
        self.default_max_tokens = default_max_tokens
        self.tool_call_parser = tool_call_parser
        self.reasoning_parser = reasoning_parser

    # --- Operator interface -------------------------------------------------
    async def transform_request(self, request: dict, context: Context) -> dict:
        req, prompt = self.preprocess(request)
        wire = req.to_wire()
        wire["annotations"] = req.annotations
        # Side-band for the response annotation path; engines ignore it.
        wire["_formatted_prompt"] = prompt
        # Output-parser directives for the Backend stage: the tool-call jail
        # arms only when the request declares tools; reasoning splitting is a
        # model property (ref: preprocessor.rs tool-call jail). A FORCED
        # tool call (guided tool_choice) must parse even without a named
        # parser — the grammar emits bare {"name":..,"arguments":{..}} JSON,
        # which the "default" config round-trips into an OpenAI tool_call.
        tool_parser = self.tool_call_parser if request.get("tools") else None
        if tool_parser is None and (req.guided_decoding or {}).get("forced_tools"):
            tool_parser = "default"
        if tool_parser or self.reasoning_parser:
            wire["parser_options"] = {
                "tool_call_parser": tool_parser,
                "reasoning_parser": self.reasoning_parser,
            }
        return wire

    def transform_response(self, stream: AsyncIterator, request: dict, context: Context) -> AsyncIterator:
        annotations = request.get("annotations") or []

        async def gen():
            # Internal metrics annotation (consumed by the HTTP service for
            # usage/ISL accounting; never emitted to clients — "_"-prefixed
            # events are internal).
            yield Annotated(event="_metrics", comment=str(len(request.get("token_ids") or [])))
            # Requested annotations are emitted before engine output
            # (ref: preprocessor.rs annotations path).
            if ANNOTATION_FORMATTED_PROMPT in annotations and request.get("_formatted_prompt") is not None:
                yield Annotated(event=ANNOTATION_FORMATTED_PROMPT, comment=request["_formatted_prompt"])
            if ANNOTATION_TOKEN_IDS in annotations:
                yield Annotated(event=ANNOTATION_TOKEN_IDS, comment=str(request.get("token_ids")))
            async for item in stream:
                yield item

        return gen()

    # --- core ---------------------------------------------------------------
    def preprocess(self, body: dict) -> PreprocessedRequest:
        image_urls: List[str] = []
        if "messages" in body:
            messages = body["messages"]
            if any(isinstance(m.get("content"), list) for m in messages):
                # Image content parts → encode worker (multimodal.py); the
                # template renders the flattened text.
                from dynamo_tpu.llm.multimodal import extract_images

                messages, image_urls = extract_images(messages)
            prompt = self.formatter.render(messages, add_generation_prompt=True)
            token_ids = self.tokenizer.encode(prompt)
        else:
            raw = body.get("prompt", "")
            if isinstance(raw, list) and raw and isinstance(raw[0], int):
                prompt, token_ids = None, list(raw)
            else:
                prompt = raw if isinstance(raw, str) else "\n".join(raw)
                token_ids = self.tokenizer.encode(prompt)

        nvext = body.get("nvext") or {}
        stop_conditions = stop_conditions_from_request(body)
        if stop_conditions.get("max_tokens") is None:
            stop_conditions["max_tokens"] = self.default_max_tokens
        # Request deadline: client ``timeout`` (seconds; the HTTP layer
        # injects the frontend's --request-timeout-ms default) becomes a
        # deadline *budget* on the wire. The scheduler evicts past-deadline
        # rows and frees their KV; the Migration operator decrements the
        # budget across replays so a migrated request cannot out-live it.
        timeout_s = body.get("timeout")
        if timeout_s:
            stop_conditions["deadline_ms"] = float(timeout_s) * 1000.0
        # Guided decoding: response_format / forced tool_choice / nvext
        # guided_* → normalized grammar spec. Unsupported or malformed
        # constraints raise RequestError here (a structured 400) — the
        # engine only ever sees pre-validated, compilable patterns.
        from dynamo_tpu.llm.guided.grammar import build_guided_spec

        guided = build_guided_spec(body)
        return PreprocessedRequest(
            token_ids=token_ids,
            sampling_options=sampling_from_request(body),
            stop_conditions=stop_conditions,
            annotations=list(nvext.get("annotations") or []),
            model=body.get("model", ""),
            router_overrides=nvext.get("router") or {},
            image_urls=image_urls,
            guided_decoding=guided,
            # Resolved by the frontend (http/service.py _resolve_tenant);
            # raw `user` is the fallback so non-HTTP entry points still bill.
            tenant=body.get("_tenant") or body.get("user") or "anon",
        ), prompt
