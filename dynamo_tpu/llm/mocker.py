"""Mocker engine: full engine emulation with no TPU.

Ref: lib/llm/src/mocker/* (3,226 LoC) — ``MockVllmEngine`` (engine.rs:48)
simulates a batched scheduler with prefill/decode timing, KV block
allocation with prefix caching, watermark-driven preemption, and KV events,
all compressed by ``speedup_ratio``; the reference's distributed test suite
runs whole router/frontend topologies against fleets of these (SURVEY.md §4
— the single highest-leverage test asset).

This mocker mirrors the real engine's architecture (scheduler.py) rather
than simulating per-request in isolation:

- ONE batched simulation loop steps all running sequences together; each
  step's duration comes from a load-dependent timing model —
  ``decode_ms(batch, active_kv_tokens)`` (bandwidth-bound decode: a base
  weights-streaming floor plus per-sequence and per-cached-token terms) and
  ``prefill_ms(chunk_tokens)`` for the chunked prefill admitted alongside —
  so routers and the planner observe the queueing effects the reference
  mocker models (mocker/scheduler.rs:240): ITL rises with batch size and
  with active context length.
- The *real* ``BlockAllocator`` + chained hashing provide prefix caching
  and block-granular KV events, bit-identical to the real engine's.
- Watermark preemption: when block allocation fails mid-decode, the newest
  running sequence is preempted (blocks released → removed events) and
  requeued for recompute — the real scheduler's policy.
- ``speedup_ratio`` compresses simulated time uniformly.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass
from typing import Any, AsyncIterator, Callable, List, Optional

from dynamo_tpu.engine.kv_cache import BlockAllocator, KvEvent, OutOfBlocksError
from dynamo_tpu.engine.scheduler import ForwardPassMetrics
from dynamo_tpu.llm.tokens import compute_block_hashes
from dynamo_tpu.runtime import faults
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.telemetry import SloConfig, SloJudge, Telemetry

logger = get_logger(__name__)

# Queue sentinel for an injected engine crash: ``generate`` turns it into an
# abrupt ConnectionResetError (the stream dies without a final frame).
_CRASH = object()


@dataclass
class MockEngineArgs:
    """Ref: mocker/protocols.rs:67 MockEngineArgs."""

    block_size: int = 16
    num_blocks: int = 512
    max_batch: int = 32
    speedup_ratio: float = 1.0
    # Fraction of blocks kept free: allocations that would dip below the
    # watermark trigger preemption (ref mocker's eviction policy).
    watermark: float = 0.01
    # Timing model — decode is bandwidth-bound (weights floor + per-seq +
    # per-active-KV-token), prefill is compute-bound (per-token).
    itl_base_ms: float = 3.0
    itl_per_seq_ms: float = 0.05
    itl_per_kv_token_us: float = 0.05
    prefill_base_ms: float = 0.5
    prefill_per_token_us: float = 40.0
    max_prefill_chunk: int = 2048
    # SLA telemetry: same knobs as SchedulerConfig — the mocker judges its
    # (wall-clock) TTFT/TPOT against these and exports the same digest/SLO
    # stats keys, so planner tests and traffic harnesses run engine-free.
    slo_ttft_ms: Optional[float] = None
    slo_tpot_ms: Optional[float] = None
    # Tenant ledger (runtime/ledger.py): heavy-hitter sketch width, same
    # knob as SchedulerConfig.ledger_top_k.
    ledger_top_k: int = 16
    # Output-token rule: "cycle" repeats the prompt (default), "position"
    # emits token = sequence position — position streams continue bit-
    # identically across a migration replay (prompt + emitted tokens fold
    # into the replay prompt), which is what the chaos suite's zero-loss /
    # zero-duplication assertions pin.
    token_rule: str = "cycle"
    # Back-compat aliases used by older callers/flags.
    prefill_time_per_token_ms: Optional[float] = None
    decode_time_per_token_ms: Optional[float] = None

    def __post_init__(self):
        if self.prefill_time_per_token_ms is not None:
            self.prefill_per_token_us = self.prefill_time_per_token_ms * 1000.0
        if self.decode_time_per_token_ms is not None:
            self.itl_base_ms = self.decode_time_per_token_ms

    def decode_ms(self, batch: int, active_kv_tokens: int) -> float:
        return (
            self.itl_base_ms
            + batch * self.itl_per_seq_ms
            + active_kv_tokens * self.itl_per_kv_token_us / 1000.0
        )

    def prefill_ms(self, chunk_tokens: int) -> float:
        return self.prefill_base_ms + chunk_tokens * self.prefill_per_token_us / 1000.0


class _Seq:
    def __init__(
        self,
        request_id: str,
        tokens: List[int],
        max_tokens: int,
        context: Context,
        forced: Optional[List[int]] = None,
        deadline_ms: Optional[float] = None,
        prefill_done: bool = False,
        prefill_len: Optional[int] = None,
        tenant: str = "anon",
    ):
        self.request_id = request_id
        self.tenant = tenant
        self.tokens = tokens
        self.max_tokens = max_tokens
        self.context = context
        # Disaggregated decode leg: the prompt's KV "arrived by transfer"
        # (the real scheduler's disagg_inject) — blocks are allocated but
        # no prefill compute is simulated and no prefix is matched or
        # registered (transferred KV is not reuse). prefill_len < prompt
        # length marks a token-boundary SPLIT leg: only the first
        # prefill_len tokens transferred; the rest prefills locally.
        self.prefill_done = prefill_done
        self.prefill_len = len(tokens) if prefill_len is None else prefill_len
        self.arrival_ts = time.monotonic()
        self.deadline_ts = (
            self.arrival_ts + deadline_ms / 1000.0 if deadline_ms else None
        )
        self.admitted_ts: Optional[float] = None
        self.first_token_ts: Optional[float] = None
        # Guided decoding: the exact token stream to emit (a grammar-valid
        # rendering of the request's constraint) instead of prompt cycling.
        self.forced = forced
        self.out: asyncio.Queue = asyncio.Queue()
        self.block_ids: List[int] = []
        self.hashes = []
        self.computed = 0  # tokens (re)computed toward prefill_span
        self.cached_tokens = 0
        self.generated = 0
        self.recompute = 0  # generated tokens whose KV must be recomputed (preemption)
        self.preemptions = 0
        self.done = False
        # Tenant capacity bill (runtime/ledger.py) — same accrual discipline
        # as the real scheduler's Sequence: simulated device-seconds per
        # phase, lazy KV block-second clock, billed-once guard.
        self.bill_prefill_s = 0.0
        self.bill_decode_s = 0.0
        self.bill_kv_block_s = 0.0
        self.kv_ts: Optional[float] = None
        self.billed = False

    @property
    def total_len(self) -> int:
        return len(self.tokens) + self.generated

    @property
    def prefill_span(self) -> int:
        """Tokens the (re)prefill must cover: the prompt, plus — after a
        preemption — the generated tokens whose KV was dropped (the real
        scheduler's recompute-preemption cost)."""
        return len(self.tokens) + self.recompute

    @property
    def in_decode(self) -> bool:
        return self.computed >= self.prefill_span


class MockTpuEngine:
    """AsyncEngine-shaped engine emulator with a batched scheduler core."""

    def __init__(
        self,
        args: Optional[MockEngineArgs] = None,
        *,
        kv_event_sink: Optional[Callable[[KvEvent], None]] = None,
        tokenizer=None,
    ):
        self.args = args or MockEngineArgs()
        self._sink = kv_event_sink
        # Guided requests render their grammar's accepted string through
        # this tokenizer (default: the byte tokenizer the mocker stacks
        # serve with), so the full wire path yields schema-valid output.
        self.tokenizer = tokenizer
        self.guided_total = 0
        self.allocator = BlockAllocator(self.args.num_blocks, on_event=self._on_event)
        self.waiting: List[_Seq] = []
        self.running: List[_Seq] = []
        self.request_total = 0
        self.prefill_tokens_done = 0
        self.preempt_total = 0
        self.cached_tokens_total = 0  # prefix-cache hit tokens (hit-rate telemetry)
        self.timeouts_total = 0  # deadline evictions (finish_reason "timeout")
        # Traffic-shape counters: the planner's observer derives request
        # rate and avg ISL/OSL from these when no frontend is in the path
        # (pure mocker fleets under the traffic harness).
        self.input_tokens_total = 0
        self.output_tokens_total = 0
        self.disagg_prefill_done_total = 0  # decode legs admitted with transferred KV
        # Per-phase step accounting, same families as the flight recorder's
        # step_{phase}_* counters: the observer derives MEASURED per-worker
        # tok/s from Δtokens/Δtime of these, so the ProfiledCapacityModel
        # closes its loop on engine-free mocker fleets too. Time is wall
        # clock (speedup applied) — the same clock MockerCapacityModel's
        # declared rates are in.
        self.step_prefill_steps_total = 0
        self.step_prefill_tokens_total = 0
        self.step_prefill_time_s = 0.0
        self.step_decode_steps_total = 0
        self.step_decode_tokens_total = 0
        self.step_decode_time_s = 0.0
        # Elastic capacity dial: same semantics as Scheduler.set_capacity_dial
        # (budget split re-derived around the configured bases), so planner
        # stacks and the traffic harness exercise ratio shifts engine-free.
        self._base_prefill_chunk = self.args.max_prefill_chunk
        self._base_max_batch = self.args.max_batch
        self._elastic_fraction = 0.5
        self.elastic_dial_changes_total = 0
        # Degradation-ladder counters (same families as the disagg handler's
        # scrape): the handler — or a harness standing in for it — reports
        # mode transitions here so mocker fleets emit the engine's keys.
        self.degrade_disagg_to_colocated_total = 0
        self.degrade_colocated_to_disagg_total = 0
        self._step_n = 0  # chaos-plane step counter (worker.step site passes)
        self.last_step_ms = 0.0  # most recent simulated step duration
        self.last_step_ts: Optional[float] = None  # stall-watchdog reference
        # Same telemetry surface as the real engine (runtime/telemetry.py):
        # wall-clock ttft/tpot/itl/queue_wait digests + SLO/goodput account,
        # exported under the same stats keys so planner and traffic-harness
        # stacks observe a mocker fleet exactly like an engine fleet.
        self.telemetry = Telemetry()
        self.slo = SloJudge(SloConfig(ttft_ms=self.args.slo_ttft_ms,
                                      tpot_ms=self.args.slo_tpot_ms))
        # Tenant capacity ledger: same sketch/digest/stats surface as the
        # real scheduler's, fed from the simulated timing model, so fleet
        # merge and Grafana's Tenants row run engine-free.
        from dynamo_tpu.runtime.ledger import TenantLedger

        self.ledger = TenantLedger(
            top_k=self.args.ledger_top_k,
            slo=SloConfig(ttft_ms=self.args.slo_ttft_ms, tpot_ms=self.args.slo_tpot_ms),
        )
        # Incident autopsy plane (runtime/incidents.py): the mocker runs the
        # REAL detector over its own simulated stats and emits the same
        # incidents_*/gauge keys as TpuEngine, so planner/autoscaler stacks
        # observe identical metric families from an engine-free fleet.
        from dynamo_tpu.runtime.incidents import IncidentConfig, IncidentPlane

        self.incidents = IncidentPlane(
            IncidentConfig(),
            config_probe=lambda: {"engine": "mocker", "args": vars(self.args)},
        )
        self._loop_task: Optional[asyncio.Task] = None
        self._wake = asyncio.Event()

    def _on_event(self, ev: KvEvent) -> None:
        if self._sink is not None:
            self._sink(ev)

    def set_kv_event_sink(self, sink: Callable[[KvEvent], None]) -> None:
        self._sink = sink

    # --- elastic capacity dial ---------------------------------------------
    def set_capacity_dial(self, prefill_fraction: float) -> dict:
        """Re-split the simulated budget between prefill and decode, live —
        the mocker mirror of Scheduler.set_capacity_dial (same clamps, same
        f=0.5 ⇒ configured-identity), reachable via the same ``set_dial``
        control op when served behind an endpoint."""
        f = min(1.0, max(0.0, float(prefill_fraction)))
        bs = self.args.block_size
        raw = int(round(2.0 * f * self._base_prefill_chunk))
        budget = max(bs, min(raw, self._base_prefill_chunk))
        slots = int(round(2.0 * (1.0 - f) * self._base_max_batch))
        slots = max(1, min(self._base_max_batch, slots))
        self._elastic_fraction = f
        self.args.max_prefill_chunk = budget
        self.args.max_batch = slots
        self.elastic_dial_changes_total += 1
        logger.info("mocker capacity dial: prefill_fraction=%.3f → prefill_chunk=%d decode_slots=%d",
                    f, budget, slots)
        return {"prefill_fraction": f, "mixed_prefill_budget": budget, "decode_slots": slots}

    def note_degrade(self, direction: str) -> None:
        """Record a degradation-ladder transition on this worker's scrape
        (the disagg handler owns the decision; mocker fleets without one
        let the harness call this so the degrade_* families still flow)."""
        if direction == "disagg_to_colocated":
            self.degrade_disagg_to_colocated_total += 1
        elif direction == "colocated_to_disagg":
            self.degrade_colocated_to_disagg_total += 1
        else:
            raise ValueError(f"unknown degrade direction: {direction}")

    # --- AsyncEngine --------------------------------------------------------
    async def generate(self, request: Any, context: Context) -> AsyncIterator[dict]:
        tokens: List[int] = list(request.get("token_ids") or [])
        stop = request.get("stop_conditions") or {}
        max_tokens = int(stop.get("max_tokens") or 16)
        deadline_ms = stop.get("deadline_ms")
        self.request_total += 1
        # Disagg decode legs are marked "_prefilled" on the engine-plane
        # wire (disagg.py); the traffic harness's synthetic requests use the
        # legacy "prefill_done" flag. Honor both so the mocker behaves like
        # the real engine when it stands in for one behind the disagg
        # handler ("prefill_done" itself is baselined in dtlint_baseline).
        pref = request.get("_prefilled") or request.get("prefill_done")
        prefilled = bool(pref)
        # Token-boundary split legs: a dict _prefilled may carry
        # "prefill_len" = N (< prompt length) — the first N tokens arrived
        # as transferred KV; the remainder prefills locally, exactly the
        # real scheduler's partial-inject path.
        prefill_len = len(tokens)
        if isinstance(pref, dict) and pref.get("prefill_len") is not None:
            prefill_len = min(int(pref["prefill_len"]), len(tokens))
        if not prefilled:
            # Disagg decode legs carry the prompt for context accounting but
            # prefill none of it — counting their input tokens would double
            # the observer's prefill-demand estimate (rate × ISL).
            self.input_tokens_total += len(tokens)
        elif prefill_len < len(tokens):
            self.input_tokens_total += len(tokens) - prefill_len  # the local remainder
        forced = self._guided_tokens(request.get("guided_decoding"))
        seq = _Seq(
            f"mock-{self.request_total}", tokens, max_tokens, context,
            forced=forced, deadline_ms=float(deadline_ms) if deadline_ms else None,
            prefill_done=prefilled, prefill_len=prefill_len,
            tenant=request.get("tenant") or "anon",
        )
        self.waiting.append(seq)
        self._ensure_loop()
        self._wake.set()
        try:
            while True:
                frame = await seq.out.get()
                if frame is None:
                    return
                if frame is _CRASH:
                    # An injected engine crash: die like a process death —
                    # the worker ingress drops the call-home socket and the
                    # client observes a genuine StreamDisconnect.
                    raise ConnectionResetError("injected worker crash")
                yield frame
                if frame.get("finish_reason"):
                    return
        finally:
            seq.done = True

    def _guided_tokens(self, spec) -> Optional[List[int]]:
        """Honor a guided-decoding spec: compile its grammar and emit the
        (deterministic) shortest accepted string as the output token stream,
        so router/frontend stacks exercise the full structured-output wire
        path — response_format in, schema-valid JSON out — with no model."""
        if not spec:
            return None
        from dynamo_tpu.llm.guided.grammar import GrammarError, spec_to_dfa

        try:
            text = spec_to_dfa(spec).shortest_accepting()
        except GrammarError as e:
            logger.warning("mocker ignoring uncompilable guided spec: %s", e)
            return None
        self.guided_total += 1
        if self.tokenizer is not None:
            return list(self.tokenizer.encode(text))
        from dynamo_tpu.llm.tokenizer import ByteTokenizer

        return list(ByteTokenizer().encode(text))

    def _ensure_loop(self) -> None:
        if self._loop_task is None or self._loop_task.done():
            self._loop_task = asyncio.get_running_loop().create_task(self._sim_loop())

    # --- batched simulation core -------------------------------------------
    async def _sim_loop(self) -> None:
        args = self.args
        while self.waiting or self.running:
            self._reap_stopped()
            step_ms = 0.0
            slow_factor = 1.0

            # Chaos plane (runtime/faults.py): the per-step site. ``crash``
            # kills the engine loop and severs every live stream abruptly
            # (process-death semantics); ``hang`` wedges the loop inside
            # afire; ``slow`` stretches this step's simulated duration.
            if faults.armed():
                self._step_n += 1
                try:
                    spec = await faults.afire("worker.step", step=self._step_n)
                except faults.InjectedFault:
                    self._crash_all()
                    return
                if spec is not None and spec.kind == "slow":
                    slow_factor = max(spec.factor, 1.0)

            # Admission: a WAVE of prefill chunks per step, bounded by a
            # max_prefill_chunk token budget — mirroring the real
            # scheduler's wave admission + mixed-step prefill budget (a
            # burst of short/cache-hit prompts admits together instead of
            # serializing one per step, which queued concentrated KV-routed
            # traffic behind an artificial one-admission rule). Prefer
            # mid-chunk sequences (they already hold blocks — leaving one
            # parked while the head can't allocate is a head-of-line
            # deadlock); otherwise take the head.
            wave_tokens = 0
            wave_bill: List[tuple] = []  # (seq, chunk) — per-seq prefill attribution
            while (
                self.waiting
                and len(self.running) < args.max_batch
                and wave_tokens < args.max_prefill_chunk
            ):
                seq = next((s for s in self.waiting if s.block_ids), self.waiting[0])
                chunk = self._admit_chunk(seq, args.max_prefill_chunk - wave_tokens)
                wave_tokens += chunk
                self.prefill_tokens_done += chunk
                if chunk:
                    wave_bill.append((seq, chunk))
                if seq.in_decode:
                    # remove() not pop(0): _admit_chunk's allocation may have
                    # preempted a victim INTO waiting[0] just now.
                    self.waiting.remove(seq)
                    self.running.append(seq)
                else:
                    break  # blocked on KV blocks, or budget consumed mid-prompt
            pre_ms = args.prefill_ms(wave_tokens) if wave_tokens else 0.0
            step_ms += pre_ms

            # Batched decode step: every running sequence produces one token;
            # latency depends on batch width and total active KV.
            decoding = [s for s in self.running if s.in_decode]
            dec_ms = 0.0
            if decoding:
                active_kv = sum(s.total_len for s in decoding)
                dec_ms = args.decode_ms(len(decoding), active_kv)
                step_ms += dec_ms

            if step_ms == 0.0:
                # Nothing admissible (block pressure): idle-wait a tick.
                step_ms = args.itl_base_ms

            step_ms *= slow_factor
            self.last_step_ms = step_ms
            await asyncio.sleep(step_ms / 1000.0 / args.speedup_ratio)
            self.last_step_ts = time.monotonic()
            # Per-phase step accounting: each phase is charged its own
            # simulated wall time (slow-factor included, so chaos slowdowns
            # show up as genuinely reduced measured capacity).
            scale = slow_factor / 1000.0 / args.speedup_ratio
            if wave_tokens:
                self.step_prefill_steps_total += 1
                self.step_prefill_tokens_total += wave_tokens
                self.step_prefill_time_s += pre_ms * scale
                # Tenant billing: the wave's simulated prefill time splits
                # pro-rata by chunk tokens — shares sum to the step exactly.
                for s, chunk in wave_bill:
                    s.bill_prefill_s += pre_ms * scale * (chunk / wave_tokens)
            if decoding:
                self.step_decode_steps_total += 1
                self.step_decode_tokens_total += len(decoding)
                self.step_decode_time_s += dec_ms * scale
                # Decode billing: each row's marginal term of the timing
                # model (per-seq + per-KV-token), normalized so the shared
                # weights-streaming floor is carried pro-rata too.
                dweights = [
                    args.itl_per_seq_ms + s.total_len * args.itl_per_kv_token_us / 1000.0
                    for s in decoding
                ]
                dsum = sum(dweights) or 1.0
                for s, w in zip(decoding, dweights):
                    s.bill_decode_s += dec_ms * scale * w / dsum
            # KV block-second accrual for every current holder (lazy clock,
            # same discipline as the real scheduler's _accrue_kv).
            kv_now = time.monotonic()
            for s in self.running + self.waiting:
                if s.block_ids or s.kv_ts is not None:
                    self._accrue_kv(s, kv_now)
            if decoding:
                # Wall-clock step time = the ITL the wire observes.
                self.telemetry.observe("itl", step_ms / 1000.0 / args.speedup_ratio)
                self.telemetry.observe("decode_step", step_ms / 1000.0 / args.speedup_ratio)

            for s in list(decoding):
                if s not in self.running:
                    continue  # preempted mid-step by another row's allocation
                if s.context.is_stopped():
                    continue  # reaped next iteration
                if not self._grow_blocks(s):
                    continue  # preempted (itself) — no token this step
                if s.forced is not None and not s.forced:
                    # Grammar accepts the empty string: finish immediately.
                    s.out.put_nowait({"token_ids": [], "finish_reason": "stop", "index": 0})
                    self._finish(s, "stop")
                    continue
                s.generated += 1
                self.output_tokens_total += 1
                if s.forced is not None:
                    # Guided: emit the grammar-valid stream; "stop" on the
                    # final token (the FSM accepted), "length" if max_tokens
                    # cuts the rendering short.
                    token = s.forced[s.generated - 1]
                    finish = "stop" if s.generated >= len(s.forced) else None
                    if finish is None and s.generated >= s.max_tokens:
                        finish = "length"
                elif args.token_rule == "position":
                    # token = 0-based sequence position: a migrated replay
                    # (prompt + already-emitted tokens) continues exactly
                    # where the dead worker stopped.
                    token = s.total_len - 1
                    finish = "length" if s.generated >= s.max_tokens else None
                else:
                    token = s.tokens[s.generated % len(s.tokens)] if s.tokens else s.generated
                    finish = "length" if s.generated >= s.max_tokens else None
                frame = {"token_ids": [token], "finish_reason": finish, "index": 0}
                if s.generated == 1:
                    s.first_token_ts = time.monotonic()
                    self.telemetry.observe(
                        "ttft", max(0.0, s.first_token_ts - s.arrival_ts)
                    )
                    # First frame carries the real engine's reuse report:
                    # prompt tokens whose simulated prefill was skipped by
                    # the prefix cache (the wire shape router/frontend
                    # accounting reads).
                    frame["cached_tokens"] = s.cached_tokens
                s.out.put_nowait(frame)
                if finish:
                    # Natural finish: judge SLA (cancelled requests aren't
                    # latency violations) and fold TPOT into the digests.
                    ttft_s = tpot_s = None
                    if s.first_token_ts is not None:
                        now = time.monotonic()
                        ttft_s = max(0.0, s.first_token_ts - s.arrival_ts)
                        if s.generated > 1:
                            tpot_s = max(0.0, now - s.first_token_ts) / (s.generated - 1)
                            self.telemetry.observe("tpot", tpot_s)
                        self.slo.judge(ttft_s, tpot_s, s.generated)
                    self._finish(s, finish, ttft_s=ttft_s, tpot_s=tpot_s)
            if not (self.waiting or self.running):
                # Wait briefly for new arrivals before exiting the loop task.
                self._wake.clear()
                try:
                    await asyncio.wait_for(self._wake.wait(), timeout=0.2)
                except asyncio.TimeoutError:
                    # A request may have landed in the shutdown window (its
                    # _wake.set() can race the cancelled waiter): only exit
                    # when there is truly no work.
                    if not (self.waiting or self.running):
                        return

    def _reap_stopped(self) -> None:
        now = time.monotonic()

        def verdict(s: _Seq) -> Optional[str]:
            if s.context.is_stopped() or s.done:
                return "cancelled"
            if s.deadline_ts is not None and now >= s.deadline_ts:
                # Deadline eviction, same semantics as the real scheduler:
                # finish_reason "timeout", blocks freed right here.
                self.timeouts_total += 1
                return "timeout"
            return None

        for s in list(self.running):
            reason = verdict(s)
            if reason is not None:
                if not s.done:
                    s.out.put_nowait({"token_ids": [], "finish_reason": reason, "index": 0})
                self._finish(s, reason)
        for s in list(self.waiting):
            reason = verdict(s)
            if reason is not None:
                self.waiting.remove(s)
                # Never-admitted requests still bill their queue time (and
                # any mid-prefill KV hold) — timeout storms in the queue are
                # exactly what tenant attribution must see.
                self._emit_bill(s, reason)
                self.allocator.release(s.block_ids)
                s.block_ids = []
                s.kv_ts = None
                if not s.done:
                    s.out.put_nowait({"token_ids": [], "finish_reason": reason, "index": 0})

    def _admit_chunk(self, seq: _Seq, budget: Optional[int] = None) -> int:
        """Advance one prefill chunk; returns simulated chunk tokens (0 when
        blocked on KV blocks). First touch matches the prefix cache —
        cached tokens shorten the simulated prefill (the chunk covers only
        the uncached remainder, the real engine's skipped-FLOPs behavior).
        ``budget`` caps the chunk (wave admission shares one per-step
        token budget across admitted sequences)."""
        args = self.args
        bs = args.block_size
        if seq.computed == 0 and not seq.block_ids and seq.prefill_done and seq.recompute == 0:
            # Disagg decode leg: KV for (the first prefill_len tokens of)
            # the prompt was transferred in. Allocate the blocks the full
            # sequence occupies, skip the prefill simulation for the
            # transferred span, and leave the prefix cache untouched
            # (transferred blocks are private — counting them as cache hits
            # would poison the router's warmth accounting). A SPLIT leg
            # (prefill_len < prompt) falls through to chunked prefill for
            # the remainder. After a preemption the transferred KV is gone
            # and the normal recompute path runs.
            needed = (seq.total_len + 1 + bs - 1) // bs
            if not self._allocate(seq, needed, preempt=False):
                return 0
            n_pref = min(seq.prefill_len, len(seq.tokens))
            full = n_pref >= len(seq.tokens)
            seq.computed = seq.prefill_span if full else n_pref
            self.disagg_prefill_done_total += 1
            if seq.admitted_ts is None:
                seq.admitted_ts = time.monotonic()
                self.telemetry.observe(
                    "queue_wait", max(0.0, seq.admitted_ts - seq.arrival_ts)
                )
            if full:
                return 0
        if seq.computed == 0 and not seq.block_ids:
            seq.hashes = compute_block_hashes(seq.tokens, bs)
            matched = self.allocator.match_prefix(seq.hashes)
            if matched and len(matched) * bs >= len(seq.tokens):
                self.allocator.release([matched[-1]])
                matched = matched[:-1]
            seq.block_ids = list(matched)
            seq.cached_tokens = len(matched) * bs
            seq.computed = min(seq.cached_tokens, seq.prefill_span)
            # Cover the full current length (prompt + any generated tokens
            # being recomputed after preemption) plus the next write slot.
            # Admission never preempts — it backpressures (the real
            # scheduler's _admit policy): preempting a decode to admit a
            # newcomer just trades one recompute for another, and under
            # wave admission it livelocks (victims re-match their own
            # still-registered prefix and thrash).
            needed = (seq.total_len + 1 + bs - 1) // bs - len(seq.block_ids)
            if needed > 0 and not self._allocate(seq, needed, preempt=False):
                # Roll back the first touch entirely; retried next step.
                self.allocator.release(seq.block_ids)
                seq.block_ids = []
                seq.computed = 0
                seq.cached_tokens = 0
                return 0
            # Count hits only on a COMMITTED first touch — a rolled-back
            # admission retries and would double-count (which inflated the
            # thrash-prone policy's hit rate in bench_router_prefix).
            self.cached_tokens_total += seq.cached_tokens
            if seq.admitted_ts is None:
                seq.admitted_ts = time.monotonic()
                self.telemetry.observe(
                    "queue_wait", max(0.0, seq.admitted_ts - seq.arrival_ts)
                )
        remaining = seq.prefill_span - seq.computed
        chunk = min(remaining, args.max_prefill_chunk)
        if budget is not None:
            chunk = min(chunk, budget)
        seq.computed += chunk
        # Register every completed block as chunks land (the real
        # scheduler's per-chunk registration): concurrent same-prefix
        # requests share KV mid-prefill.
        n_done = min(seq.computed, len(seq.tokens)) // bs
        n_done = min(n_done, len(seq.hashes), len(seq.block_ids))
        if n_done:
            self.allocator.register_hashes(seq.block_ids[:n_done], seq.hashes[:n_done])
        return chunk

    def _allocate(self, seq: _Seq, n: int, preempt: bool = True) -> bool:
        """Allocate n blocks, preempting the newest running sequence when the
        pool dips below the watermark (ref mocker's eviction policy).
        ``preempt=False`` (admission path) backpressures instead."""
        args = self.args
        floor = int(args.num_blocks * args.watermark)
        while True:
            if self.allocator.num_blocks - self.allocator.num_active - n >= floor:
                try:
                    seq.block_ids.extend(self.allocator.allocate(n))
                    return True
                except OutOfBlocksError:
                    pass
            if not preempt or not self._preempt_newest(exclude=seq):
                return False

    def _grow_blocks(self, seq: _Seq) -> bool:
        bs = self.args.block_size
        while seq.total_len + 1 > len(seq.block_ids) * bs:
            if not self._allocate(seq, 1):
                # Could not grow even after preempting others: preempt SELF.
                self._preempt(seq)
                return False
        return True

    def _preempt_newest(self, exclude: Optional[_Seq] = None) -> bool:
        candidates = [s for s in self.running if s is not exclude and s.in_decode]
        if not candidates:
            return False
        self._preempt(candidates[-1])
        return True

    def _preempt(self, seq: _Seq) -> None:
        if seq in self.running:
            self.running.remove(seq)
        # Close the KV clock at the true release point (recompute holds none).
        self._accrue_kv(seq)
        seq.kv_ts = None
        self.allocator.release(seq.block_ids)
        seq.block_ids = []
        seq.hashes = []
        seq.computed = 0
        seq.cached_tokens = 0
        seq.recompute = seq.generated  # dropped KV must be recomputed
        seq.preemptions += 1
        self.preempt_total += 1
        self.waiting.insert(0, seq)

    def _finish(self, seq: _Seq, reason: str = "cancelled",
                ttft_s: Optional[float] = None, tpot_s: Optional[float] = None) -> None:
        if seq in self.running:
            self.running.remove(seq)
        if seq in self.waiting:
            self.waiting.remove(seq)
        # Bill while blocks are still held so the KV accrual closes at the
        # true release point — same choke-point discipline as the scheduler.
        self._emit_bill(seq, reason, ttft_s=ttft_s, tpot_s=tpot_s)
        self.allocator.release(seq.block_ids)
        seq.block_ids = []
        seq.kv_ts = None

    def _accrue_kv(self, seq: _Seq, now: Optional[float] = None) -> None:
        """Lazy KV block-second accrual (real scheduler's _accrue_kv)."""
        if now is None:
            now = time.monotonic()
        if seq.kv_ts is not None:
            seq.bill_kv_block_s += len(seq.block_ids) * (now - seq.kv_ts)
        seq.kv_ts = now if seq.block_ids else None

    def _emit_bill(self, seq: _Seq, reason: str,
                   ttft_s: Optional[float] = None,
                   tpot_s: Optional[float] = None) -> None:
        if seq.billed:
            return
        seq.billed = True
        from dynamo_tpu.runtime.ledger import RequestBill

        self._accrue_kv(seq)
        queue_end = seq.admitted_ts if seq.admitted_ts is not None else time.monotonic()
        self.ledger.record(RequestBill(
            tenant=seq.tenant,
            request_id=seq.request_id,
            queue_s=max(0.0, queue_end - seq.arrival_ts),
            prefill_device_s=seq.bill_prefill_s,
            decode_device_s=seq.bill_decode_s,
            flops=0.0,  # the mocker has no cost model — device time is the truth
            output_tokens=seq.generated,
            kv_block_s=seq.bill_kv_block_s,
            finish_reason=reason,
            ttft_s=ttft_s,
            tpot_s=tpot_s,
        ))

    def _crash_all(self) -> None:
        """Injected engine death: sever every live stream without a final
        frame (clients observe StreamDisconnect and migrate) and free the
        pool — the next request restarts the sim loop, i.e. the worker
        'process' comes back empty, exactly like a restart."""
        logger.warning("mocker crash injected: dropping %d stream(s)",
                       len(self.running) + len(self.waiting))
        for s in self.running + self.waiting:
            self.allocator.release(s.block_ids)
            s.block_ids = []
            s.kv_ts = None  # process death: in-flight consumption bills nowhere
            s.out.put_nowait(_CRASH)
        self.running.clear()
        self.waiting.clear()

    # --- stats --------------------------------------------------------------
    def metrics(self) -> ForwardPassMetrics:
        return ForwardPassMetrics(
            num_running=len(self.running),
            num_waiting=len(self.waiting),
            kv_usage=self.allocator.usage(),
            kv_total_blocks=self.allocator.num_blocks,
            kv_active_blocks=self.allocator.num_active,
            prefill_tokens_in_flight=sum(len(s.tokens) - s.computed for s in self.waiting),
            request_total=self.request_total,
            cached_tokens_total=self.cached_tokens_total,
            prefix_hit_blocks_total=self.allocator.hit_blocks_total,
            prefix_miss_blocks_total=self.allocator.miss_blocks_total,
            prefix_evicted_blocks_total=self.allocator.evicted_blocks_total,
            elastic_prefill_fraction=self._elastic_fraction,
            elastic_prefill_budget=self.args.max_prefill_chunk,
            elastic_decode_slots=self.args.max_batch,
            elastic_dial_changes_total=self.elastic_dial_changes_total,
        )

    def stats_handler(self) -> dict:
        m = self.metrics()
        a = self.allocator
        hits, misses = a.hit_blocks_total, a.miss_blocks_total
        stats = {
            "kv_usage": m.kv_usage,
            "num_running": m.num_running,
            "num_waiting": m.num_waiting,
            # Prefix-cache hit accounting over the scrape path, same keys as
            # the real engine's stats_handler (aggregator counters).
            "cached_tokens_total": m.cached_tokens_total,
            "prefix_hit_blocks_total": m.prefix_hit_blocks_total,
            "prefix_miss_blocks_total": m.prefix_miss_blocks_total,
            "prefix_evicted_blocks_total": m.prefix_evicted_blocks_total,
            # Utilization gauges, same keys as Scheduler.kv_gauges().
            "kv_free_blocks": len(a._free),
            "kv_cached_blocks": a.num_cached,
            "prefix_hit_rate": round(hits / (hits + misses), 6) if (hits + misses) else 0.0,
            # KV warmth: fraction of the pool holding registered (reusable)
            # prefix KV — the engine-side half of the planner's
            # coldest-worker scale-down signal.
            "kv_warmth": round(a.num_cached / a.num_blocks, 6) if a.num_blocks else 0.0,
            "preemptions_total": self.preempt_total,
            "request_total": self.request_total,
            "request_timeouts_total": self.timeouts_total,
            # Traffic shape for the observer (rate = Δrequest_total/Δt,
            # ISL/OSL = token deltas per request delta) on frontend-less
            # mocker fleets.
            "input_tokens_total": self.input_tokens_total,
            "output_tokens_total": self.output_tokens_total,
            "disagg_prefill_done_total": self.disagg_prefill_done_total,
            # Elastic capacity dial + degradation ladder: same key families
            # as the engine scrape (stats_handler) and the disagg handler's,
            # so planner stacks exercise ratio shifts engine-free.
            "elastic_prefill_fraction": self._elastic_fraction,
            "elastic_prefill_budget": self.args.max_prefill_chunk,
            "elastic_decode_slots": self.args.max_batch,
            "elastic_dial_changes_total": self.elastic_dial_changes_total,
            "degrade_disagg_to_colocated_total": self.degrade_disagg_to_colocated_total,
            "degrade_colocated_to_disagg_total": self.degrade_colocated_to_disagg_total,
            # Per-phase step families (flight-recorder key parity): the
            # observer's measured tok/s derivation reads Δtokens/Δseconds.
            "step_prefill_steps_total": self.step_prefill_steps_total,
            "step_prefill_tokens_total": self.step_prefill_tokens_total,
            "step_prefill_time_seconds_total": round(self.step_prefill_time_s, 6),
            "step_decode_steps_total": self.step_decode_steps_total,
            "step_decode_tokens_total": self.step_decode_tokens_total,
            "step_decode_time_seconds_total": round(self.step_decode_time_s, 6),
        }
        # Device-truth parity: plausible synthetic measured siblings so the
        # aggregator/Grafana/planner stack runs engine-free. The mocker's
        # simulated clock IS its device, so the synthetic sampler reports
        # one 250ms window per 30s of simulated busy time, 85% device-busy,
        # a perfectly calibrated cost model, and the fused window holding
        # its 1-launch invariant.
        sim_busy_s = self.step_prefill_time_s + self.step_decode_time_s
        windows = int(sim_busy_s / 30.0) + (1 if sim_busy_s > 0 else 0)
        stats.update({
            "device_profile_windows_total": windows,
            "device_profile_window_seconds_total": round(windows * 0.25, 6),
            "device_profile_skipped_busy_total": 0,
            "device_profile_errors_total": 0,
            "device_profile_duty_cycle": round(0.25 / 30.0, 6),
            "cost_model_calibrated": 1.0,
        })
        if windows:
            stats.update({
                "measured_windows_total": windows,
                "measured_device_seconds_total": round(windows * 0.25 * 0.85, 6),
                "measured_wall_seconds_total": round(windows * 0.25, 6),
                "measured_mfu": 0.45,
                "measured_hbm_frac": 0.6,
                "measured_device_frac": 0.85,
                "measured_modeled_mfu_ratio": 1.0,
                "measured_top_kernel_share": 0.55,
                "measured_launches_per_fused_window": 1.0,
            })
        # Chaos plane: injected-fault counters, same keys as the engine's
        # scrape (only present on chaos-armed workers).
        stats.update(faults.stats())
        # SLO/goodput account + latency digests: identical keys/shape to
        # TpuEngine.stats_handler, so the aggregator/planner/observer stack
        # can run against pure mocker fleets.
        stats.update(self.slo.to_stats())
        stats["digests"] = self.telemetry.to_wire()
        # Tenant ledger: identical flat tenant_* keys + sketch wire as the
        # real engine's scrape, so the aggregator's fleet merge and the
        # Grafana Tenants row run against mocker fleets unchanged.
        stats.update(self.ledger.to_stats())
        stats["tenant_ledger"] = self.ledger.to_wire()
        # Incident plane: same detector, same incidents_*/profiler keys as
        # the real engine's scrape (engine-free planner stacks included).
        self.incidents.observe(stats)
        stats.update(self.incidents.to_stats())
        return stats
