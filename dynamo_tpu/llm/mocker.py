"""Mocker engine: full engine emulation with no TPU.

Ref: lib/llm/src/mocker/* (3,226 LoC) — ``MockVllmEngine`` (engine.rs:48)
simulates prefill/decode timing, KV block allocation with prefix caching, and
KV events at ``speedup_ratio``; the reference's distributed test suite runs
whole router/frontend topologies against fleets of these (SURVEY.md §4 — the
single highest-leverage test asset).

This mocker reuses the *real* BlockAllocator + chained hashing, so its KV
events and prefix-cache hit behavior are bit-identical to the real engine's;
only the compute is replaced by sleeps.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Callable, List, Optional

from dynamo_tpu.engine.kv_cache import BlockAllocator, KvEvent, OutOfBlocksError
from dynamo_tpu.engine.scheduler import ForwardPassMetrics
from dynamo_tpu.llm.tokens import compute_block_hashes
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.logging import get_logger

logger = get_logger(__name__)


@dataclass
class MockEngineArgs:
    """Ref: mocker/protocols.rs:67 MockEngineArgs."""

    block_size: int = 16
    num_blocks: int = 512
    max_batch: int = 32
    speedup_ratio: float = 1.0
    prefill_time_per_token_ms: float = 0.05
    decode_time_per_token_ms: float = 5.0
    watermark: float = 0.01


class MockTpuEngine:
    """AsyncEngine-shaped engine emulator."""

    def __init__(self, args: Optional[MockEngineArgs] = None, *, kv_event_sink: Optional[Callable[[KvEvent], None]] = None):
        self.args = args or MockEngineArgs()
        self._sink = kv_event_sink
        self.allocator = BlockAllocator(self.args.num_blocks, on_event=self._on_event)
        self._batch = asyncio.Semaphore(self.args.max_batch)
        self._active = 0
        self._waiting = 0
        self.request_total = 0
        self.prefill_tokens_done = 0

    def _on_event(self, ev: KvEvent) -> None:
        if self._sink is not None:
            self._sink(ev)

    def set_kv_event_sink(self, sink: Callable[[KvEvent], None]) -> None:
        self._sink = sink

    # --- AsyncEngine --------------------------------------------------------
    async def generate(self, request: Any, context: Context) -> AsyncIterator[dict]:
        args = self.args
        tokens: List[int] = list(request.get("token_ids") or [])
        stop = request.get("stop_conditions") or {}
        max_tokens = int(stop.get("max_tokens") or 16)
        self.request_total += 1
        self._waiting += 1
        async with self._batch:
            self._waiting -= 1
            self._active += 1
            block_ids: List[int] = []
            try:
                hashes = compute_block_hashes(tokens, args.block_size)
                matched = self.allocator.match_prefix(hashes)
                cached_tokens = len(matched) * args.block_size
                block_ids = list(matched)
                needed = (len(tokens) + max_tokens + args.block_size - 1) // args.block_size - len(block_ids)
                while needed > 0:
                    try:
                        block_ids.extend(self.allocator.allocate(needed))
                        needed = 0
                    except OutOfBlocksError:
                        await asyncio.sleep(0.005 / args.speedup_ratio)  # backpressure
                        if context.is_stopped():
                            return

                # Prefill: time proportional to uncached tokens.
                uncached = max(0, len(tokens) - cached_tokens)
                await asyncio.sleep(uncached * args.prefill_time_per_token_ms / 1000.0 / args.speedup_ratio)
                self.prefill_tokens_done += uncached
                n_full = len(hashes)
                self.allocator.register_hashes(block_ids[:n_full], hashes)

                # Decode: one token per step at the configured ITL.
                for i in range(max_tokens):
                    if context.is_stopped():
                        yield {"token_ids": [], "finish_reason": "cancelled", "index": 0}
                        return
                    await asyncio.sleep(args.decode_time_per_token_ms / 1000.0 / args.speedup_ratio)
                    token = tokens[i % len(tokens)] if tokens else i
                    finish = "length" if i == max_tokens - 1 else None
                    yield {"token_ids": [token], "finish_reason": finish, "index": 0}
            finally:
                self.allocator.release(block_ids)
                self._active -= 1

    # --- stats --------------------------------------------------------------
    def metrics(self) -> ForwardPassMetrics:
        return ForwardPassMetrics(
            num_running=self._active,
            num_waiting=self._waiting,
            kv_usage=self.allocator.usage(),
            kv_total_blocks=self.allocator.num_blocks,
            kv_active_blocks=self.allocator.num_active,
            request_total=self.request_total,
        )

    def stats_handler(self) -> dict:
        m = self.metrics()
        return {"kv_usage": m.kv_usage, "num_running": m.num_running, "num_waiting": m.num_waiting}
