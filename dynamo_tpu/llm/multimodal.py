"""Multimodal serving: image content parts → encode worker → prefill.

Ref: the trtllm encode-worker flow (components/backends/trtllm/src/dynamo/
trtllm/utils/encode_helper.py) and the image paths in the vllm/sglang
adapters. Topology mirrors the reference's disagg pattern:

    frontend → preprocessor → [EncodeOperator] → LM worker
                                   │ images
                                   ▼
                              encode worker (ViT, its own chip pool)

- :func:`extract_images` pulls ``image_url`` content parts out of chat
  messages (data: URLs — the zero-egress environment has no fetch path)
  and flattens the remaining text for the chat template.
- :class:`EncodeWorkerHandler` is the encode worker's endpoint: decodes +
  resizes images, runs the JAX ViT (engine/models/vision.py), and returns
  features (wire: base64 f32; in-process: the array itself).
- :class:`EncodeOperator` is the frontend-side pipeline stage: when a
  request carries images it obtains features (local encoder or remote
  encode worker), prepends one placeholder token per feature row to
  ``token_ids``, and attaches the features for the engine to inject at
  those positions (llama.prefill ``mm_feats``).
"""

from __future__ import annotations

import base64
import io
from typing import Any, AsyncIterator, List, Optional, Tuple

import numpy as np

from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.pipeline import Operator

logger = get_logger(__name__)

# Placeholder token id occupying image-feature positions in the prompt.
# Position bookkeeping (KV blocks, usage accounting) sees ordinary tokens;
# prefill overrides their embeddings with the feature rows.
IMAGE_PLACEHOLDER_TOKEN = 0


def decode_image_data_url(url: str, size: int) -> np.ndarray:
    """data:image/...;base64,... → [size, size, 3] f32 in [0, 1]."""
    if not url.startswith("data:"):
        raise ValueError(
            "only data: image URLs are supported (zero-egress environment)"
        )
    try:
        b64 = url.split(",", 1)[1]
        raw = base64.b64decode(b64)
    except (IndexError, ValueError) as e:
        raise ValueError(f"malformed image data URL: {e}") from None
    from PIL import Image

    img = Image.open(io.BytesIO(raw)).convert("RGB").resize((size, size))
    return np.asarray(img, dtype=np.float32) / 255.0


def extract_images(messages: List[dict]) -> Tuple[List[dict], List[str]]:
    """Split image_url parts out of chat messages. Returns (messages with
    flattened text content, image URLs in order of appearance)."""
    out, urls = [], []
    for msg in messages:
        content = msg.get("content")
        if not isinstance(content, list):
            out.append(msg)
            continue
        texts = []
        for part in content:
            if not isinstance(part, dict):
                continue
            ptype = part.get("type")
            if ptype == "image_url":
                url = (part.get("image_url") or {}).get("url")
                if not url:
                    raise ValueError("image_url part missing url")
                urls.append(url)
            elif ptype in ("text", "input_text"):
                texts.append(part.get("text", ""))
        out.append({**msg, "content": "".join(texts)})
    return out, urls


def features_to_wire(features: np.ndarray) -> dict:
    f = np.ascontiguousarray(features, dtype=np.float32)
    return {
        "features_b64": base64.b64encode(f.tobytes()).decode(),
        "shape": list(f.shape),
    }


def features_from_wire(d: dict) -> np.ndarray:
    raw = base64.b64decode(d["features_b64"])
    return np.frombuffer(raw, dtype=np.float32).reshape(d["shape"]).copy()


class LocalVisionEncoder:
    """In-process ViT (testing / aggregated single-host serving)."""

    def __init__(self, config=None, params=None, *, preset: str = "tiny-vit", seed: int = 0):
        import jax
        import jax.numpy as jnp

        from dynamo_tpu.engine.models import vision

        self.config = config or vision.PRESETS[preset]
        self.params = params if params is not None else vision.init_params(
            self.config, jax.random.PRNGKey(seed)
        )
        self._encode = jax.jit(lambda p, imgs: vision.encode(p, self.config, imgs))
        self._jnp = jnp

    def encode_urls(self, urls: List[str]) -> np.ndarray:
        """Image URLs → stacked features [n_images * P, lm_hidden] f32."""
        imgs = np.stack(
            [decode_image_data_url(u, self.config.image_size) for u in urls]
        )
        feats = self._encode(self.params, self._jnp.asarray(imgs))
        return np.asarray(feats).reshape(-1, self.config.lm_hidden_size)


class EncodeWorkerHandler:
    """Encode worker endpoint (AsyncEngine shape): request
    ``{"image_urls": [...]}`` → one frame ``{"features_b64", "shape"}``.
    Serve with ``endpoint.serve_endpoint(handler.generate)``."""

    def __init__(self, encoder: Optional[LocalVisionEncoder] = None):
        self.encoder = encoder or LocalVisionEncoder()
        self.requests_total = 0

    async def generate(self, request: Any, context: Context) -> AsyncIterator[dict]:
        import asyncio

        urls = list(request.get("image_urls") or [])
        if not urls:
            raise ValueError("encode request carries no image_urls")
        self.requests_total += 1
        feats = await asyncio.to_thread(self.encoder.encode_urls, urls)
        yield features_to_wire(feats)

    def stats_handler(self) -> dict:
        # Wire key matches the aggregator's registered counter name
        # (COUNTER_KEYS has "request_total" — an encode-worker fleet scrape
        # would silently drop a "requests_total" key).
        return {"request_total": self.requests_total}


class EncodeOperator(Operator):
    """Frontend-side stage bridging image parts to the encode worker.

    ``encoder`` (local) or ``client`` (PushRouter/Client to the encode
    worker's endpoint) — exactly one. The preprocessor upstream has already
    extracted images into ``request["_mm_image_urls"]``."""

    def __init__(self, encoder: Optional[LocalVisionEncoder] = None, client=None):
        if (encoder is None) == (client is None):
            raise ValueError("EncodeOperator needs exactly one of encoder|client")
        self.encoder = encoder
        self.client = client

    async def transform_request(self, request: dict, context: Context) -> dict:
        urls = request.pop("_mm_image_urls", None)
        if not urls:
            return request
        if self.encoder is not None:
            import asyncio

            feats = await asyncio.to_thread(self.encoder.encode_urls, urls)
        else:
            wire = None
            async for frame in self.client.generate({"image_urls": urls}, context):
                data = frame.data if hasattr(frame, "data") else frame
                if isinstance(data, dict) and "features_b64" in data:
                    wire = data
            if wire is None:
                raise RuntimeError("encode worker returned no features")
            feats = features_from_wire(wire)
        request = dict(request)
        # One placeholder token per feature row, PREPENDED (vision-prefix
        # early fusion): positions [0, F) carry the image, text follows.
        request["token_ids"] = [IMAGE_PLACEHOLDER_TOKEN] * feats.shape[0] + list(
            request.get("token_ids") or []
        )
        request["multimodal"] = features_to_wire(feats)
        return request
