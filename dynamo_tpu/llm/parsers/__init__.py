"""Tool-call + reasoning parsers (ref: lib/parsers/src/{tool_calling,reasoning},
SURVEY.md §2 N6).

The reference ships per-format Rust parsers behind a name registry
(tool_calling/parsers.rs:15 ``get_tool_parser_map``). Here the same surface
is config-driven: one JSON extractor + one pythonic extractor + one harmony
extractor, parameterized by :class:`ToolCallConfig` (start/end markers, name
and argument keys). Streaming gets a *jail*: once a chunk looks like the
start of a tool call, deltas are withheld until the call parses or the
stream ends (ref: preprocessor.rs tool-call jail behavior).
"""

from dynamo_tpu.llm.parsers.tool_calling import (
    ToolCallConfig,
    ToolCall,
    detect_tool_call_start,
    get_available_tool_parsers,
    get_tool_parser,
    try_tool_call_parse,
)
from dynamo_tpu.llm.parsers.reasoning import (
    ReasoningParser,
    ReasoningResult,
    get_available_reasoning_parsers,
    get_reasoning_parser,
)
from dynamo_tpu.llm.parsers.stream import StreamingToolCallJail

__all__ = [
    "ToolCall",
    "ToolCallConfig",
    "detect_tool_call_start",
    "get_available_tool_parsers",
    "get_tool_parser",
    "try_tool_call_parse",
    "ReasoningParser",
    "ReasoningResult",
    "get_available_reasoning_parsers",
    "get_reasoning_parser",
    "StreamingToolCallJail",
]
