"""Reasoning-block parsers: split model output into reasoning vs content.

Ref surface: lib/parsers/src/reasoning (base_parser.rs marker splitting;
mod.rs:81 ReasoningParserType — DeepseekR1 / Basic / Qwen / Mistral / Kimi /
Step3 / NemotronDeci / GptOss). Incremental: feed deltas, get
(reasoning_delta, content_delta) back; a truncated stream counts everything
after the start marker as reasoning.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass
class ReasoningResult:
    reasoning: str = ""
    content: str = ""


@dataclass
class ReasoningParser:
    think_start: str = "<think>"
    think_end: str = "</think>"
    # DeepSeek-R1-style models open the response already inside reasoning
    # (the template emits the start marker before generation).
    starts_in_reasoning: bool = False

    _in_reasoning: bool = field(default=False, init=False)
    _buffer: str = field(default="", init=False)
    _started: bool = field(default=False, init=False)

    def __post_init__(self):
        self._in_reasoning = self.starts_in_reasoning

    # --- one-shot ----------------------------------------------------------
    def parse(self, text: str) -> ReasoningResult:
        """Parse a complete message."""
        p = ReasoningParser(self.think_start, self.think_end, self.starts_in_reasoning)
        r, c = p.feed(text)
        rr, cc = p.flush()
        return ReasoningResult(reasoning=r + rr, content=c + cc)

    # --- streaming ---------------------------------------------------------
    def feed(self, delta: str) -> Tuple[str, str]:
        """Feed a text delta; returns (reasoning_delta, content_delta).
        Holds back marker-prefix-ambiguous tails until resolved."""
        self._buffer += delta
        reasoning_out: List[str] = []
        content_out: List[str] = []
        while True:
            marker = self.think_end if self._in_reasoning else self.think_start
            idx = self._buffer.find(marker)
            if idx >= 0:
                seg = self._buffer[:idx]
                (reasoning_out if self._in_reasoning else content_out).append(seg)
                self._buffer = self._buffer[idx + len(marker) :]
                self._in_reasoning = not self._in_reasoning
                continue
            # No full marker: emit all but a potential marker prefix at the tail.
            keep = 0
            for k in range(min(len(marker) - 1, len(self._buffer)), 0, -1):
                if marker.startswith(self._buffer[-k:]):
                    keep = k
                    break
            emit = self._buffer[: len(self._buffer) - keep]
            self._buffer = self._buffer[len(self._buffer) - keep :]
            if emit:
                (reasoning_out if self._in_reasoning else content_out).append(emit)
            break
        return "".join(reasoning_out), "".join(content_out)

    def flush(self) -> Tuple[str, str]:
        """End of stream: release any held-back tail."""
        emit, self._buffer = self._buffer, ""
        return (emit, "") if self._in_reasoning else ("", emit)


class HarmonyReasoningParser(ReasoningParser):
    """gpt-oss: reasoning rides the ``analysis`` channel, the answer the
    ``final`` channel (ref: reasoning/gpt_oss_parser.rs)."""

    _ANALYSIS = re.compile(r"<\|channel\|>analysis<\|message\|>(.*?)(?:<\|end\|>|$)", re.DOTALL)
    _FINAL = re.compile(r"<\|channel\|>final<\|message\|>(.*?)(?:<\|end\|>|<\|return\|>|$)", re.DOTALL)

    def parse(self, text: str) -> ReasoningResult:
        reasoning = "".join(m for m in self._ANALYSIS.findall(text))
        final = self._FINAL.search(text)
        content = final.group(1) if final else ""
        if not reasoning and not final:
            return ReasoningResult(reasoning="", content=text)
        return ReasoningResult(reasoning=reasoning.strip(), content=content.strip())

    def feed(self, delta: str) -> Tuple[str, str]:  # buffered: channels interleave
        self._buffer += delta
        return "", ""

    def flush(self) -> Tuple[str, str]:
        result = self.parse(self._buffer)
        self._buffer = ""
        return result.reasoning, result.content


_REGISTRY: Dict[str, Tuple[type, dict]] = {
    "basic": (ReasoningParser, {}),
    "deepseek_r1": (ReasoningParser, {"starts_in_reasoning": True}),
    "qwen": (ReasoningParser, {}),
    "step3": (ReasoningParser, {"starts_in_reasoning": True}),
    "nemotron_deci": (ReasoningParser, {}),
    "kimi": (ReasoningParser, {"think_start": "◁think▷", "think_end": "◁/think▷"}),
    "mistral": (ReasoningParser, {"think_start": "[THINK]", "think_end": "[/THINK]"}),
    "gpt_oss": (HarmonyReasoningParser, {}),
}


def get_reasoning_parser(name: Optional[str]) -> ReasoningParser:
    key = name if name else "basic"
    try:
        cls, kwargs = _REGISTRY[key]
    except KeyError:
        raise ValueError(f"unknown reasoning parser {key!r}; available: {sorted(_REGISTRY)}") from None
    return cls(**kwargs)


def get_available_reasoning_parsers() -> List[str]:
    return sorted(_REGISTRY)
