"""Tool-call extraction from model output text.

Ref surface: lib/parsers/src/tool_calling — formats Json / Pythonic /
Harmony / Typescript / Xml (config.rs:8), named configs hermes /
nemotron_deci / llama3_json / mistral / phi4 / pythonic / harmony /
deepseek_v3_1 / default (parsers.rs:15-29). Each parse returns
``(tool_calls, remaining_content)`` like try_tool_call_parse
(parsers.rs:35+).
"""

from __future__ import annotations

import ast
import json
import re
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass
class ToolCall:
    """OpenAI-wire tool call (id + function name + JSON-encoded arguments)."""

    name: str
    arguments: str  # JSON string, like OpenAI's function.arguments
    id: str = field(default_factory=lambda: f"call-{uuid.uuid4().hex[:24]}")

    def to_openai(self) -> dict:
        return {
            "id": self.id,
            "type": "function",
            "function": {"name": self.name, "arguments": self.arguments},
        }


@dataclass
class ToolCallConfig:
    format: str = "json"  # json | pythonic | harmony | typescript | xml
    # Markers wrapping a whole parallel-call list (e.g. "<TOOLCALL>[...]</TOOLCALL>").
    list_start: List[str] = field(default_factory=list)
    list_end: List[str] = field(default_factory=list)
    # Markers wrapping each individual call.
    call_start: List[str] = field(default_factory=list)
    call_end: List[str] = field(default_factory=list)
    name_keys: List[str] = field(default_factory=lambda: ["name"])
    arguments_keys: List[str] = field(default_factory=lambda: ["arguments", "parameters"])
    # Parse bare top-level JSON objects with a name key (no markers needed).
    allow_bare_json: bool = True

    def all_start_markers(self) -> List[str]:
        return [m for m in (self.list_start + self.call_start) if m]


def _first_json_value(text: str) -> Tuple[Optional[object], int, int]:
    """Find the first complete JSON object/array in ``text``.

    Returns (value, start, end) or (None, -1, -1). Scans for balanced
    braces/brackets respecting strings — tolerant of surrounding prose, the
    way the reference's find_json parsers behave."""
    decoder = json.JSONDecoder()
    for i, ch in enumerate(text):
        if ch not in "{[":
            continue
        try:
            value, end = decoder.raw_decode(text, i)
        except ValueError:
            continue
        return value, i, end
    return None, -1, -1


def _calls_from_json_value(value: object, config: ToolCallConfig) -> List[ToolCall]:
    items = value if isinstance(value, list) else [value]
    calls: List[ToolCall] = []
    for item in items:
        if not isinstance(item, dict):
            continue
        name = next((item[k] for k in config.name_keys if k in item), None)
        if name is None and isinstance(item.get("function"), dict):
            fn = item["function"]
            name = next((fn[k] for k in config.name_keys if k in fn), None)
            item = fn
        if not isinstance(name, str):
            continue
        args = next((item[k] for k in config.arguments_keys if k in item), {})
        if isinstance(args, str):
            try:
                args = json.loads(args)
            except ValueError:
                pass
        calls.append(ToolCall(name=name, arguments=json.dumps(args)))
    return calls


def _strip_markers(text: str, config: ToolCallConfig) -> Tuple[str, bool]:
    """Remove the outermost list/call markers. Returns (inner, found)."""
    found = False
    for start in sorted(config.list_start + config.call_start, key=len, reverse=True):
        if start and start in text:
            text = text.replace(start, "\n")
            found = True
    for end in sorted(config.list_end + config.call_end, key=len, reverse=True):
        if end and end in text:
            text = text.replace(end, "\n")
    return text, found


def _parse_json_format(text: str, config: ToolCallConfig) -> Tuple[List[ToolCall], Optional[str]]:
    inner, had_markers = _strip_markers(text, config)
    if not had_markers and not config.allow_bare_json:
        return [], text
    calls: List[ToolCall] = []
    content_parts: List[str] = []
    rest = inner
    while rest:
        value, start, end = _first_json_value(rest)
        if value is None:
            content_parts.append(rest)
            break
        parsed = _calls_from_json_value(value, config)
        if parsed:
            calls.extend(parsed)
            content_parts.append(rest[:start])
        else:
            # JSON that isn't a tool call stays in the content.
            content_parts.append(rest[: end])
        rest = rest[end:]
    if not calls:
        return [], text
    content = "".join(content_parts).strip() or None
    return calls, content


_PYTHONIC_CALL = re.compile(r"\[\s*[\w.]+\s*\(.*\)\s*\]", re.DOTALL)


def _parse_pythonic(text: str) -> Tuple[List[ToolCall], Optional[str]]:
    """``[get_weather(city="SF"), get_time(tz="PST")]`` (llama-4 style)."""
    m = _PYTHONIC_CALL.search(text)
    if not m:
        return [], text
    try:
        tree = ast.parse(m.group(0), mode="eval")
    except SyntaxError:
        return [], text
    if not isinstance(tree.body, ast.List):
        return [], text
    calls: List[ToolCall] = []
    for el in tree.body.elts:
        if not isinstance(el, ast.Call):
            return [], text
        name = el.func.attr if isinstance(el.func, ast.Attribute) else getattr(el.func, "id", None)
        if name is None:
            return [], text
        try:
            args = {kw.arg: ast.literal_eval(kw.value) for kw in el.keywords if kw.arg}
        except ValueError:
            return [], text
        calls.append(ToolCall(name=name, arguments=json.dumps(args)))
    content = (text[: m.start()] + text[m.end() :]).strip() or None
    return calls, content


_HARMONY_CALL = re.compile(
    r"<\|channel\|>commentary to=(?:functions\.)?([\w.]+)"
    r".*?<\|message\|>(.*?)(?:<\|call\|>|$)",
    re.DOTALL,
)
_HARMONY_FINAL = re.compile(r"<\|channel\|>final<\|message\|>(.*?)(?:<\|end\|>|<\|return\|>|$)", re.DOTALL)


def _parse_harmony(text: str) -> Tuple[List[ToolCall], Optional[str]]:
    """gpt-oss harmony channels: commentary-to-functions carries the call."""
    calls = []
    for name, payload in _HARMONY_CALL.findall(text):
        value, _, _ = _first_json_value(payload)
        calls.append(ToolCall(name=name, arguments=json.dumps(value if value is not None else {})))
    if not calls:
        return [], text
    final = _HARMONY_FINAL.search(text)
    content = final.group(1).strip() if final else None
    return calls, content or None


_TYPESCRIPT_CALL = re.compile(r"functions\.([\w.]+)\s*\(\s*(\{.*?\})\s*\)", re.DOTALL)


def _parse_typescript(text: str) -> Tuple[List[ToolCall], Optional[str]]:
    """``<function_call>```typescript\nfunctions.f({...})\n``` `` style."""
    calls = []
    for name, payload in _TYPESCRIPT_CALL.findall(text):
        value, _, _ = _first_json_value(payload)
        if value is None:
            continue
        calls.append(ToolCall(name=name, arguments=json.dumps(value)))
    if not calls:
        return [], text
    content = _TYPESCRIPT_CALL.sub("", text)
    content = re.sub(r"<function_call>|```(typescript)?|</function_call>", "", content).strip()
    return calls, content or None


_XML_INVOKE = re.compile(r"<invoke\s+name=\"([^\"]+)\"\s*>(.*?)</invoke>", re.DOTALL)
_XML_PARAM = re.compile(r"<parameter\s+name=\"([^\"]+)\"\s*>(.*?)</parameter>", re.DOTALL)


def _parse_xml(text: str) -> Tuple[List[ToolCall], Optional[str]]:
    calls = []
    for name, body in _XML_INVOKE.findall(text):
        args: Dict[str, object] = {}
        for pname, pval in _XML_PARAM.findall(body):
            pval = pval.strip()
            try:
                args[pname] = json.loads(pval)
            except ValueError:
                args[pname] = pval
        calls.append(ToolCall(name=name, arguments=json.dumps(args)))
    if not calls:
        return [], text
    content = re.sub(r"<function_calls>.*?</function_calls>", "", text, flags=re.DOTALL).strip()
    return calls, content or None


def try_tool_call_parse(text: str, config: ToolCallConfig) -> Tuple[List[ToolCall], Optional[str]]:
    """Parse tool calls out of a complete message. Returns
    ``(calls, normal_content)`` — ``([], text)`` when nothing parses."""
    if config.format == "json":
        return _parse_json_format(text, config)
    if config.format == "pythonic":
        return _parse_pythonic(text)
    if config.format == "harmony":
        return _parse_harmony(text)
    if config.format == "typescript":
        return _parse_typescript(text)
    if config.format == "xml":
        return _parse_xml(text)
    raise ValueError(f"unknown tool-call format: {config.format}")


def detect_tool_call_start(chunk: str, config: ToolCallConfig) -> bool:
    """Could ``chunk`` be the beginning of a tool call? Used by the
    streaming jail — errs on the side of True for any marker prefix."""
    chunk = chunk.lstrip()
    if not chunk:
        return False
    markers = config.all_start_markers()
    if config.format == "pythonic":
        markers = markers + ["["]
    if config.format == "harmony":
        markers = markers + ["<|channel|>"]
    if config.format == "typescript":
        markers = markers + ["<function_call>", "functions."]
    if config.format == "xml":
        markers = markers + ["<function_calls>", "<invoke"]
    if config.format == "json" and config.allow_bare_json:
        markers = markers + ["{", "["]
    for m in markers:
        if chunk.startswith(m) or m.startswith(chunk):
            return True
    return False


# --- named registry (parity with parsers.rs:15-29) --------------------------

PARSER_MAP: Dict[str, ToolCallConfig] = {
    "hermes": ToolCallConfig(
        call_start=["<tool_call>"], call_end=["</tool_call>"], allow_bare_json=False
    ),
    "nemotron_deci": ToolCallConfig(list_start=["<TOOLCALL>"], list_end=["</TOOLCALL>"], allow_bare_json=False),
    "llama3_json": ToolCallConfig(call_start=["<|python_tag|>"], call_end=["<|eom_id|>"]),
    "mistral": ToolCallConfig(list_start=["[TOOL_CALLS]"], list_end=[]),
    "phi4": ToolCallConfig(list_start=["functools"], list_end=[], allow_bare_json=False),
    "deepseek_v3_1": ToolCallConfig(
        call_start=["<｜tool▁call▁begin｜>", "<｜tool▁calls▁begin｜>"],
        call_end=["<｜tool▁call▁end｜>", "<｜tool▁calls▁end｜>"],
        allow_bare_json=False,
    ),
    "pythonic": ToolCallConfig(format="pythonic"),
    "harmony": ToolCallConfig(format="harmony"),
    "typescript": ToolCallConfig(format="typescript"),
    "xml": ToolCallConfig(format="xml"),
    "default": ToolCallConfig(call_start=["<TOOLCALL>", "<|python_tag|>"], call_end=["</TOOLCALL>"]),
}


def get_tool_parser(name: Optional[str]) -> ToolCallConfig:
    key = name if name else "default"
    try:
        return PARSER_MAP[key]
    except KeyError:
        raise ValueError(f"unknown tool parser {key!r}; available: {sorted(PARSER_MAP)}") from None


def get_available_tool_parsers() -> List[str]:
    return sorted(PARSER_MAP)
