"""Streaming tool-call jail + reasoning splitting for the Backend operator.

Ref behavior: the reference's preprocessor "jails" streamed deltas once the
text could be the opening of a tool call, releasing either parsed tool calls
at end-of-stream or the withheld text when it turns out not to be a call
(preprocessor.rs streaming postprocess, SURVEY.md §2b).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from dynamo_tpu.llm.parsers.reasoning import ReasoningParser
from dynamo_tpu.llm.parsers.tool_calling import (
    ToolCall,
    ToolCallConfig,
    detect_tool_call_start,
    try_tool_call_parse,
)


@dataclass
class StreamingToolCallJail:
    """Feed text deltas; withholds anything that might be a tool call.

    ``feed`` returns the text safe to stream now. Once jailed, nothing
    streams until ``finish``, which parses the held text into tool calls
    (or releases it verbatim when parsing fails).
    """

    config: ToolCallConfig
    reasoning: Optional[ReasoningParser] = None

    _jailed: bool = field(default=False, init=False)
    _held: str = field(default="", init=False)
    _reasoning_parts: List[str] = field(default_factory=list, init=False)

    def feed(self, delta: str) -> Tuple[str, str]:
        """Returns (reasoning_delta, content_delta) safe to emit now."""
        r_delta = ""
        if self.reasoning is not None:
            r_delta, delta = self.reasoning.feed(delta)
        if self._jailed:
            self._held += delta
            return r_delta, ""
        candidate = self._held + delta
        if detect_tool_call_start(candidate, self.config):
            self._jailed = True
            self._held = candidate
            return r_delta, ""
        # Hold a whitespace-only tail: a marker could still start after it.
        if candidate.strip() == "":
            self._held = candidate
            return r_delta, ""
        self._held = ""
        return r_delta, candidate

    def finish(self) -> Tuple[str, str, List[ToolCall]]:
        """End of stream → (reasoning_tail, content_tail, tool_calls)."""
        r_tail = ""
        if self.reasoning is not None:
            rr, cc = self.reasoning.flush()
            r_tail = rr
            self._held += cc
        held, self._held = self._held, ""
        if not held:
            return r_tail, "", []
        if self._jailed:
            calls, content = try_tool_call_parse(held, self.config)
            if calls:
                return r_tail, content or "", calls
        return r_tail, held, []
