"""ModelDeploymentCard: the per-model config record published to discovery.

Ref: lib/llm/src/model_card.rs:91 — tokenizer, prompt formatter, context
length, kv block size, ``migration_limit`` (:136), runtime config; stored in
the KV store under ``models/`` (discovery/model_entry.rs:22 MODEL_ROOT_PATH)
and watched by frontends (ModelWatcher).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

MODEL_ROOT_PATH = "models"


@dataclass
class ModelDeploymentCard:
    name: str
    model_type: str = "chat"  # chat | completions | embeddings
    tokenizer_path: Optional[str] = None
    chat_template: Optional[str] = None
    context_length: int = 8192
    kv_cache_block_size: int = 16
    migration_limit: int = 0
    # Output parsers (ref: parsers.rs registry names; None = defaults).
    tool_call_parser: Optional[str] = None
    reasoning_parser: Optional[str] = None
    runtime_config: Dict[str, Any] = field(default_factory=dict)

    def to_json(self) -> bytes:
        return json.dumps(self.__dict__).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "ModelDeploymentCard":
        return cls(**json.loads(raw))


@dataclass
class ModelEntry:
    """Discovery record: model name → serving endpoint + card
    (ref: discovery/model_entry.rs:22)."""

    name: str
    namespace: str
    component: str
    endpoint: str
    card: ModelDeploymentCard

    @property
    def store_key(self) -> str:
        return f"{MODEL_ROOT_PATH}/{self.namespace}/{self.component}/{self.endpoint}/{self.name}"

    def to_json(self) -> bytes:
        return json.dumps(
            {
                "name": self.name,
                "namespace": self.namespace,
                "component": self.component,
                "endpoint": self.endpoint,
                "card": self.card.__dict__,
            }
        ).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "ModelEntry":
        d = json.loads(raw)
        return cls(
            name=d["name"],
            namespace=d["namespace"],
            component=d["component"],
            endpoint=d["endpoint"],
            card=ModelDeploymentCard(**d["card"]),
        )
