"""Stream perf capture + JSONL event recording.

Ref: lib/llm/src/perf.rs (``TimestampedResponse`` :32, ``RecordedStream`` —
zero-overhead stream timestamping for TTFT/ITL analysis), recorder.rs:26
(JSONL event ``Recorder`` with a background writer task), kv_router/
recorder.rs (``KvRecorder`` taps the router event stream), perf/logprobs.rs
(per-token logprobs analysis).

Capture is append-only on the hot path: ``record_stream`` wraps an async
response stream, stamps each item with a monotonic ns clock as it passes
through, and defers all analysis to after the stream closes.
"""

from __future__ import annotations

import asyncio
import json
import math
import time
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Dict, List, Optional

from dynamo_tpu.runtime.logging import get_logger

logger = get_logger(__name__)


# ---------------------------------------------------------------------------
# Stream timestamping (perf.rs)
# ---------------------------------------------------------------------------


@dataclass
class TimestampedResponse:
    """One stream item + its arrival time (ref: perf.rs:32)."""

    data: Any
    t_ns: int
    seq: int


@dataclass
class RecordedStream:
    """Accumulates timestamps while a stream flows; analysis afterwards."""

    start_ns: int = field(default_factory=time.perf_counter_ns)
    responses: List[TimestampedResponse] = field(default_factory=list)

    def append(self, data: Any) -> None:
        self.responses.append(TimestampedResponse(data, time.perf_counter_ns(), len(self.responses)))

    @property
    def ttft_s(self) -> Optional[float]:
        """Time to first response (the TTFT histogram's input)."""
        if not self.responses:
            return None
        return (self.responses[0].t_ns - self.start_ns) / 1e9

    @property
    def itls_s(self) -> List[float]:
        """Inter-token latencies between consecutive responses."""
        ts = [r.t_ns for r in self.responses]
        return [(b - a) / 1e9 for a, b in zip(ts, ts[1:])]

    @property
    def duration_s(self) -> float:
        if not self.responses:
            return 0.0
        return (self.responses[-1].t_ns - self.start_ns) / 1e9

    def summarize(self) -> Dict[str, Any]:
        itls = self.itls_s
        return {
            "responses": len(self.responses),
            "ttft_s": self.ttft_s,
            "duration_s": self.duration_s,
            "itl_mean_s": sum(itls) / len(itls) if itls else None,
            "itl_p50_s": _quantile(itls, 0.5),
            "itl_p99_s": _quantile(itls, 0.99),
        }


def _quantile(xs: List[float], q: float) -> Optional[float]:
    if not xs:
        return None
    ys = sorted(xs)
    idx = min(int(q * len(ys)), len(ys) - 1)
    return ys[idx]


async def record_stream(stream: AsyncIterator, recorded: Optional[RecordedStream] = None):
    """Wrap ``stream``: yields items unchanged while stamping arrivals into a
    ``RecordedStream``. Usage::

        rec = RecordedStream()
        async for item in record_stream(engine.generate(...), rec):
            ...
        print(rec.summarize())
    """
    rec = recorded if recorded is not None else RecordedStream()
    async for item in stream:
        rec.append(item)
        yield item


# ---------------------------------------------------------------------------
# JSONL event recorder (recorder.rs)
# ---------------------------------------------------------------------------


class Recorder:
    """Append events to a JSONL file off the hot path (ref: recorder.rs:26).

    ``emit`` is synchronous and non-blocking: events go to an unbounded
    queue; a background task serializes and writes them. ``close`` drains."""

    def __init__(self, path: str):
        self.path = path
        self._queue: "asyncio.Queue[Optional[dict]]" = asyncio.Queue()
        self._task: Optional[asyncio.Task] = None
        self.events_written = 0

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._writer())

    def emit(self, event: str, **data: Any) -> None:
        self._queue.put_nowait({"ts": time.time(), "event": event, **data})

    async def _writer(self) -> None:
        loop = asyncio.get_running_loop()
        with open(self.path, "a") as f:
            while True:
                item = await self._queue.get()
                stop = item is None
                # Batch whatever is already queued into one write.
                batch = [] if stop else [item]
                while not self._queue.empty():
                    nxt = self._queue.get_nowait()
                    if nxt is None:
                        stop = True
                        break
                    batch.append(nxt)
                if batch:
                    # File IO off the event loop: a slow disk must not stall
                    # in-flight request streams.
                    await loop.run_in_executor(None, self._drain_batch, f, batch)
                if stop:
                    return

    def _drain_batch(self, f, batch: List[dict]) -> None:
        for ev in batch:
            f.write(json.dumps(ev) + "\n")
        f.flush()
        self.events_written += len(batch)

    async def close(self) -> None:
        if self._task is not None:
            self._queue.put_nowait(None)
            await self._task
            self._task = None


class KvRecorder:
    """Tap a worker's KV event stream into a Recorder (ref:
    kv_router/recorder.rs) — replayable traces for router tuning."""

    def __init__(self, drt, namespace: str, component: str, recorder: Recorder):
        from dynamo_tpu.llm.kv_router.publisher import kv_events_stream_name

        self.drt = drt
        self.stream_name = kv_events_stream_name(namespace, component)
        self.recorder = recorder
        self._task: Optional[asyncio.Task] = None
        self._stop = asyncio.Event()

    async def start(self, from_seq: int = 1) -> None:
        stream = await self.drt.bus.stream(self.stream_name)

        async def loop():
            it = stream.consume(from_seq)
            while not self._stop.is_set():
                nxt = asyncio.ensure_future(anext(it))
                stop = asyncio.ensure_future(self._stop.wait())
                done, pending = await asyncio.wait({nxt, stop}, return_when=asyncio.FIRST_COMPLETED)
                for t in pending:
                    t.cancel()
                await asyncio.gather(*pending, return_exceptions=True)
                if nxt in done and nxt.exception() is None:
                    msg = nxt.result()
                    try:
                        payload = json.loads(msg.data)
                    except ValueError:
                        payload = {"raw": msg.data.hex()}
                    self.recorder.emit("kv_event", seq=msg.seq, **payload)
                else:
                    return

        self._task = asyncio.get_running_loop().create_task(loop())

    async def stop(self) -> None:
        self._stop.set()
        if self._task is not None:
            await self._task
            self._task = None


# ---------------------------------------------------------------------------
# Logprobs analysis (perf/logprobs.rs)
# ---------------------------------------------------------------------------


def analyze_logprobs(token_logprobs: List[float]) -> Dict[str, Any]:
    """Sequence-level stats over per-token logprobs: perplexity and
    uncertainty markers (ref: perf/logprobs.rs)."""
    if not token_logprobs:
        return {"tokens": 0, "perplexity": None, "mean_logprob": None, "min_logprob": None}
    n = len(token_logprobs)
    mean_lp = sum(token_logprobs) / n
    return {
        "tokens": n,
        "perplexity": math.exp(-mean_lp),
        "mean_logprob": mean_lp,
        "min_logprob": min(token_logprobs),
    }
