"""Migration operator: replay in-flight requests on another worker when the
response stream drops.

Ref: lib/llm/src/migration.rs:26-734 (``Migration``, ``RetryManager``) — on
stream drop, the accumulated output tokens are appended to the prompt and the
request is re-pushed (the router picks a live instance), up to
``migration_limit`` times (model_card.rs:136). The log line "recreating
stream" is load-bearing: the reference's fault-tolerance test asserts it
(tests/fault_tolerance/test_request_migration.py), so we keep it verbatim.
"""

from __future__ import annotations

from typing import Any, AsyncIterator

from dynamo_tpu.llm.protocols.common import LLMEngineOutput
from dynamo_tpu.runtime.engine import Annotated, AsyncEngine, Context, StreamDisconnect
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.pipeline import Operator
from dynamo_tpu.runtime.push_router import NoInstancesError

logger = get_logger(__name__)


class Migration(Operator):
    def __init__(self, migration_limit: int):
        self.migration_limit = migration_limit

    def attach(self, downstream: AsyncEngine) -> AsyncEngine:
        return _MigrationEngine(self.migration_limit, downstream)


class _MigrationEngine:
    def __init__(self, limit: int, downstream: AsyncEngine):
        self.limit = limit
        self.downstream = downstream

    async def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        attempts_left = self.limit
        req = dict(request)
        emitted_tokens = 0

        while True:
            try:
                async for item in self.downstream.generate(req, context):
                    out = item.data if isinstance(item, Annotated) else item
                    if isinstance(out, dict) and out.get("token_ids"):
                        emitted_tokens += len(out["token_ids"])
                        # Fold emitted tokens into the replay request so a
                        # migrated continuation resumes, not restarts.
                        req = self._fold(req, out["token_ids"])
                    yield item
                return
            except StreamDisconnect:
                if attempts_left <= 0 or context.is_stopped():
                    raise
                attempts_left -= 1
                self._trace_migration(context, emitted_tokens, attempts_left)
                logger.warning(
                    "recreating stream for request %s (%d migrations left, %d tokens emitted)",
                    context.id,
                    attempts_left,
                    emitted_tokens,
                )
            except NoInstancesError:
                if attempts_left <= 0:
                    raise
                attempts_left -= 1
                logger.warning("recreating stream for request %s: no instances yet", context.id)

    @staticmethod
    def _trace_migration(context: Context, emitted: int, attempts_left: int) -> None:
        tp = context.traceparent
        if tp is None:
            return
        from dynamo_tpu.runtime.tracing import get_tracer

        get_tracer().event(
            "migration", tp.trace_id, parent_id=tp.parent_id, service="frontend",
            request_id=context.id, tokens_emitted=emitted, attempts_left=attempts_left,
        )

    @staticmethod
    def _fold(req: dict, new_tokens) -> dict:
        req = dict(req)
        req["token_ids"] = list(req.get("token_ids") or []) + list(new_tokens)
        stop = dict(req.get("stop_conditions") or {})
        if stop.get("max_tokens"):
            stop["max_tokens"] = max(1, stop["max_tokens"] - len(new_tokens))
        req["stop_conditions"] = stop
        return req
