"""Migration operator: replay in-flight requests on another worker when the
response stream drops.

Ref: lib/llm/src/migration.rs:26-734 (``Migration``, ``RetryManager``) — on
stream drop, the accumulated output tokens are appended to the prompt and the
request is re-pushed (the router picks a live instance), up to
``migration_limit`` times (model_card.rs:136). The log line "recreating
stream" is load-bearing: the reference's fault-tolerance test asserts it
(tests/fault_tolerance/test_request_migration.py), so we keep it verbatim.

Replay accounting is kept honest across the fold:

- ``max_tokens`` decrements by the tokens already emitted, so a migrated
  request can never overshoot its budget;
- ``deadline_ms`` (when the request carries a deadline budget) decrements by
  the elapsed wall time, so a replay cannot out-live the client's deadline;
- ``cached_tokens`` reports are clamped to the *original* prompt length and
  deduplicated — the replay's warm-prefix hit covers the folded output
  tokens too, but those were generated work, not client prompt, and the
  frontend's usage counter must not double-count across attempts.

On exhaustion the final StreamDisconnect re-raises with the partial token
count in ``context.metadata["migration"]`` so the frontend can answer a
structured 502 instead of an opaque 500.
"""

from __future__ import annotations

import time
from typing import Any, AsyncIterator, Callable, Optional

from dynamo_tpu.llm.protocols.common import LLMEngineOutput
from dynamo_tpu.runtime.engine import Annotated, AsyncEngine, Context, StreamDisconnect
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.pipeline import Operator
from dynamo_tpu.runtime.push_router import NoInstancesError

logger = get_logger(__name__)


class Migration(Operator):
    def __init__(self, migration_limit: int, *, on_migrate: Optional[Callable[[], None]] = None):
        self.migration_limit = migration_limit
        # Counter hook (frontend wires migrations_total{model} here).
        self.on_migrate = on_migrate

    def attach(self, downstream: AsyncEngine) -> AsyncEngine:
        return _MigrationEngine(self.migration_limit, downstream, on_migrate=self.on_migrate)


class _MigrationEngine:
    def __init__(self, limit: int, downstream: AsyncEngine,
                 on_migrate: Optional[Callable[[], None]] = None):
        self.limit = limit
        self.downstream = downstream
        self.on_migrate = on_migrate

    async def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        attempts_left = self.limit
        req = dict(request)
        start = time.monotonic()
        orig_prompt_len = len(req.get("token_ids") or [])
        emitted_tokens = 0
        cached_reported = False

        while True:
            try:
                async for item in self.downstream.generate(req, context):
                    out = item.data if isinstance(item, Annotated) else item
                    if isinstance(out, dict):
                        if out.get("token_ids"):
                            emitted_tokens += len(out["token_ids"])
                            # Fold emitted tokens into the replay request so a
                            # migrated continuation resumes, not restarts.
                            req = self._fold(req, out["token_ids"], start)
                        if out.get("cached_tokens") is not None:
                            item = self._honest_cached(
                                item, out, orig_prompt_len, cached_reported
                            )
                            cached_reported = True
                            if item is None:
                                continue
                    yield item
                return
            except StreamDisconnect:
                if attempts_left <= 0 or context.is_stopped():
                    # Exhausted (or the client left): annotate the context so
                    # the frontend can answer a structured 502 with the
                    # partial token count instead of an opaque 500.
                    context.metadata["migration"] = {
                        "tokens_emitted": emitted_tokens,
                        "attempts": self.limit - attempts_left,
                    }
                    raise
                attempts_left -= 1
                if self.on_migrate is not None:
                    self.on_migrate()
                self._trace_migration(context, emitted_tokens, attempts_left)
                logger.warning(
                    "recreating stream for request %s (%d migrations left, %d tokens emitted)",
                    context.id,
                    attempts_left,
                    emitted_tokens,
                )
            except NoInstancesError:
                if attempts_left <= 0:
                    raise
                attempts_left -= 1
                logger.warning("recreating stream for request %s: no instances yet", context.id)

    @staticmethod
    def _trace_migration(context: Context, emitted: int, attempts_left: int) -> None:
        tp = context.traceparent
        if tp is None:
            return
        from dynamo_tpu.runtime.tracing import get_tracer

        get_tracer().event(
            "migration", tp.trace_id, parent_id=tp.parent_id, service="frontend",
            request_id=context.id, tokens_emitted=emitted, attempts_left=attempts_left,
        )

    @staticmethod
    def _honest_cached(item, out: dict, orig_prompt_len: int, already_reported: bool):
        """Keep the ``cached_tokens`` report honest across attempts: clamp a
        replay's warm-prefix hit to the client's original prompt (the folded
        output tokens it also re-served were generated work, not prompt),
        and drop duplicate reports (the frontend counter inc()s per report).
        Returns the item to yield, or None to swallow it."""
        clamped = min(int(out["cached_tokens"]), orig_prompt_len)
        if already_reported:
            if not out.get("token_ids") and not out.get("finish_reason"):
                return None  # pure duplicate report — swallow the frame
            out = dict(out)
            out.pop("cached_tokens", None)
        elif clamped != out["cached_tokens"]:
            out = dict(out)
            out["cached_tokens"] = clamped
        else:
            return item
        if isinstance(item, Annotated):
            return Annotated(data=out, event=item.event, comment=item.comment, id=item.id)
        return out

    @staticmethod
    def _fold(req: dict, new_tokens, start: float) -> dict:
        req = dict(req)
        req["token_ids"] = list(req.get("token_ids") or []) + list(new_tokens)
        stop = dict(req.get("stop_conditions") or {})
        if stop.get("max_tokens"):
            stop["max_tokens"] = max(1, stop["max_tokens"] - len(new_tokens))
        if stop.get("deadline_ms"):
            # The deadline budget is relative to worker arrival: a replay
            # must carry only what remains of the client's budget, not a
            # fresh one (floor 1 ms — the worker evicts immediately, the
            # client still gets its deterministic timeout finish).
            elapsed_ms = (time.monotonic() - start) * 1000.0
            orig = req.get("_deadline_budget_ms", stop["deadline_ms"])
            req["_deadline_budget_ms"] = orig
            stop["deadline_ms"] = max(1.0, float(orig) - elapsed_ms)
        req["stop_conditions"] = stop
        return req
