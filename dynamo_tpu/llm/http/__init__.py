"""HTTP frontend (ref: lib/llm/src/http/service)."""
