"""OpenAI-compatible HTTP frontend.

Ref: lib/llm/src/http/service/{openai.rs,service_v2.rs,metrics.rs,
disconnect.rs} — routes ``/v1/chat/completions`` (openai.rs:481),
``/v1/completions`` (:245), ``/v1/models``, SSE streaming with ``[DONE]``
sentinel, client-disconnect → context cancellation (disconnect.rs), per-route
metrics: TTFT/ITL histograms, inflight gauges (metrics.rs:1-700).

Built on aiohttp (the axum role). The service is engine-agnostic: it looks
up pipelines in the ModelManager, so aggregated single-process, routed
multi-worker, and disaggregated deployments all serve through this one
frontend.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import AsyncIterator, Optional

from aiohttp import web

from dynamo_tpu.llm.discovery import ModelManager
from dynamo_tpu.llm.protocols import openai as oai
from dynamo_tpu.llm.protocols.common import LLMEngineOutput, as_engine_output
from dynamo_tpu.runtime.engine import Annotated, Context, StreamDisconnect
from dynamo_tpu.runtime.logging import TraceParent, get_logger
from dynamo_tpu.runtime.push_router import NoInstancesError
from dynamo_tpu.runtime.tracing import NULL_SPAN, get_tracer
from dynamo_tpu.runtime.metrics import (
    DURATION_BUCKETS,
    FRONTEND_PREFIX,
    ITL_BUCKETS,
    TTFT_BUCKETS,
    MetricsRegistry,
)
from dynamo_tpu.runtime.telemetry import (
    DigestCollector,
    SloConfig,
    SloJudge,
    Telemetry,
)

logger = get_logger(__name__)

# Digest-exported frontend families (DigestCollector live mode): each stream
# renders as "<name>_seconds" (native histogram, cumulative) plus
# "<name>_seconds_quantile" (rolling-window p50/p90/p99 gauges). These are
# the frontend's OWN end-to-end measurements — client-observed TTFT/TPOT
# including routing and the serving plane, judged against the same SLO
# targets the engine judges its internal latencies with.
FRONTEND_DIGEST_FAMILIES = (
    "ttft_seconds", "ttft_seconds_quantile",
    "tpot_seconds", "tpot_seconds_quantile",
    "request_seconds", "request_seconds_quantile",
)


class HttpService:
    def __init__(
        self,
        manager: ModelManager,
        *,
        host: str = "0.0.0.0",
        port: int = 8000,
        metrics: Optional[MetricsRegistry] = None,
        tls_cert: Optional[str] = None,
        tls_key: Optional[str] = None,
        slo: Optional[SloConfig] = None,
        request_timeout_ms: Optional[float] = None,
    ):
        self.manager = manager
        self.host = host
        self.port = port
        # Default end-to-end request deadline (--request-timeout-ms). A
        # client ``timeout`` (seconds) overrides per request. The budget
        # rides the wire (stop_conditions.deadline_ms) so the scheduler
        # evicts past-deadline rows; the frontend's own watchdog is the
        # backstop for hung workers — either way the client gets a 504
        # with partial-usage accounting, never a silent hang.
        self.request_timeout_ms = request_timeout_ms
        # TLS termination (ref: frontend --tls-cert-path/--tls-key-path,
        # components/frontend/src/dynamo/frontend/main.py:81-286): both paths
        # or neither.
        if bool(tls_cert) != bool(tls_key):
            raise ValueError("TLS needs both tls_cert and tls_key")
        self._ssl = None
        if tls_cert:
            import ssl

            self._ssl = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            self._ssl.load_cert_chain(tls_cert, tls_key)
        self.metrics = metrics or MetricsRegistry(prefix=FRONTEND_PREFIX)
        self._runner: Optional[web.AppRunner] = None
        # Optional KServe gRPC twin sharing this manager; attached by the
        # entrypoint (start_frontend) and stopped with this service.
        self.grpc_service = None

        m = self.metrics
        self._m_requests = lambda model, status: m.counter(
            "requests_total", "HTTP requests", model=model, status=status
        )
        self._m_inflight = lambda model: m.gauge("inflight_requests", "in-flight requests", model=model)
        self._m_ttft = lambda model: m.histogram(
            "time_to_first_token_seconds", "TTFT", buckets=TTFT_BUCKETS, model=model
        )
        self._m_itl = lambda model: m.histogram(
            "inter_token_latency_seconds", "ITL", buckets=ITL_BUCKETS, model=model
        )
        self._m_duration = lambda model: m.histogram(
            "request_duration_seconds", "request duration", buckets=DURATION_BUCKETS, model=model
        )
        # Engine-admission queue time (ref: http_queue_guard / queue-time
        # histograms in http/service/metrics.rs) — the saturation signal the
        # SLA planner inverts for prefill replica math.
        self._m_queue = lambda model: m.histogram(
            "queue_time_seconds", "request queue time before engine admission",
            buckets=TTFT_BUCKETS, model=model,
        )
        self._m_output_tokens = lambda model: m.counter("output_tokens_total", "output tokens", model=model)
        # Failure lifecycle: deadline expiries (504s / timeout finishes),
        # migration replays (stream drops recovered on another worker),
        # exhausted migrations (502s), and no-instance rejections (503s).
        self._m_timeouts = lambda model: m.counter(
            "request_timeouts_total", "requests that exceeded their deadline", model=model
        )
        self._m_migrations = lambda model: m.counter(
            "migrations_total", "stream drops replayed on another worker", model=model
        )
        self._m_migration_exhausted = lambda model: m.counter(
            "migration_exhausted_total", "requests whose migration budget ran out (502)", model=model
        )
        self._m_no_instances = lambda model: m.counter(
            "no_instances_total", "requests rejected because no workers were live (503)", model=model
        )
        self._m_input_tokens = lambda model: m.counter("input_tokens_total", "input (prompt) tokens", model=model)
        # Engine-reported prefix-cache reuse: prompt tokens served from
        # resident KV (usage.prompt_tokens_details.cached_tokens).
        self._m_cached_tokens = lambda model: m.counter(
            "input_cached_tokens_total", "prompt tokens served from the prefix cache", model=model
        )
        # SLA telemetry: the frontend's own e2e digests (ttft/tpot/request
        # — FRONTEND_DIGEST_FAMILIES) and per-request SLO judgments against
        # --slo-ttft-ms/--slo-tpot-ms. Goodput = SLO-attained req/tok.
        self.slo = slo or SloConfig()
        self.telemetry = Telemetry()
        self._slo_judge = SloJudge(self.slo)
        self._digest_collector = DigestCollector(
            FRONTEND_PREFIX, registry=m.registry, telemetry=self.telemetry
        )
        self._m_slo = lambda model, phase, verdict: m.counter(
            "slo_attained_total" if verdict == "attained" else "slo_violated_total",
            "request phases meeting/missing the SLO target",
            model=model, phase=phase,
        )
        self._m_goodput_requests = lambda model: m.counter(
            "goodput_requests_total", "requests that attained every configured SLO", model=model
        )
        self._m_goodput_tokens = lambda model: m.counter(
            "goodput_tokens_total", "output tokens of SLO-attained requests", model=model
        )
        self._m_goodput_req_s = m.gauge(
            "goodput_requests_per_s", "SLO-attained requests/s over the rolling window"
        )
        self._m_goodput_tok_s = m.gauge(
            "goodput_tokens_per_s", "SLO-attained output tokens/s over the rolling window"
        )

    def _record_request_telemetry(
        self,
        model: str,
        start: float,
        first_at: Optional[float],
        last_at: Optional[float],
        n_tokens: int,
        ctx=None,
    ) -> None:
        """End-of-request e2e telemetry: digests + SLO judgment + goodput.
        Requests that never produced a token (errors, rejections) are not
        judged — they are failures, not latency violations."""
        if first_at is None:
            return
        now = time.monotonic()
        ttft_s = max(0.0, first_at - start)
        self.telemetry.observe("ttft", ttft_s)
        self.telemetry.observe("request", max(0.0, now - start))
        tpot_s = None
        if n_tokens > 1 and last_at is not None and last_at > first_at:
            tpot_s = (last_at - first_at) / (n_tokens - 1)
            self.telemetry.observe("tpot", tpot_s)
        if not self.slo.enabled:
            return
        good = self._slo_judge.judge(ttft_s, tpot_s, n_tokens)
        if not good and ctx is not None and get_tracer().tail:
            # Tail-based sampling: a request that violated its SLO keeps
            # its full span set regardless of the head-sampling rate. The
            # promotion itself is deferred to the request handler's finally
            # — the root http_request span has not ended yet here, and it
            # must be in the ring before the trace is promoted.
            ctx.metadata["_slo_promote"] = True
        if self.slo.ttft_ms is not None:
            verdict = "attained" if ttft_s * 1000.0 <= self.slo.ttft_ms else "violated"
            self._m_slo(model, "ttft", verdict).inc()
        if self.slo.tpot_ms is not None and tpot_s is not None:
            verdict = "attained" if tpot_s * 1000.0 <= self.slo.tpot_ms else "violated"
            self._m_slo(model, "tpot", verdict).inc()
        if good:
            self._m_goodput_requests(model).inc()
            self._m_goodput_tokens(model).inc(n_tokens)
        req_s, tok_s = self._slo_judge.goodput_rates()
        self._m_goodput_req_s.set(req_s)
        self._m_goodput_tok_s.set(tok_s)

    # --- lifecycle ----------------------------------------------------------
    def build_app(self) -> web.Application:
        app = web.Application()
        app.router.add_post("/v1/chat/completions", self.chat_completions)
        app.router.add_post("/v1/completions", self.completions)
        app.router.add_post("/v1/embeddings", self.embeddings)
        app.router.add_post("/v1/responses", self.responses)
        app.router.add_get("/v1/models", self.list_models)
        app.router.add_get("/health", self.health)
        app.router.add_get("/metrics", self.metrics_route)
        app.router.add_post("/clear_kv_blocks", self.clear_kv_blocks)
        return app

    async def start(self) -> None:
        import socket as _socket

        self._runner = web.AppRunner(self.build_app(), access_log=None)
        await self._runner.setup()
        # Bind the socket ourselves: aiohttp exposes no public API for the
        # OS-assigned port when port=0 (reaching into site._server.sockets is
        # a private-API trap across versions).
        # Bind off the loop: create_server resolves the host and binds
        # synchronously, which can stall an already-serving process loop
        # (multi-frontend startup, slow resolvers).
        sock = await asyncio.to_thread(
            _socket.create_server, (self.host, self.port), reuse_port=False
        )
        self.port = sock.getsockname()[1]
        site = web.SockSite(self._runner, sock, ssl_context=self._ssl)
        await site.start()
        logger.info(
            "OpenAI HTTP%s frontend on %s:%d", "S" if self._ssl else "", self.host, self.port
        )

    async def stop(self) -> None:
        try:
            if self.grpc_service is not None:
                await self.grpc_service.stop()
                self.grpc_service = None
        finally:
            if self._runner is not None:
                await self._runner.cleanup()
                self._runner = None

    # --- routes -------------------------------------------------------------
    async def health(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "healthy", "models": self.manager.list_models()})

    async def metrics_route(self, request: web.Request) -> web.Response:
        return web.Response(body=self.metrics.render(), content_type="text/plain")

    async def list_models(self, request: web.Request) -> web.Response:
        return web.json_response(
            {
                "object": "list",
                "data": [
                    {"id": name, "object": "model", "created": int(time.time()), "owned_by": "dynamo-tpu"}
                    for name in self.manager.list_models()
                ],
            }
        )

    async def clear_kv_blocks(self, request: web.Request) -> web.Response:
        # Ref: clear_kv_blocks.rs — forwarded to workers in the routed setup;
        # local engines expose a hook via the manager entry.
        results = {}
        for name in self.manager.list_models():
            engine = self.manager.get("chat", name) or self.manager.get("completions", name)
            hook = getattr(engine, "clear_kv_blocks", None)
            results[name] = "ok" if hook and await _maybe_await(hook()) is not None else "unsupported"
        return web.json_response(results)

    async def chat_completions(self, request: web.Request) -> web.StreamResponse:
        return await self._serve(request, kind="chat")

    async def completions(self, request: web.Request) -> web.StreamResponse:
        return await self._serve(request, kind="completions")

    def _unary_envelope(self, model: str):
        """Shared request lifecycle for unary JSON endpoints: inflight gauge,
        duration histogram, status counters, structured 500 bodies."""

        service = self

        class _Scope:
            async def __aenter__(self):
                service._m_inflight(model).inc()
                self.start = time.monotonic()
                return self

            async def __aexit__(self, exc_type, exc, tb):
                service._m_inflight(model).dec()
                service._m_duration(model).observe(time.monotonic() - self.start)
                return False

            def run(self, coro):
                async def wrapped():
                    try:
                        resp = await coro()
                        service._m_requests(model, "200").inc()
                        return resp
                    except oai.RequestError as e:
                        service._m_requests(model, "400").inc()
                        return web.json_response(oai.error_body(str(e)), status=400)
                    except Exception as e:
                        logger.exception("request for %s failed", model)
                        service._m_requests(model, "500").inc()
                        return web.json_response(oai.error_body(str(e), "internal_error", 500), status=500)

                return wrapped()

        return _Scope()

    async def embeddings(self, request: web.Request) -> web.Response:
        """/v1/embeddings (ref: openai.rs:369) — routed to an engine
        registered under model_type 'embeddings'."""
        try:
            body = oai.validate_embedding_request(await request.json())
        except (json.JSONDecodeError, oai.RequestError) as e:
            return web.json_response(oai.error_body(str(e)), status=400)
        model = body["model"]
        engine = self.manager.get("embeddings", model)
        if engine is None:
            self._m_requests(model, "404").inc()
            return web.json_response(
                oai.error_body(f"no embeddings model {model!r}", "model_not_found", 404), status=404
            )

        async def handle():
            vectors, prompt_tokens = [], 0
            async for item in engine.generate(body, Context()):
                if isinstance(item, Annotated) and item.is_annotation():
                    continue
                wire = item.data if isinstance(item, Annotated) else item
                if isinstance(wire, dict) and "embeddings" in wire:
                    vectors = wire["embeddings"]
                    prompt_tokens = int(wire.get("prompt_tokens") or 0)
            self._m_input_tokens(model).inc(prompt_tokens)
            usage = oai.usage_dict(prompt_tokens=prompt_tokens, completion_tokens=0)
            return web.json_response(oai.embedding_response(oai.make_id("embd"), model, vectors, usage))

        async with self._unary_envelope(model) as scope:
            return await scope.run(handle)

    async def responses(self, request: web.Request) -> web.StreamResponse:
        """/v1/responses (ref: openai.rs:714) — mapped onto the chat
        pipeline; input items are converted to chat messages."""
        try:
            body = oai.validate_responses_request(await request.json())
        except (json.JSONDecodeError, oai.RequestError) as e:
            return web.json_response(oai.error_body(str(e)), status=400)
        model = body["model"]
        engine = self.manager.get("chat", model)
        if engine is None:
            self._m_requests(model, "404").inc()
            return web.json_response(oai.error_body(f"model {model!r} not found", "model_not_found", 404), status=404)
        rid = oai.make_id("resp")

        try:
            messages = oai.responses_input_to_messages(body)  # RequestError on bad items
        except oai.RequestError as e:
            self._m_requests(model, "400").inc()
            return web.json_response(oai.error_body(str(e)), status=400)
        chat_body = {
            "model": model,
            "messages": messages,
            "stream": False,
        }
        for key in ("temperature", "top_p", "max_output_tokens"):
            if body.get(key) is not None:
                chat_body["max_tokens" if key == "max_output_tokens" else key] = body[key]
        if body.get("tools"):
            chat_body["tools"] = oai.responses_tools_to_chat(body["tools"])
        if body.get("tool_choice") is not None:
            chat_body["tool_choice"] = oai.responses_tool_choice_to_chat(body["tool_choice"])
        rf = oai.responses_text_format_to_response_format(body)
        if rf is not None:
            chat_body["response_format"] = rf
        try:
            # Mirror the chat-side structural validation (response_format /
            # tools / tool_choice) so Responses clients get the same
            # structured 400s, not worker-side failures.
            oai.validate_chat_request(chat_body)
        except oai.RequestError as e:
            self._m_requests(model, "400").inc()
            return web.json_response(oai.error_body(str(e)), status=400)

        if body.get("stream"):
            return await self._responses_stream(request, engine, chat_body, rid, model)

        async def handle():
            text_parts, n_tokens, prompt_tokens = [], 0, 0
            cached_tokens = None
            tool_calls = None
            async for item in engine.generate(chat_body, Context()):
                if isinstance(item, Annotated) and item.is_annotation():
                    if item.event == "_metrics":
                        prompt_tokens = int(item.comment or 0)
                        self._m_input_tokens(model).inc(prompt_tokens)
                    elif item.event == "_queue":
                        self._m_queue(model).observe(float(item.comment or 0))
                    elif item.event == "_cached":
                        cached_tokens = int(item.comment or 0)
                        self._m_cached_tokens(model).inc(cached_tokens)
                    continue
                out = _as_output(item)
                if out is None:
                    continue
                if out.text:
                    text_parts.append(out.text)
                if out.tool_calls:
                    tool_calls = out.tool_calls
                n_tokens += len(out.token_ids)
            self._m_output_tokens(model).inc(n_tokens)
            usage = oai.usage_dict(
                prompt_tokens=prompt_tokens, completion_tokens=n_tokens,
                cached_tokens=cached_tokens,
            )
            return web.json_response(
                oai.responses_response(rid, model, "".join(text_parts), usage, tool_calls=tool_calls)
            )

        async with self._unary_envelope(model) as scope:
            return await scope.run(handle)

    async def _responses_stream(
        self, request: web.Request, engine, chat_body: dict, rid: str, model: str
    ) -> web.StreamResponse:
        """Responses-API semantic SSE stream (ref: openai.rs:714,
        protocols/openai/responses.rs): response.created →
        output_item.added → content_part.added → output_text.delta* →
        *.done → (function_call items) → response.completed."""
        ctx = Context(traceparent=TraceParent.from_headers(request.headers) or None)
        resp = web.StreamResponse(
            status=200,
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "Connection": "keep-alive",
                **_trace_headers(ctx),
            },
        )
        await resp.prepare(request)
        seq = [0]
        start = time.monotonic()

        async def emit(etype: str, payload: dict) -> None:
            payload = {"type": etype, "sequence_number": seq[0], **payload}
            seq[0] += 1
            await resp.write(
                b"event: " + etype.encode()
                + b"\ndata: " + json.dumps(payload, ensure_ascii=False).encode() + b"\n\n"
            )

        text_parts: list = []
        tool_calls = None
        n_tokens, prompt_tokens = 0, 0
        cached_tokens = None
        status = "200"
        msg_id = f"msg-{rid}"
        msg_started = False

        async def ensure_message_started() -> None:
            # The message output item opens lazily at the first text delta:
            # tool-call-only responses must match the unary shape (no empty
            # message item; function_call items start at output_index 0).
            nonlocal msg_started
            if msg_started:
                return
            msg_started = True
            await emit(
                "response.output_item.added",
                {"output_index": 0, "item": {"type": "message", "id": msg_id, "role": "assistant",
                                             "status": "in_progress", "content": []}},
            )
            await emit(
                "response.content_part.added",
                {"item_id": msg_id, "output_index": 0, "content_index": 0,
                 "part": {"type": "output_text", "text": "", "annotations": []}},
            )

        self._m_inflight(model).inc()
        try:
            await emit("response.created", {"response": oai.responses_envelope(rid, model, [], status="in_progress")})
            await emit("response.in_progress", {"response": oai.responses_envelope(rid, model, [], status="in_progress")})
            async for item in engine.generate(chat_body, ctx):
                if isinstance(item, Annotated) and item.is_annotation():
                    if item.event == "_metrics":
                        prompt_tokens = int(item.comment or 0)
                        self._m_input_tokens(model).inc(prompt_tokens)
                    elif item.event == "_queue":
                        self._m_queue(model).observe(float(item.comment or 0))
                    elif item.event == "_cached":
                        cached_tokens = int(item.comment or 0)
                        self._m_cached_tokens(model).inc(cached_tokens)
                    continue
                out = _as_output(item)
                if out is None:
                    continue
                n_tokens += len(out.token_ids)
                if out.text:
                    await ensure_message_started()
                    text_parts.append(out.text)
                    await emit(
                        "response.output_text.delta",
                        {"item_id": msg_id, "output_index": 0, "content_index": 0, "delta": out.text},
                    )
                if out.tool_calls:
                    tool_calls = out.tool_calls
            text = "".join(text_parts)
            output = []
            if msg_started or not tool_calls:
                await ensure_message_started()
                await emit(
                    "response.output_text.done",
                    {"item_id": msg_id, "output_index": 0, "content_index": 0, "text": text},
                )
                await emit(
                    "response.content_part.done",
                    {"item_id": msg_id, "output_index": 0, "content_index": 0,
                     "part": {"type": "output_text", "text": text, "annotations": []}},
                )
                output.append(oai.responses_message_item(rid, text))
                await emit("response.output_item.done", {"output_index": 0, "item": output[0]})
            for i, call in enumerate(tool_calls or []):
                idx = len(output)
                fc = oai.responses_function_call_item(rid, i, call)
                output.append(fc)
                await emit(
                    "response.output_item.added",
                    {"output_index": idx, "item": {**fc, "arguments": "", "status": "in_progress"}},
                )
                await emit(
                    "response.function_call_arguments.delta",
                    {"item_id": fc["id"], "output_index": idx, "delta": fc["arguments"]},
                )
                await emit(
                    "response.function_call_arguments.done",
                    {"item_id": fc["id"], "output_index": idx, "arguments": fc["arguments"]},
                )
                await emit("response.output_item.done", {"output_index": idx, "item": fc})
            usage = oai.usage_dict(
                prompt_tokens=prompt_tokens, completion_tokens=n_tokens,
                cached_tokens=cached_tokens,
            )
            await emit(
                "response.completed",
                {"response": oai.responses_envelope(rid, model, output, usage)},
            )
        except (ConnectionResetError, asyncio.CancelledError):
            ctx.stop_generating()
            status = "499"
            raise
        except Exception as e:  # noqa: BLE001 — stream errors become SSE error events
            logger.exception("responses stream %s failed", ctx.id)
            status = "500"
            await emit("error", {"message": str(e)})
        finally:
            self._m_inflight(model).dec()
            self._m_duration(model).observe(time.monotonic() - start)
            self._m_requests(model, status).inc()
            self._m_output_tokens(model).inc(n_tokens)
        await resp.write_eof()
        return resp

    # --- core serving path --------------------------------------------------
    async def _serve(self, request: web.Request, kind: str) -> web.StreamResponse:
        model = "unknown"
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.json_response(oai.error_body("invalid JSON body"), status=400)
        try:
            body = oai.validate_chat_request(body) if kind == "chat" else oai.validate_completion_request(body)
            model = body["model"]
            # Capacity-ledger attribution: resolve the tenant once, here,
            # so the preprocessor can put it on the wire and every usage
            # block echoes the id the request was billed under.
            body["_tenant"] = _resolve_tenant(body, request.headers)
        except oai.RequestError as e:
            self._m_requests(model, "400").inc()
            return web.json_response(oai.error_body(str(e)), status=400)

        engine = self.manager.get(kind, model) or self.manager.get(
            "chat" if kind == "completions" else "completions", model
        )
        if engine is None:
            self._m_requests(model, "404").inc()
            return web.json_response(oai.error_body(f"model {model!r} not found", "model_not_found", 404), status=404)

        # Pre-flight availability (routed pipelines expose the router's live
        # instance count): with zero workers the answer is an immediate,
        # retryable 503 — not a 500 after the router exhausts its budget,
        # and for SSE not an error event on an already-200 stream.
        probe = getattr(engine, "availability_probe", None)
        if probe is not None and probe() == 0:
            await asyncio.sleep(0.05)  # one watch delivery: absorb races
            if probe() == 0:
                self._m_no_instances(model).inc()
                self._m_requests(model, "503").inc()
                return web.json_response(
                    oai.error_body("no workers are live for this model; retry shortly",
                                   "service_unavailable", 503),
                    status=503, headers={"Retry-After": "1"},
                )

        # Request deadline: client ``timeout`` (seconds) or the frontend
        # default. Normalized into the body so the preprocessor puts the
        # budget on the wire (stop_conditions.deadline_ms).
        timeout_s = body.get("timeout")
        if timeout_s is None and self.request_timeout_ms:
            timeout_s = self.request_timeout_ms / 1000.0
            body["timeout"] = timeout_s
        deadline = (time.monotonic() + float(timeout_s)) if timeout_s else None

        stream = bool(body.get("stream", False))
        ctx = Context(traceparent=TraceParent.from_headers(request.headers) or None)
        # Root (or continuation) span for the request. When sampled, the
        # span becomes the parent of every downstream hop: ctx.traceparent
        # is re-rooted under it, and the same deterministic sampling
        # decision repeats in the worker and scheduler.
        span = get_tracer().span_from(
            "http_request", ctx.traceparent, service="frontend",
            model=model, kind=kind, stream=stream, tenant=body["_tenant"],
        )
        if span is not NULL_SPAN:
            ctx.traceparent = span.child_traceparent()
        rid = oai.make_id("chatcmpl" if kind == "chat" else "cmpl")
        start = time.monotonic()
        self._m_inflight(model).inc()
        try:
            if stream:
                return await self._serve_stream(request, engine, body, ctx, rid, kind, model, start, deadline)
            return await self._serve_unary(engine, body, ctx, rid, kind, model, start, deadline)
        except oai.RequestError as e:
            # Pipeline-stage rejection (e.g. image parts with no encode
            # path): a client/deployment-configuration 400, not a 500.
            self._m_requests(model, "400").inc()
            return web.json_response(
                oai.error_body(str(e)), status=400, headers=_trace_headers(ctx)
            )
        finally:
            self._m_inflight(model).dec()
            self._m_duration(model).observe(time.monotonic() - start)
            span.end()
            if ctx.metadata.pop("_slo_promote", False):
                tracer = get_tracer()
                tp = getattr(ctx, "traceparent", None)
                if tp is not None:
                    promoted = tracer.promote(tp.trace_id)
                    if promoted:
                        logger.info(
                            "slo violation: promoted %d buffered trace records for %s",
                            promoted, tp.trace_id,
                        )

    def _timeout_response(self, ctx, model, prompt_tokens, completion_tokens,
                          cached_tokens=None, tenant=None) -> web.Response:
        """504 with partial-usage accounting: the tokens that did stream are
        real work the client may be billed for, and the counts tell the
        operator how close the request got before the deadline."""
        self._m_timeouts(model).inc()
        self._m_requests(model, "504").inc()
        body = oai.error_body("request deadline exceeded", "timeout_error", 504)
        body["usage"] = oai.usage_dict(prompt_tokens, completion_tokens, cached_tokens,
                                       tenant=tenant)
        return web.json_response(body, status=504, headers=_trace_headers(ctx))

    def _failure_response(self, e, ctx, model, prompt_tokens, completion_tokens):
        """Map infrastructure failures to structured statuses: no live
        workers → retryable 503; migration budget exhausted mid-stream →
        502 carrying the partial token count. None = not ours (500 path)."""
        if isinstance(e, NoInstancesError):
            self._m_no_instances(model).inc()
            self._m_requests(model, "503").inc()
            return web.json_response(
                oai.error_body("no workers are live for this model; retry shortly",
                               "service_unavailable", 503),
                status=503, headers={"Retry-After": "1", **_trace_headers(ctx)},
            )
        if isinstance(e, StreamDisconnect):
            mig = ctx.metadata.get("migration") or {}
            self._m_migration_exhausted(model).inc()
            self._m_requests(model, "502").inc()
            body = oai.error_body(
                "upstream worker stream disconnected and the migration budget "
                "is exhausted", "bad_gateway", 502,
            )
            body["error"]["partial_tokens"] = int(
                mig.get("tokens_emitted", completion_tokens)
            )
            body["error"]["migrations"] = int(mig.get("attempts", 0))
            body["usage"] = oai.usage_dict(prompt_tokens, completion_tokens)
            return web.json_response(body, status=502, headers=_trace_headers(ctx))
        return None

    @staticmethod
    def _choice_bodies(body: dict) -> list:
        """Per-choice request bodies for n>1: each choice is an independent
        generation; seeded requests get seed+i so choices differ the way
        OpenAI's do (ref: protocols/openai n handling)."""
        n = int(body.get("n") or 1)
        if n == 1:
            return [body]
        out = []
        for i in range(n):
            b = dict(body)
            b["n"] = 1
            if body.get("seed") is not None:
                b["seed"] = int(body["seed"]) + i
            out.append(b)
        return out

    async def _serve_unary(self, engine, body, ctx, rid, kind, model, start, deadline=None) -> web.Response:
        bodies = self._choice_bodies(body)
        prompt_tokens_box = [0]
        cached_tokens_box = [None]
        first_box = [None]
        last_box = [None]
        # Per-choice live token counts: the 504/502 paths report honest
        # partial usage even for choices that never reached their final
        # frame.
        tokens_box = [0] * len(bodies)

        async def run_choice(i: int, b: dict, c: Context) -> dict:
            text_parts = []
            reasoning_parts = []
            tool_calls = None
            n_tokens = 0
            finish_reason = "stop"
            logprobs: list = []
            top_logprobs: list = []
            async for item in engine.generate(b, c):
                if isinstance(item, Annotated) and item.is_annotation():
                    if item.event == "_metrics" and i == 0:
                        prompt_tokens_box[0] = int(item.comment or 0)
                        self._m_input_tokens(model).inc(prompt_tokens_box[0])
                    elif item.event == "_queue" and i == 0:
                        self._m_queue(model).observe(float(item.comment or 0))
                    elif item.event == "_cached" and i == 0:
                        cached_tokens_box[0] = int(item.comment or 0)
                        self._m_cached_tokens(model).inc(cached_tokens_box[0])
                    continue
                out = _as_output(item)
                if out is None:
                    continue
                if out.token_ids:
                    last_box[0] = time.monotonic()
                if out.text:
                    if first_box[0] is None:
                        first_box[0] = time.monotonic()
                        self._m_ttft(model).observe(first_box[0] - start)
                    text_parts.append(out.text)
                if out.reasoning:
                    reasoning_parts.append(out.reasoning)
                if out.tool_calls:
                    tool_calls = out.tool_calls
                if out.logprobs:
                    logprobs.extend(out.logprobs)
                    # Keep alternatives index-aligned with the chosen-token
                    # list even if a frame carried logprobs without tops.
                    tops = out.top_logprobs or []
                    top_logprobs.extend(tops[: len(out.logprobs)])
                    while len(top_logprobs) < len(logprobs):
                        top_logprobs.append(None)
                n_tokens += len(out.token_ids)
                tokens_box[i] = n_tokens
                if out.finish_reason:
                    finish_reason = out.finish_reason
            return {
                "index": i,
                "text": "".join(text_parts),
                "reasoning": "".join(reasoning_parts) or None,
                "tool_calls": tool_calls,
                "finish_reason": finish_reason,
                "n_tokens": n_tokens,
                "logprobs": logprobs,
                "top_logprobs": top_logprobs if any(top_logprobs) else None,
            }

        # Children need UNIQUE ids: the engine keys sequences by context.id,
        # so sharing the parent's id would collide all n choices in the
        # scheduler (un-abortable orphans once one finishes).
        ctxs = [ctx] + [ctx.child(id=f"{ctx.id}-c{i}") for i in range(1, len(bodies))]
        tasks = [
            asyncio.create_task(run_choice(i, b, c))
            for i, (b, c) in enumerate(zip(bodies, ctxs))
        ]
        frontend_timed_out = False
        try:
            if deadline is None:
                results = await asyncio.gather(*tasks)
            else:
                # Frontend deadline backstop: the scheduler evicts
                # past-deadline rows itself, so the grace window only trips
                # when a worker is hung or unreachable — then we cancel into
                # the pipeline and answer 504 with whatever tokens landed.
                grace = max(0.5, 0.25 * max(deadline - start, 0.0))
                done, pending = await asyncio.wait(
                    set(tasks), timeout=max(0.0, deadline + grace - time.monotonic())
                )
                if pending:
                    frontend_timed_out = True
                    for c in ctxs:
                        c.stop_generating()
                    _, still = await asyncio.wait(pending, timeout=2.0)
                    for t in still:
                        t.cancel()
                    await asyncio.gather(*tasks, return_exceptions=True)
                for t in tasks:
                    if t.done() and not t.cancelled() and t.exception() is not None:
                        raise t.exception()
                results = [
                    t.result() for t in tasks if t.done() and not t.cancelled()
                ]
        except Exception as e:
            # Stop and reap the sibling choices — leaving them running wastes
            # engine work and leaks never-retrieved task exceptions.
            for c in ctxs:
                c.stop_generating()
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            if isinstance(e, oai.RequestError):
                # Pipeline-stage rejection (e.g. image parts with no encode
                # path): a client/configuration 400, not a server fault.
                self._m_requests(model, "400").inc()
                return web.json_response(
                    oai.error_body(str(e)), status=400, headers=_trace_headers(ctx)
                )
            mapped = self._failure_response(e, ctx, model, prompt_tokens_box[0], sum(tokens_box))
            if mapped is not None:
                return mapped
            logger.exception("request %s failed", ctx.id)
            self._m_requests(model, "500").inc()
            return web.json_response(
                oai.error_body(str(e), "internal_error", 500), status=500,
                headers=_trace_headers(ctx),
            )
        if frontend_timed_out or any(r["finish_reason"] == "timeout" for r in results):
            # Deadline expiry — engine-evicted (finish_reason "timeout") or
            # the frontend watchdog above. 504 with partial-usage accounting.
            return self._timeout_response(ctx, model, prompt_tokens_box[0],
                                          sum(tokens_box), cached_tokens_box[0],
                                          tenant=body.get("_tenant"))
        self._m_requests(model, "200").inc()
        total_tokens = sum(r["n_tokens"] for r in results)
        self._m_output_tokens(model).inc(total_tokens)
        self._record_request_telemetry(
            model, start, first_box[0], last_box[0], results[0]["n_tokens"], ctx=ctx
        )
        usage = oai.usage_dict(
            prompt_tokens=prompt_tokens_box[0], completion_tokens=total_tokens,
            cached_tokens=cached_tokens_box[0], tenant=body.get("_tenant"),
        )
        if kind == "chat":
            choices = [
                oai.chat_choice(
                    r["index"], r["text"], r["finish_reason"], r["tool_calls"], r["reasoning"],
                    logprobs=oai.chat_logprobs_content(None, r["logprobs"], r["top_logprobs"])
                    if r["logprobs"] else None,
                )
                for r in results
            ]
            return web.json_response(
                oai.chat_response_multi(rid, model, choices, usage), headers=_trace_headers(ctx)
            )
        choices = [
            oai.completion_choice(
                r["index"], r["text"], r["finish_reason"],
                logprobs=oai.completion_logprobs_block(
                    [""] * len(r["logprobs"]), r["logprobs"], r["top_logprobs"]
                )
                if r["logprobs"] else None,
            )
            for r in results
        ]
        return web.json_response(
            oai.completion_response_multi(rid, model, choices, usage), headers=_trace_headers(ctx)
        )

    @staticmethod
    async def _iter_with_deadline(stream, deadline: Optional[float], start: float):
        """Yield stream items, raising TimeoutError when the deadline (plus
        a hung-worker grace window — the engine's own eviction should fire
        first and arrives as a normal finish_reason='timeout' frame) lapses
        between items."""
        if deadline is None:
            async for item in stream:
                yield item
            return
        grace = max(0.5, 0.25 * max(deadline - start, 0.0))
        it = stream.__aiter__()
        while True:
            remaining = deadline + grace - time.monotonic()
            if remaining <= 0:
                raise asyncio.TimeoutError
            try:
                item = await asyncio.wait_for(it.__anext__(), remaining)
            except StopAsyncIteration:
                return
            yield item

    async def _serve_stream(self, request, engine, body, ctx, rid, kind, model, start, deadline=None) -> web.StreamResponse:
        if int(body.get("n") or 1) > 1:
            return await self._serve_stream_multi(request, engine, body, ctx, rid, kind, model, start, deadline)
        resp = web.StreamResponse(
            status=200,
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "Connection": "keep-alive",
                **_trace_headers(ctx),
            },
        )
        await resp.prepare(request)
        first = True
        first_at = None
        prev_tok_at = None
        n_tokens = 0
        prompt_tokens = 0
        cached_tokens = None
        status = "200"
        try:
            if kind == "chat":
                await _sse(resp, oai.chat_chunk(rid, model, {"role": "assistant", "content": ""}))
            async for item in self._iter_with_deadline(engine.generate(body, ctx), deadline, start):
                if isinstance(item, Annotated) and item.is_annotation():
                    if item.event.startswith("_"):
                        if item.event == "_metrics":
                            prompt_tokens = int(item.comment or 0)
                            self._m_input_tokens(model).inc(prompt_tokens)
                        elif item.event == "_queue":
                            self._m_queue(model).observe(float(item.comment or 0))
                        elif item.event == "_cached":
                            cached_tokens = int(item.comment or 0)
                            self._m_cached_tokens(model).inc(cached_tokens)
                        continue
                    await _sse_event(resp, item.event, item.comment)
                    continue
                out = _as_output(item)
                if out is None:
                    continue
                now = time.monotonic()
                if out.text or out.token_ids:
                    if first:
                        self._m_ttft(model).observe(now - start)
                        first = False
                        first_at = now
                    elif prev_tok_at is not None:
                        self._m_itl(model).observe(now - prev_tok_at)
                    prev_tok_at = now
                    n_tokens += len(out.token_ids)
                if out.reasoning and kind == "chat":
                    await _sse(resp, oai.chat_chunk(rid, model, {"reasoning_content": out.reasoning}))
                if out.text or out.logprobs:
                    # Tokens whose text is withheld (detok partials / stop
                    # jail) still stream their logprobs on an empty delta.
                    text = out.text or ""
                    lp = None
                    if out.logprobs:
                        lp = (
                            oai.chat_logprobs_content(text, out.logprobs, out.top_logprobs)
                            if kind == "chat"
                            else oai.completion_logprobs_block([text], out.logprobs, out.top_logprobs)
                        )
                    if kind == "chat":
                        await _sse(resp, oai.chat_chunk(rid, model, {"content": text}, logprobs=lp))
                    else:
                        await _sse(resp, oai.completion_chunk(rid, model, text, logprobs=lp))
                if out.tool_calls and kind == "chat":
                    delta_calls = [
                        {**tc, "index": i, "function": tc["function"]}
                        for i, tc in enumerate(out.tool_calls)
                    ]
                    await _sse(resp, oai.chat_chunk(rid, model, {"tool_calls": delta_calls}))
                if out.finish_reason:
                    if out.finish_reason == "timeout":
                        # Engine-side deadline eviction: headers are long
                        # gone, so the 504 lives in the finish_reason and
                        # the status counter.
                        status = "504"
                        self._m_timeouts(model).inc()
                    # Final frame carries the usage block (OpenAI
                    # stream_options include_usage shape) with the resolved
                    # tenant echoed — the client sees who it was billed as.
                    usage = oai.usage_dict(
                        prompt_tokens, n_tokens, cached_tokens,
                        tenant=body.get("_tenant"),
                    )
                    chunk = (
                        oai.chat_chunk(rid, model, {}, finish_reason=out.finish_reason,
                                       usage=usage)
                        if kind == "chat"
                        else oai.completion_chunk(rid, model, "", finish_reason=out.finish_reason)
                    )
                    if kind != "chat":
                        chunk["usage"] = usage
                    await _sse(resp, chunk)
        except (ConnectionResetError, asyncio.CancelledError):
            # Client went away: cancel into the pipeline (ref: disconnect.rs).
            ctx.stop_generating()
            status = "499"
            raise
        except asyncio.TimeoutError:
            # Frontend deadline backstop (hung/unreachable worker): cancel
            # into the pipeline and close the stream with a timeout finish.
            ctx.stop_generating()
            status = "504"
            self._m_timeouts(model).inc()
            chunk = (
                oai.chat_chunk(rid, model, {}, finish_reason="timeout")
                if kind == "chat"
                else oai.completion_chunk(rid, model, "", finish_reason="timeout")
            )
            await _sse(resp, chunk)
        except NoInstancesError:
            status = "503"
            self._m_no_instances(model).inc()
            await _sse(resp, oai.error_body(
                "no workers are live for this model; retry shortly",
                "service_unavailable", 503,
            ))
        except StreamDisconnect:
            mig = ctx.metadata.get("migration") or {}
            status = "502"
            self._m_migration_exhausted(model).inc()
            err = oai.error_body(
                "upstream worker stream disconnected and the migration budget "
                "is exhausted", "bad_gateway", 502,
            )
            err["error"]["partial_tokens"] = int(mig.get("tokens_emitted", n_tokens))
            err["error"]["migrations"] = int(mig.get("attempts", 0))
            await _sse(resp, err)
        except Exception as e:
            logger.exception("stream %s failed", ctx.id)
            status = "500"
            await _sse(resp, oai.error_body(str(e), "internal_error", 500))
        finally:
            self._m_requests(model, status).inc()
            self._m_output_tokens(model).inc(n_tokens)
            if status == "200":
                self._record_request_telemetry(model, start, first_at, prev_tok_at, n_tokens, ctx=ctx)
        await resp.write(b"data: [DONE]\n\n")
        await resp.write_eof()
        return resp

    async def _serve_stream_multi(self, request, engine, body, ctx, rid, kind, model, start, deadline=None) -> web.StreamResponse:
        """n>1 streaming: one generation per choice, chunks multiplexed onto
        one SSE stream with their choice index (ref: OpenAI n semantics)."""
        resp = web.StreamResponse(
            status=200,
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "Connection": "keep-alive",
                **_trace_headers(ctx),
            },
        )
        await resp.prepare(request)
        bodies = self._choice_bodies(body)
        # Unique-id children of the request context: sequences key on the id
        # (collision = orphaned choices) and children inherit the traceparent.
        ctxs = [ctx] + [ctx.child(id=f"{ctx.id}-c{i}") for i in range(1, len(bodies))]
        queue: "asyncio.Queue" = asyncio.Queue()
        n_tokens = 0
        status = "200"

        async def pump(i: int, b: dict, c: Context):
            try:
                async for item in engine.generate(b, c):
                    if isinstance(item, Annotated) and item.is_annotation():
                        if item.event == "_metrics" and i == 0:
                            self._m_input_tokens(model).inc(int(item.comment or 0))
                        elif item.event == "_queue" and i == 0:
                            self._m_queue(model).observe(float(item.comment or 0))
                        elif item.event == "_cached" and i == 0:
                            self._m_cached_tokens(model).inc(int(item.comment or 0))
                        continue
                    out = _as_output(item)
                    if out is not None:
                        await queue.put((i, out, None))
            except Exception as e:  # noqa: BLE001 — surfaced on the stream
                await queue.put((i, None, e))
            finally:
                await queue.put((i, None, None))  # choice done

        tasks = [asyncio.create_task(pump(i, b, c)) for i, (b, c) in enumerate(zip(bodies, ctxs))]
        live = len(tasks)
        try:
            if kind == "chat":
                for i in range(len(bodies)):
                    await _sse(resp, oai.chat_chunk(rid, model, {"role": "assistant", "content": ""}, index=i))
            grace = max(0.5, 0.25 * max(deadline - start, 0.0)) if deadline else 0.0
            while live:
                if deadline is None:
                    i, out, err = await queue.get()
                else:
                    remaining = deadline + grace - time.monotonic()
                    if remaining <= 0:
                        raise asyncio.TimeoutError
                    i, out, err = await asyncio.wait_for(queue.get(), remaining)
                if err is not None:
                    raise err
                if out is None:
                    live -= 1
                    continue
                n_tokens += len(out.token_ids)
                if out.reasoning and kind == "chat":
                    await _sse(resp, oai.chat_chunk(rid, model, {"reasoning_content": out.reasoning}, index=i))
                if out.text or out.logprobs:
                    text = out.text or ""
                    lp = None
                    if out.logprobs:
                        lp = (
                            oai.chat_logprobs_content(text, out.logprobs, out.top_logprobs)
                            if kind == "chat"
                            else oai.completion_logprobs_block([text], out.logprobs, out.top_logprobs)
                        )
                    if kind == "chat":
                        await _sse(resp, oai.chat_chunk(rid, model, {"content": text}, index=i, logprobs=lp))
                    else:
                        await _sse(resp, oai.completion_chunk(rid, model, text, index=i, logprobs=lp))
                if out.tool_calls and kind == "chat":
                    delta_calls = [
                        {**tc, "index": j, "function": tc["function"]}
                        for j, tc in enumerate(out.tool_calls)
                    ]
                    await _sse(resp, oai.chat_chunk(rid, model, {"tool_calls": delta_calls}, index=i))
                if out.finish_reason:
                    chunk = (
                        oai.chat_chunk(rid, model, {}, finish_reason=out.finish_reason, index=i)
                        if kind == "chat"
                        else oai.completion_chunk(rid, model, "", finish_reason=out.finish_reason, index=i)
                    )
                    await _sse(resp, chunk)
        except (ConnectionResetError, asyncio.CancelledError):
            status = "499"
            raise
        except asyncio.TimeoutError:
            # Frontend deadline backstop: finish every live choice with a
            # timeout chunk (headers are long gone; the finally below
            # cancels into the pipeline).
            status = "504"
            self._m_timeouts(model).inc()
            for i in range(len(bodies)):
                chunk = (
                    oai.chat_chunk(rid, model, {}, finish_reason="timeout", index=i)
                    if kind == "chat"
                    else oai.completion_chunk(rid, model, "", finish_reason="timeout", index=i)
                )
                await _sse(resp, chunk)
        except Exception as e:
            logger.exception("stream %s failed", ctx.id)
            status = "500"
            await _sse(resp, oai.error_body(str(e), "internal_error", 500))
        finally:
            for c in ctxs:
                c.stop_generating()
            for t in tasks:
                t.cancel()
            self._m_requests(model, status).inc()
            self._m_output_tokens(model).inc(n_tokens)
        await resp.write(b"data: [DONE]\n\n")
        await resp.write_eof()
        return resp


_as_output = as_engine_output

# The request's trace id is echoed on every response (SSE included) so a
# client report ("this request was slow") maps straight to the JSONL trace
# and ``tools/trace_view.py`` — even for unsampled requests, where it still
# correlates with the structured logs.
TRACE_ID_HEADER = "x-dynamo-trace-id"

# Capacity-ledger tenant attribution (runtime/ledger.py). Resolution order:
# the OpenAI ``user`` field, then this header, then a hash of the API key —
# "anon" only when the request carries nothing attributable.
TENANT_HEADER = "x-dynamo-tenant"


def _resolve_tenant(body: dict, headers) -> str:
    user = body.get("user")
    if user:
        return oai.validate_tenant(user, "user")
    hdr = headers.get(TENANT_HEADER)
    if hdr:
        return oai.validate_tenant(hdr, TENANT_HEADER)
    auth = headers.get("Authorization") or ""
    if auth:
        # Stable pseudonymous id per API key: attribution without storing
        # (or ever re-emitting) the credential itself.
        import hashlib

        token = auth.split(None, 1)[-1]
        return "key-" + hashlib.sha256(token.encode()).hexdigest()[:16]
    return "anon"


def _trace_headers(ctx: Context) -> dict:
    tp = getattr(ctx, "traceparent", None)
    return {TRACE_ID_HEADER: tp.trace_id} if tp is not None else {}


async def _sse(resp: web.StreamResponse, obj: dict) -> None:
    await resp.write(b"data: " + json.dumps(obj, ensure_ascii=False).encode() + b"\n\n")


async def _sse_event(resp: web.StreamResponse, event: str, comment: Optional[str]) -> None:
    payload = json.dumps({"event": event, "comment": comment}, ensure_ascii=False).encode()
    await resp.write(b"event: " + event.encode() + b"\ndata: " + payload + b"\n\n")


async def _maybe_await(x):
    if asyncio.iscoroutine(x):
        return await x
    return x
