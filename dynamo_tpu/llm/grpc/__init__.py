"""KServe v2 gRPC frontend (ref: lib/llm/src/grpc/service/kserve.rs)."""

from dynamo_tpu.llm.grpc.service import KserveGrpcService  # noqa: F401
