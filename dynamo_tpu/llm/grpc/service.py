"""KServe v2 ``GRPCInferenceService`` frontend.

Ref: lib/llm/src/grpc/service/kserve.rs:31+ (tonic service over
inference.proto) — same tensor conventions:

- input ``text_input`` (BYTES) — the prompt;
- input ``streaming`` (BOOL) — only valid on ModelStreamInfer;
- request parameters map → sampling options (``max_tokens``,
  ``temperature``, ``top_p``, ...);
- output ``text_output`` (BYTES) — generated text (one chunk per stream
  response on ModelStreamInfer; the full completion on ModelInfer).

The service dispatches into the same ``ModelManager`` pipelines as the HTTP
frontend (completions shape), so routing/preprocessing/detokenization are
shared. Implemented with ``grpc.aio`` generic handlers over protoc-generated
messages (no grpcio-tools dependency).
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator, Optional

import grpc

from dynamo_tpu.llm.discovery import ModelManager
from dynamo_tpu.llm.grpc import kserve_pb2 as pb
from dynamo_tpu.llm.protocols.common import as_engine_output as _as_output
from dynamo_tpu.llm.protocols import openai as oai
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.logging import get_logger

logger = get_logger(__name__)

SERVICE = "inference.GRPCInferenceService"


def _param_value(p: "pb.InferParameter"):
    kind = p.WhichOneof("parameter_choice")
    return getattr(p, kind) if kind else None


def _get_text_input(req: "pb.ModelInferRequest") -> Optional[str]:
    for i, t in enumerate(req.inputs):
        if t.name != "text_input":
            continue
        if t.contents.bytes_contents:
            return t.contents.bytes_contents[0].decode("utf-8", "replace")
        if i < len(req.raw_input_contents):
            raw = req.raw_input_contents[i]
            # BYTES raw wire format: u32-le length prefix + payload.
            if len(raw) >= 4:
                n = int.from_bytes(raw[:4], "little")
                if 4 + n <= len(raw):
                    return raw[4 : 4 + n].decode("utf-8", "replace")
            return raw.decode("utf-8", "replace")
    return None


def _get_bool_input(req: "pb.ModelInferRequest", name: str) -> bool:
    for i, t in enumerate(req.inputs):
        if t.name != name:
            continue
        if t.contents.bool_contents:
            return bool(t.contents.bool_contents[0])
        if i < len(req.raw_input_contents) and req.raw_input_contents[i]:
            # BOOL raw wire format: one byte per element.
            return bool(req.raw_input_contents[i][0])
    return False


class BadRequest(ValueError):
    """Client-side protocol error → INVALID_ARGUMENT / in-stream error."""


def _to_body(req: "pb.ModelInferRequest", stream: bool) -> dict:
    body = {"model": req.model_name, "prompt": _get_text_input(req) or "", "stream": stream}
    for key, p in req.parameters.items():
        val = _param_value(p)
        try:
            if key in ("max_tokens", "min_tokens", "top_k", "seed", "n"):
                body[key] = int(val)
            elif key in ("temperature", "top_p", "frequency_penalty", "presence_penalty"):
                body[key] = float(val)
            elif key in ("stop",):
                body[key] = str(val)
            elif key == "ignore_eos":
                body[key] = bool(val)
        except (TypeError, ValueError):
            raise BadRequest(f"bad value for parameter {key!r}: {val!r}")
    return body


def _infer_response(req_id: str, model: str, text: str, finish_reason: Optional[str] = None) -> "pb.ModelInferResponse":
    resp = pb.ModelInferResponse(model_name=model, id=req_id)
    out = resp.outputs.add()
    out.name = "text_output"
    out.datatype = "BYTES"
    out.shape.extend([1])
    out.contents.bytes_contents.append(text.encode())
    if finish_reason:
        resp.parameters["finish_reason"].string_param = finish_reason
    return resp


class KserveGrpcService:
    """gRPC twin of ``HttpService``: same manager, same pipelines."""

    def __init__(self, manager: ModelManager, host: str = "0.0.0.0", port: int = 0):
        self.manager = manager
        self.host = host
        self.port = port
        self.server: Optional[grpc.aio.Server] = None

    # --- lifecycle ----------------------------------------------------------
    async def start(self) -> None:
        self.server = grpc.aio.server()
        u = grpc.unary_unary_rpc_method_handler
        handlers = {
            "ServerLive": u(
                self.server_live,
                request_deserializer=pb.ServerLiveRequest.FromString,
                response_serializer=pb.ServerLiveResponse.SerializeToString,
            ),
            "ServerReady": u(
                self.server_ready,
                request_deserializer=pb.ServerReadyRequest.FromString,
                response_serializer=pb.ServerReadyResponse.SerializeToString,
            ),
            "ModelReady": u(
                self.model_ready,
                request_deserializer=pb.ModelReadyRequest.FromString,
                response_serializer=pb.ModelReadyResponse.SerializeToString,
            ),
            "ServerMetadata": u(
                self.server_metadata,
                request_deserializer=pb.ServerMetadataRequest.FromString,
                response_serializer=pb.ServerMetadataResponse.SerializeToString,
            ),
            "ModelMetadata": u(
                self.model_metadata,
                request_deserializer=pb.ModelMetadataRequest.FromString,
                response_serializer=pb.ModelMetadataResponse.SerializeToString,
            ),
            "ModelInfer": u(
                self.model_infer,
                request_deserializer=pb.ModelInferRequest.FromString,
                response_serializer=pb.ModelInferResponse.SerializeToString,
            ),
            "ModelStreamInfer": grpc.stream_stream_rpc_method_handler(
                self.model_stream_infer,
                request_deserializer=pb.ModelInferRequest.FromString,
                response_serializer=pb.ModelStreamInferResponse.SerializeToString,
            ),
        }
        self.server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE, handlers),)
        )
        bound = self.server.add_insecure_port(f"{self.host}:{self.port}")
        if bound == 0:
            raise RuntimeError(f"could not bind grpc frontend to {self.host}:{self.port}")
        self.port = bound
        await self.server.start()
        logger.info("kserve grpc frontend on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self.server is not None:
            await self.server.stop(grace=1.0)
            self.server = None

    # --- health/metadata ----------------------------------------------------
    async def server_live(self, request, context) -> "pb.ServerLiveResponse":
        return pb.ServerLiveResponse(live=True)

    async def server_ready(self, request, context) -> "pb.ServerReadyResponse":
        return pb.ServerReadyResponse(ready=True)

    async def model_ready(self, request, context) -> "pb.ModelReadyResponse":
        return pb.ModelReadyResponse(ready=self.manager.has_model(request.name))

    async def server_metadata(self, request, context) -> "pb.ServerMetadataResponse":
        return pb.ServerMetadataResponse(name="dynamo-tpu", version="0", extensions=[])

    async def model_metadata(self, request, context) -> "pb.ModelMetadataResponse":
        if not self.manager.has_model(request.name):
            await context.abort(grpc.StatusCode.NOT_FOUND, f"model {request.name!r} not found")
        resp = pb.ModelMetadataResponse(name=request.name, versions=["1"], platform="dynamo")
        for name, dt in (("text_input", "BYTES"), ("streaming", "BOOL")):
            t = resp.inputs.add()
            t.name, t.datatype = name, dt
            t.shape.extend([1])
        out = resp.outputs.add()
        out.name, out.datatype = "text_output", "BYTES"
        out.shape.extend([-1])
        return resp

    # --- inference ----------------------------------------------------------
    def _engine_for(self, model: str):
        return self.manager.get("completions", model) or self.manager.get("chat", model)

    async def model_infer(self, request: "pb.ModelInferRequest", context) -> "pb.ModelInferResponse":
        if _get_bool_input(request, "streaming"):
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "streaming is only supported via ModelStreamInfer",
            )
        engine = self._engine_for(request.model_name)
        if engine is None:
            await context.abort(grpc.StatusCode.NOT_FOUND, f"model {request.model_name!r} not found")
        if _get_text_input(request) is None:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, "missing text_input tensor")
        try:
            body = _to_body(request, stream=False)
        except BadRequest as e:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        ctx = Context()
        parts, finish = [], None
        try:
            async for item in engine.generate(body, ctx):
                out = _as_output(item)
                if out is None:
                    continue
                if out.text:
                    parts.append(out.text)
                finish = out.finish_reason or finish
        except asyncio.CancelledError:
            # Client cancelled the RPC: stop the worker-side generation too.
            ctx.stop_generating()
            raise
        except Exception as e:  # noqa: BLE001 — becomes a gRPC status
            logger.exception("grpc infer %s failed", ctx.id)
            await context.abort(grpc.StatusCode.INTERNAL, str(e))
        return _infer_response(request.id or oai.make_id("infer"), request.model_name, "".join(parts), finish)

    async def model_stream_infer(
        self, request_iterator, context
    ) -> AsyncIterator["pb.ModelStreamInferResponse"]:
        async for request in request_iterator:
            engine = self._engine_for(request.model_name)
            if engine is None:
                yield pb.ModelStreamInferResponse(
                    error_message=f"model {request.model_name!r} not found"
                )
                continue
            if _get_text_input(request) is None:
                yield pb.ModelStreamInferResponse(error_message="missing text_input tensor")
                continue
            try:
                body = _to_body(request, stream=True)
            except BadRequest as e:
                yield pb.ModelStreamInferResponse(error_message=str(e))
                continue
            rid = request.id or oai.make_id("infer")
            ctx = Context()
            try:
                async for item in engine.generate(body, ctx):
                    out = _as_output(item)
                    if out is None:
                        continue
                    if out.text or out.finish_reason:
                        yield pb.ModelStreamInferResponse(
                            infer_response=_infer_response(rid, request.model_name, out.text or "", out.finish_reason)
                        )
            except asyncio.CancelledError:
                ctx.stop_generating()
                raise
            except Exception as e:  # noqa: BLE001 — becomes an in-stream error
                logger.exception("grpc stream infer %s failed", ctx.id)
                yield pb.ModelStreamInferResponse(error_message=str(e))
