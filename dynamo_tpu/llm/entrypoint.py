"""Entrypoints: pipeline assembly + worker registration + serve modes.

Ref: lib/llm/src/entrypoint/* — ``EngineConfig`` variants (entrypoint.rs:42),
``run_input`` (input.rs:109), pipeline builders (input/common.rs:194
``build_pipeline``, :226 ``build_routed_pipeline``: frontend → preprocessor →
backend → migration → router → engine), worker-side ``input/endpoint.rs``
(serve a ``dyn://ns.comp.ep`` engine), and ``register_llm`` (bindings).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, AsyncIterator, List, Optional

from dynamo_tpu.llm.backend import Backend
from dynamo_tpu.llm.discovery import ModelManager, ModelWatcher
from dynamo_tpu.llm.http.service import HttpService
from dynamo_tpu.llm.migration import Migration
from dynamo_tpu.llm.model_card import ModelDeploymentCard, ModelEntry
from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor, PromptFormatter
from dynamo_tpu.llm.tokenizer import Tokenizer, load_tokenizer
from dynamo_tpu.runtime.component import Endpoint
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.engine import Annotated, AsyncEngine, Context
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.pipeline import Operator, link
from dynamo_tpu.runtime.push_router import PushRouter, RouterMode

logger = get_logger(__name__)


class RouterEngine:
    """Adapts a PushRouter (or KvPushRouter) to the AsyncEngine shape."""

    def __init__(self, router):
        self.router = router

    def generate(self, request: Any, context: Context) -> AsyncIterator[Annotated]:
        return self.router.generate(request, context)


def _encode_op(encoder, encode_client):
    if encoder is None and encode_client is None:
        return None
    from dynamo_tpu.llm.multimodal import EncodeOperator

    return EncodeOperator(encoder=encoder, client=encode_client)


def build_local_pipeline(
    tokenizer: Tokenizer,
    engine: AsyncEngine,
    card: Optional[ModelDeploymentCard] = None,
    *,
    encoder=None,
    encode_client=None,
) -> AsyncEngine:
    """Aggregated in-process pipeline: preprocessor → [encode] → backend →
    engine (ref: EngineConfig::StaticFull). ``encoder``/``encode_client``
    enable the multimodal image path (multimodal.py)."""
    formatter = PromptFormatter(card.chat_template if card else None)
    pre = OpenAIPreprocessor(
        tokenizer,
        formatter,
        tool_call_parser=card.tool_call_parser if card else None,
        reasoning_parser=card.reasoning_parser if card else None,
    )
    ops = [pre]
    enc = _encode_op(encoder, encode_client)
    if enc is not None:
        ops.append(enc)
    ops.append(Backend(tokenizer))
    # Guided decoding needs the serving tokenizer engine-side (token-FSM
    # lifting); attach it unless the engine already has one.
    if (
        hasattr(engine, "attach_guided_tokenizer")
        and getattr(getattr(engine, "scheduler", None), "guided", None) is None
    ):
        engine.attach_guided_tokenizer(tokenizer)
    return link(ops, engine)


def build_routed_pipeline(
    tokenizer: Tokenizer,
    router: PushRouter,
    card: Optional[ModelDeploymentCard] = None,
    *,
    migration_limit: int = 0,
    encoder=None,
    encode_client=None,
    on_migrate=None,
) -> AsyncEngine:
    """Frontend-side routed pipeline: preprocessor → [encode] → backend →
    migration → router (ref: input/common.rs:226)."""
    formatter = PromptFormatter(card.chat_template if card else None)
    pre = OpenAIPreprocessor(
        tokenizer,
        formatter,
        tool_call_parser=card.tool_call_parser if card else None,
        reasoning_parser=card.reasoning_parser if card else None,
    )
    ops = [pre]
    enc = _encode_op(encoder, encode_client)
    if enc is not None:
        ops.append(enc)
    ops.append(Backend(tokenizer))
    limit = migration_limit if migration_limit else (card.migration_limit if card else 0)
    if limit > 0:
        ops.append(Migration(limit, on_migrate=on_migrate))
    composed = link(ops, RouterEngine(router))
    # Pre-flight availability for the HTTP layer: zero live instances ⇒ an
    # immediate retryable 503 instead of a 500 after the retry budget burns.
    client = getattr(router, "client", None)
    if client is not None:
        composed.availability_probe = lambda: len(client.instances)
    return composed


async def register_llm(
    drt: DistributedRuntime,
    endpoint: Endpoint,
    engine: AsyncEngine,
    card: ModelDeploymentCard,
    *,
    stats_handler=None,
) -> "tuple":
    """Worker-side: serve the engine on the endpoint and publish the model
    entry so frontends discover it (ref: register_llm + ModelEntry put,
    SURVEY.md §3B)."""
    handle = await endpoint.serve_endpoint(
        engine.generate if hasattr(engine, "generate") else engine, stats_handler=stats_handler
    )
    entry = ModelEntry(
        name=card.name,
        namespace=endpoint.namespace,
        component=endpoint.component,
        endpoint=endpoint.name,
        card=card,
    )
    # Per-instance model key (ref: model_entry.rs keys carry the lease):
    # N workers serving the same model register N keys, so the frontend
    # watcher's refcount drops the model only when the LAST one goes — a
    # drained/crashed worker cannot take the model down for its survivors.
    key = f"{entry.store_key}:{handle.lease.id:x}"
    await drt.store.put(key, entry.to_json(), lease_id=handle.lease.id)
    logger.info("registered model %s at %s", card.name, key)
    return handle, entry


@dataclass
class FrontendConfig:
    """Mirrors the reference frontend CLI surface
    (components/frontend main.py:81-286)."""

    host: str = "0.0.0.0"
    port: int = 8000
    grpc_port: Optional[int] = None  # serve the KServe gRPC frontend too
    router_mode: str = "round-robin"  # round-robin | random | kv
    busy_threshold: Optional[float] = None
    migration_limit: int = 0
    kv_overlap_score_weight: float = 1.0
    kv_temperature: float = 0.0
    namespace: str = "dynamo"
    # TLS termination (ref frontend --tls-cert-path/--tls-key-path).
    tls_cert: Optional[str] = None
    tls_key: Optional[str] = None
    # Multimodal: route image parts to the encode-worker pool at this
    # component (ref: trtllm encode_helper.py); None = images rejected.
    encode_component: Optional[str] = None
    # SLA targets for the frontend's e2e SLO judgments + goodput account
    # (--slo-ttft-ms/--slo-tpot-ms; None = phase unjudged).
    slo_ttft_ms: Optional[float] = None
    slo_tpot_ms: Optional[float] = None
    # Default end-to-end request deadline (--request-timeout-ms); a client
    # ``timeout`` (seconds) overrides per request. None = no deadline.
    request_timeout_ms: Optional[float] = None
    # Router failure lifecycle: NoInstances retry budget (jittered
    # exponential backoff) and the per-worker circuit breaker.
    retry_max: int = 3
    retry_backoff_base_s: float = 0.05
    breaker_threshold: int = 3
    breaker_cooldown_s: float = 5.0


async def start_frontend(drt: DistributedRuntime, config: FrontendConfig) -> HttpService:
    """Start the OpenAI frontend with dynamic model discovery: every model
    registered in the KV store gets a routed pipeline."""
    from dynamo_tpu.runtime.metrics import FRONTEND_PREFIX, MetricsRegistry
    from dynamo_tpu.runtime.push_router import CircuitBreaker, RetryPolicy

    manager = ModelManager()
    # One registry shared by the HTTP service and the per-model routers so
    # circuit_open{worker} / migrations_total land on the same /metrics.
    metrics = MetricsRegistry(prefix=FRONTEND_PREFIX)

    async def engine_factory(entry: ModelEntry) -> AsyncEngine:
        ep = drt.namespace(entry.namespace).component(entry.component).endpoint(entry.endpoint)
        client = await ep.client()
        retry = RetryPolicy(max_retries=config.retry_max,
                            backoff_base_s=config.retry_backoff_base_s)
        if config.router_mode == "kv":
            from dynamo_tpu.llm.kv_router import KvPushRouter, KvRouterConfig

            router = await KvPushRouter.create(
                client,
                KvRouterConfig(
                    overlap_score_weight=config.kv_overlap_score_weight,
                    temperature=config.kv_temperature,
                    block_size=entry.card.kv_cache_block_size,
                ),
            )
            router.push._metrics = metrics
            router.push.retry = retry
            router.push.breaker = CircuitBreaker(
                threshold=config.breaker_threshold,
                cooldown_s=config.breaker_cooldown_s,
                on_transition=router.push._on_circuit_transition,
            )
        else:
            mode = RouterMode.RANDOM if config.router_mode == "random" else RouterMode.ROUND_ROBIN
            router = PushRouter(client, mode, metrics=metrics, retry=retry)
            router.breaker = CircuitBreaker(
                threshold=config.breaker_threshold,
                cooldown_s=config.breaker_cooldown_s,
                on_transition=router._on_circuit_transition,
            )
            if config.busy_threshold is not None:
                router.monitor.busy_threshold = config.busy_threshold
        tokenizer = load_tokenizer(entry.card.tokenizer_path)
        encode_client = None
        if config.encode_component:
            enc_ep = drt.namespace(entry.namespace).component(config.encode_component).endpoint(
                entry.endpoint
            )
            encode_client = PushRouter(await enc_ep.client(), RouterMode.ROUND_ROBIN)
        model = entry.card.name
        return build_routed_pipeline(
            tokenizer, router, entry.card, migration_limit=config.migration_limit,
            encode_client=encode_client,
            on_migrate=lambda: metrics.counter(
                "migrations_total", "stream drops replayed on another worker",
                model=model,
            ).inc(),
        )

    watcher = ModelWatcher(drt, manager, engine_factory)
    await watcher.start()
    from dynamo_tpu.runtime.telemetry import SloConfig

    service = HttpService(
        manager, host=config.host, port=config.port,
        metrics=metrics,
        tls_cert=config.tls_cert, tls_key=config.tls_key,
        slo=SloConfig(ttft_ms=config.slo_ttft_ms, tpot_ms=config.slo_tpot_ms),
        request_timeout_ms=config.request_timeout_ms,
    )
    service.watcher = watcher  # keep alive / stoppable
    await service.start()
    if config.grpc_port is not None:
        # KServe gRPC twin over the same manager (ref: Input::Grpc,
        # entrypoint/input.rs:32 + grpc/service/kserve.rs).
        from dynamo_tpu.llm.grpc import KserveGrpcService

        grpc_service = KserveGrpcService(manager, host=config.host, port=config.grpc_port)
        await grpc_service.start()
        service.grpc_service = grpc_service
    return service


class EmbeddingsPreprocessor(Operator):
    """Tokenizes /v1/embeddings input (string / strings / token-id arrays)
    into ``batch_token_ids`` for the EmbeddingEngine."""

    def __init__(self, tokenizer: Tokenizer):
        self.tokenizer = tokenizer

    async def transform_request(self, request: dict, context: Context) -> dict:
        inp = request.get("input")
        if isinstance(inp, str):
            batches = [self.tokenizer.encode(inp)]
        elif inp and isinstance(inp[0], int):
            batches = [list(inp)]
        elif inp and isinstance(inp[0], list):
            batches = [list(x) for x in inp]
        else:
            batches = [self.tokenizer.encode(s) for s in (inp or [])]
        return {"batch_token_ids": batches, "model": request.get("model", "")}


def build_embeddings_pipeline(tokenizer: Tokenizer, engine: AsyncEngine) -> AsyncEngine:
    """Embeddings pipeline: tokenize → EmbeddingEngine (ref: ModelType::
    Embedding engines behind /v1/embeddings, openai.rs:369)."""
    return link([EmbeddingsPreprocessor(tokenizer)], engine)
