"""Backend operator: incremental detokenization + stop-string jailing.

Ref: lib/llm/src/backend.rs (``Backend::from_tokenizer``, ``into_operator``)
— sits between the engine stream (token ids) and the frontend (text deltas).

Stop-string jail: generated text that could be the beginning of a stop
string is withheld until it either completes the stop string (sequence ends,
jailed text dropped) or diverges (jailed text released). This is the same
"jail" the reference implements for stop conditions and tool-call opening
tags (backend.rs).
"""

from __future__ import annotations

from typing import AsyncIterator, List, Optional, Sequence

from dynamo_tpu.llm.protocols.common import LLMEngineOutput
from dynamo_tpu.llm.tokenizer import DecodeStream, Tokenizer
from dynamo_tpu.runtime.engine import Annotated, Context
from dynamo_tpu.runtime.pipeline import Operator


class StopStringJail:
    def __init__(self, stop_strings: Sequence[str]):
        self.stops = [s for s in stop_strings if s]
        self._held = ""

    def feed(self, delta: str) -> tuple[Optional[str], bool]:
        """Returns (text_to_emit_or_None, hit). On hit, held text before the
        stop string is emitted and the stop string itself is dropped."""
        if not self.stops:
            return delta, False
        buf = self._held + delta
        for s in self.stops:
            idx = buf.find(s)
            if idx != -1:
                self._held = ""
                return (buf[:idx] or None), True
        # Hold the longest tail that is a proper prefix of any stop string.
        hold = 0
        for s in self.stops:
            for k in range(min(len(s) - 1, len(buf)), 0, -1):
                if buf.endswith(s[:k]):
                    hold = max(hold, k)
                    break
        if hold:
            self._held = buf[-hold:]
            emit = buf[:-hold]
        else:
            self._held = ""
            emit = buf
        return (emit or None), False

    def flush(self) -> str:
        held, self._held = self._held, ""
        return held


class Backend(Operator):
    """Attaches ``text`` to engine output frames by detokenizing incrementally."""

    def __init__(self, tokenizer: Tokenizer):
        self.tokenizer = tokenizer

    async def transform_request(self, request, context: Context):
        # Images must have been consumed by an EncodeOperator upstream; a
        # pipeline without one must REJECT image requests, not silently
        # answer from the text alone (multimodal.py topology).
        if isinstance(request, dict) and request.get("_mm_image_urls"):
            from dynamo_tpu.llm.protocols.openai import RequestError

            raise RequestError(
                "request carries image content but no encode path is "
                "configured (frontend --encode-component / pipeline encoder)"
            )
        return request

    def transform_response(self, stream: AsyncIterator, request: dict, context: Context) -> AsyncIterator:
        stop_strings: List[str] = list((request.get("stop_conditions") or {}).get("stop") or [])
        # EOS/stop tokens are stripped from text output.
        skip_ids = set(self.tokenizer.eos_token_ids) | set(
            (request.get("stop_conditions") or {}).get("stop_token_ids") or []
        )
        decoder = DecodeStream(self.tokenizer, skip_token_ids=skip_ids)
        jail = StopStringJail(stop_strings)
        parser_jail = _build_parser_jail(request.get("parser_options"))

        def finalize(
            out: LLMEngineOutput, emit_text: Optional[str], finish: str, *, include_tail: bool = True
        ) -> LLMEngineOutput:
            """Assemble the final frame, folding in parser results. On a
            stop-string hit the detokenizer/jail tails are at/after the stop
            string and must be dropped (include_tail=False)."""
            tail = (decoder.flush() + jail.flush()) if include_tail else ""
            text = (emit_text or "") + tail
            tool_calls = None
            reasoning = None
            if parser_jail is not None:
                r0, c0 = ("", text)
                if text:
                    r0, c0 = parser_jail.feed(text)
                r1, c1, calls = parser_jail.finish()
                reasoning = (r0 + r1) or None
                text = c0 + c1
                if calls:
                    tool_calls = [c.to_openai() for c in calls]
                    finish = "tool_calls"
            return LLMEngineOutput(
                token_ids=out.token_ids,
                text=text or None,
                finish_reason=finish,
                logprobs=out.logprobs,
                top_logprobs=out.top_logprobs,
                index=out.index,
                tool_calls=tool_calls,
                reasoning=reasoning,
            )

        async def gen():
            stopped = False
            async for item in stream:
                if isinstance(item, Annotated) and item.is_annotation():
                    yield item
                    continue
                wire = item.data if isinstance(item, Annotated) else item
                out = LLMEngineOutput.from_wire(wire)
                if isinstance(wire, dict) and wire.get("queue_s") is not None:
                    # Engine admission queue time (first frame): surfaced as
                    # an annotation so the frontend can histogram it — the
                    # saturation signal the SLA planner needs (ref:
                    # http_queue_guard, http/service/metrics.rs).
                    yield Annotated(event="_queue", comment=str(wire["queue_s"]))
                if isinstance(wire, dict) and wire.get("cached_tokens") is not None:
                    # Prefix-cache reuse (first frame): the engine's count of
                    # prompt tokens served from resident KV — the frontend
                    # reports it as usage.prompt_tokens_details.cached_tokens.
                    yield Annotated(event="_cached", comment=str(wire["cached_tokens"]))
                if stopped:
                    # Upstream kept generating past a stop hit (shouldn't with
                    # prompt engines, possible with remote) — swallow.
                    if out.finish_reason:
                        yield Annotated(data=LLMEngineOutput(finish_reason="stop", index=out.index).to_wire())
                        return
                    continue
                delta = decoder.step(out.token_ids) if out.token_ids else ""
                emit_text, hit = jail.feed(delta) if delta else (None, False)
                if hit:
                    stopped = True
                    yield Annotated(data=finalize(out, emit_text, "stop", include_tail=False).to_wire())
                    context.stop_generating()  # propagate abort to the engine
                    return
                if out.finish_reason:
                    yield Annotated(data=finalize(out, emit_text, out.finish_reason).to_wire())
                    return
                reasoning_delta = None
                if parser_jail is not None and emit_text:
                    r, c = parser_jail.feed(emit_text)
                    reasoning_delta, emit_text = (r or None), (c or None)
                if emit_text or reasoning_delta or out.token_ids:
                    yield Annotated(
                        data=LLMEngineOutput(
                            token_ids=out.token_ids,
                            text=emit_text,
                            logprobs=out.logprobs,
                            top_logprobs=out.top_logprobs,
                            index=out.index,
                            reasoning=reasoning_delta,
                        ).to_wire()
                    )

        return gen()


def _build_parser_jail(parser_options: Optional[dict]):
    if not parser_options:
        return None
    from dynamo_tpu.llm.parsers import StreamingToolCallJail, get_reasoning_parser, get_tool_parser
    from dynamo_tpu.llm.parsers.tool_calling import ToolCallConfig

    tool_name = parser_options.get("tool_call_parser")
    reasoning_name = parser_options.get("reasoning_parser")
    config = get_tool_parser(tool_name) if tool_name else ToolCallConfig(format="json", allow_bare_json=False)
    reasoning = get_reasoning_parser(reasoning_name) if reasoning_name else None
    return StreamingToolCallJail(config=config, reasoning=reasoning)
