"""Device↔host block transfer: the ``block_copy.cu`` equivalent.

Ref: lib/llm/src/kernels/block_copy.cu (758 LoC of vectorized strided copy
kernels) + block/transfer/cuda.rs. On TPU the same job is a jitted XLA
gather/scatter (XLA emits the optimal DMA) + ``jax.device_get/put`` across
PCIe. Jitted once per cache shape; block id is a traced scalar so every block
reuses the same executable.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.kv_cache import KvCacheArrays


@jax.jit
def _gather(k_cache: jax.Array, v_cache: jax.Array, block_id: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """[L, N, BS, KVH, HD] → block [L, BS, KVH, HD]."""
    return k_cache[:, block_id], v_cache[:, block_id]


@jax.jit
def _scatter(k_cache: jax.Array, v_cache: jax.Array, block_id: jax.Array, k: jax.Array, v: jax.Array):
    return k_cache.at[:, block_id].set(k), v_cache.at[:, block_id].set(v)


def _has_v(cache: KvCacheArrays) -> bool:
    # MLA caches carry everything in the latent ``k`` array; ``v`` is a
    # [L,1,1,1,1] placeholder that must not be block-indexed.
    return cache.v.shape[1:] == cache.k.shape[1:]


def gather_blocks(cache: KvCacheArrays, block_id: int) -> Tuple[np.ndarray, np.ndarray]:
    """Device block → host numpy (device_get performs the DMA)."""
    if not _has_v(cache):
        k_dev = _gather_k(cache.k, jnp.int32(block_id))
        return np.asarray(jax.device_get(k_dev)), np.zeros((0,), dtype=cache.k.dtype)
    k_dev, v_dev = _gather(cache.k, cache.v, jnp.int32(block_id))
    return np.asarray(jax.device_get(k_dev)), np.asarray(jax.device_get(v_dev))


def scatter_blocks(cache: KvCacheArrays, block_id: int, k: np.ndarray, v: np.ndarray) -> None:
    """Host numpy → device block (in-place on the cache handle)."""
    if not _has_v(cache):
        cache.k = _scatter_k(cache.k, jnp.int32(block_id), jnp.asarray(k))
        return
    cache.k, cache.v = _scatter(cache.k, cache.v, jnp.int32(block_id), jnp.asarray(k), jnp.asarray(v))


@jax.jit
def _gather_k(k_cache: jax.Array, block_id: jax.Array) -> jax.Array:
    return k_cache[:, block_id]


@jax.jit
def _scatter_k(k_cache: jax.Array, block_id: jax.Array, k: jax.Array) -> jax.Array:
    return k_cache.at[:, block_id].set(k)
