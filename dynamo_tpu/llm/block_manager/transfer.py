"""Device↔host block transfer: the ``block_copy.cu`` equivalent.

Ref: lib/llm/src/kernels/block_copy.cu (758 LoC of vectorized strided copy
kernels) + block/transfer/cuda.rs. On TPU the same job is a jitted XLA
gather/scatter (XLA emits the optimal DMA) + ``jax.device_get/put`` across
PCIe. Jitted once per cache shape; block id is a traced scalar so every block
reuses the same executable.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.kv_cache import KvCacheArrays, QuantKv, quantize_kv_rows


@jax.jit
def _gather(k_cache: jax.Array, v_cache: jax.Array, block_id: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """[L, N, BS, KVH, HD] → block [L, BS, KVH, HD]."""
    return k_cache[:, block_id], v_cache[:, block_id]


@partial(jax.jit, donate_argnums=(0, 1))
def _scatter(k_cache: jax.Array, v_cache: jax.Array, block_id: jax.Array, k: jax.Array, v: jax.Array):
    return k_cache.at[:, block_id].set(k), v_cache.at[:, block_id].set(v)


def _has_v(cache: KvCacheArrays) -> bool:
    # MLA caches carry everything in the latent ``k`` array; ``v`` is a
    # [L,1,1,1,1] placeholder that must not be block-indexed.
    return cache.v.shape[1:] == cache.k.shape[1:]


# int8 caches cross the transfer boundary as real-valued blocks: gather
# dequantizes, scatter requantizes. Payload format (host numpy / device
# stacks) is therefore identical for quantized and plain caches — KVBM
# tiers and disagg pulls interoperate across workers with different
# kv_cache_dtype settings. Requantizing a dequantized row recomputes the
# same scale to float rounding, so round-trips are stable to within one
# int8 code step.


@jax.jit
def _gather_one_quant(qkv: QuantKv, block_id: jax.Array) -> jax.Array:
    return (qkv.q[:, block_id].astype(jnp.float32) * qkv.scale[:, block_id]).astype(jnp.float32)


@partial(jax.jit, donate_argnums=(0,))
def _scatter_one_quant(qkv: QuantKv, block_id: jax.Array, rows: jax.Array) -> QuantKv:
    qk = quantize_kv_rows(rows)
    return QuantKv(qkv.q.at[:, block_id].set(qk.q), qkv.scale.at[:, block_id].set(qk.scale))


def gather_blocks(cache: KvCacheArrays, block_id: int) -> Tuple[np.ndarray, np.ndarray]:
    """Device block → host numpy (device_get performs the DMA)."""
    if isinstance(cache.k, QuantKv):
        k_dev = _gather_one_quant(cache.k, jnp.int32(block_id))
        v_dev = _gather_one_quant(cache.v, jnp.int32(block_id))
        return np.asarray(jax.device_get(k_dev)), np.asarray(jax.device_get(v_dev))
    if not _has_v(cache):
        k_dev = _gather_k(cache.k, jnp.int32(block_id))
        return np.asarray(jax.device_get(k_dev)), np.zeros((0,), dtype=cache.k.dtype)
    k_dev, v_dev = _gather(cache.k, cache.v, jnp.int32(block_id))
    return np.asarray(jax.device_get(k_dev)), np.asarray(jax.device_get(v_dev))


def gather_blocks_async(cache: KvCacheArrays, block_id: int):
    """Device-side snapshot of one block — NO host sync. The gather
    dispatch is queued before any later write to the block (single device
    stream), so the returned device arrays are a consistent copy even
    though the caller reuses the block immediately; the host transfer
    happens when the offload queue drains (KvbmManager.flush_pending)."""
    if isinstance(cache.k, QuantKv):
        return _gather_one_quant(cache.k, jnp.int32(block_id)), _gather_one_quant(
            cache.v, jnp.int32(block_id)
        )
    if not _has_v(cache):
        return _gather_k(cache.k, jnp.int32(block_id)), None
    return _gather(cache.k, cache.v, jnp.int32(block_id))


def scatter_blocks(cache: KvCacheArrays, block_id: int, k: np.ndarray, v: np.ndarray) -> None:
    """Host numpy → device block (in-place on the cache handle)."""
    if isinstance(cache.k, QuantKv):
        cache.k = _scatter_one_quant(cache.k, jnp.int32(block_id), jnp.asarray(k, dtype=jnp.float32))
        cache.v = _scatter_one_quant(cache.v, jnp.int32(block_id), jnp.asarray(v, dtype=jnp.float32))
        return
    if not _has_v(cache):
        cache.k = _scatter_k(cache.k, jnp.int32(block_id), jnp.asarray(k))
        return
    cache.k, cache.v = _scatter(cache.k, cache.v, jnp.int32(block_id), jnp.asarray(k), jnp.asarray(v))


@jax.jit
def _gather_k(k_cache: jax.Array, block_id: jax.Array) -> jax.Array:
    return k_cache[:, block_id]


@partial(jax.jit, donate_argnums=(0,))
def _scatter_k(k_cache: jax.Array, block_id: jax.Array, k: jax.Array) -> jax.Array:
    return k_cache.at[:, block_id].set(k)


# ---------------------------------------------------------------------------
# Device-native block movement (the NIXL data-plane role): blocks never
# leave the accelerator. Stacked layout [L, n, BS, KVH, HD] matches the
# cache's own, so gather/scatter are single XLA ops (one fused DMA each).
# ---------------------------------------------------------------------------


@jax.jit
def _gather_many(cache: jax.Array, block_ids: jax.Array) -> jax.Array:
    """[L, N, BS, ...] × [n] → [L, n, BS, ...]."""
    return cache[:, block_ids]


@partial(jax.jit, donate_argnums=(0,))
def _scatter_many(cache: jax.Array, block_ids: jax.Array, blocks: jax.Array) -> jax.Array:
    return cache.at[:, block_ids].set(blocks)


@jax.jit
def _gather_many_quant(qkv: QuantKv, block_ids: jax.Array) -> jax.Array:
    return (qkv.q[:, block_ids].astype(jnp.float32) * qkv.scale[:, block_ids]).astype(jnp.bfloat16)


@partial(jax.jit, donate_argnums=(0,))
def _scatter_many_quant(qkv: QuantKv, block_ids: jax.Array, blocks: jax.Array) -> QuantKv:
    qk = quantize_kv_rows(blocks)
    return QuantKv(qkv.q.at[:, block_ids].set(qk.q), qkv.scale.at[:, block_ids].set(qk.scale))


def gather_blocks_device(cache: KvCacheArrays, block_ids) -> Tuple[jax.Array, Optional[jax.Array]]:
    """Stack blocks into fresh device arrays (no host round-trip). The copy
    is independent of the cache, so the source blocks may be released
    immediately while the stack awaits a remote pull."""
    bids = jnp.asarray(list(block_ids), dtype=jnp.int32)
    if isinstance(cache.k, QuantKv):
        return _gather_many_quant(cache.k, bids), _gather_many_quant(cache.v, bids)
    k = _gather_many(cache.k, bids)
    v = _gather_many(cache.v, bids) if _has_v(cache) else None
    return k, v


def scatter_blocks_device(cache: KvCacheArrays, block_ids, k_stack: jax.Array, v_stack) -> None:
    """Write stacked device blocks into the cache (in-place on the handle)."""
    bids = jnp.asarray(list(block_ids), dtype=jnp.int32)
    if isinstance(cache.k, QuantKv):
        cache.k = _scatter_many_quant(cache.k, bids, k_stack)
        if v_stack is not None:
            cache.v = _scatter_many_quant(cache.v, bids, v_stack)
        return
    cache.k = _scatter_many(cache.k, bids, k_stack)
    if v_stack is not None and _has_v(cache):
        cache.v = _scatter_many(cache.v, bids, v_stack)


@jax.jit
def _copy_between(src_k, src_v, dst_k, dst_v, src_ids, dst_ids):
    return dst_k.at[:, dst_ids].set(src_k[:, src_ids]), dst_v.at[:, dst_ids].set(src_v[:, src_ids])


def copy_blocks_between(src: KvCacheArrays, src_ids, dst: KvCacheArrays, dst_ids) -> None:
    """Same-process cache→cache block copy, entirely on device — the
    fast path when prefill and decode engines share a host process
    (ref: NIXL NVLink same-node transfers, dynamo_flow.md S8-S10)."""
    s = jnp.asarray(list(src_ids), dtype=jnp.int32)
    d = jnp.asarray(list(dst_ids), dtype=jnp.int32)
    src_q = isinstance(src.k, QuantKv)
    dst_q = isinstance(dst.k, QuantKv)
    if src_q and dst_q:
        # Quantized→quantized: move codes + scales directly, no requant.
        dst.k = QuantKv(dst.k.q.at[:, d].set(src.k.q[:, s]), dst.k.scale.at[:, d].set(src.k.scale[:, s]))
        dst.v = QuantKv(dst.v.q.at[:, d].set(src.v.q[:, s]), dst.v.scale.at[:, d].set(src.v.scale[:, s]))
        return
    if src_q or dst_q:
        k_stack, v_stack = gather_blocks_device(src, list(src_ids))
        scatter_blocks_device(dst, list(dst_ids), k_stack, v_stack)
        return
    if _has_v(src):
        dst.k, dst.v = _copy_between(src.k, src.v, dst.k, dst.v, s, d)
    else:
        dst.k = _scatter_many(dst.k, d, _gather_many(src.k, s))
