"""KVBM: multi-tier KV block management (HBM → host DRAM → disk).

Ref: lib/llm/src/block_manager (20k LoC) — ``KvBlockManager``
(block_manager.rs:99), tiers ``CacheLevel::{G1,G2,G3,G4}`` (:62-75),
offload cascade on registration/eviction (offload.rs), onboarding
(``onboard_blocks`` :144), sequence-hash registry (block/registry.rs:478).

TPU-native mapping:
- **G1** — device HBM: the engine's paged ``KvCacheArrays`` + BlockAllocator.
- **G2** — host DRAM: numpy block pool, filled by the offload cascade when G1
  evicts a cached block (copy-out happens *before* reuse via the allocator's
  eviction hook). The reference's ``block_copy.cu`` kernels become jitted XLA
  gather/scatter + ``jax.device_get/put`` DMA (transfer.py).
- **G3** — local disk: file-per-block spill from G2 eviction.
- **G4** — remote pool: hash-addressed blocks in the control-plane object
  store (storage.RemotePool), filled by G3 (or G2) spill and onboardable by
  ANY worker — the cross-host tier (ref: CacheLevel::G4
  block_manager.rs:62-75).

Lookup walks tiers: G1 hit ⇒ free; G2/G3 hit ⇒ *onboard* (copy back into
freshly allocated G1 blocks) — still far cheaper than recomputing prefill
(the reference reports +40% TTFT from host offload alone, BASELINE.md).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from dynamo_tpu.engine.kv_cache import BlockAllocator, KvCacheArrays
from dynamo_tpu.llm.block_manager.storage import DiskPool, HostPool
from dynamo_tpu.llm.block_manager.transfer import (
    gather_blocks_async,
    scatter_blocks_device,
)
from dynamo_tpu.runtime.logging import get_logger

logger = get_logger(__name__)


class CacheLevel(enum.Enum):
    G1 = "device"
    G2 = "host"
    G3 = "disk"
    G4 = "remote"


@dataclass
class KvbmMetrics:
    offloads_g2: int = 0
    offloads_g3: int = 0
    offloads_g4: int = 0
    onboards_g2: int = 0
    onboards_g3: int = 0
    onboards_g4: int = 0
    matched_tokens_g1: int = 0
    matched_tokens_tiered: int = 0


@dataclass
class TieredMatch:
    """Result of a tiered prefix lookup."""

    g1_blocks: List[int] = field(default_factory=list)  # device blocks, ref-acquired
    onboardable: List[Tuple[int, CacheLevel]] = field(default_factory=list)  # (hash, tier)

    @property
    def total_blocks(self) -> int:
        return len(self.g1_blocks) + len(self.onboardable)


class KvBlockManager:
    """Owns the tier hierarchy around a device cache + allocator."""

    def __init__(
        self,
        cache: KvCacheArrays,
        allocator: BlockAllocator,
        *,
        host_blocks: int = 0,
        disk_dir: Optional[str] = None,
        disk_blocks: int = 0,
    ):
        self.cache = cache
        self.allocator = allocator
        self.host = HostPool(capacity=host_blocks) if host_blocks > 0 else None
        self.disk = DiskPool(disk_dir, capacity=disk_blocks) if disk_dir and disk_blocks > 0 else None
        self.remote = None  # G4 — attach_remote()
        self.metrics = KvbmMetrics()
        # Async offload: eviction snapshots the block ON DEVICE (dispatch-
        # ordered, no host sync — the old inline gather stalled every
        # admission on a device→host DMA under memory pressure, ref's
        # equivalent machinery: block_manager/offload.rs pending queues);
        # the host transfer happens in one batched drain.
        self._pending: Dict[int, Tuple] = {}
        self._pending_cap = 32
        allocator.on_evict = self._offload_block

    def attach_remote(self, remote) -> None:
        """Enable the G4 remote tier (storage.RemotePool): deepest-spill
        target of the offload cascade, onboardable by any worker sharing the
        object store."""
        self.remote = remote

    # --- offload cascade (G1 → G2 → G3 → G4) --------------------------------
    def _offload_block(self, block_id: int, block_hash: int) -> None:
        """Eviction hook — runs on the scheduler's admission path, so it
        must not block: queue a device-side snapshot and return."""
        if self.host is None:
            return
        if (
            block_hash in self._pending
            or self.host.has(block_hash)
            or (self.disk is not None and self.disk.has(block_hash))
        ):
            return
        self._pending[block_hash] = gather_blocks_async(self.cache, block_id)
        if len(self._pending) >= self._pending_cap:
            self.flush_pending()

    def flush_pending(self) -> int:
        """Drain queued offload snapshots to the host tier in ONE batched
        device→host transfer. Called when the queue fills, before tier
        lookups (pending blocks must be onboardable), and at shutdown."""
        if not self._pending:
            return 0
        items, self._pending = list(self._pending.items()), {}
        import jax

        flat = jax.device_get([d for _, pair in items for d in pair if d is not None])
        it = iter(flat)
        for h, (k_dev, v_dev) in items:
            k_np = np.asarray(next(it))
            v_np = np.asarray(next(it)) if v_dev is not None else np.zeros((0,), k_np.dtype)
            self._cascade_put(h, k_np, v_np)
        return len(items)

    def _cascade_put(self, block_hash: int, k_np: np.ndarray, v_np: np.ndarray) -> None:
        spilled = self.host.put(block_hash, k_np, v_np)
        self.metrics.offloads_g2 += 1
        if spilled is not None and self.disk is not None:
            sh, sk, sv = spilled
            if not self.disk.has(sh):
                spilled = self.disk.put(sh, sk, sv)
                self.metrics.offloads_g3 += 1
            else:
                spilled = None
        if spilled is not None and self.remote is not None:
            sh, sk, sv = spilled
            if not self.remote.has(sh):
                self.remote.put(sh, sk, sv)
                self.metrics.offloads_g4 += 1

    # --- tiered lookup ------------------------------------------------------
    def match_prefix(self, block_hashes: Sequence[int]) -> TieredMatch:
        """Longest-prefix match across tiers. G1 blocks come back
        ref-acquired; deeper-tier hits come back as onboard candidates.
        The chain must stay contiguous: a tier miss ends the walk."""
        self.flush_pending()  # pending snapshots become G2-visible here
        match = TieredMatch()
        g1 = self.allocator.match_prefix(block_hashes)
        match.g1_blocks = g1
        self.metrics.matched_tokens_g1 += len(g1)
        for h in block_hashes[len(g1) :]:
            if self.host is not None and self.host.has(h):
                match.onboardable.append((h, CacheLevel.G2))
            elif self.disk is not None and self.disk.has(h):
                match.onboardable.append((h, CacheLevel.G3))
            elif self.remote is not None and self.remote.has(h):
                match.onboardable.append((h, CacheLevel.G4))
            else:
                break
        self.metrics.matched_tokens_tiered += len(match.onboardable)
        return match

    # --- onboarding (ref: onboard_blocks block_manager.rs:144) --------------
    def onboard(self, match: TieredMatch, block_hashes: Sequence[int]) -> List[int]:
        """Copy onboardable blocks into fresh G1 blocks; returns the full
        ref-held device block list (g1 + onboarded). On allocation failure the
        match degrades to its G1 prefix (caller prefills the rest).

        The device write is ASYNC: every onboarded block rides ONE stacked
        host→device upload plus one fused scatter dispatch — no host sync —
        so the caller's uncached-suffix prefill enqueues right behind the
        onboard on the device stream. A warm-DRAM hit overlaps its copy-back
        with the suffix compute instead of stalling admission on per-block
        DMAs (the per-block scatter_blocks loop it replaces)."""
        if not match.onboardable:
            return match.g1_blocks
        try:
            new_blocks = self.allocator.allocate(len(match.onboardable))
        except Exception:
            match.onboardable = []
            return match.g1_blocks
        entries = []
        for i, (h, tier) in enumerate(match.onboardable):
            if tier == CacheLevel.G2:
                entry = self.host.get(h)
                self.metrics.onboards_g2 += 1
            elif tier == CacheLevel.G3:
                entry = self.disk.get(h)
                self.metrics.onboards_g3 += 1
            else:
                entry = self.remote.get(h)
                self.metrics.onboards_g4 += 1
            if entry is None:  # raced out of the pool — stop onboarding here
                self.allocator.release(new_blocks[i:])
                match.onboardable = match.onboardable[:i]
                new_blocks = new_blocks[:i]
                break
            entries.append(entry)
        if not new_blocks:
            return match.g1_blocks
        import jax.numpy as jnp

        k_stack = jnp.asarray(np.stack([k for k, _ in entries], axis=1))
        v_stack = (
            jnp.asarray(np.stack([v for _, v in entries], axis=1))
            if entries[0][1].size
            else None
        )
        scatter_blocks_device(self.cache, new_blocks, k_stack, v_stack)
        # Register the onboarded blocks under their hashes so future requests
        # hit them in G1 directly.
        n_g1 = len(match.g1_blocks)
        hashes = list(block_hashes[n_g1 : n_g1 + len(new_blocks)])
        self.allocator.register_hashes(new_blocks, hashes)
        return match.g1_blocks + new_blocks

    # --- introspection ------------------------------------------------------
    def usage(self) -> Dict[str, float]:
        out = {"g1": self.allocator.usage()}
        if self.host is not None:
            out["g2"] = self.host.usage()
        if self.disk is not None:
            out["g3"] = self.disk.usage()
        if self.remote is not None:
            out["g4_known_blocks"] = float(len(self.remote))
        return out

    def reset_tier(self, level: CacheLevel) -> int:
        """Ref: block_manager/controller.rs reset endpoints."""
        if level == CacheLevel.G1:
            return self.allocator.clear_cached()
        if level == CacheLevel.G2 and self.host is not None:
            self._pending.clear()
            return self.host.clear()
        if level == CacheLevel.G3 and self.disk is not None:
            return self.disk.clear()
        return 0
