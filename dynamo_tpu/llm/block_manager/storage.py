"""Tier storage backends: host DRAM pool + disk pool.

Ref: lib/llm/src/block_manager/storage.rs (``Storage`` trait,
``PinnedStorage``/``DiskStorage`` allocators) and pool/managed.rs (LRU
inactive sets). Host blocks are plain numpy (the pinned-memory role — on TPU
hosts, jax transfers from host numpy already use the fast path); disk blocks
are one ``.npz`` per block hash (the reference's GDS file-per-layout role).
"""

from __future__ import annotations

import os
from collections import OrderedDict
from typing import Dict, Optional, Tuple

import numpy as np


class HostPool:
    """LRU pool of KV block pairs in host memory. ``put`` may spill the LRU
    entry: it is returned to the caller for cascade to the next tier."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        self._data: "OrderedDict[int, Tuple[np.ndarray, np.ndarray]]" = OrderedDict()

    def has(self, block_hash: int) -> bool:
        return block_hash in self._data

    def put(self, block_hash: int, k: np.ndarray, v: np.ndarray) -> Optional[Tuple[int, np.ndarray, np.ndarray]]:
        spilled = None
        if block_hash in self._data:
            self._data.move_to_end(block_hash)
            return None
        if len(self._data) >= self.capacity:
            h, (sk, sv) = self._data.popitem(last=False)
            spilled = (h, sk, sv)
        self._data[block_hash] = (k, v)
        return spilled

    def get(self, block_hash: int) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        entry = self._data.get(block_hash)
        if entry is not None:
            self._data.move_to_end(block_hash)
        return entry

    def usage(self) -> float:
        return len(self._data) / max(self.capacity, 1)

    def clear(self) -> int:
        n = len(self._data)
        self._data.clear()
        return n

    def __len__(self) -> int:
        return len(self._data)


class DiskPool:
    """File-per-block spill tier (one .npz per block hash), LRU by mtime
    order maintained in-memory."""

    def __init__(self, directory: str, capacity: int):
        self.directory = directory
        self.capacity = capacity
        os.makedirs(directory, exist_ok=True)
        self._index: "OrderedDict[int, str]" = OrderedDict()
        # Recover existing blocks (restart resume — ref: KVBM disk persistence
        # as a resume mechanism, SURVEY.md §5 checkpoint/resume).
        for fname in sorted(os.listdir(directory)):
            if fname.endswith(".npz"):
                try:
                    self._index[int(fname[:-4], 16)] = os.path.join(directory, fname)
                except ValueError:
                    continue

    def _path(self, block_hash: int) -> str:
        return os.path.join(self.directory, f"{block_hash & 0xFFFFFFFFFFFFFFFF:016x}.npz")

    def has(self, block_hash: int) -> bool:
        return block_hash in self._index

    def put(self, block_hash: int, k: np.ndarray, v: np.ndarray) -> None:
        if block_hash in self._index:
            return
        while len(self._index) >= self.capacity:
            h, path = self._index.popitem(last=False)
            try:
                os.remove(path)
            except OSError:
                pass
        path = self._path(block_hash)
        np.savez(path, k=k, v=v)
        self._index[block_hash] = path

    def get(self, block_hash: int) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        path = self._index.get(block_hash)
        if path is None:
            return None
        try:
            with np.load(path) as z:
                self._index.move_to_end(block_hash)
                return z["k"], z["v"]
        except (OSError, KeyError):
            self._index.pop(block_hash, None)
            return None

    def usage(self) -> float:
        return len(self._index) / max(self.capacity, 1)

    def clear(self) -> int:
        n = len(self._index)
        for h, path in self._index.items():
            try:
                os.remove(path)
            except OSError:
                pass
        self._index.clear()
        return n

    def __len__(self) -> int:
        return len(self._index)
