"""Tier storage backends: host DRAM pool, disk pool, remote (G4) pool.

Ref: lib/llm/src/block_manager/storage.rs (``Storage`` trait,
``PinnedStorage``/``DiskStorage`` allocators) and pool/managed.rs (LRU
inactive sets). Host blocks are plain numpy (the pinned-memory role — on TPU
hosts, jax transfers from host numpy already use the fast path); disk blocks
are one ``.npz`` per block hash (the reference's GDS file-per-layout role);
remote blocks are hash-addressed objects in the control-plane object store
(``CacheLevel::G4``, block_manager.rs:62-75) — any worker can onboard blocks
another worker spilled.
"""

from __future__ import annotations

import asyncio
import io
import os
import time
from collections import OrderedDict
from typing import Dict, Optional, Tuple

import numpy as np


class HostPool:
    """LRU pool of KV block pairs in host memory. ``put`` may spill the LRU
    entry: it is returned to the caller for cascade to the next tier."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        self._data: "OrderedDict[int, Tuple[np.ndarray, np.ndarray]]" = OrderedDict()

    def has(self, block_hash: int) -> bool:
        return block_hash in self._data

    def put(self, block_hash: int, k: np.ndarray, v: np.ndarray) -> Optional[Tuple[int, np.ndarray, np.ndarray]]:
        spilled = None
        if block_hash in self._data:
            self._data.move_to_end(block_hash)
            return None
        if len(self._data) >= self.capacity:
            h, (sk, sv) = self._data.popitem(last=False)
            spilled = (h, sk, sv)
        self._data[block_hash] = (k, v)
        return spilled

    def get(self, block_hash: int) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        entry = self._data.get(block_hash)
        if entry is not None:
            self._data.move_to_end(block_hash)
        return entry

    def usage(self) -> float:
        return len(self._data) / max(self.capacity, 1)

    def clear(self) -> int:
        n = len(self._data)
        self._data.clear()
        return n

    def __len__(self) -> int:
        return len(self._data)


class DiskPool:
    """File-per-block spill tier (one .npz per block hash), LRU by mtime
    order maintained in-memory."""

    def __init__(self, directory: str, capacity: int):
        self.directory = directory
        self.capacity = capacity
        os.makedirs(directory, exist_ok=True)
        self._index: "OrderedDict[int, str]" = OrderedDict()
        # Recover existing blocks (restart resume — ref: KVBM disk persistence
        # as a resume mechanism, SURVEY.md §5 checkpoint/resume).
        for fname in sorted(os.listdir(directory)):
            if fname.endswith(".npz"):
                try:
                    self._index[int(fname[:-4], 16)] = os.path.join(directory, fname)
                except ValueError:
                    continue

    def _path(self, block_hash: int) -> str:
        return os.path.join(self.directory, f"{block_hash & 0xFFFFFFFFFFFFFFFF:016x}.npz")

    def has(self, block_hash: int) -> bool:
        return block_hash in self._index

    def put(
        self, block_hash: int, k: np.ndarray, v: np.ndarray
    ) -> Optional[Tuple[int, np.ndarray, np.ndarray]]:
        """Store a block; returns the LRU entry evicted to make room (for
        cascade to the next tier), or None."""
        if block_hash in self._index:
            return None
        spilled = None
        while len(self._index) >= self.capacity:
            h, path = self._index.popitem(last=False)
            try:
                if spilled is None:
                    with np.load(path) as z:
                        spilled = (h, z["k"], z["v"])
            except (OSError, KeyError):
                pass  # corrupt block: nothing to cascade
            finally:
                try:
                    os.remove(path)  # always reclaim the file, even unreadable
                except OSError:
                    pass
        path = self._path(block_hash)
        np.savez(path, k=k, v=v)
        self._index[block_hash] = path
        return spilled

    def get(self, block_hash: int) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        path = self._index.get(block_hash)
        if path is None:
            return None
        try:
            with np.load(path) as z:
                self._index.move_to_end(block_hash)
                return z["k"], z["v"]
        except (OSError, KeyError):
            self._index.pop(block_hash, None)
            return None

    def usage(self) -> float:
        return len(self._index) / max(self.capacity, 1)

    def clear(self) -> int:
        n = len(self._index)
        for h, path in self._index.items():
            try:
                os.remove(path)
            except OSError:
                pass
        self._index.clear()
        return n

    def __len__(self) -> int:
        return len(self._index)


class RemotePool:
    """G4: cross-host KV block pool on the control-plane object store
    (ref: ``CacheLevel::G4``, lib/llm/src/block_manager.rs:62-75).

    Blocks live under hash-addressed names in a shared bucket, so a block
    spilled by worker A is onboardable by worker B. The pool is called from
    the scheduler's step THREAD while the store client lives on the asyncio
    loop — all store traffic goes through ``run_coroutine_threadsafe``:

    - ``put`` is fire-and-forget (the offload cascade must not stall the
      allocator's eviction hook on a network round-trip);
    - ``has`` serves from a listing cache refreshed at most every
      ``refresh_s`` (prefix walks probe many hashes);
    - ``get`` blocks up to ``timeout_s`` (onboarding is already a copy).

    Calling from the loop thread itself would deadlock; a guard raises
    instead (production calls come from the engine's step thread).
    """

    def __init__(self, drt, loop: asyncio.AbstractEventLoop, *,
                 bucket: str = "kvbm-g4", timeout_s: float = 5.0, refresh_s: float = 1.0):
        self.drt = drt
        self.loop = loop
        self.bucket_name = bucket
        self.timeout_s = timeout_s
        self.refresh_s = refresh_s
        self._known: set = set()
        self._listed_at = 0.0

    def _assert_worker_thread(self) -> None:
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is self.loop:
            raise RuntimeError(
                "RemotePool must be called from a worker thread, not the event loop"
            )

    def _call(self, coro):
        return asyncio.run_coroutine_threadsafe(coro, self.loop).result(self.timeout_s)

    async def _bucket(self):
        return await self.drt.bus.object_store(self.bucket_name)

    @staticmethod
    def _name(block_hash: int) -> str:
        return f"{block_hash & 0xFFFFFFFFFFFFFFFF:016x}"

    def has(self, block_hash: int) -> bool:
        if block_hash in self._known:
            return True
        self._assert_worker_thread()
        now = time.monotonic()
        if now - self._listed_at >= self.refresh_s:
            async def _list():
                return await (await self._bucket()).list()
            try:
                names = self._call(_list())
            except Exception:  # noqa: BLE001 — a flaky store must not fail matching
                # Back off: without this, every has() probe of a prefix walk
                # would block the step thread up to timeout_s during an
                # outage (one stalled listing per block hash).
                self._listed_at = now
                return False
            self._known = set()
            for n in names:
                try:
                    self._known.add(int(n, 16))
                except ValueError:
                    continue
            self._listed_at = now
        return block_hash in self._known

    def put(self, block_hash: int, k: np.ndarray, v: np.ndarray) -> None:
        buf = io.BytesIO()
        np.savez(buf, k=k, v=v)
        data = buf.getvalue()

        async def _put():
            await (await self._bucket()).put(self._name(block_hash), data)

        asyncio.run_coroutine_threadsafe(_put(), self.loop)  # fire-and-forget
        self._known.add(block_hash)

    def get(self, block_hash: int) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        self._assert_worker_thread()

        async def _get():
            return await (await self._bucket()).get(self._name(block_hash))

        try:
            data = self._call(_get())
        except Exception:  # noqa: BLE001
            return None
        if data is None:
            self._known.discard(block_hash)
            return None
        with np.load(io.BytesIO(data)) as z:
            return z["k"], z["v"]

    def __len__(self) -> int:
        return len(self._known)
