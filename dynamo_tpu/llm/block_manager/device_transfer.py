"""Device-to-device KV transfer plane — the NIXL replacement.

Ref: the reference moves KV blocks GPU→GPU with NIXL one-sided RDMA
(lib/bindings/python src/dynamo/nixl_connect/__init__.py:501-1417; vllm
handlers.py:153-204). The TPU equivalent rides
``jax.experimental.transfer`` — XLA's cross-process transfer server, which
moves device buffers peer-to-peer over the fastest available fabric (ICI
within a slice, DCN/TCP across hosts) in a one-sided *pull* model exactly
like NIXL:

- producer: ``offer(uuid, arrays)`` schedules device buffers for pickup;
- consumer: ``pull(address, uuid, specs)`` lands them on its own devices;
- rendezvous metadata (address/uuid/shape/dtype — the ``RdmaMetadata``
  role) travels out-of-band on the control plane.

The same class serves the in-process case via
``transfer.copy_blocks_between`` (no server round-trip at all).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

import jax

from dynamo_tpu.runtime.logging import get_logger

logger = get_logger(__name__)


def _uuid_of(request_id: str) -> int:
    import hashlib

    return int.from_bytes(hashlib.blake2s(request_id.encode(), digest_size=8).digest(), "big") >> 1


class DeviceTransferPlane:
    """One per process. Lazily starts the transfer server on first use."""

    def __init__(self, transport_ip: str = "127.0.0.1"):
        self.transport_ip = transport_ip
        self._server = None
        self._connections: Dict[str, Any] = {}
        self._offers: Dict[int, Any] = {}  # uuid -> arrays (keep-alive until acked)
        self._lock = threading.Lock()

    # --- lifecycle ----------------------------------------------------------
    def _ensure_server(self):
        with self._lock:
            if self._server is None:
                from jax.experimental import transfer

                client = jax.devices()[0].client
                self._server = transfer.start_transfer_server(
                    client, "[::]:0", [f"{self.transport_ip}:0"]
                )
                logger.info("device transfer server on %s", self._server.address())
            return self._server

    @property
    def address(self) -> str:
        return self._ensure_server().address()

    # --- producer side ------------------------------------------------------
    def offer(self, request_id: str, arrays) -> dict:
        """Schedule device arrays for one-sided pull. Returns the rendezvous
        metadata to send to the consumer (RdmaMetadata role)."""
        server = self._ensure_server()
        uuid = _uuid_of(request_id)
        flat = jax.tree.leaves(arrays)
        server.await_pull(uuid, flat)
        self._offers[uuid] = flat  # keep buffers alive until consumer acks
        return {
            "address": server.address(),
            "uuid": uuid,
            "specs": [{"shape": list(x.shape), "dtype": str(x.dtype)} for x in flat],
        }

    def release_offer(self, request_id: str) -> None:
        self._offers.pop(_uuid_of(request_id), None)

    # --- consumer side ------------------------------------------------------
    def pull(self, meta: dict, sharding: Optional[jax.sharding.Sharding] = None):
        """One-sided pull of the offered buffers onto local devices."""
        import jax.numpy as jnp

        server = self._ensure_server()
        addr = meta["address"]
        conn = self._connections.get(addr)
        if conn is None:
            conn = server.connect(addr)
            self._connections[addr] = conn
        sharding = sharding or jax.sharding.SingleDeviceSharding(jax.devices()[0])
        specs = [
            jax.ShapeDtypeStruct(tuple(s["shape"]), jnp.dtype(s["dtype"]), sharding=sharding)
            for s in meta["specs"]
        ]
        return conn.pull(meta["uuid"], specs)


_plane: Optional[DeviceTransferPlane] = None


def get_plane() -> DeviceTransferPlane:
    """Process-wide singleton (the transfer server binds per process)."""
    global _plane
    if _plane is None:
        _plane = DeviceTransferPlane()
    return _plane
