"""Model discovery: ModelManager (name → pipeline engine) + ModelWatcher
(KV-store watch → add/remove models as workers come and go).

Ref: lib/llm/src/discovery/{model_manager,watcher}.rs — ``ModelWatcher``
(watcher.rs:47) watches etcd prefix ``models`` (MODEL_ROOT_PATH) and
builds/retires routed pipelines in the ``ModelManager``.
"""

from __future__ import annotations

import asyncio
from typing import Callable, Dict, List, Optional

from dynamo_tpu.llm.model_card import MODEL_ROOT_PATH, ModelEntry
from dynamo_tpu.runtime.engine import AsyncEngine
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.transports.kvstore import EventType

logger = get_logger(__name__)


class ModelManager:
    """Registry of live model pipelines keyed by (model_type, name)."""

    def __init__(self):
        self._engines: Dict[str, Dict[str, AsyncEngine]] = {"chat": {}, "completions": {}, "embeddings": {}}
        self._entries: Dict[str, ModelEntry] = {}

    def add_model(self, model_type: str, name: str, engine: AsyncEngine) -> None:
        self._engines.setdefault(model_type, {})[name] = engine

    def remove_model(self, model_type: str, name: str) -> None:
        self._engines.get(model_type, {}).pop(name, None)

    def get(self, model_type: str, name: str) -> Optional[AsyncEngine]:
        return self._engines.get(model_type, {}).get(name)

    def list_models(self) -> List[str]:
        names = set()
        for engines in self._engines.values():
            names.update(engines)
        return sorted(names)

    def has_model(self, name: str) -> bool:
        return any(name in engines for engines in self._engines.values())


class ModelWatcher:
    """Watches discovery and keeps the ModelManager in sync.

    ``engine_factory(entry) -> AsyncEngine`` builds the routed pipeline for a
    newly discovered model (frontend → preprocessor → backend → router);
    multiple workers serving the same model share one pipeline (the router's
    instance discovery handles fan-out), mirroring watcher.rs semantics.
    """

    def __init__(
        self,
        drt,
        manager: ModelManager,
        engine_factory: Callable[[ModelEntry], "asyncio.Future"],
    ):
        self.drt = drt
        self.manager = manager
        self.engine_factory = engine_factory
        self._task: Optional[asyncio.Task] = None
        self._entries_by_key: Dict[str, ModelEntry] = {}
        self._refcount: Dict[str, int] = {}

    async def start(self) -> None:
        snapshot, watch = await self.drt.store.get_and_watch_prefix(f"{MODEL_ROOT_PATH}/")
        for entry in snapshot:
            await self._on_put(entry.key, entry.value)
        self._watch = watch
        self._task = asyncio.get_running_loop().create_task(self._loop())

    async def _loop(self) -> None:
        async for ev in self._watch:
            try:
                if ev.type == EventType.PUT and ev.value is not None:
                    await self._on_put(ev.key, ev.value)
                elif ev.type == EventType.DELETE:
                    await self._on_delete(ev.key)
            except Exception:
                logger.exception("model watcher failed handling %s %s", ev.type, ev.key)

    async def _on_put(self, key: str, value: bytes) -> None:
        entry = ModelEntry.from_json(value)
        self._entries_by_key[key] = entry
        n = self._refcount.get(entry.name, 0)
        self._refcount[entry.name] = n + 1
        if n == 0:
            engine = await self.engine_factory(entry)
            self.manager.add_model(entry.card.model_type, entry.name, engine)
            self.manager._entries[entry.name] = entry
            logger.info("model added: %s (%s) via %s/%s/%s", entry.name, entry.card.model_type, entry.namespace, entry.component, entry.endpoint)

    async def _on_delete(self, key: str) -> None:
        entry = self._entries_by_key.pop(key, None)
        if entry is None:
            return
        n = self._refcount.get(entry.name, 1) - 1
        self._refcount[entry.name] = n
        if n <= 0:
            self.manager.remove_model(entry.card.model_type, entry.name)
            self.manager._entries.pop(entry.name, None)
            self._refcount.pop(entry.name, None)
            logger.info("model removed: %s", entry.name)

    async def stop(self) -> None:
        if self._task:
            await self._watch.cancel()
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
