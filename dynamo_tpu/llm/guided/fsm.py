"""Character DFA → token-level FSM against a served vocabulary.

For every (DFA state, vocab token) pair, walk the token's character string
through the DFA once at compile time. The result is two dense tables:

- ``next_state`` ``[S, V] int32`` — landing state (-1 = the token would make
  the string unmatchable);
- ``allow_words`` ``[S, ceil(V/32)] uint32`` — the same information as a
  packed bitmask, the shape the device mask pool uploads (32 tokens per
  word keeps a 128k vocab row at 4 KB).

EOS tokens are allowed exactly in accepting states; tokens that decode to
the empty string (or contain characters outside the grammar alphabet) are
never allowed — an empty token makes no FSM progress and would loop forever.
The walk is trie-structured (shared token prefixes walk once per state), so
compile cost is O(states × trie nodes), not O(states × vocab × token len).

Compiled FSMs are LRU-cached by (pattern, tokenizer), so repeated schemas —
the overwhelmingly common case for tool/extraction traffic — compile once.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from dynamo_tpu.llm.guided.grammar import ALPHASET, CharDFA


class TokenFSM:
    __slots__ = (
        "num_states",
        "vocab_size",
        "next_state",
        "allow_words",
        "accepting",
        "accept_only",
        "eos_ids",
        "pattern",
        "compile_s",
    )

    def __init__(
        self,
        num_states: int,
        vocab_size: int,
        next_state: np.ndarray,
        allow_words: np.ndarray,
        accepting: np.ndarray,
        accept_only: np.ndarray,
        eos_ids: frozenset,
        pattern: str,
        compile_s: float,
    ):
        self.num_states = num_states
        self.vocab_size = vocab_size
        self.next_state = next_state
        self.allow_words = allow_words
        self.accepting = accepting
        self.accept_only = accept_only
        self.eos_ids = eos_ids
        self.pattern = pattern
        self.compile_s = compile_s

    @property
    def mask_words(self) -> int:
        return self.allow_words.shape[1]

    def allows(self, state: int, token: int) -> bool:
        if not (0 <= state < self.num_states and 0 <= token < self.vocab_size):
            return False
        return bool((self.allow_words[state, token >> 5] >> np.uint32(token & 31)) & 1)


def _build_trie(token_strs: Sequence[str]) -> dict:
    """Char trie over token strings; terminal token ids under the None key.
    Tokens with empty text or out-of-alphabet characters are dropped (they
    can never legally advance the FSM)."""
    root: dict = {}
    for tid, s in enumerate(token_strs):
        if not s or any(c not in ALPHASET for c in s):
            continue
        node = root
        for c in s:
            node = node.setdefault(c, {})
        node.setdefault(None, []).append(tid)
    return root


def compile_token_fsm(
    dfa: CharDFA,
    token_strs: Sequence[str],
    eos_ids: Sequence[int] = (),
) -> TokenFSM:
    t0 = time.perf_counter()
    S = dfa.num_states
    V = len(token_strs)
    trie = _build_trie(token_strs)
    next_state = np.full((S, V), -1, dtype=np.int32)
    for s in range(S):
        # Iterative DFS: (trie node, dfa state after consuming the prefix).
        stack: List[Tuple[dict, int]] = [(trie, s)]
        while stack:
            node, st = stack.pop()
            row = dfa.transitions[st]
            for c, child in node.items():
                if c is None:
                    next_state[s, child] = st  # type: ignore[index]
                    continue
                nxt = row.get(c, -1)
                if nxt >= 0:
                    stack.append((child, nxt))
    accepting = np.asarray(dfa.accepting, dtype=bool)
    eos = frozenset(int(e) for e in eos_ids if 0 <= int(e) < V)

    allow = next_state >= 0
    for e in eos:
        allow[:, e] = accepting
        next_state[:, e] = np.where(accepting, np.arange(S, dtype=np.int32), -1)

    words = (V + 31) // 32
    padded = np.zeros((S, words * 32), dtype=bool)
    padded[:, :V] = allow
    bits = padded.reshape(S, words, 32).astype(np.uint32)
    allow_words = (bits << np.arange(32, dtype=np.uint32)).sum(axis=2, dtype=np.uint32)

    non_eos = allow.copy()
    for e in eos:
        non_eos[:, e] = False
    accept_only = accepting & ~non_eos.any(axis=1)

    return TokenFSM(
        num_states=S,
        vocab_size=V,
        next_state=next_state,
        allow_words=allow_words,
        accepting=accepting,
        accept_only=accept_only,
        eos_ids=eos,
        pattern=dfa.pattern,
        compile_s=time.perf_counter() - t0,
    )


class FsmCache:
    """LRU of compiled token FSMs keyed by (pattern, tokenizer identity)."""

    def __init__(self, maxsize: int = 64):
        self.maxsize = maxsize
        self._d: "OrderedDict[tuple, TokenFSM]" = OrderedDict()

    def get(self, key: tuple, builder: Callable[[], TokenFSM]) -> Tuple[TokenFSM, bool]:
        """Returns (fsm, was_cached)."""
        fsm = self._d.get(key)
        if fsm is not None:
            self._d.move_to_end(key)
            return fsm, True
        fsm = builder()
        self._d[key] = fsm
        while len(self._d) > self.maxsize:
            self._d.popitem(last=False)
        return fsm, False

    def __len__(self) -> int:
        return len(self._d)
