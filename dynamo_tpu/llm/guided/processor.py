"""Device-side application of token FSMs: the mask pool + per-sequence state.

All compiled grammars share ONE device-resident mask pool — a
``[pool_rows, ceil(V/32)] uint32`` array where each grammar occupies a
contiguous block of rows (one row per FSM state) starting at its base
offset. Row 0 is reserved as the allow-everything row, so unguided rows in
a mixed batch map to row 0 and pass through the masked sampler unchanged —
one compiled executable serves every guided/unguided batch composition.

The pool's capacity is bucketed (pow2 growth from
``SchedulerConfig.guided_pool_rows``), matching the repo's bucketed-compile
discipline: the masked-sampling executable's shape only changes when total
registered FSM states outgrow the current bucket, and ``Scheduler.warmup``
precompiles it at the initial bucket — so guided rows joining a warmed batch
add zero post-warmup XLA compiles.

Per step, the scheduler packs one i32 row id per batch row
(``pool_base + fsm_state``); the jit'd sampler gathers the mask row and adds
``-inf`` to disallowed logits (engine/sampling.py ``apply_token_masks``).
The FSM *advance* is a host-side O(1) table lookup on the sampled token the
scheduler already reads back — no extra device↔host sync anywhere.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from dynamo_tpu.llm.guided.fsm import FsmCache, TokenFSM, compile_token_fsm
from dynamo_tpu.llm.guided.grammar import GrammarError, compile_regex, spec_to_pattern
from dynamo_tpu.runtime.logging import get_logger

logger = get_logger(__name__)


class GuidedMaskPool:
    """Shared device mask pool: one row per FSM state across all live
    grammars, row 0 = allow-all (the unguided pass-through)."""

    def __init__(self, vocab_size: int, min_rows: int = 1024):
        self.vocab_size = vocab_size
        self.words = (vocab_size + 31) // 32
        self.capacity = max(int(min_rows), 2)
        self._host = np.zeros((self.capacity, self.words), dtype=np.uint32)
        self._host[0] = self._allow_all_row()
        self._used = 1
        self._bases: Dict[int, int] = {}  # id(fsm) -> base row
        self._keep: List[TokenFSM] = []  # pin fsms so id() stays stable
        self._device = None
        self._next_device = None

    def _allow_all_row(self) -> np.ndarray:
        row = np.full((self.words,), 0xFFFFFFFF, dtype=np.uint32)
        tail = self.vocab_size & 31
        if tail:
            row[-1] = np.uint32((1 << tail) - 1)  # pad bits stay 0
        return row

    def register(self, fsm: TokenFSM) -> int:
        """Ensure ``fsm``'s mask rows are in the pool; returns its base row.
        Growing past the capacity bucket doubles it (a new executable shape,
        logged — size ``guided_pool_rows`` to your grammar working set)."""
        base = self._bases.get(id(fsm))
        if base is not None:
            return base
        need = self._used + fsm.num_states
        if need > self.capacity:
            cap = self.capacity
            while cap < need:
                cap *= 2
            logger.warning(
                "guided mask pool grew %d -> %d rows (masked-sampling "
                "executables recompile at the new shape)", self.capacity, cap,
            )
            host = np.zeros((cap, self.words), dtype=np.uint32)
            host[: self._used] = self._host[: self._used]
            self._host = host
            self.capacity = cap
        base = self._used
        self._host[base : base + fsm.num_states] = fsm.allow_words
        self._used = base + fsm.num_states
        self._bases[id(fsm)] = base
        self._keep.append(fsm)
        self._device = None  # re-upload lazily
        self._next_device = None
        return base

    def device(self):
        """Device copy of the pool, padded to the capacity bucket. Uploaded
        once per registration, not per step."""
        if self._device is None:
            import jax.numpy as jnp

            self._device = jnp.asarray(self._host)
        return self._device

    def next_pool_bytes(self) -> int:
        """Size of the ``[capacity, V] int32`` next-row pool the fused
        window's on-chip FSM advance reads — the fused-eligibility gate
        charges this against the VMEM window budget."""
        return self.capacity * self.vocab_size * 4

    def next_device(self):
        """Device next-row pool: ``next[row, token]`` is the mask-pool row
        the FSM lands on after emitting ``token`` from ``row`` — the fused
        window advances guided rows ON-CHIP through this table instead of
        flushing to the host every step. Dead transitions and row 0 map to
        row 0 (allow-all); the host replay stops the sequence before a
        dead/EOS transition would ever be sampled against."""
        if self._next_device is None:
            import jax.numpy as jnp

            host = np.zeros((self.capacity, self.vocab_size), dtype=np.int32)
            for fsm in self._keep:
                base = self._bases[id(fsm)]
                ns = fsm.next_state  # [S, V] i32, -1 = dead
                rows = np.where(ns >= 0, base + ns, 0).astype(np.int32)
                host[base : base + fsm.num_states, : ns.shape[1]] = rows
            self._next_device = jnp.asarray(host)
        return self._next_device


class GuidedState:
    """Per-sequence FSM cursor, advanced host-side from each sampled token."""

    __slots__ = ("fsm", "pool_base", "state", "finished", "from_cache")

    def __init__(self, fsm: TokenFSM, pool_base: int, from_cache: bool = False):
        self.fsm = fsm
        self.pool_base = pool_base
        self.state = 0
        self.finished = False
        self.from_cache = from_cache

    @property
    def row_id(self) -> int:
        """Mask-pool row for the current state (allow-all row once done —
        the sequence stops before it would sample again)."""
        if self.state < 0 or self.finished:
            return 0
        return self.pool_base + self.state

    @property
    def exhausted(self) -> bool:
        """The grammar is complete (or unrecoverable): force-finish with
        ``finish_reason="stop"`` — the FSM accepts and only EOS remains."""
        if self.finished or self.state < 0:
            return True
        return bool(self.fsm.accept_only[self.state])

    def advance(self, token: int) -> None:
        if self.finished:
            return
        if token in self.fsm.eos_ids:
            self.finished = True
            return
        if 0 <= token < self.fsm.vocab_size and self.state >= 0:
            self.state = int(self.fsm.next_state[self.state, token])
        else:
            self.state = -1
        if self.state < 0:
            # Only possible when something outside the mask forced a token
            # (host logits processor, logit_bias): stop rather than emit
            # unconstrained text under a structured-output contract.
            self.finished = True


class GuidedDecoder:
    """Scheduler-owned facade: spec → cached token FSM → pool registration.

    Counters feed the worker stats scrape (``guided_requests_total``,
    grammar-compile totals) through ``stats()``."""

    def __init__(
        self,
        tokenizer,
        *,
        eos_ids: Sequence[int] = (),
        vocab_size: Optional[int] = None,
        pool_rows: int = 1024,
        cache_size: int = 64,
    ):
        self.tokenizer = tokenizer
        self.vocab_size = int(vocab_size or tokenizer.vocab_size)
        self.eos_ids = list(eos_ids) or list(getattr(tokenizer, "eos_token_ids", []) or [])
        self.pool = GuidedMaskPool(self.vocab_size, min_rows=pool_rows)
        self.cache = FsmCache(maxsize=cache_size)
        self._token_strs: Optional[List[str]] = None
        self.requests_total = 0
        self.compiles_total = 0
        self.compile_seconds_total = 0.0

    def _token_strings(self) -> List[str]:
        if self._token_strs is None:
            strs = []
            for tid in range(self.vocab_size):
                try:
                    strs.append(self.tokenizer.decode([tid]))
                except Exception:  # noqa: BLE001 — out-of-vocab ids stay unusable
                    strs.append("")
            self._token_strs = strs
        return self._token_strs

    def open(self, spec: dict) -> GuidedState:
        """Compile (or fetch) the spec's token FSM and hand out a fresh
        per-sequence cursor. Raises ValueError (GrammarError) on a bad spec —
        the frontend validates first, so this is the defense line for raw
        engine API users."""
        pattern = spec_to_pattern(spec)
        key = (pattern, id(self.tokenizer), self.vocab_size)

        def build() -> TokenFSM:
            t0 = time.perf_counter()
            fsm = compile_token_fsm(compile_regex(pattern), self._token_strings(), self.eos_ids)
            self.compiles_total += 1
            self.compile_seconds_total += time.perf_counter() - t0
            return fsm

        fsm, cached = self.cache.get(key, build)
        base = self.pool.register(fsm)
        self.requests_total += 1
        return GuidedState(fsm, base, from_cache=cached)

    def stats(self) -> dict:
        return {
            "guided_requests_total": self.requests_total,
            "guided_grammar_compiles_total": self.compiles_total,
            "guided_grammar_compile_seconds_total": round(self.compile_seconds_total, 6),
        }
