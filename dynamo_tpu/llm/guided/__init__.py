"""Guided decoding: grammar-constrained structured outputs.

The subsystem compiles a constraint spec (JSON Schema subset, raw regex, or
a literal choice list) into a character-level DFA (:mod:`grammar`), lifts it
to a token-level FSM against the served tokenizer's vocabulary
(:mod:`fsm` — per-state allowed-token bitmasks + a dense next-state table),
and applies it jit-side through a device-resident mask pool fused into the
batched sampling step (:mod:`processor` + engine/sampling.py) — guided rows
ride the normal batched/mixed decode path with zero per-step host sync.
"""

from dynamo_tpu.llm.guided.grammar import (  # noqa: F401
    CharDFA,
    GrammarError,
    build_guided_spec,
    compile_regex,
    json_object_regex,
    schema_to_regex,
    spec_to_dfa,
)
from dynamo_tpu.llm.guided.fsm import TokenFSM, compile_token_fsm  # noqa: F401
from dynamo_tpu.llm.guided.processor import GuidedDecoder, GuidedState  # noqa: F401
