"""Constraint spec → character-level DFA.

Three spec kinds compile here: a JSON Schema subset (``schema_to_regex``),
a raw regex (``compile_regex``), and a literal choice list. Everything is
normalized to a regex first, then compiled Thompson-NFA → subset-construction
DFA with dead-state pruning, so the DFA is *exact*: a state exists iff some
completion from it can still accept. That exactness is what makes the token
masks tight — a token is allowed iff the string stays matchable.

The regex dialect is the ``re``-compatible subset a DFA can honor: literals,
escapes (``\\d \\w \\s`` + punctuation), classes ``[a-z]`` / ``[^...]``,
``.``, groups ``(...)`` / ``(?:...)``, alternation, and the quantifiers
``* + ? {m} {m,} {m,n}`` (non-greedy suffixes are accepted and ignored — the
matched *language* is identical). Backreferences, lookarounds, and anchors
raise :class:`GrammarError` (matching is whole-string, so anchors are
implicit). The alphabet is printable ASCII plus ``\\n \\t \\r``; JSON string
escapes (``\\uXXXX``) keep non-ASCII content expressible.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


class GrammarError(ValueError):
    """Constraint spec that cannot be compiled (client error — the protocol
    layer maps it to a structured 400, never a 500)."""


ALPHABET: Tuple[str, ...] = tuple(chr(c) for c in range(32, 127)) + ("\n", "\t", "\r")
ALPHASET = frozenset(ALPHABET)
_DIGITS = frozenset("0123456789")
_WORD = frozenset("abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_")
_SPACE = frozenset(" \t\n\r")

# Subset-construction safety valve: a runaway pattern (huge bounded repeats,
# pathological alternations) errors instead of eating the serving process.
MAX_DFA_STATES = 8192

_RX_SPECIALS = set("\\.[]{}()*+?|^$")


def rx_escape(text: str) -> str:
    """Escape ``text`` so it matches literally."""
    return "".join("\\" + c if c in _RX_SPECIALS else c for c in text)


# --- regex parsing -----------------------------------------------------------
# AST nodes: ("lit", frozenset) | ("cat", [nodes]) | ("alt", [nodes])
#          | ("rep", node, min, max|None)


class _RxParser:
    def __init__(self, pattern: str):
        self.p = pattern
        self.i = 0
        self.n = len(pattern)

    def parse(self):
        node = self._alt()
        if self.i != self.n:
            raise GrammarError(f"unexpected {self.p[self.i]!r} at position {self.i}")
        return node

    def _peek(self) -> Optional[str]:
        return self.p[self.i] if self.i < self.n else None

    def _alt(self):
        branches = [self._cat()]
        while self._peek() == "|":
            self.i += 1
            branches.append(self._cat())
        return branches[0] if len(branches) == 1 else ("alt", branches)

    def _cat(self):
        items = []
        while True:
            c = self._peek()
            if c is None or c in "|)":
                break
            items.append(self._rep())
        if not items:
            return ("cat", [])
        return items[0] if len(items) == 1 else ("cat", items)

    def _rep(self):
        node = self._atom()
        while True:
            c = self._peek()
            if c == "*":
                self.i += 1
                lo, hi = 0, None
            elif c == "+":
                self.i += 1
                lo, hi = 1, None
            elif c == "?":
                self.i += 1
                lo, hi = 0, 1
            elif c == "{":
                spec = self._brace()
                if spec is None:
                    break  # bare '{' is a literal (re semantics)
                lo, hi = spec
            else:
                break
            if self._peek() == "?":  # non-greedy: same language, ignore
                self.i += 1
            if hi is not None and hi < lo:
                raise GrammarError(f"bad repeat range {{{lo},{hi}}}")
            node = ("rep", node, lo, hi)
        return node

    def _brace(self) -> Optional[Tuple[int, Optional[int]]]:
        j = self.p.find("}", self.i)
        if j == -1:
            return None
        body = self.p[self.i + 1 : j]
        parts = body.split(",")
        if not all(p.isdigit() or p == "" for p in parts) or len(parts) > 2 or not body:
            return None
        if not parts[0].isdigit():
            return None
        lo = int(parts[0])
        if len(parts) == 1:
            hi: Optional[int] = lo
        else:
            hi = int(parts[1]) if parts[1] else None
        self.i = j + 1
        return lo, hi

    def _atom(self):
        c = self.p[self.i]
        if c == "(":
            self.i += 1
            if self._peek() == "?":
                if self.i + 1 < self.n and self.p[self.i + 1] == ":":
                    self.i += 2
                else:
                    raise GrammarError(
                        "only (?:...) groups are supported (no lookarounds/named groups)"
                    )
            node = self._alt()
            if self._peek() != ")":
                raise GrammarError("unbalanced '('")
            self.i += 1
            return node
        if c == "[":
            self.i += 1
            return ("lit", self._cls())
        if c == ".":
            self.i += 1
            return ("lit", ALPHASET)
        if c == "\\":
            self.i += 1
            return ("lit", self._esc())
        if c in "^$":
            raise GrammarError(
                "anchors are unsupported (guided matching is whole-string)"
            )
        if c in "*+?":
            raise GrammarError(f"nothing to repeat at position {self.i}")
        self.i += 1
        if c not in ALPHASET:
            raise GrammarError(f"character {c!r} outside the supported alphabet")
        return ("lit", frozenset((c,)))

    def _esc(self) -> frozenset:
        if self.i >= self.n:
            raise GrammarError("dangling escape")
        c = self.p[self.i]
        self.i += 1
        if c == "d":
            return _DIGITS
        if c == "D":
            return ALPHASET - _DIGITS
        if c == "w":
            return _WORD
        if c == "W":
            return ALPHASET - _WORD
        if c == "s":
            return _SPACE
        if c == "S":
            return ALPHASET - _SPACE
        if c == "n":
            return frozenset("\n")
        if c == "t":
            return frozenset("\t")
        if c == "r":
            return frozenset("\r")
        if c.isdigit():
            raise GrammarError("backreferences are unsupported")
        if c.isalpha():
            raise GrammarError(f"unsupported escape \\{c}")
        return frozenset((c,))

    def _cls(self) -> frozenset:
        neg = False
        if self._peek() == "^":
            neg = True
            self.i += 1
        chars: set = set()
        first = True
        while True:
            c = self._peek()
            if c is None:
                raise GrammarError("unterminated character class")
            if c == "]" and not first:
                self.i += 1
                break
            first = False
            if c == "\\":
                self.i += 1
                s = self._esc()
                if len(s) == 1:
                    c = next(iter(s))
                else:
                    chars |= s
                    continue
            else:
                self.i += 1
            # Range?
            if (
                self._peek() == "-"
                and self.i + 1 < self.n
                and self.p[self.i + 1] != "]"
            ):
                self.i += 1
                hi = self.p[self.i]
                self.i += 1
                if hi == "\\":
                    s = self._esc()
                    if len(s) != 1:
                        raise GrammarError("bad range end in character class")
                    hi = next(iter(s))
                if ord(hi) < ord(c):
                    raise GrammarError(f"bad range {c}-{hi} in character class")
                chars |= {chr(o) for o in range(ord(c), ord(hi) + 1)}
            else:
                chars.add(c)
        out = frozenset(chars) & ALPHASET if not neg else ALPHASET - frozenset(chars)
        if not out:
            raise GrammarError("empty character class")
        return out


# --- NFA / DFA ---------------------------------------------------------------


class _Nfa:
    def __init__(self):
        self.eps: List[List[int]] = []
        self.trans: List[List[Tuple[frozenset, int]]] = []

    def state(self) -> int:
        self.eps.append([])
        self.trans.append([])
        return len(self.eps) - 1


def _thompson(node, nfa: _Nfa) -> Tuple[int, int]:
    kind = node[0]
    if kind == "lit":
        s, e = nfa.state(), nfa.state()
        nfa.trans[s].append((node[1], e))
        return s, e
    if kind == "cat":
        if not node[1]:
            s = nfa.state()
            return s, s
        s, e = _thompson(node[1][0], nfa)
        for sub in node[1][1:]:
            s2, e2 = _thompson(sub, nfa)
            nfa.eps[e].append(s2)
            e = e2
        return s, e
    if kind == "alt":
        s, e = nfa.state(), nfa.state()
        for sub in node[1]:
            s2, e2 = _thompson(sub, nfa)
            nfa.eps[s].append(s2)
            nfa.eps[e2].append(e)
        return s, e
    if kind == "rep":
        _, sub, lo, hi = node
        # Expand the mandatory prefix, then optional tail (or a star).
        s = e = nfa.state()
        for _ in range(lo):
            s2, e2 = _thompson(sub, nfa)
            nfa.eps[e].append(s2)
            e = e2
        if hi is None:
            s2, e2 = _thompson(sub, nfa)
            loop_out = nfa.state()
            nfa.eps[e].append(s2)
            nfa.eps[e].append(loop_out)
            nfa.eps[e2].append(s2)
            nfa.eps[e2].append(loop_out)
            e = loop_out
        else:
            out = nfa.state()
            nfa.eps[e].append(out)
            for _ in range(hi - lo):
                s2, e2 = _thompson(sub, nfa)
                nfa.eps[e].append(s2)
                nfa.eps[e2].append(out)
                e = e2
            nfa.eps[e].append(out)
            e = out
        return s, e
    raise GrammarError(f"internal: unknown AST node {kind}")


@dataclass
class CharDFA:
    """Exact character-level DFA: every state can still reach acceptance
    (dead states pruned), so "has a transition" ≡ "string stays matchable"."""

    transitions: List[Dict[str, int]] = field(default_factory=list)
    accepting: List[bool] = field(default_factory=list)
    start: int = 0
    pattern: str = ""

    @property
    def num_states(self) -> int:
        return len(self.transitions)

    def step(self, state: int, char: str) -> int:
        """Next state, or -1 (dead)."""
        if state < 0:
            return -1
        return self.transitions[state].get(char, -1)

    def match(self, text: str) -> bool:
        state = self.start
        for c in text:
            state = self.step(state, c)
            if state < 0:
                return False
        return self.accepting[state]

    def shortest_accepting(self) -> str:
        """BFS shortest accepted string (deterministic: ties broken by char
        order). Used by the mocker to emit schema-valid output."""
        from collections import deque

        if self.accepting[self.start]:
            return ""
        seen = {self.start}
        q = deque([(self.start, "")])
        while q:
            state, s = q.popleft()
            for c in sorted(self.transitions[state]):
                nxt = self.transitions[state][c]
                if nxt in seen:
                    continue
                if self.accepting[nxt]:
                    return s + c
                seen.add(nxt)
                q.append((nxt, s + c))
        raise GrammarError("grammar matches nothing")


def compile_regex(pattern: str) -> CharDFA:
    """Parse + compile ``pattern`` (anchored, whole-string) to an exact DFA."""
    ast = _RxParser(pattern).parse()
    nfa = _Nfa()
    start, accept = _thompson(ast, nfa)

    def closure(states: frozenset) -> frozenset:
        stack = list(states)
        out = set(states)
        while stack:
            s = stack.pop()
            for t in nfa.eps[s]:
                if t not in out:
                    out.add(t)
                    stack.append(t)
        return frozenset(out)

    start_set = closure(frozenset((start,)))
    index = {start_set: 0}
    order = [start_set]
    transitions: List[Dict[str, int]] = [{}]
    i = 0
    while i < len(order):
        cur = order[i]
        # Only chars on an outgoing edge can move; group targets per char.
        moves: Dict[str, set] = {}
        for s in cur:
            for chars, t in nfa.trans[s]:
                for c in chars:
                    moves.setdefault(c, set()).add(t)
        for c, targets in moves.items():
            nxt = closure(frozenset(targets))
            if nxt not in index:
                if len(order) >= MAX_DFA_STATES:
                    raise GrammarError(
                        f"grammar too large (> {MAX_DFA_STATES} DFA states)"
                    )
                index[nxt] = len(order)
                order.append(nxt)
                transitions.append({})
            transitions[i][c] = index[nxt]
        i += 1
    accepting = [accept in st for st in order]

    # Dead-state pruning: backward reachability from accepting states. Any
    # transition into a state that can never accept is dropped, making the
    # DFA (and therefore the token masks) exact.
    rev: List[List[int]] = [[] for _ in order]
    for s, tr in enumerate(transitions):
        for t in tr.values():
            rev[t].append(s)
    live = set(i for i, a in enumerate(accepting) if a)
    stack = list(live)
    while stack:
        s = stack.pop()
        for p in rev[s]:
            if p not in live:
                live.add(p)
                stack.append(p)
    if 0 not in live:
        raise GrammarError("grammar matches nothing")
    remap = {}
    for s in range(len(order)):
        if s in live:
            remap[s] = len(remap)
    new_trans = [
        {c: remap[t] for c, t in transitions[s].items() if t in live}
        for s in range(len(order))
        if s in live
    ]
    new_accept = [accepting[s] for s in range(len(order)) if s in live]
    return CharDFA(transitions=new_trans, accepting=new_accept, start=remap[0], pattern=pattern)


# --- JSON Schema subset → regex ----------------------------------------------
# The canonical emitted form is whitespace-free JSON (the tightest DFA). The
# supported subset is documented in README "Structured outputs".

_RX_STR_CHAR = r'[^"\\\n\t\r]'
_RX_STR_ESC = r'\\(?:["\\/bfnrt]|u[0-9a-fA-F]{4})'
RX_INTEGER = r"-?(?:0|[1-9][0-9]*)"
RX_NUMBER = RX_INTEGER + r"(?:\.[0-9]+)?(?:[eE][-+]?[0-9]+)?"

_MAX_SCHEMA_DEPTH = 16


def rx_string(min_len: Optional[int] = None, max_len: Optional[int] = None) -> str:
    inner = f"(?:{_RX_STR_CHAR}|{_RX_STR_ESC})"
    if min_len is None and max_len is None:
        return f'"{inner}*"'
    lo = int(min_len or 0)
    hi = "" if max_len is None else str(int(max_len))
    return f'"{inner}{{{lo},{hi}}}"'


def _json_literal_rx(value) -> str:
    try:
        return rx_escape(json.dumps(value, separators=(",", ":")))
    except (TypeError, ValueError) as e:
        raise GrammarError(f"unencodable literal in schema: {e}") from None


def json_value_regex(depth: int = 2) -> str:
    """Generic JSON *value* with nesting bounded at ``depth`` container
    levels (regular languages can't count arbitrary nesting)."""
    scalar = f"(?:{rx_string()}|{RX_NUMBER}|true|false|null)"
    v = scalar
    for _ in range(max(depth, 0)):
        pair = f"{rx_string()}:{v}"
        obj = r"\{(?:" + pair + r"(?:," + pair + r")*)?\}"
        arr = r"\[(?:" + v + r"(?:," + v + r")*)?\]"
        v = f"(?:{scalar}|{obj}|{arr})"
    return v


def json_object_regex(depth: int = 3) -> str:
    """``response_format: json_object`` — any JSON object (values nested up
    to ``depth - 1`` container levels)."""
    v = json_value_regex(max(depth - 1, 0))
    pair = f"{rx_string()}:{v}"
    return r"\{(?:" + pair + r"(?:," + pair + r")*)?\}"


def schema_to_regex(schema: dict, _depth: int = 0) -> str:
    """Compile the supported JSON Schema subset to a whitespace-free regex.

    Supported: type string (minLength/maxLength/pattern) / integer / number /
    boolean / null, enum, const, arrays (items, minItems/maxItems), objects
    (properties emitted in declaration order — every declared property is
    emitted), anyOf/oneOf, and type lists. ``$ref``, ``allOf``, and
    ``additionalProperties`` schemas raise :class:`GrammarError`."""
    if not isinstance(schema, dict):
        raise GrammarError("schema must be a JSON object")
    if _depth > _MAX_SCHEMA_DEPTH:
        raise GrammarError(f"schema nests deeper than {_MAX_SCHEMA_DEPTH}")
    if "$ref" in schema:
        raise GrammarError("$ref is not supported in guided schemas")
    if "allOf" in schema:
        raise GrammarError("allOf is not supported in guided schemas")
    if "enum" in schema:
        vals = schema["enum"]
        if not isinstance(vals, list) or not vals:
            raise GrammarError("enum must be a non-empty array")
        return "(?:" + "|".join(_json_literal_rx(v) for v in vals) + ")"
    if "const" in schema:
        return _json_literal_rx(schema["const"])
    for key in ("anyOf", "oneOf"):
        if key in schema:
            subs = schema[key]
            if not isinstance(subs, list) or not subs:
                raise GrammarError(f"{key} must be a non-empty array")
            return "(?:" + "|".join(schema_to_regex(s, _depth + 1) for s in subs) + ")"
    t = schema.get("type")
    if isinstance(t, list):
        if not t:
            raise GrammarError("type list must be non-empty")
        return "(?:" + "|".join(
            schema_to_regex({**schema, "type": one}, _depth + 1) for one in t
        ) + ")"
    if t == "string":
        if "pattern" in schema:
            if not isinstance(schema["pattern"], str):
                raise GrammarError("string pattern must be a string")
            return f'"(?:{schema["pattern"]})"'
        return rx_string(schema.get("minLength"), schema.get("maxLength"))
    if t == "integer":
        return RX_INTEGER
    if t == "number":
        return RX_NUMBER
    if t == "boolean":
        return "(?:true|false)"
    if t == "null":
        return "null"
    if t == "array":
        items = schema.get("items")
        item = schema_to_regex(items, _depth + 1) if isinstance(items, dict) else json_value_regex(1)
        lo = int(schema.get("minItems") or 0)
        hi = schema.get("maxItems")
        if hi is not None and int(hi) < lo:
            raise GrammarError("maxItems < minItems")
        if hi is not None and int(hi) == 0:
            return r"\[\]"
        if hi is None:
            body = f"{item}(?:,{item})*" if lo >= 1 else f"(?:{item}(?:,{item})*)?"
            if lo > 1:
                body = f"{item}(?:,{item}){{{lo - 1},}}"
        else:
            body = f"{item}(?:,{item}){{{max(lo - 1, 0)},{int(hi) - 1}}}"
            if lo == 0:
                body = f"(?:{body})?"
        return r"\[" + body + r"\]"
    if t == "object" or (t is None and isinstance(schema.get("properties"), dict)):
        props = schema.get("properties")
        if not isinstance(props, dict) or not props:
            return json_object_regex(2)
        parts = []
        for key, sub in props.items():
            if not isinstance(key, str):
                raise GrammarError("property names must be strings")
            parts.append(_json_literal_rx(key) + ":" + schema_to_regex(sub, _depth + 1))
        return r"\{" + ",".join(parts) + r"\}"
    if t is None:
        return json_value_regex(2)
    raise GrammarError(f"unsupported schema type {t!r}")


# --- spec normalization ------------------------------------------------------


def spec_to_pattern(spec: dict) -> str:
    """Canonical regex for a wire guided-decoding spec (kinds: ``regex``,
    ``choice``)."""
    if not isinstance(spec, dict):
        raise GrammarError("guided spec must be an object")
    kind = spec.get("kind")
    if kind == "regex":
        pattern = spec.get("pattern")
        if not isinstance(pattern, str) or not pattern:
            raise GrammarError("guided regex spec needs a non-empty pattern")
        return pattern
    if kind == "choice":
        choices = spec.get("choices")
        if not isinstance(choices, list) or not choices or not all(
            isinstance(c, str) and c for c in choices
        ):
            raise GrammarError("guided choice spec needs a non-empty list of strings")
        return "(?:" + "|".join(rx_escape(c) for c in choices) + ")"
    raise GrammarError(f"unknown guided spec kind {kind!r}")


def spec_to_dfa(spec: dict) -> CharDFA:
    return compile_regex(spec_to_pattern(spec))


def _tool_call_pattern(tools: list, names: List[str]) -> str:
    """Forced tool call grammar: the model must emit
    ``{"name":"<tool>","arguments":{...}}`` with arguments matching the
    chosen tool's parameter schema — exactly what the JSON tool-call parser
    round-trips into an OpenAI tool_call."""
    alts = []
    for tool in tools:
        fn = (tool or {}).get("function") or {}
        name = fn.get("name")
        if name not in names:
            continue
        params = fn.get("parameters")
        if params is None:
            params = {"type": "object"}
        args_rx = schema_to_regex(params)
        alts.append(
            r"\{" + _json_literal_rx("name") + ":" + _json_literal_rx(name)
            + "," + _json_literal_rx("arguments") + ":" + args_rx + r"\}"
        )
    if not alts:
        raise GrammarError("tool_choice names no known tool")
    return "(?:" + "|".join(alts) + ")"


def build_guided_spec(body: dict) -> Optional[dict]:
    """Validated request body → wire guided-decoding spec (or None).

    Precedence: forced ``tool_choice`` (named or ``required``) >
    ``response_format`` (json_schema / json_object) > nvext extensions
    (``guided_regex`` / ``guided_choice`` / ``guided_json``). Every produced
    pattern is compiled once here so malformed/unsupported constraints
    surface as a structured 400 at the frontend, never a worker-side 500."""
    from dynamo_tpu.llm.protocols.openai import RequestError

    try:
        spec = _build_spec(body)
        if spec is not None:
            compile_regex(spec["pattern"])  # frontend-side compilability check
        return spec
    except GrammarError as e:
        raise RequestError(f"invalid guided-decoding constraint: {e}") from None


def _build_spec(body: dict) -> Optional[dict]:
    tools = body.get("tools") or []
    tc = body.get("tool_choice")
    if isinstance(tc, dict):
        name = ((tc.get("function") or {}).get("name")) or ""
        return {
            "kind": "regex",
            "pattern": _tool_call_pattern(tools, [name]),
            "source": "tool_choice",
            "forced_tools": [name],
        }
    if tc == "required":
        names = [((t or {}).get("function") or {}).get("name") for t in tools]
        names = [n for n in names if n]
        return {
            "kind": "regex",
            "pattern": _tool_call_pattern(tools, names),
            "source": "tool_choice",
            "forced_tools": names,
        }
    rf = body.get("response_format") or {}
    if rf.get("type") == "json_schema":
        schema = (rf.get("json_schema") or {}).get("schema")
        return {
            "kind": "regex",
            "pattern": schema_to_regex(schema),
            "source": "json_schema",
        }
    if rf.get("type") == "json_object":
        return {"kind": "regex", "pattern": json_object_regex(), "source": "json_object"}
    nv = body.get("nvext") or {}
    if nv.get("guided_regex") is not None:
        return {"kind": "regex", "pattern": nv["guided_regex"], "source": "guided_regex"}
    if nv.get("guided_choice") is not None:
        return {
            "kind": "regex",
            "pattern": spec_to_pattern({"kind": "choice", "choices": nv["guided_choice"]}),
            "source": "guided_choice",
        }
    if nv.get("guided_json") is not None:
        return {
            "kind": "regex",
            "pattern": schema_to_regex(nv["guided_json"]),
            "source": "guided_json",
        }
    return None
