"""OpenAI frontend CLI: ``python -m dynamo_tpu.frontend``.

Ref: components/frontend/src/dynamo/frontend/main.py:81-286 — flags mirror
the reference's CLI surface (router mode, kv knobs, busy threshold,
migration limit, ports).
"""

from __future__ import annotations

import argparse
import asyncio

from dynamo_tpu.llm.entrypoint import FrontendConfig, start_frontend
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.logging import get_logger, init_logging

logger = get_logger(__name__)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="dynamo-tpu OpenAI frontend")
    p.add_argument("--http-host", default="0.0.0.0")
    p.add_argument("--http-port", type=int, default=8000)
    p.add_argument("--grpc-port", type=int, default=None, help="also serve the KServe v2 gRPC frontend")
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--router-mode", choices=["round-robin", "random", "kv"], default="round-robin")
    p.add_argument("--busy-threshold", type=float, default=None, help="kv-usage above which a worker is skipped")
    p.add_argument("--migration-limit", type=int, default=0, help="max stream-drop replays per request")
    p.add_argument("--kv-overlap-score-weight", type=float, default=1.0)
    p.add_argument("--router-temperature", type=float, default=0.0)
    p.add_argument("--kv-cache-block-size", type=int, default=16)
    p.add_argument("--tls-cert-path", default=None, help="PEM cert: serve HTTPS")
    p.add_argument("--tls-key-path", default=None, help="PEM private key")
    p.add_argument("--encode-component", default=None,
                   help="route image content parts to this encode-worker component (multimodal)")
    # Request tracing (runtime/tracing.py): JSONL span export + sampling.
    # Defaults come from DYN_TRACE_FILE / DYN_TRACE_SAMPLE.
    p.add_argument("--trace-file", default=None, help="JSONL span export path (enables tracing)")
    p.add_argument("--trace-sample", type=float, default=None,
                   help="trace sampling ratio in [0,1]; decision is per-trace-id (default 1.0)")
    p.add_argument("--trace-ring", type=int, default=None,
                   help="in-memory trace black-box depth in records (default 256; 0 disables)")
    p.add_argument("--trace-tail", action="store_true",
                   help="tail-based keep: requests that violate their SLO keep their full "
                        "span set regardless of --trace-sample (promoted from the ring)")
    # SLA telemetry: judge every request's e2e TTFT/TPOT against these
    # targets — slo_{attained,violated}_total{phase} counters + goodput
    # (SLO-attained req/s, tok/s) on /metrics.
    p.add_argument("--slo-ttft-ms", type=float, default=None,
                   help="TTFT SLO target in ms (enables SLO/goodput accounting)")
    p.add_argument("--slo-tpot-ms", type=float, default=None,
                   help="per-output-token latency SLO target in ms")
    # Failure lifecycle: request deadlines, router retry budget, breaker.
    p.add_argument("--request-timeout-ms", type=float, default=None,
                   help="default end-to-end request deadline; past-deadline "
                        "requests are evicted engine-side and answered 504 "
                        "with partial usage (client 'timeout' overrides)")
    p.add_argument("--retry-max", type=int, default=3,
                   help="router NoInstances retries (jittered exponential backoff)")
    p.add_argument("--retry-backoff-ms", type=float, default=50.0,
                   help="base backoff between NoInstances retries")
    p.add_argument("--breaker-threshold", type=int, default=3,
                   help="consecutive stream failures that trip a worker's circuit OPEN")
    p.add_argument("--breaker-cooldown-s", type=float, default=5.0,
                   help="seconds a tripped circuit stays OPEN before one half-open probe")
    # Chaos plane (runtime/faults.py): deterministic fault injection.
    p.add_argument("--fault-scenario", default=None,
                   help="arm the fault injector: inline JSON or @/path/to/scenario.json "
                        "(DYN_FAULTS env is the default)")
    return p


async def amain(args) -> None:
    drt = await DistributedRuntime.from_settings()
    drt.runtime.install_signal_handlers()
    config = FrontendConfig(
        host=args.http_host,
        port=args.http_port,
        grpc_port=args.grpc_port,
        router_mode=args.router_mode,
        busy_threshold=args.busy_threshold,
        migration_limit=args.migration_limit,
        kv_overlap_score_weight=args.kv_overlap_score_weight,
        kv_temperature=args.router_temperature,
        namespace=args.namespace,
        tls_cert=args.tls_cert_path,
        tls_key=args.tls_key_path,
        encode_component=args.encode_component,
        slo_ttft_ms=args.slo_ttft_ms,
        slo_tpot_ms=args.slo_tpot_ms,
        request_timeout_ms=args.request_timeout_ms,
        retry_max=args.retry_max,
        retry_backoff_base_s=args.retry_backoff_ms / 1000.0,
        breaker_threshold=args.breaker_threshold,
        breaker_cooldown_s=args.breaker_cooldown_s,
    )
    service = await start_frontend(drt, config)
    logger.info("frontend ready on %s:%d (router=%s)", args.http_host, service.port, args.router_mode)
    try:
        await drt.runtime.cancellation.cancelled()
    finally:
        await service.watcher.stop()
        await service.stop()
        await drt.shutdown()


def main() -> None:
    init_logging()
    args = build_parser().parse_args()
    from dynamo_tpu.runtime.tracing import configure_tracing

    configure_tracing(path=args.trace_file, sample=args.trace_sample, service="frontend",
                      ring_size=args.trace_ring, tail=args.trace_tail or None)
    from dynamo_tpu.runtime import faults

    if args.fault_scenario:
        faults.arm_from_spec(args.fault_scenario)
    else:
        faults.maybe_arm_from_env()
    try:
        asyncio.run(amain(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
