"""Render a GraphDeployment to Kubernetes manifests.

Ref: deploy/cloud/operator — the reconcile loop that materializes
DynamoGraphDeployment CRDs into Deployments/Services; and deploy/helm.
Here rendering is a pure function so it can be unit-tested and piped to
``kubectl apply -f -`` without a controller in the cluster.

TPU conventions (GKE): chips are requested via the ``google.com/tpu``
resource on containers and the node pool is selected with
``cloud.google.com/gke-tpu-accelerator`` / ``gke-tpu-topology`` selectors.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import yaml

from dynamo_tpu.deploy.spec import GraphDeployment, ServiceSpec


def _labels(graph: GraphDeployment, service: str) -> Dict[str, str]:
    return {
        "app.kubernetes.io/name": graph.name,
        "app.kubernetes.io/component": service,
        "app.kubernetes.io/managed-by": "dynamo-tpu",
    }


def _container(graph: GraphDeployment, svc: ServiceSpec, image: str) -> dict:
    env = {**graph.base_env(), **svc.env}
    limits: Dict[str, str] = {"cpu": svc.resources.cpu, "memory": svc.resources.memory}
    if svc.resources.tpu_chips > 0:
        limits["google.com/tpu"] = str(svc.resources.tpu_chips)
    return {
        "name": svc.name,
        "image": image,
        "command": list(svc.command),
        "env": [{"name": k, "value": v} for k, v in sorted(env.items())],
        "resources": {"limits": limits, "requests": dict(limits)},
        "ports": [{"containerPort": 8000, "name": "http"}],
    }


def _deployment(graph: GraphDeployment, svc: ServiceSpec, image: str,
                tpu_accelerator: Optional[str], tpu_topology: Optional[str]) -> dict:
    labels = _labels(graph, svc.name)
    pod_spec: dict = {"containers": [_container(graph, svc, image)]}
    if svc.resources.tpu_chips > 0:
        selector = {}
        if tpu_accelerator:
            selector["cloud.google.com/gke-tpu-accelerator"] = tpu_accelerator
        if tpu_topology:
            selector["cloud.google.com/gke-tpu-topology"] = tpu_topology
        if selector:
            pod_spec["nodeSelector"] = selector
    # Copies, not references: shared dicts would serialize as YAML anchors.
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {
            "name": f"{graph.name}-{svc.name}",
            "namespace": graph.namespace,
            "labels": dict(labels),
        },
        "spec": {
            "replicas": svc.replicas,
            "selector": {"matchLabels": dict(labels)},
            "template": {"metadata": {"labels": dict(labels)}, "spec": pod_spec},
        },
    }


def _service(graph: GraphDeployment, svc: ServiceSpec) -> dict:
    labels = _labels(graph, svc.name)
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": f"{graph.name}-{svc.name}",
            "namespace": graph.namespace,
            "labels": dict(labels),
        },
        "spec": {
            "selector": dict(labels),
            "ports": [{"port": 8000, "targetPort": "http", "name": "http"}],
        },
    }


def render_manifests(
    graph: GraphDeployment,
    *,
    image: str = "dynamo-tpu:latest",
    tpu_accelerator: Optional[str] = None,
    tpu_topology: Optional[str] = None,
    expose: Optional[List[str]] = None,
) -> List[dict]:
    """Graph → [Deployment + (optional) Service per service]. ``expose``
    lists services that get a k8s Service (default: any named 'frontend')."""
    expose = expose if expose is not None else [n for n in graph.services if n == "frontend"]
    out: List[dict] = []
    for svc in graph.services.values():
        out.append(_deployment(graph, svc, image, tpu_accelerator, tpu_topology))
        if svc.name in expose:
            out.append(_service(graph, svc))
    return out


def render_yaml(graph: GraphDeployment, **kwargs) -> str:
    """Multi-document YAML ready for ``kubectl apply -f -``."""
    return "\n---\n".join(yaml.safe_dump(m, sort_keys=False) for m in render_manifests(graph, **kwargs))
