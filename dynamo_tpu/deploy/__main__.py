"""Deploy CLI: ``python -m dynamo_tpu.deploy {render,run} graph.yaml``.

- ``render`` — print Kubernetes manifests for the graph (pipe to
  ``kubectl apply -f -``); the reference's operator reconcile output.
- ``run``    — supervise the graph locally: spawn each service's replicas,
  restart crashes, SIGTERM drains on exit (single TPU-host deployments).
"""

from __future__ import annotations

import argparse
import asyncio

from dynamo_tpu.deploy.manifests import render_yaml
from dynamo_tpu.deploy.operator import LocalOperator
from dynamo_tpu.deploy.spec import GraphDeployment
from dynamo_tpu.runtime.logging import init_logging


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="dynamo_tpu.deploy")
    sub = p.add_subparsers(dest="cmd", required=True)
    r = sub.add_parser("render", help="render k8s manifests")
    r.add_argument("graph", help="graph deployment YAML path")
    r.add_argument("--image", default="dynamo-tpu:latest")
    r.add_argument("--tpu-accelerator", default=None, help="GKE node selector value")
    r.add_argument("--tpu-topology", default=None)
    c = sub.add_parser("cluster", help="render the DynamoGraphDeployment CRD + CR")
    c.add_argument("graph", help="graph deployment YAML path")
    u = sub.add_parser("run", help="supervise the graph locally")
    u.add_argument("graph", help="graph deployment YAML path")
    u.add_argument("--interval", type=float, default=1.0, help="reconcile interval seconds")
    return p


async def _run(graph: GraphDeployment, interval: float) -> None:
    import signal

    op = LocalOperator(graph)
    op.start(interval_s=interval)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    # SIGTERM (systemd/k8s stop) must drain children, same as ctrl-c.
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, stop.set)
    try:
        await stop.wait()
    finally:
        await op.shutdown()


def main() -> None:
    args = build_parser().parse_args()
    graph = GraphDeployment.load(args.graph)
    if args.cmd == "render":
        try:
            print(render_yaml(
                graph,
                image=args.image,
                tpu_accelerator=args.tpu_accelerator,
                tpu_topology=args.tpu_topology,
            ))
        except BrokenPipeError:  # e.g. piped into head
            pass
        return
    if args.cmd == "cluster":
        from dynamo_tpu.deploy.crd import render_cluster_yaml

        try:
            print(render_cluster_yaml(graph))
        except BrokenPipeError:
            pass
        return
    init_logging()
    try:
        asyncio.run(_run(graph, args.interval))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
