"""Graph deployment spec — the DynamoGraphDeployment CRD equivalent.

Ref: deploy/cloud/operator/api/v1alpha1 (DynamoGraphDeployment /
DynamoComponentDeployment CRDs): a named graph of services (frontend,
decode workers, prefill workers, planner, ...) each with a command,
replica count, resources, and environment. The same spec drives both the
local process operator (operator.py) and k8s manifest rendering
(manifests.py), so a graph tested on one TPU host deploys unchanged to a
cluster.

Example YAML::

    name: llama-8b-disagg
    namespace: dynamo
    control_plane: tcp://cp.dynamo.svc:6650
    services:
      frontend:
        command: [python, -m, dynamo_tpu.frontend, --router-mode, kv]
        replicas: 1
      decode:
        command: [python, -m, dynamo_tpu.worker, --model, llama-3-8b]
        replicas: 2
        resources: {tpu_chips: 4, memory: 32Gi}
      prefill:
        command: [python, -m, dynamo_tpu.worker, --model, llama-3-8b, --is-prefill-worker]
        replicas: 1
        resources: {tpu_chips: 4, memory: 32Gi}
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import yaml


@dataclass
class ResourceSpec:
    """Per-replica resource ask (TPU chips map to ``google.com/tpu``)."""

    tpu_chips: int = 0
    cpu: str = "1"
    memory: str = "2Gi"

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "ResourceSpec":
        d = d or {}
        return cls(
            tpu_chips=int(d.get("tpu_chips", 0)),
            cpu=str(d.get("cpu", "1")),
            memory=str(d.get("memory", "2Gi")),
        )

    def to_dict(self) -> dict:
        return {"tpu_chips": self.tpu_chips, "cpu": self.cpu, "memory": self.memory}


@dataclass
class ServiceSpec:
    """One service in the graph (ref: DynamoComponentDeployment)."""

    name: str
    command: List[str]
    replicas: int = 1
    resources: ResourceSpec = field(default_factory=ResourceSpec)
    env: Dict[str, str] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, name: str, d: dict) -> "ServiceSpec":
        if not d.get("command"):
            raise ValueError(f"service {name!r}: command is required")
        return cls(
            name=name,
            command=[str(c) for c in d["command"]],
            replicas=int(d.get("replicas", 1)),
            resources=ResourceSpec.from_dict(d.get("resources")),
            env={k: str(v) for k, v in (d.get("env") or {}).items()},
        )

    def to_dict(self) -> dict:
        return {
            "command": list(self.command),
            "replicas": self.replicas,
            "resources": self.resources.to_dict(),
            "env": dict(self.env),
        }


@dataclass
class GraphDeployment:
    """A complete serving graph (ref: DynamoGraphDeployment CRD)."""

    name: str
    services: Dict[str, ServiceSpec]
    namespace: str = "dynamo"
    control_plane: str = ""  # e.g. tcp://host:6650; empty = per-process mem

    @classmethod
    def from_dict(cls, d: dict) -> "GraphDeployment":
        if not d.get("name"):
            raise ValueError("graph deployment needs a name")
        services = {
            name: ServiceSpec.from_dict(name, sd) for name, sd in (d.get("services") or {}).items()
        }
        if not services:
            raise ValueError(f"graph {d['name']!r} has no services")
        return cls(
            name=str(d["name"]),
            services=services,
            namespace=str(d.get("namespace", "dynamo")),
            control_plane=str(d.get("control_plane", "")),
        )

    @classmethod
    def from_yaml(cls, text: str) -> "GraphDeployment":
        return cls.from_dict(yaml.safe_load(text))

    @classmethod
    def load(cls, path: str) -> "GraphDeployment":
        with open(path) as f:
            return cls.from_yaml(f.read())

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "namespace": self.namespace,
            "control_plane": self.control_plane,
            "services": {n: s.to_dict() for n, s in self.services.items()},
        }

    def to_yaml(self) -> str:
        return yaml.safe_dump(self.to_dict(), sort_keys=False)

    def base_env(self) -> Dict[str, str]:
        """Environment every service gets: namespace + control plane."""
        env = {"DYN_NAMESPACE": self.namespace}
        if self.control_plane:
            scheme, sep, address = self.control_plane.partition("://")
            if not sep:  # schemeless "host:port" → default tcp backend
                scheme, address = "tcp", self.control_plane
            env["DYN_CONTROL_PLANE"] = scheme
            env["DYN_CONTROL_PLANE_ADDRESS"] = address
        return env
