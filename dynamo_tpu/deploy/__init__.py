"""Deployment tooling (ref: deploy/cloud — the Go k8s operator + CRDs).

- :mod:`spec`      — ``GraphDeployment``: the DynamoGraphDeployment-CRD
  equivalent, a declarative multi-service serving graph in YAML.
- :mod:`manifests` — render a GraphDeployment to Kubernetes manifests
  (what the reference operator's reconcile loop materializes).
- :mod:`operator`  — a local process-supervising reconciler: desired
  replicas → running OS processes, with crash restart and graceful
  scale-down; the planner scales it through ``GraphConnector``.
"""

from dynamo_tpu.deploy.manifests import render_manifests  # noqa: F401
from dynamo_tpu.deploy.operator import GraphConnector, LocalOperator  # noqa: F401
from dynamo_tpu.deploy.spec import GraphDeployment, ResourceSpec, ServiceSpec  # noqa: F401
