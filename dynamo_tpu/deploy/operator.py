"""Local graph operator: reconcile desired replicas → running OS processes.

Ref: deploy/cloud/operator (Go) — the controller that reconciles
DynamoGraphDeployment state; here scoped to one host (a TPU VM), which is
also how the planner e2e path runs a real scaling loop without a cluster.

Semantics:
- ``reconcile()`` spawns/terminates child processes until each service's
  live count matches its spec.
- Crashed children are detected on the next reconcile tick and respawned
  (up to ``max_restarts`` per service within the backoff window; then the
  service is marked degraded — visible in ``status()``).
- Scale-down terminates newest-first with SIGTERM, escalating to SIGKILL
  after ``grace_s`` (the graceful-drain window; workers drain in-flight
  requests on SIGTERM via runtime signal handlers).

The planner drives this through :class:`GraphConnector` (the same
``Connector`` interface as the kubectl/virtual connectors).
"""

from __future__ import annotations

import asyncio
import os
import signal
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from dynamo_tpu.deploy.spec import GraphDeployment
from dynamo_tpu.planner.connectors import Connector
from dynamo_tpu.runtime.logging import get_logger

logger = get_logger(__name__)


@dataclass
class _Child:
    proc: asyncio.subprocess.Process
    started_at: float = field(default_factory=time.monotonic)

    @property
    def alive(self) -> bool:
        return self.proc.returncode is None


class LocalOperator:
    def __init__(
        self,
        graph: GraphDeployment,
        *,
        grace_s: float = 10.0,
        max_restarts: int = 3,
        restart_window_s: float = 60.0,
    ):
        self.graph = graph
        self.grace_s = grace_s
        self.max_restarts = max_restarts
        self.restart_window_s = restart_window_s
        self._children: Dict[str, List[_Child]] = {name: [] for name in graph.services}
        self._restarts: Dict[str, List[float]] = {name: [] for name in graph.services}
        self._task: Optional[asyncio.Task] = None
        self._stop = asyncio.Event()
        # Serializes reconcile(): the background tick and planner-driven
        # GraphConnector calls must not interleave mid-spawn (over-spawning
        # would double-book TPU chips until the next tick corrects it).
        self._lock = asyncio.Lock()

    # --- desired state ------------------------------------------------------
    def set_replicas(self, service: str, replicas: int) -> None:
        if service not in self.graph.services:
            raise KeyError(f"unknown service {service!r}")
        self.graph.services[service].replicas = max(0, int(replicas))

    def status(self) -> Dict[str, dict]:
        return {
            name: {
                "desired": spec.replicas,
                "live": sum(c.alive for c in self._children[name]),
                "degraded": self._degraded(name),
            }
            for name, spec in self.graph.services.items()
        }

    def _degraded(self, service: str) -> bool:
        cutoff = time.monotonic() - self.restart_window_s
        # Prune outside the window so the list stays O(max_restarts) for
        # long-lived crash-looping services.
        self._restarts[service] = [t for t in self._restarts[service] if t > cutoff]
        return len(self._restarts[service]) >= self.max_restarts

    # --- reconcile ----------------------------------------------------------
    async def reconcile(self) -> None:
        async with self._lock:
            if self._stop.is_set():
                return  # shutting down: no further spawns
            for name, spec in self.graph.services.items():
                try:
                    await self._reconcile_service(name, spec)
                except Exception:
                    # One service failing to spawn (bad command, resources)
                    # must not starve the rest; count it toward the crash
                    # window so a persistent failure degrades instead of
                    # log-spamming forever.
                    self._restarts[name].append(time.monotonic())
                    logger.exception("reconcile of %s/%s failed", self.graph.name, name)

    async def _reconcile_service(self, name: str, spec) -> None:
        children = self._children[name]
        # Reap the dead; count them as restarts-needed.
        dead = [c for c in children if not c.alive]
        for c in dead:
            children.remove(c)
            self._restarts[name].append(time.monotonic())
            logger.warning("%s/%s exited rc=%s", self.graph.name, name, c.proc.returncode)
        if self._degraded(name):
            return  # crash-looping: hold off until the window clears
        while sum(c.alive for c in children) < spec.replicas:
            children.append(await self._spawn(name))
        excess = sum(c.alive for c in children) - spec.replicas
        if excess > 0:
            victims = [c for c in children if c.alive][-excess:]
            await asyncio.gather(*(self._terminate(name, c) for c in victims))
            for c in victims:
                if c in children:
                    children.remove(c)

    async def _spawn(self, service: str) -> _Child:
        spec = self.graph.services[service]
        env = {**os.environ, **self.graph.base_env(), **spec.env}
        # Children inherit our stdout/stderr: under systemd or piped logging
        # the workers' output flows through the supervisor's redirection
        # instead of vanishing into DEVNULL.
        proc = await asyncio.create_subprocess_exec(*spec.command, env=env)
        logger.info("%s/%s spawned pid=%d", self.graph.name, service, proc.pid)
        return _Child(proc=proc)

    async def _terminate(self, service: str, child: _Child) -> None:
        if not child.alive:
            return
        child.proc.send_signal(signal.SIGTERM)  # graceful drain window
        try:
            await asyncio.wait_for(child.proc.wait(), timeout=self.grace_s)
        except asyncio.TimeoutError:
            logger.warning("%s/%s pid=%d did not drain; killing", self.graph.name, service, child.proc.pid)
            child.proc.kill()
            await child.proc.wait()

    # --- run loop -----------------------------------------------------------
    def start(self, interval_s: float = 1.0) -> None:
        async def loop():
            while not self._stop.is_set():
                try:
                    await self.reconcile()
                except Exception:
                    logger.exception("reconcile failed")
                try:
                    await asyncio.wait_for(self._stop.wait(), timeout=interval_s)
                except asyncio.TimeoutError:
                    pass

        self._task = asyncio.get_running_loop().create_task(loop())

    async def shutdown(self) -> None:
        self._stop.set()
        if self._task is not None:
            await self._task
            self._task = None
        # Under the lock: a concurrent planner-driven reconcile must not
        # respawn children we are terminating.
        async with self._lock:
            for name, children in self._children.items():
                await asyncio.gather(*(self._terminate(name, c) for c in children))
                children.clear()


class GraphConnector(Connector):
    """Planner-facing adapter: SLA/load planner decisions land on the local
    operator exactly as KubernetesConnector lands them on a DGD."""

    def __init__(self, operator: LocalOperator):
        self.operator = operator

    async def set_replicas(self, component: str, replicas: int) -> None:
        self.operator.set_replicas(component, replicas)
        await self.operator.reconcile()

    async def get_replicas(self, component: str) -> int:
        return self.operator.graph.services[component].replicas
