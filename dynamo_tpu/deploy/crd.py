"""DynamoGraphDeployment CRD: schema + custom-resource round-trip.

Ref: deploy/cloud/operator/api/v1alpha1 (DynamoGraphDeployment /
DynamoComponentDeployment Go types). The reference ships a ~17k-LoC Go
operator; the TPU build keeps the cluster contract — the CRD schema and
the CR shape — declarative and language-neutral:

- :func:`crd_manifest` emits the CustomResourceDefinition (openAPIV3Schema
  validating the graph spec) for ``kubectl apply``.
- :func:`graph_to_cr` / :func:`cr_to_graph` convert between the local
  :class:`GraphDeployment` spec and the cluster CR, so a graph tested with
  the local process operator (operator.py) deploys unchanged.
- The planner's :class:`~dynamo_tpu.planner.connectors.KubernetesConnector`
  scales either the CR's per-service replicas (an in-cluster controller
  reconciles) or the rendered Deployments directly (manifests.py path,
  no controller needed).
"""

from __future__ import annotations

from typing import List

import yaml

from dynamo_tpu.deploy.spec import GraphDeployment, ResourceSpec, ServiceSpec

GROUP = "dynamo.tpu.io"
VERSION = "v1alpha1"
KIND = "DynamoGraphDeployment"
PLURAL = "dynamographdeployments"


def crd_manifest() -> dict:
    """CustomResourceDefinition for DynamoGraphDeployment."""
    service_schema = {
        "type": "object",
        "required": ["command"],
        "properties": {
            "command": {"type": "array", "items": {"type": "string"}},
            "replicas": {"type": "integer", "minimum": 0, "default": 1},
            "env": {"type": "object", "additionalProperties": {"type": "string"}},
            "resources": {
                "type": "object",
                "properties": {
                    "tpu_chips": {"type": "integer", "minimum": 0},
                    "cpu": {"type": "string"},
                    "memory": {"type": "string"},
                },
            },
        },
    }
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"{PLURAL}.{GROUP}"},
        "spec": {
            "group": GROUP,
            "names": {
                "kind": KIND,
                "plural": PLURAL,
                "singular": "dynamographdeployment",
                "shortNames": ["dgd"],
            },
            "scope": "Namespaced",
            "versions": [
                {
                    "name": VERSION,
                    "served": True,
                    "storage": True,
                    "subresources": {"status": {}},
                    "schema": {
                        "openAPIV3Schema": {
                            "type": "object",
                            "properties": {
                                "spec": {
                                    "type": "object",
                                    "required": ["services"],
                                    "properties": {
                                        "control_plane": {"type": "string"},
                                        "services": {
                                            "type": "object",
                                            "additionalProperties": service_schema,
                                        },
                                    },
                                },
                                "status": {
                                    "type": "object",
                                    "properties": {
                                        "phase": {"type": "string"},
                                        "ready_replicas": {
                                            "type": "object",
                                            "additionalProperties": {"type": "integer"},
                                        },
                                    },
                                },
                            },
                        }
                    },
                }
            ],
        },
    }


def graph_to_cr(graph: GraphDeployment) -> dict:
    """GraphDeployment spec → DynamoGraphDeployment custom resource."""
    services = {}
    for svc in graph.services.values():
        services[svc.name] = {
            "command": list(svc.command),
            "replicas": svc.replicas,
            "env": dict(svc.env),
            "resources": {
                "tpu_chips": svc.resources.tpu_chips,
                "cpu": svc.resources.cpu,
                "memory": svc.resources.memory,
            },
        }
    return {
        "apiVersion": f"{GROUP}/{VERSION}",
        "kind": KIND,
        "metadata": {"name": graph.name, "namespace": graph.namespace},
        "spec": {"control_plane": graph.control_plane or "", "services": services},
    }


def cr_to_graph(cr: dict) -> GraphDeployment:
    """DynamoGraphDeployment CR → GraphDeployment (inverse of graph_to_cr)."""
    if cr.get("kind") != KIND:
        raise ValueError(f"not a {KIND}: kind={cr.get('kind')!r}")
    meta = cr.get("metadata") or {}
    spec = cr.get("spec") or {}
    services = {}
    for name, s in (spec.get("services") or {}).items():
        services[name] = ServiceSpec(
            name=name,
            command=list(s.get("command") or []),
            replicas=int(s.get("replicas", 1)),
            env=dict(s.get("env") or {}),
            resources=ResourceSpec.from_dict(s.get("resources")),
        )
    return GraphDeployment(
        name=meta.get("name", "graph"),
        namespace=meta.get("namespace", "default"),
        control_plane=spec.get("control_plane") or "",
        services=services,
    )


def render_cluster_yaml(graph: GraphDeployment) -> str:
    """CRD + CR multi-document YAML (``kubectl apply -f -``)."""
    docs: List[dict] = [crd_manifest(), graph_to_cr(graph)]
    return "\n---\n".join(yaml.safe_dump(d, sort_keys=False) for d in docs)
