"""In-cluster reconcile loop for DynamoGraphDeployment CRs.

The role the reference's ~17k-LoC Go operator plays
(deploy/cloud/operator/internal/controller/): watch DGD custom resources,
drive the cluster toward their spec by creating/scaling/deleting the
per-service Deployments that manifests.py renders, and write observed
state back to each CR's status. kubectl is the only cluster client — the
binary is injectable exactly like planner/connectors.KubernetesConnector,
so tests run the full create→scale→delete→status loop against a stub.

Reconcile semantics per DGD:
- missing Deployment            → ``kubectl apply`` the rendered manifest
- rendered-manifest drift       → apply again (server-side merge). Drift is
  detected by a hash of the FULL rendered manifest carried in an
  annotation — image, env, resource, and command changes all re-apply,
  not just ``spec.replicas`` — plus a live-replicas check so out-of-band
  ``kubectl scale`` is reverted even though it leaves the annotation
  intact
- Deployment labeled for this graph but absent from its spec → delete
- status merge-patched onto the CR: per-service desired/ready counts and
  a Ready condition (the reference writes status conditions the same way)

Orphan sweep: Deployments carrying the operator's managed-by label whose
graph CR no longer exists are deleted — CR deletion tears the graph down
even without ownerReference GC.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import shutil
from typing import Dict, List, Optional

from dynamo_tpu.deploy.crd import cr_to_graph
from dynamo_tpu.deploy.manifests import render_manifests
from dynamo_tpu.runtime.logging import get_logger

logger = get_logger(__name__)

MANAGED_BY = "dynamo-tpu-operator"
HASH_ANNOTATION = "dynamo-tpu/manifest-hash"


def manifest_hash(man: dict) -> str:
    """Stable digest of a rendered manifest (computed BEFORE the hash
    annotation itself is attached)."""
    return hashlib.sha256(json.dumps(man, sort_keys=True).encode()).hexdigest()[:16]


class KubeReconciler:
    def __init__(
        self,
        namespace: str = "dynamo",
        *,
        image: str = "dynamo-tpu:latest",
        kubectl_cmd: Optional[List[str]] = None,
        interval_s: float = 5.0,
    ):
        self.kubectl = list(kubectl_cmd) if kubectl_cmd else ["kubectl"]
        if kubectl_cmd is None and shutil.which("kubectl") is None:
            raise RuntimeError("kubectl not found in PATH")
        self.namespace = namespace
        self.image = image
        self.interval_s = interval_s
        self._task: Optional[asyncio.Task] = None
        self.reconcile_count = 0

    # --- kubectl plumbing ---------------------------------------------------
    async def _run(self, *args: str, stdin: Optional[str] = None) -> str:
        proc = await asyncio.create_subprocess_exec(
            *self.kubectl, "-n", self.namespace, *args,
            stdin=asyncio.subprocess.PIPE if stdin is not None else None,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.PIPE,
        )
        out, err = await proc.communicate(stdin.encode() if stdin is not None else None)
        if proc.returncode != 0:
            raise RuntimeError(f"kubectl {' '.join(args[:3])}...: {err.decode().strip()[-300:]}")
        return out.decode()

    async def _get_json(self, *args: str) -> dict:
        out = await self._run(*args, "-o", "json")
        return json.loads(out or "{}")

    # --- reconcile ----------------------------------------------------------
    async def reconcile_once(self) -> Dict[str, dict]:
        """One pass over every DGD CR. Returns {graph: status} as written."""
        dgds = (await self._get_json("get", "dynamographdeployments")).get("items", [])
        live = (await self._get_json(
            "get", "deployments", "-l", f"app.kubernetes.io/managed-by={MANAGED_BY}"
        )).get("items", [])
        by_name = {d["metadata"]["name"]: d for d in live}
        claimed: set = set()
        statuses: Dict[str, dict] = {}

        for cr in dgds:
            graph = cr_to_graph(cr)
            desired = [
                m for m in render_manifests(graph, image=self.image)
                if m.get("kind") == "Deployment"
            ]
            status_services = {}
            for man in desired:
                man["metadata"].setdefault("labels", {})["app.kubernetes.io/managed-by"] = MANAGED_BY
                man["metadata"]["labels"]["dynamo-graph"] = graph.name
                name = man["metadata"]["name"]
                claimed.add(name)
                want_hash = manifest_hash(man)
                man["metadata"].setdefault("annotations", {})[HASH_ANNOTATION] = want_hash
                existing = by_name.get(name)
                want = man["spec"]["replicas"]
                if existing is None:
                    await self._run("apply", "-f", "-", stdin=json.dumps(man))
                    logger.info("created deployment %s (graph %s)", name, graph.name)
                    ready = 0
                elif (
                    existing["metadata"].get("annotations", {}).get(HASH_ANNOTATION)
                    != want_hash
                    or existing["spec"].get("replicas") != want
                ):
                    # Any rendered drift (image/env/resources/command, not
                    # just replicas) OR live replica drift re-applies.
                    await self._run("apply", "-f", "-", stdin=json.dumps(man))
                    logger.info("re-applied drifted deployment %s", name)
                    ready = int(existing.get("status", {}).get("readyReplicas") or 0)
                else:
                    ready = int(existing.get("status", {}).get("readyReplicas") or 0)
                svc = name.split(f"{graph.name}-", 1)[-1]
                status_services[svc] = {"desired": want, "ready": ready}

            all_ready = all(s["ready"] >= s["desired"] for s in status_services.values())
            status = {
                "services": status_services,
                "conditions": [{
                    "type": "Ready",
                    "status": "True" if all_ready else "False",
                    "reason": "AllReplicasReady" if all_ready else "Reconciling",
                }],
            }
            await self._run(
                "patch", "dynamographdeployment", cr["metadata"]["name"],
                "--type=merge", "-p", json.dumps({"status": status}),
            )
            statuses[graph.name] = status

        # Orphans: managed Deployments whose graph CR is gone (or whose
        # service left the spec).
        for name, dep in by_name.items():
            if name not in claimed:
                await self._run("delete", "deployment", name)
                logger.info("deleted orphan deployment %s", name)

        self.reconcile_count += 1
        return statuses

    # --- loop ---------------------------------------------------------------
    def start(self) -> None:
        async def loop():
            while True:
                try:
                    await self.reconcile_once()
                except Exception as e:  # noqa: BLE001 — the loop must survive
                    logger.warning("reconcile failed: %s", e)
                await asyncio.sleep(self.interval_s)

        self._task = asyncio.get_running_loop().create_task(loop(), name="kube-reconciler")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
