"""Namespace → Component → Endpoint hierarchy with lease-bound discovery.

Ref: lib/runtime/src/component.rs — roots :75-78, ``Component`` :120,
``Endpoint`` :358, ``subject_to`` :492-503, ``Namespace`` :520, ``Instance``
:98; component/endpoint.rs (EndpointConfigBuilder → serving), component/
service.rs.

Discovery contract (identical to the reference's):
- instance key   ``instances/{ns}/{comp}/{ep}:{lease_id:x}`` → Instance JSON,
  bound to the worker's lease (lease lapse ⇒ key vanishes ⇒ routers prune).
- request subject ``rq.{ns}.{comp}.{ep}.{lease_id:x}`` — one subject per
  instance; the push router publishes requests here with TCP call-home info.
- control subject ``ctl.{ns}.{comp}.{ep}.{lease_id:x}`` — cancellation et al.
- stats subject   ``stats.{ns}.{comp}.{ep}.{lease_id:x}`` — request/reply
  stats scrape (ref: component.rs:280-334 NATS service stats).
"""

from __future__ import annotations

import asyncio
import json
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Callable, Dict, Optional, TYPE_CHECKING

import msgpack

from dynamo_tpu.runtime import faults
from dynamo_tpu.runtime.engine import Annotated, AsyncEngine, Context
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.tracing import NULL_SPAN, get_tracer
from dynamo_tpu.runtime.transports.tcp import ConnectionInfo, TcpCallHome

if TYPE_CHECKING:
    from dynamo_tpu.runtime.distributed import DistributedRuntime

logger = get_logger(__name__)

INSTANCE_ROOT = "instances"


def sanitize(token: str) -> str:
    return token.replace(".", "_").replace("/", "_")


@dataclass(frozen=True)
class Instance:
    """A live endpoint instance (ref: component.rs:98)."""

    namespace: str
    component: str
    endpoint: str
    instance_id: int  # the lease id

    @property
    def etcd_key(self) -> str:
        return f"{INSTANCE_ROOT}/{self.namespace}/{self.component}/{self.endpoint}:{self.instance_id:x}"

    @property
    def subject(self) -> str:
        return f"rq.{sanitize(self.namespace)}.{sanitize(self.component)}.{sanitize(self.endpoint)}.{self.instance_id:x}"

    @property
    def control_subject(self) -> str:
        return f"ctl.{sanitize(self.namespace)}.{sanitize(self.component)}.{sanitize(self.endpoint)}.{self.instance_id:x}"

    @property
    def stats_subject(self) -> str:
        return f"stats.{sanitize(self.namespace)}.{sanitize(self.component)}.{sanitize(self.endpoint)}.{self.instance_id:x}"

    def to_json(self) -> bytes:
        return json.dumps(
            {
                "namespace": self.namespace,
                "component": self.component,
                "endpoint": self.endpoint,
                "instance_id": self.instance_id,
            }
        ).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "Instance":
        d = json.loads(raw)
        return cls(
            namespace=d["namespace"],
            component=d["component"],
            endpoint=d["endpoint"],
            instance_id=int(d["instance_id"]),
        )


class Namespace:
    def __init__(self, drt: "DistributedRuntime", name: str):
        self.drt = drt
        self.name = name

    def component(self, name: str) -> "Component":
        return Component(self.drt, self.name, name)


class Component:
    def __init__(self, drt: "DistributedRuntime", namespace: str, name: str):
        self.drt = drt
        self.namespace = namespace
        self.name = name

    def endpoint(self, name: str) -> "Endpoint":
        return Endpoint(self.drt, self.namespace, self.name, name)

    async def create_service(self) -> None:
        """No-op placeholder kept for API parity with the reference's NATS
        service creation (service registration happens per-endpoint here)."""
        return None

    @property
    def instance_prefix(self) -> str:
        return f"{INSTANCE_ROOT}/{self.namespace}/{self.name}/"


class ServeHandle:
    """A running endpoint instance: owns the lease keepalive + ingress loop."""

    def __init__(self, endpoint: "Endpoint", instance: Instance, lease, tasks,
                 ingress: Optional["_PushEndpoint"] = None):
        self.endpoint = endpoint
        self.instance = instance
        self.lease = lease
        self._tasks = tasks
        self._ingress = ingress
        self._stopped = False

    @property
    def draining(self) -> bool:
        return self._ingress.draining if self._ingress is not None else False

    async def stop(self, *, drain: bool = True, timeout_s: Optional[float] = None) -> None:
        """The drain lifecycle (planner scale-down's primitive, and the
        SIGTERM / POST /drain path):

        1. deregister from discovery — routers prune within one watch
           delivery and stop sending;
        2. stop admitting — requests already queued on the pub/sub subject
           are answered with a disconnect error, which the client's
           Migration operator replays on a surviving worker;
        3. finish in-flight work within ``timeout_s`` (default
           ``shutdown_timeout_s``) — on timeout the remaining streams are
           severed (task cancel drops the call-home sockets without a
           final frame), which *migrates* them instead of finishing them;
        4. revoke the lease.

        The wait is scoped to THIS instance's in-flight requests: in a
        multi-worker process (autoscaled mocker fleets, demo stacks) the
        runtime-global shutdown tracker never reaches zero under sustained
        fleet traffic, which turned every one-worker scale-down drain into
        a guaranteed full-timeout stall.
        """
        if self._stopped:
            return
        self._stopped = True
        drt = self.endpoint.drt
        timeout = (
            timeout_s if timeout_s is not None
            else drt.runtime.config.runtime.shutdown_timeout_s
        )
        # Deregister first so routers stop sending, then drain, then drop tasks.
        await drt.store.delete(self.instance.etcd_key)
        drt.local_engines.pop(self.instance.instance_id, None)
        if drain:
            if self._ingress is not None:
                self._ingress.begin_drain()
                drained = await self._ingress.wait_drained(timeout)
            else:
                drained = await drt.runtime.shutdown_tracker.wait_drained(timeout)
            if not drained:
                logger.warning(
                    "drain of %x timed out with %d in-flight; severing streams "
                    "(clients will migrate)",
                    self.instance.instance_id,
                    len(self._ingress.in_flight) if self._ingress is not None
                    else drt.runtime.shutdown_tracker.in_flight,
                )
                if self._ingress is not None:
                    await self._ingress.sever()
            if self._ingress is not None:
                self._ingress.finish_drain()
        for t in self._tasks:
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        await self.lease.revoke()


class Endpoint:
    """An addressable unit of work (ref: component.rs:358)."""

    def __init__(self, drt: "DistributedRuntime", namespace: str, component: str, name: str):
        self.drt = drt
        self.namespace = namespace
        self.component = component
        self.name = name

    @property
    def path(self) -> str:
        return f"{self.namespace}/{self.component}/{self.name}"

    @property
    def instance_prefix(self) -> str:
        return f"{INSTANCE_ROOT}/{self.namespace}/{self.component}/{self.name}:"

    async def client(self, **kwargs) -> "Client":
        from dynamo_tpu.runtime.client import Client

        client = Client(self)
        await client.start(**kwargs)
        return client

    async def serve_endpoint(
        self,
        handler: AsyncEngine | Callable[[Any, Context], AsyncIterator[Any]],
        *,
        stats_handler: Optional[Callable[[], dict]] = None,
        graceful_shutdown: bool = True,
        lease_ttl_s: Optional[float] = None,
    ) -> ServeHandle:
        """Register and serve this endpoint (ref: component/endpoint.rs
        EndpointConfigBuilder.start).

        ``handler`` is an AsyncEngine or a bare async-generator function
        ``(request, context) -> AsyncIterator``.
        """
        drt = self.drt
        engine = handler if isinstance(handler, AsyncEngine) else _FnEngine(handler)
        ttl = lease_ttl_s if lease_ttl_s is not None else drt.config.control_plane.lease_ttl_s
        lease = await drt.store.grant_lease(ttl)
        drt.spawn_lease_keepalive(lease)
        instance = Instance(self.namespace, self.component, self.name, lease.id)

        ingress = _PushEndpoint(drt, instance, engine, graceful_shutdown=graceful_shutdown)
        tasks = await ingress.start(stats_handler=stats_handler)

        # In-process fast path: callers in this process bypass pub/sub + TCP.
        drt.local_engines[instance.instance_id] = engine

        # Register last: the instance only becomes routable once it can serve.
        await drt.store.put(instance.etcd_key, instance.to_json(), lease_id=lease.id)
        logger.info("serving endpoint %s as instance %x", self.path, lease.id)
        handle = ServeHandle(self, instance, lease, tasks, ingress=ingress)
        drt.serve_handles.append(handle)
        return handle


class _FnEngine:
    def __init__(self, fn):
        self._fn = fn

    def generate(self, request, context):
        return self._fn(request, context)


class _PushEndpoint:
    """Worker-side ingress loop (ref: pipeline/network/ingress/push_endpoint.rs:21-164,
    push_handler.rs). Consumes pushed requests, runs the handler, streams
    responses back over the TCP call-home channel."""

    def __init__(self, drt: "DistributedRuntime", instance: Instance, engine: AsyncEngine, graceful_shutdown: bool):
        self.drt = drt
        self.instance = instance
        self.engine = engine
        self.graceful_shutdown = graceful_shutdown
        self.in_flight: Dict[str, Context] = {}
        # Drain lifecycle: while draining, newly pushed requests are
        # answered with a disconnect error (the client migrates) instead of
        # being admitted. drains_total counts completed drains (0 or 1 for
        # a worker process; scrape-visible while the drain runs).
        self.draining = False
        self.drains_total = 0

        self._request_tasks: set = set()

    def begin_drain(self) -> None:
        self.draining = True
        logger.info("instance %x draining: rejecting new work, %d in-flight",
                    self.instance.instance_id, len(self.in_flight))

    def finish_drain(self) -> None:
        self.drains_total += 1

    async def wait_drained(self, timeout: Optional[float] = None) -> bool:
        """Wait for THIS instance's in-flight handlers to finish. Scoped to
        the instance (not the runtime-global shutdown tracker) so a
        one-worker drain in a multi-worker process completes as soon as
        *its* streams end, however busy the rest of the fleet is."""
        deadline = None if timeout is None else asyncio.get_running_loop().time() + timeout
        while self._request_tasks:
            remaining = None
            if deadline is not None:
                remaining = deadline - asyncio.get_running_loop().time()
                if remaining <= 0:
                    return False
            _, pending = await asyncio.wait(set(self._request_tasks), timeout=remaining)
            if pending:
                return False
        return True

    async def sever(self) -> None:
        """Cancel the remaining in-flight handler tasks: each drops its
        call-home socket without a final frame, so the client observes a
        genuine StreamDisconnect and its Migration operator replays the
        request on a surviving worker."""
        tasks = list(self._request_tasks)
        for t in tasks:
            t.cancel()
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)

    async def start(self, stats_handler=None) -> list:
        sub = await self.drt.bus.subscribe(self.instance.subject)
        ctl = await self.drt.bus.subscribe(self.instance.control_subject)
        stats_sub = await self.drt.bus.subscribe(self.instance.stats_subject)
        loop = asyncio.get_running_loop()
        tasks = [
            loop.create_task(self._ingress_loop(sub), name=f"ingress-{self.instance.instance_id:x}"),
            loop.create_task(self._control_loop(ctl), name=f"ctl-{self.instance.instance_id:x}"),
            loop.create_task(self._stats_loop(stats_sub, stats_handler), name=f"stats-{self.instance.instance_id:x}"),
        ]
        return tasks

    async def _ingress_loop(self, sub) -> None:
        async for msg in sub:
            try:
                payload = msgpack.unpackb(msg.data, raw=False)
            except Exception:
                # A malformed message must never kill the ingress loop — the
                # instance would stay registered but unreachable.
                logger.warning("dropping malformed request on %s", self.instance.subject)
                continue
            handler = self._reject_draining if self.draining else self._handle
            task = asyncio.get_running_loop().create_task(handler(payload))
            # Hold a strong reference: the loop keeps only weak refs to tasks.
            self._request_tasks.add(task)
            task.add_done_callback(self._request_tasks.discard)

    async def _reject_draining(self, payload: dict) -> None:
        """A request raced the drain (queued on the subject before the
        deregistration propagated): answer with a disconnect error so the
        caller's Migration operator replays it on a surviving worker."""
        conn = payload.get("conn")
        try:
            call_home = TcpCallHome(ConnectionInfo.from_dict(conn))
            if await call_home.connect():
                await call_home.error("worker draining", disconnect=True)
                await call_home.close()
        except (ConnectionError, TypeError, KeyError):
            pass  # caller is gone or the payload is malformed — nothing to do

    async def _control_loop(self, sub) -> None:
        async for msg in sub:
            try:
                payload = msgpack.unpackb(msg.data, raw=False)
            except Exception:
                continue
            op = payload.get("op")
            if op == "set_dial":
                # Elastic capacity dial: re-split this worker's prefill vs
                # decode budget live (engine.set_capacity_dial → scheduler).
                # Ack over reply_to when the caller wants the applied values.
                dial = getattr(self.engine, "set_capacity_dial", None)
                if dial is None:
                    logger.warning("set_dial received but engine exposes no capacity dial")
                    continue
                try:
                    applied = dial(float(payload.get("prefill_fraction", 0.5)))
                    logger.info("set_dial applied on %s: %s", self.instance.endpoint, applied)
                except Exception as e:
                    logger.exception("set_dial failed")
                    applied = {"error": str(e)}
                if msg.reply_to:
                    await self.drt.bus.publish(
                        msg.reply_to, msgpack.packb(applied, use_bin_type=True)
                    )
                continue
            if op in ("cancel", "kill"):
                ctx = self.in_flight.get(payload.get("request_id", ""))
                if ctx is not None:
                    logger.info("%s received for request %s", op, payload.get("request_id"))
                    if op == "kill":
                        # Hard abandon: the handler breaks out mid-stream.
                        ctx.kill()
                    else:
                        # Graceful: the engine aborts the sequence, frees
                        # its KV, and closes the stream with a final
                        # finish_reason="cancelled" frame — the client
                        # observes a clean end, not an error.
                        ctx.stop_generating()

    async def _stats_loop(self, sub, stats_handler) -> None:
        async for msg in sub:
            if msg.reply_to:
                if faults.armed():
                    try:
                        await faults.afire(
                            "stats.reply", instance=f"{self.instance.instance_id:x}"
                        )
                    except faults.InjectedFault:
                        continue  # scrape blackout: the scraper times out
                data = {
                    "in_flight": len(self.in_flight),
                    # Drain lifecycle: visible on the scrape while it runs
                    # (the planner's scale-down signal that a shrink was
                    # coordinated, not a crash).
                    "draining": 1.0 if self.draining else 0.0,
                    "worker_drains_total": self.drains_total,
                }
                if stats_handler is not None:
                    try:
                        data.update(stats_handler() or {})
                    except Exception as e:  # stats must never break serving
                        data["stats_error"] = str(e)
                await self.drt.bus.publish(msg.reply_to, msgpack.packb(data, use_bin_type=True))

    async def _handle(self, payload: dict) -> None:
        ctx = Context.from_wire(payload.get("ctx", {}))
        conn = payload.get("conn")
        request = payload.get("request")
        self.in_flight[ctx.id] = ctx
        # Worker-side hop span: continues the caller's trace (the wire
        # traceparent) and re-roots the context so engine/scheduler events
        # parent under this instance's span.
        span = get_tracer().span_from(
            "worker_handle", ctx.traceparent, service="worker",
            endpoint=self.instance.endpoint, instance=f"{self.instance.instance_id:x}",
            request_id=ctx.id,
        )
        if span is not NULL_SPAN:
            ctx.traceparent = span.child_traceparent()
        tracker = self.drt.runtime.shutdown_tracker
        if self.graceful_shutdown:
            tracker.enter()
        call_home: Optional[TcpCallHome] = None
        try:
            call_home = TcpCallHome(ConnectionInfo.from_dict(conn))
            ok = await call_home.connect()
            if not ok:
                return  # caller is gone; drop the request
            try:
                frame_i = 0
                async for item in self.engine.generate(request, ctx):
                    if ctx.is_killed():
                        break
                    if faults.armed():
                        # Chaos plane, per response frame: stream_drop
                        # raises (handled below — the socket is severed
                        # without a final frame, a genuine mid-stream
                        # death); hang/slow sleep inside afire.
                        frame_i += 1
                        await faults.afire(
                            "worker.frame",
                            instance=f"{self.instance.instance_id:x}",
                            request_id=ctx.id, frame=frame_i,
                            trace_id=getattr(ctx.traceparent, "trace_id", None),
                        )
                    wire = item.to_wire() if isinstance(item, Annotated) else {"data": item}
                    await call_home.send(wire)
                if ctx.is_killed():
                    await call_home.error("request cancelled")
                else:
                    await call_home.complete()
            except faults.InjectedFault:
                # Injected mid-stream death: identical observable semantics
                # to the ConnectionError branch below — no final frame, the
                # caller sees a real StreamDisconnect and migrates.
                logger.warning("injected stream drop for request %s; severing call-home", ctx.id)
            except ConnectionError:
                # Engine/infrastructure death (the EngineDeadError class of
                # failure): drop the socket without a final frame so the
                # caller observes a genuine stream disconnect and the
                # Migration operator can replay elsewhere.
                logger.warning("engine connection failure for request %s; dropping stream", ctx.id)
            except Exception as e:
                logger.exception("handler error for request %s", ctx.id)
                try:
                    await call_home.error(f"{type(e).__name__}: {e}")
                except Exception:
                    pass
        except ConnectionError:
            logger.warning("call-home connection failed for request %s", ctx.id)
        finally:
            span.end()
            if call_home is not None:
                await call_home.close()
            self.in_flight.pop(ctx.id, None)
            if self.graceful_shutdown:
                tracker.exit()
