"""Python-facing decorators mirroring the reference SDK's ergonomics.

Ref: lib/bindings/python/src/dynamo/runtime/__init__.py:36 (``dynamo_worker``)
and :65 (``dynamo_endpoint``).
"""

from __future__ import annotations

import asyncio
import functools
import inspect
from typing import Any, AsyncIterator, Callable

from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.runtime import Runtime


def dynamo_worker(static: bool = False):
    """Wrap an async ``main(runtime: DistributedRuntime, ...)`` so it receives
    a connected DistributedRuntime and signal handling, then run it."""

    def decorator(fn: Callable):
        @functools.wraps(fn)
        async def wrapped(*args, **kwargs):
            runtime = Runtime()
            drt = await (DistributedRuntime.detached(runtime) if static else DistributedRuntime.from_settings(runtime))
            runtime.install_signal_handlers()
            try:
                return await fn(drt, *args, **kwargs)
            finally:
                await drt.shutdown()

        return wrapped

    return decorator


def dynamo_endpoint(fn: Callable) -> Callable:
    """Normalise an endpoint handler to ``(request, context) -> AsyncIterator``.

    Accepts handlers declared with or without a context parameter, returning
    either an async generator or a single awaitable value.
    """
    sig = inspect.signature(fn)
    wants_ctx = len(sig.parameters) >= 2

    if inspect.isasyncgenfunction(fn):
        if wants_ctx:
            return fn

        @functools.wraps(fn)
        async def gen_no_ctx(request: Any, context: Context) -> AsyncIterator[Any]:
            async for item in fn(request):
                yield item

        return gen_no_ctx

    @functools.wraps(fn)
    async def coro_wrapper(request: Any, context: Context) -> AsyncIterator[Any]:
        result = fn(request, context) if wants_ctx else fn(request)
        if asyncio.iscoroutine(result):
            result = await result
        if hasattr(result, "__aiter__"):
            async for item in result:
                yield item
        else:
            yield result

    return coro_wrapper
