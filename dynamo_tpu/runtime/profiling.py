"""On-demand profiling: programmatic device traces + host stack sampling.

Two tools for the "why is it slow *right now*" question, both exposed on
the worker health server (``POST /debug/profile``) and attachable to
incident bundles (``--profile-on-incident``):

- ``DeviceProfiler`` — programmatic ``jax.profiler.start_trace`` /
  ``stop_trace`` capture windows. Until now the only way to get a device
  profile was re-running the workload with tracing pre-armed; this makes a
  capture a POST against a live worker. The output directory holds the
  standard XPlane/Perfetto artifacts (``xplane.pb``, ``trace.json.gz``)
  that TensorBoard's profile plugin and Perfetto open directly.
- ``HostStackSampler`` — a pure-stdlib sampling profiler over
  ``sys._current_frames()``: periodically snapshots every thread's Python
  stack and aggregates hit counts by frame. The decode host gap (the
  bubble between a dispatch returning and the next being issued) is host
  time by definition — this attributes it to actual scheduler code paths
  (``engine/scheduler.py`` frames get their own rollup) without a native
  profiler dependency.

Both are strictly off the hot path: the device profiler runs in its own
thread around a sleep window, the sampler's cost is bounded by its period
(a stack walk every few ms), and the observability bench runs with the
sampler armed to prove the combination stays inside the ≤2% budget.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import Counter
from typing import Dict, List, Optional

from dynamo_tpu.runtime.logging import get_logger

logger = get_logger(__name__)

PROFILE_DIR_ENV = "DYN_PROFILE_DIR"

# /debug/profile refuses windows beyond this: a forgotten profiler is a
# disk- and overhead-leak on a production worker.
MAX_CAPTURE_SECONDS = 60.0


class DeviceProfiler:
    """Serialized programmatic jax.profiler captures.

    One capture at a time (jax's profiler is process-global); concurrent
    requests get a structured "busy" answer instead of a crash. Capture
    errors (no backend, profiler unavailable) land in the result dict —
    a debug surface must degrade, not 500.
    """

    def __init__(self, out_dir: Optional[str] = None):
        self.out_dir = out_dir or os.environ.get(PROFILE_DIR_ENV) or "/tmp/dynamo_profiles"
        self._lock = threading.Lock()
        self._busy = False  # guarded-by: _lock
        self.captures_total = 0  # guarded-by: _lock
        self.last: Optional[dict] = None  # guarded-by: _lock

    def capture(self, seconds: float, label: str = "manual") -> dict:
        """Blocking capture: start the device trace, hold it open for
        ``seconds`` of live traffic, stop, return the artifact location."""
        seconds = min(max(float(seconds), 0.05), MAX_CAPTURE_SECONDS)
        with self._lock:
            if self._busy:
                return {"status": "busy", "error": "a capture is already running"}
            self._busy = True
            seq = self.captures_total + 1
        path = os.path.join(self.out_dir, f"profile_{seq:04d}_{label}")
        result = {"status": "ok", "path": path, "seconds": seconds, "label": label}
        try:
            import jax

            os.makedirs(path, exist_ok=True)
            jax.profiler.start_trace(path)
            try:
                time.sleep(seconds)
            finally:
                jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001 — degrade to a structured error
            result = {"status": f"error: {type(e).__name__}: {e}", "path": path,
                      "seconds": seconds, "label": label}
            logger.warning("device profile capture failed: %s", result["status"])
        with self._lock:
            self._busy = False
            if result["status"] == "ok":
                self.captures_total += 1
            self.last = result
        return result

    def capture_background(self, seconds: float, label: str = "incident") -> threading.Thread:
        """Fire-and-forget capture on a daemon thread (the incident-capture
        path: the stats scrape must not block on the profile window)."""
        t = threading.Thread(
            target=self.capture, args=(seconds, label),
            name="device-profile-capture", daemon=True,
        )
        t.start()
        return t

    def status(self) -> dict:
        with self._lock:
            return {
                "busy": self._busy,
                "captures_total": self.captures_total,
                "out_dir": self.out_dir,
                "last": dict(self.last) if self.last else None,
            }


def _frame_key(frame) -> Optional[str]:
    """Innermost frame inside this package, as ``file:line func`` — the
    attribution unit. Frames entirely outside dynamo_tpu (idle selector
    loops, queue waits in aiohttp) collapse to their leaf frame."""
    f = frame
    while f is not None:
        fn = f.f_code.co_filename
        if "dynamo_tpu" in fn:
            short = fn[fn.rindex("dynamo_tpu"):]
            return f"{short}:{f.f_lineno} {f.f_code.co_name}"
        f = f.f_back
    return None


class HostStackSampler:
    """Stdlib sampling profiler: attributes host time to code paths.

    ``start()``/``stop()`` run it continuously from a daemon thread;
    ``sample_for(seconds)`` is the blocking one-shot used by
    ``POST /debug/profile?kind=host``. ``report()`` returns the top frames
    overall plus the ``engine/scheduler.py`` rollup — the "which scheduler
    code path owns the host gap" answer.
    """

    def __init__(self, interval_s: float = 0.005):
        self.interval_s = max(float(interval_s), 0.001)
        self._lock = threading.Lock()
        self._counts: Counter = Counter()  # guarded-by: _lock
        self._other = 0  # guarded-by: _lock  (samples with no dynamo frame)
        self.samples = 0  # guarded-by: _lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # --- continuous mode ----------------------------------------------------
    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="host-stack-sampler", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._sample()

    # --- one-shot mode ------------------------------------------------------
    def sample_for(self, seconds: float) -> dict:
        """Blocking burst of samples for ``seconds``; returns the report of
        ONLY this burst (state is reset first)."""
        self.reset()
        deadline = time.monotonic() + min(max(float(seconds), 0.05), MAX_CAPTURE_SECONDS)
        while time.monotonic() < deadline:
            self._sample()
            time.sleep(self.interval_s)
        return self.report()

    # --- core ---------------------------------------------------------------
    def _sample(self) -> None:
        me = threading.get_ident()
        hits: List[str] = []
        misses = 0
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            key = _frame_key(frame)
            if key is None:
                misses += 1
            else:
                hits.append(key)
        with self._lock:
            self.samples += 1
            self._other += misses
            for key in hits:
                self._counts[key] += 1

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()
            self._other = 0
            self.samples = 0

    def report(self, top: int = 15) -> dict:
        """Top frames by samples + the scheduler-path rollup share."""
        with self._lock:
            counts = Counter(self._counts)
            samples = self.samples
            other = self._other
        total_hits = sum(counts.values())
        sched = sum(c for k, c in counts.items() if "engine/scheduler.py" in k)
        return {
            "samples": samples,
            "attributed": total_hits,
            "unattributed_thread_samples": other,
            "scheduler_share": round(sched / total_hits, 4) if total_hits else 0.0,
            "top": [
                {
                    "frame": key,
                    "count": c,
                    "share": round(c / total_hits, 4) if total_hits else 0.0,
                }
                for key, c in counts.most_common(top)
            ],
        }
