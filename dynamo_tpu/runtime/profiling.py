"""Device-truth profiling: on-demand captures, a continuous sampler, and a
pure-stdlib trace-event parser.

Three layers, all exposed on the worker health server and the stats plane:

- ``DeviceProfiler`` — programmatic ``jax.profiler.start_trace`` /
  ``stop_trace`` capture windows. jax's profiler is process-global, so ALL
  capture paths (``POST /debug/profile``, incident-triggered captures, the
  continuous sampler) serialize through one capture lock; a caller that
  will not wait gets a structured "busy" answer and the collision is
  counted in ``capture_conflicts_total`` — never silently dropped.
- ``parse_trace_events`` / ``load_trace_dir`` — a pure-stdlib parser for
  the Chrome trace-event JSON jax writes next to the XPlane protos. It
  attributes device time per kernel name (count, total, max), computes the
  device-busy interval union per device lane, and tolerates truncated or
  malformed traces (a profiler window chopped by process exit must degrade
  to a partial summary, not a crash). Because it is plain ``json`` +
  ``zlib`` it runs on CPU CI against recorded fixtures.
- ``ContinuousProfiler`` — a duty-cycled background sampler that opens
  short capture windows at a bounded rate, parses the artifact, and feeds
  the per-window deltas (device time, kernel top-N, fused-window launch
  counts) into the flight recorder so the modeled ``mfu_*`` / ``hbm_frac_*``
  gauges gain *measured* siblings. The duty cycle is clamped
  (``window_s / effective_interval ≤ max_duty``) so the plane stays inside
  the observability budget, and the gating is pure arithmetic over an
  injected clock so CI can drive it deterministically.

- ``HostStackSampler`` — a pure-stdlib sampling profiler over
  ``sys._current_frames()`` attributing host time (the decode host gap) to
  actual scheduler code paths.

All of it is strictly off the hot path: captures run around a sleep
window on their own threads, parsing happens after the window closes, and
the observability bench runs with the continuous sampler ARMED to prove
the combination stays inside the ≤2% budget.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import threading
import time
import zlib
from collections import Counter
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from dynamo_tpu.runtime.logging import get_logger

logger = get_logger(__name__)

PROFILE_DIR_ENV = "DYN_PROFILE_DIR"

# /debug/profile refuses windows beyond this: a forgotten profiler is a
# disk- and overhead-leak on a production worker.
MAX_CAPTURE_SECONDS = 60.0


class DeviceProfiler:
    """Serialized programmatic jax.profiler captures.

    One capture at a time (jax's profiler is process-global). Concurrent
    callers pick their behavior: ``wait=False`` (the HTTP 409 path) gets a
    structured "busy" answer, ``wait=True`` (incident captures, which must
    not lose their window to a routine continuous sample) queues behind
    the running capture. Either way the collision increments
    ``capture_conflicts_total`` — a counter, not a silent drop. Capture
    errors (no backend, profiler unavailable) land in the result dict —
    a debug surface must degrade, not 500.
    """

    def __init__(self, out_dir: Optional[str] = None):
        self.out_dir = out_dir or os.environ.get(PROFILE_DIR_ENV) or "/tmp/dynamo_profiles"
        self._lock = threading.Lock()
        # Held for the whole trace window; THE serialization point for every
        # capture path (HTTP, incident, continuous).
        self._capture_lock = threading.Lock()
        self._busy = False  # guarded-by: _lock
        self._seq = 0  # guarded-by: _lock
        self.captures_total = 0  # guarded-by: _lock
        self.capture_conflicts_total = 0  # guarded-by: _lock
        self.last: Optional[dict] = None  # guarded-by: _lock

    def capture(self, seconds: float, label: str = "manual", wait: bool = False) -> dict:
        """Blocking capture: start the device trace, hold it open for
        ``seconds`` of live traffic, stop, return the artifact location.

        ``wait=False``: if another capture is running, return
        ``{"status": "busy"}`` immediately (and count the conflict).
        ``wait=True``: serialize behind the running capture instead.
        """
        seconds = min(max(float(seconds), 0.05), MAX_CAPTURE_SECONDS)
        if not self._capture_lock.acquire(blocking=False):
            with self._lock:
                self.capture_conflicts_total += 1
            if not wait:
                return {"status": "busy", "error": "a capture is already running",
                        "label": label}
            self._capture_lock.acquire()
        try:
            with self._lock:
                self._busy = True
                self._seq += 1
                seq = self._seq
            path = os.path.join(self.out_dir, f"profile_{seq:04d}_{label}")
            result = {"status": "ok", "path": path, "seconds": seconds, "label": label}
            try:
                import jax

                os.makedirs(path, exist_ok=True)
                jax.profiler.start_trace(path)
                try:
                    time.sleep(seconds)
                finally:
                    jax.profiler.stop_trace()
            except Exception as e:  # noqa: BLE001 — degrade to a structured error
                result = {"status": f"error: {type(e).__name__}: {e}", "path": path,
                          "seconds": seconds, "label": label}
                logger.warning("device profile capture failed: %s", result["status"])
            with self._lock:
                self._busy = False
                if result["status"] == "ok":
                    self.captures_total += 1
                self.last = result
            return result
        finally:
            self._capture_lock.release()

    def capture_background(self, seconds: float, label: str = "incident") -> threading.Thread:
        """Fire-and-forget capture on a daemon thread (the incident-capture
        path: the stats scrape must not block on the profile window). Waits
        for a running capture rather than dropping the incident's window."""
        t = threading.Thread(
            target=self.capture, args=(seconds, label), kwargs={"wait": True},
            name="device-profile-capture", daemon=True,
        )
        t.start()
        return t

    def status(self) -> dict:
        with self._lock:
            return {
                "busy": self._busy,
                "captures_total": self.captures_total,
                "capture_conflicts_total": self.capture_conflicts_total,
                "out_dir": self.out_dir,
                "last": dict(self.last) if self.last else None,
            }


# ---------------------------------------------------------------------------
# Trace-event parsing (pure stdlib; runs on CPU CI against fixtures)
# ---------------------------------------------------------------------------

# Process-name patterns (lowercased substring match) that mark a trace lane
# as a device lane. jax/XProf names device processes "/device:TPU:0 ...";
# the fallback when no lane matches is to treat every duration event as a
# kernel (fixture traces and exotic backends still parse).
DEVICE_PROCESS_PATTERNS = ("/device:", "tpu", "gpu", "accelerator")

# Within a device process, kernels live on the "XLA Ops" thread; "XLA
# Modules"/"Steps" lanes hold enclosing spans that would double-count.
DEVICE_OPS_THREAD_PATTERNS = ("xla ops",)


@dataclass
class KernelStat:
    """Aggregate device time for one kernel name."""

    name: str
    count: int = 0
    total_us: float = 0.0
    max_us: float = 0.0

    def observe(self, dur_us: float) -> None:
        self.count += 1
        self.total_us += dur_us
        if dur_us > self.max_us:
            self.max_us = dur_us


@dataclass
class TraceSummary:
    """What one profile window measured, attributed per kernel."""

    kernels: Dict[str, KernelStat] = field(default_factory=dict)
    device_time_us: float = 0.0  # interval union of kernel events, per lane
    wall_us: float = 0.0  # span from first kernel start to last kernel end
    events_total: int = 0  # all ph=="X" events seen (host + device)
    kernel_events: int = 0  # ph=="X" events attributed to device lanes
    device_lanes: int = 0  # distinct (pid, tid) lanes kernels came from
    device_lane_found: bool = False  # False → fallback: every X event counted
    truncated: bool = False  # trace was cut; summary covers the prefix

    def top(self, n: int = 10) -> List[dict]:
        total = sum(k.total_us for k in self.kernels.values()) or 1.0
        ranked = sorted(self.kernels.values(), key=lambda k: -k.total_us)[:n]
        return [
            {"name": k.name, "count": k.count, "total_us": round(k.total_us, 3),
             "max_us": round(k.max_us, 3), "share": round(k.total_us / total, 4)}
            for k in ranked
        ]

    def launch_count(self, pattern: str) -> int:
        """Launches of kernels whose name contains ``pattern`` — the
        dynamic side of the 1-launch-per-fused-window invariant."""
        return sum(k.count for name, k in self.kernels.items() if pattern in name)

    def top_share(self) -> float:
        total = sum(k.total_us for k in self.kernels.values())
        if total <= 0:
            return 0.0
        return max(k.total_us for k in self.kernels.values()) / total


def _union_us(intervals: List[Tuple[float, float]]) -> float:
    """Total covered length of a set of (start, end) intervals."""
    if not intervals:
        return 0.0
    intervals.sort()
    total = 0.0
    cur_s, cur_e = intervals[0]
    for s, e in intervals[1:]:
        if s > cur_e:
            total += cur_e - cur_s
            cur_s, cur_e = s, e
        elif e > cur_e:
            cur_e = e
    total += cur_e - cur_s
    return total


def parse_trace_events(events: List[dict], *, truncated: bool = False) -> TraceSummary:
    """Attribute a Chrome trace-event list to per-kernel device time.

    Metadata events (``ph=="M"``) name the processes/threads; duration
    events (``ph=="X"``) on device lanes are kernels. When no lane looks
    like a device (CPU fixtures, unknown backends) every duration event is
    counted instead, so the parser degrades to "everything is a kernel"
    rather than an empty summary.
    """
    out = TraceSummary(truncated=truncated)
    process_names: Dict[object, str] = {}
    thread_names: Dict[Tuple[object, object], str] = {}
    durations: List[dict] = []
    for ev in events:
        if not isinstance(ev, dict):
            continue
        ph = ev.get("ph")
        if ph == "M":
            args = ev.get("args") or {}
            if ev.get("name") == "process_name":
                process_names[ev.get("pid")] = str(args.get("name", ""))
            elif ev.get("name") == "thread_name":
                thread_names[(ev.get("pid"), ev.get("tid"))] = str(args.get("name", ""))
        elif ph == "X":
            durations.append(ev)
    out.events_total = len(durations)

    device_pids = {
        pid for pid, name in process_names.items()
        if any(p in name.lower() for p in DEVICE_PROCESS_PATTERNS)
    }
    out.device_lane_found = bool(device_pids)

    def _is_kernel(ev: dict) -> bool:
        if not device_pids:
            return True  # fallback: no device lane — count everything
        pid = ev.get("pid")
        if pid not in device_pids:
            return False
        tname = thread_names.get((pid, ev.get("tid")), "").lower()
        # Only filter by thread when the device pid HAS named ops threads;
        # fixtures without thread metadata keep all device events.
        has_ops = any(
            any(p in tn.lower() for p in DEVICE_OPS_THREAD_PATTERNS)
            for (tpid, _), tn in thread_names.items() if tpid == pid
        )
        if not has_ops:
            return True
        return any(p in tname for p in DEVICE_OPS_THREAD_PATTERNS)

    lanes: Dict[Tuple[object, object], List[Tuple[float, float]]] = {}
    t0 = float("inf")
    t1 = float("-inf")
    for ev in durations:
        if not _is_kernel(ev):
            continue
        try:
            ts = float(ev.get("ts", 0.0))
            dur = float(ev.get("dur", 0.0))
        except (TypeError, ValueError):
            continue
        if dur < 0:
            continue
        name = str(ev.get("name", "?"))
        stat = out.kernels.get(name)
        if stat is None:
            stat = out.kernels[name] = KernelStat(name)
        stat.observe(dur)
        out.kernel_events += 1
        lanes.setdefault((ev.get("pid"), ev.get("tid")), []).append((ts, ts + dur))
        t0 = min(t0, ts)
        t1 = max(t1, ts + dur)
    out.device_lanes = len(lanes)
    # Busy time is the per-lane interval union (nested/overlapping events in
    # one lane don't double-count) summed across lanes (parallel devices add).
    out.device_time_us = sum(_union_us(iv) for iv in lanes.values())
    out.wall_us = (t1 - t0) if out.kernel_events else 0.0
    return out


def _decompress_partial(data: bytes) -> bytes:
    """Gunzip as much as survives — a truncated .gz yields its prefix."""
    d = zlib.decompressobj(16 + zlib.MAX_WBITS)
    out = []
    for i in range(0, len(data), 1 << 16):
        try:
            out.append(d.decompress(data[i:i + (1 << 16)]))
        except zlib.error:
            break
    return b"".join(out)


def _scan_events(text: str) -> Tuple[List[dict], bool]:
    """Extract the traceEvents list, tolerating truncation: when the full
    document fails to parse, raw_decode individual events until the cut."""
    try:
        obj = json.loads(text)
        if isinstance(obj, dict):
            events = obj.get("traceEvents", [])
        elif isinstance(obj, list):
            events = obj
        else:
            events = []
        return [e for e in events if isinstance(e, dict)], False
    except ValueError:
        pass
    idx = text.find('"traceEvents"')
    start = text.find("[", idx if idx >= 0 else 0)
    if start < 0:
        return [], True
    dec = json.JSONDecoder()
    events: List[dict] = []
    i = start + 1
    n = len(text)
    while True:
        while i < n and text[i] in " \t\r\n,":
            i += 1
        if i >= n or text[i] == "]":
            break
        try:
            ev, i = dec.raw_decode(text, i)
        except ValueError:
            break  # the cut point — keep what we recovered
        if isinstance(ev, dict):
            events.append(ev)
    return events, True


def parse_trace_bytes(data: bytes) -> TraceSummary:
    """Parse raw trace-event bytes (gzipped or plain, possibly truncated)."""
    if data[:2] == b"\x1f\x8b":
        data = _decompress_partial(data)
    text = data.decode("utf-8", "replace")
    events, truncated = _scan_events(text)
    return parse_trace_events(events, truncated=truncated)


def load_trace_dir(path: str) -> Optional[TraceSummary]:
    """Find and parse the newest ``*.trace.json[.gz]`` under a capture
    directory (jax writes ``plugins/profile/<run>/<host>.trace.json.gz``).
    Returns None when no trace artifact exists."""
    newest: Optional[str] = None
    newest_mtime = -1.0
    try:
        for root, _dirs, files in os.walk(path):
            for fn in files:
                if fn.endswith(".trace.json.gz") or fn.endswith(".trace.json"):
                    p = os.path.join(root, fn)
                    try:
                        m = os.path.getmtime(p)
                    except OSError:
                        continue
                    if m > newest_mtime:
                        newest, newest_mtime = p, m
    except OSError:
        return None
    if newest is None:
        return None
    try:
        with open(newest, "rb") as f:
            return parse_trace_bytes(f.read())
    except OSError:
        return None


# ---------------------------------------------------------------------------
# Continuous sampling
# ---------------------------------------------------------------------------


@dataclass
class ContinuousProfileConfig:
    """Knobs for the background device-truth sampler.

    Defaults are production-safe: a 250ms window every 30s is a 0.83%
    profiling duty cycle, further clamped by ``max_duty`` — the effective
    interval is ``max(interval_s, window_s / max_duty)``.
    """

    enabled: bool = True
    window_s: float = 0.25
    interval_s: float = 30.0
    max_duty: float = 0.02
    keep_artifacts: bool = False
    top_n: int = 8
    # Kernel-name substring whose launch count is cross-checked against the
    # flight recorder's fused-window count (1-launch-per-window, measured).
    fused_kernel_pattern: str = "fused_decode_window"


class ContinuousProfiler:
    """Duty-cycled background device captures feeding measured truth into
    the flight recorder.

    ``cost_probe`` returns the flight recorder's cumulative
    ``(flops, bytes, step_seconds, fused_windows)`` so each window's deltas
    attribute measured device time to modeled work done in the same span;
    ``sink`` receives the per-window record (normally
    ``FlightRecorder.record_measured_window``). The sampler always YIELDS
    to on-demand/incident captures: a busy profiler means the window is
    skipped and counted, never queued behind debug traffic.
    """

    def __init__(
        self,
        profiler: DeviceProfiler,
        config: Optional[ContinuousProfileConfig] = None,
        *,
        cost_probe: Optional[Callable[[], Tuple[float, float, float, int]]] = None,
        sink: Optional[Callable[[dict], None]] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.profiler = profiler
        self.config = config or ContinuousProfileConfig()
        self.cost_probe = cost_probe
        self.sink = sink
        self.clock = clock
        self._lock = threading.Lock()
        self._last_attempt = clock()  # guarded-by: _lock — first window waits a full interval
        self.windows_total = 0  # guarded-by: _lock
        self.window_seconds_total = 0.0  # guarded-by: _lock
        self.skipped_busy_total = 0  # guarded-by: _lock
        self.errors_total = 0  # guarded-by: _lock
        self.last: Optional[dict] = None  # guarded-by: _lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # --- pure gating (unit-testable under an injected clock) ---------------
    @property
    def effective_interval_s(self) -> float:
        floor = self.config.window_s / max(self.config.max_duty, 1e-6)
        return max(self.config.interval_s, floor)

    @property
    def duty_cycle(self) -> float:
        return self.config.window_s / self.effective_interval_s

    def due(self, now: float) -> bool:
        with self._lock:
            return (now - self._last_attempt) >= self.effective_interval_s

    # --- one window ---------------------------------------------------------
    def sample_once(self, now: Optional[float] = None, force: bool = False) -> dict:
        """Open one capture window if the rate limiter allows, parse the
        artifact, and push the measured record to the sink."""
        if now is None:
            now = self.clock()
        with self._lock:
            if not force and (now - self._last_attempt) < self.effective_interval_s:
                return {"status": "not_due"}
            self._last_attempt = now
        pre = self.cost_probe() if self.cost_probe else (0.0, 0.0, 0.0, 0)
        res = self.profiler.capture(self.config.window_s, label="continuous", wait=False)
        status = res.get("status")
        if status == "busy":
            with self._lock:
                self.skipped_busy_total += 1
            return {"status": "skipped_busy"}
        if status != "ok":
            with self._lock:
                self.errors_total += 1
            return res
        post = self.cost_probe() if self.cost_probe else (0.0, 0.0, 0.0, 0)
        summary = load_trace_dir(res["path"])
        if not self.config.keep_artifacts:
            shutil.rmtree(res["path"], ignore_errors=True)
        if summary is None:
            with self._lock:
                self.errors_total += 1
            return {"status": "error: no trace artifact", "path": res["path"]}
        fused_delta = max(0, int(post[3]) - int(pre[3]))
        fused_launches = summary.launch_count(self.config.fused_kernel_pattern)
        record = {
            "status": "ok",
            "wall_s": self.config.window_s,
            "device_time_s": summary.device_time_us / 1e6,
            "flops": max(0.0, post[0] - pre[0]),
            "bytes": max(0.0, post[1] - pre[1]),
            "step_seconds": max(0.0, post[2] - pre[2]),
            "kernel_events": summary.kernel_events,
            "device_lanes": summary.device_lanes,
            "device_lane_found": summary.device_lane_found,
            "truncated": summary.truncated,
            "top_kernels": summary.top(self.config.top_n),
            "top_kernel_share": summary.top_share(),
            "fused_windows": fused_delta,
            "fused_kernel_launches": fused_launches,
            "launches_per_fused_window": (
                fused_launches / fused_delta if fused_delta > 0 else None
            ),
        }
        with self._lock:
            self.windows_total += 1
            self.window_seconds_total += self.config.window_s
            self.last = record
        if self.sink is not None:
            try:
                self.sink(record)
            except Exception as e:  # noqa: BLE001 — a sink bug must not kill the sampler
                logger.warning("measured-window sink failed: %s", e)
        return record

    # --- background thread --------------------------------------------------
    def start(self) -> None:
        if not self.config.enabled:
            return
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="continuous-profiler", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    @property
    def armed(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def _run(self) -> None:
        poll = min(5.0, max(0.25, self.effective_interval_s / 20.0))
        while not self._stop.wait(poll):
            try:
                self.sample_once()
            except Exception as e:  # noqa: BLE001 — the sampler must outlive one bad window
                with self._lock:
                    self.errors_total += 1
                logger.warning("continuous profile window failed: %s", e)

    def to_stats(self) -> dict:
        """Wire-format stats families (pure dict assembly, no device work)."""
        with self._lock:
            return {
                "device_profile_windows_total": self.windows_total,
                "device_profile_window_seconds_total": self.window_seconds_total,
                "device_profile_skipped_busy_total": self.skipped_busy_total,
                "device_profile_errors_total": self.errors_total,
                "device_profile_duty_cycle": self.duty_cycle,
            }


def _frame_key(frame) -> Optional[str]:
    """Innermost frame inside this package, as ``file:line func`` — the
    attribution unit. Frames entirely outside dynamo_tpu (idle selector
    loops, queue waits in aiohttp) collapse to their leaf frame."""
    f = frame
    while f is not None:
        fn = f.f_code.co_filename
        if "dynamo_tpu" in fn:
            short = fn[fn.rindex("dynamo_tpu"):]
            return f"{short}:{f.f_lineno} {f.f_code.co_name}"
        f = f.f_back
    return None


class HostStackSampler:
    """Stdlib sampling profiler: attributes host time to code paths.

    ``start()``/``stop()`` run it continuously from a daemon thread;
    ``sample_for(seconds)`` is the blocking one-shot used by
    ``POST /debug/profile?kind=host``. ``report()`` returns the top frames
    overall plus the ``engine/scheduler.py`` rollup — the "which scheduler
    code path owns the host gap" answer.
    """

    def __init__(self, interval_s: float = 0.005):
        self.interval_s = max(float(interval_s), 0.001)
        self._lock = threading.Lock()
        self._counts: Counter = Counter()  # guarded-by: _lock
        self._other = 0  # guarded-by: _lock  (samples with no dynamo frame)
        self.samples = 0  # guarded-by: _lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # --- continuous mode ----------------------------------------------------
    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="host-stack-sampler", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._sample()

    # --- one-shot mode ------------------------------------------------------
    def sample_for(self, seconds: float) -> dict:
        """Blocking burst of samples for ``seconds``; returns the report of
        ONLY this burst (state is reset first)."""
        self.reset()
        deadline = time.monotonic() + min(max(float(seconds), 0.05), MAX_CAPTURE_SECONDS)
        while time.monotonic() < deadline:
            self._sample()
            time.sleep(self.interval_s)
        return self.report()

    # --- core ---------------------------------------------------------------
    def _sample(self) -> None:
        me = threading.get_ident()
        hits: List[str] = []
        misses = 0
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            key = _frame_key(frame)
            if key is None:
                misses += 1
            else:
                hits.append(key)
        with self._lock:
            self.samples += 1
            self._other += misses
            for key in hits:
                self._counts[key] += 1

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()
            self._other = 0
            self.samples = 0

    def report(self, top: int = 15) -> dict:
        """Top frames by samples + the scheduler-path rollup share."""
        with self._lock:
            counts = Counter(self._counts)
            samples = self.samples
            other = self._other
        total_hits = sum(counts.values())
        sched = sum(c for k, c in counts.items() if "engine/scheduler.py" in k)
        return {
            "samples": samples,
            "attributed": total_hits,
            "unattributed_thread_samples": other,
            "scheduler_share": round(sched / total_hits, 4) if total_hits else 0.0,
            "top": [
                {
                    "frame": key,
                    "count": c,
                    "share": round(c / total_hits, 4) if total_hits else 0.0,
                }
                for key, c in counts.most_common(top)
            ],
        }
