"""AsyncEngine abstraction + request Context.

Ref: lib/runtime/src/engine.rs:1-509 — ``AsyncEngine<Req, Resp, E>`` (:201),
``AsyncEngineContext`` (:112-160 — id / stop / kill / stopped) — and
pipeline/context.rs:1-515 (``Context`` carrying request id + trace).

An engine is anything with ``generate(request, context) -> AsyncIterator``:
model engines, routers, pipeline operators, and remote clients all share the
shape, which is what lets the reference compose them into pipelines
(frontend → preprocessor → backend → migration → router → engine).
"""

from __future__ import annotations

import asyncio
import uuid
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Dict, Optional, Protocol, runtime_checkable

from dynamo_tpu.runtime.logging import TraceParent


class Context:
    """Per-request context: identity, cancellation, tracing.

    Cancellation is two-level (ref: engine.rs AsyncEngineContext):
    - ``stop_generating()`` — graceful: the engine should finish the current
      step and stop emitting (client disconnect, stop-conditions met).
    - ``kill()`` — hard: abandon the request immediately.

    Contexts form a tree: child contexts are stopped/killed when the parent is.
    """

    __slots__ = ("id", "traceparent", "metadata", "_stopped", "_killed", "_children")

    def __init__(
        self,
        id: Optional[str] = None,
        traceparent: Optional[TraceParent] = None,
        metadata: Optional[Dict[str, Any]] = None,
    ):
        self.id = id or uuid.uuid4().hex
        self.traceparent = traceparent or TraceParent.new_root()
        self.metadata: Dict[str, Any] = metadata or {}
        self._stopped = asyncio.Event()
        self._killed = asyncio.Event()
        self._children: list["Context"] = []

    def child(self, id: Optional[str] = None) -> "Context":
        c = Context(id=id or self.id, traceparent=self.traceparent.child(), metadata=dict(self.metadata))
        self._children.append(c)
        if self.is_stopped():
            c.stop_generating()
        if self.is_killed():
            c.kill()
        return c

    def stop_generating(self) -> None:
        self._stopped.set()
        for c in self._children:
            c.stop_generating()

    def kill(self) -> None:
        self._killed.set()
        self._stopped.set()
        for c in self._children:
            c.kill()

    def is_stopped(self) -> bool:
        return self._stopped.is_set()

    def is_killed(self) -> bool:
        return self._killed.is_set()

    async def stopped(self) -> None:
        await self._stopped.wait()

    def to_wire(self) -> dict:
        return {"id": self.id, "traceparent": self.traceparent.to_header()}

    @classmethod
    def from_wire(cls, d: dict) -> "Context":
        tp = TraceParent.from_header(d.get("traceparent", "")) or TraceParent.new_root()
        return cls(id=d.get("id"), traceparent=tp)


@runtime_checkable
class AsyncEngine(Protocol):
    """The universal engine shape (ref: engine.rs:201)."""

    def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        ...


EngineStream = AsyncIterator[Any]


@dataclass
class Annotated:
    """A response envelope that can carry side-band annotations alongside (or
    instead of) data — e.g. ``formatted_prompt`` / ``token_ids`` annotations
    emitted by the preprocessor (ref: preprocessor.rs annotations; the
    ``Annotated<T>`` wrapper in lib/runtime pipeline)."""

    data: Any = None
    event: Optional[str] = None
    comment: Optional[str] = None
    id: Optional[str] = None

    def is_annotation(self) -> bool:
        return self.event is not None and self.data is None

    def to_wire(self) -> dict:
        d: Dict[str, Any] = {}
        if self.data is not None:
            d["data"] = self.data
        if self.event is not None:
            d["event"] = self.event
        if self.comment is not None:
            d["comment"] = self.comment
        if self.id is not None:
            d["id"] = self.id
        return d

    @classmethod
    def from_wire(cls, d: dict) -> "Annotated":
        return cls(data=d.get("data"), event=d.get("event"), comment=d.get("comment"), id=d.get("id"))


def annotated(data: Any) -> Annotated:
    return Annotated(data=data)


class EngineError(Exception):
    """Base error for engine failures."""


class StreamDisconnect(EngineError):
    """The response stream dropped mid-flight (worker died / network reset).

    The Migration operator catches this and replays the request to another
    instance (ref: migration.rs:26 — 'recreating stream')."""

    def __init__(self, message: str = "stream disconnected"):
        super().__init__(message)
