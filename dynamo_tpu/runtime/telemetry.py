"""Streaming latency telemetry: mergeable percentile digests, SLO/goodput
accounting, and the engine stall watchdog.

The planner's control inputs are latency *distributions*, not averages —
"Taming the Chaos" (arXiv:2508.19559) scales pools off TTFT/TPOT quantiles
and SLO attainment, and averaging per-worker histograms does not compose
(the mean of two p99s is not the fleet p99). The primitive here is a
DDSketch-style log-bucketed sketch:

- **Fixed relative error.** Bucket ``i`` covers ``(γ^(i-1), γ^i]`` with
  ``γ = (1+α)/(1-α)``; reporting the bucket midpoint guarantees every
  quantile estimate is within relative error ``α`` of a true sample value.
- **Mergeable.** Two sketches with the same ``α`` share bucket boundaries,
  so ``merge`` is bucket-wise addition and ``merge(a, b)`` is *identical*
  to the sketch of the concatenated stream — the aggregator computes true
  fleet-wide p50/p90/p99 from per-worker wire snapshots.
- **Serializable.** ``to_wire``/``from_wire`` round-trip through the
  msgpack stats scrape and JSON.

Everything here is host-side Python — observing a sample is a dict update
and one ``math.log`` — so the hot path adds no device dispatches and stays
inside the observability bench's ≤2% budget.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from dynamo_tpu.runtime.logging import get_logger

logger = get_logger(__name__)

# Default relative error: 1% keeps the sketch small (a 9-decade latency
# range spans ~1000 buckets worst case; real streams touch a few dozen).
DEFAULT_RELATIVE_ERROR = 0.01

# Values below this are clamped into the zero bucket (sub-nanosecond
# latencies are measurement noise, and log() needs a positive floor).
_MIN_TRACKABLE = 1e-9


class LatencyDigest:
    """DDSketch-style log-bucketed quantile sketch (sparse buckets)."""

    __slots__ = ("relative_error", "_gamma", "_log_gamma", "buckets",
                 "zero_count", "count", "sum", "min", "max")

    def __init__(self, relative_error: float = DEFAULT_RELATIVE_ERROR):
        if not 0.0 < relative_error < 1.0:
            raise ValueError(f"relative_error must be in (0, 1), got {relative_error}")
        self.relative_error = relative_error
        self._gamma = (1.0 + relative_error) / (1.0 - relative_error)
        self._log_gamma = math.log(self._gamma)
        self.buckets: Dict[int, int] = {}
        self.zero_count = 0
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = 0.0

    # --- recording ----------------------------------------------------------
    def observe(self, value: float) -> None:
        self.count += 1
        if value <= _MIN_TRACKABLE:
            self.zero_count += 1
            if value > 0:
                self.sum += value
            self.min = min(self.min, max(value, 0.0))
            return
        self.sum += value
        self.min = min(self.min, value)
        self.max = max(self.max, value)
        key = math.ceil(math.log(value) / self._log_gamma)
        self.buckets[key] = self.buckets.get(key, 0) + 1

    # --- queries ------------------------------------------------------------
    def _bucket_value(self, key: int) -> float:
        # Midpoint of (γ^(k-1), γ^k]: within relative_error of any sample
        # that landed in the bucket.
        return 2.0 * (self._gamma ** key) / (self._gamma + 1.0)

    def quantile(self, q: float) -> float:
        """q in [0, 1]. Returns 0.0 on an empty digest."""
        if self.count == 0:
            return 0.0
        q = min(max(q, 0.0), 1.0)
        rank = q * (self.count - 1)
        if rank < self.zero_count:
            return 0.0
        seen = self.zero_count
        for key in sorted(self.buckets):
            seen += self.buckets[key]
            if seen > rank:
                return self._bucket_value(key)
        return self._bucket_value(max(self.buckets)) if self.buckets else 0.0

    def percentiles(self, qs: Sequence[float] = (0.5, 0.9, 0.99)) -> List[float]:
        return [self.quantile(q) for q in qs]

    def rank(self, value: float) -> float:
        """Fraction of observed samples ≤ ``value`` — a value's percentile
        position in the distribution (the inverse of ``quantile``). The
        autopsy uses this for fleet context: "this request's 480 ms queue
        wait sits at p99.7 of the window"."""
        if self.count == 0:
            return 0.0
        seen = self.zero_count
        for key, n in sorted(self._buckets_snapshot().items()):
            if self._bucket_value(key) <= value:
                seen += n
            else:
                break
        return seen / self.count

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def histogram(self, bounds: Sequence[float]) -> Tuple[List[int], float]:
        """Cumulative counts ≤ each bound plus the total (the +Inf count) —
        the shape a native Prometheus histogram family wants. Bucket
        contents are attributed at their midpoint estimate."""
        cum = [0] * len(bounds)
        items = sorted(self.buckets.items())
        for i, b in enumerate(bounds):
            c = self.zero_count
            for key, n in items:
                if self._bucket_value(key) <= b:
                    c += n
                else:
                    break
            cum[i] = c
        return cum, float(self.count)

    # --- merge / wire -------------------------------------------------------
    def _buckets_snapshot(self) -> Dict[int, int]:
        """Copy of the bucket map, safe against a concurrent observe() on
        another thread (a new key landing mid-iteration raises
        RuntimeError; monitoring reads just retry)."""
        for _ in range(8):
            try:
                return dict(self.buckets)
            except RuntimeError:
                continue
        return dict(self.buckets)

    def merge(self, other: "LatencyDigest") -> "LatencyDigest":
        """In-place bucket-wise merge. Digests must share relative_error so
        bucket boundaries align (merge is then exact: merge(a,b) equals the
        single-stream digest)."""
        if abs(other.relative_error - self.relative_error) > 1e-12:
            raise ValueError(
                f"cannot merge digests with different relative error "
                f"({self.relative_error} vs {other.relative_error})"
            )
        for key, n in other._buckets_snapshot().items():
            self.buckets[key] = self.buckets.get(key, 0) + n
        self.zero_count += other.zero_count
        self.count += other.count
        self.sum += other.sum
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)
        return self

    def to_wire(self) -> dict:
        return {
            "re": self.relative_error,
            "zero": self.zero_count,
            "count": self.count,
            "sum": round(self.sum, 9),
            "min": self.min if math.isfinite(self.min) else None,
            "max": self.max,
            # String keys: strict msgpack unpackers reject int map keys,
            # and JSON stringifies them anyway — from_wire accepts both.
            "buckets": {str(k): v for k, v in self._buckets_snapshot().items()},
        }

    @classmethod
    def from_wire(cls, d: dict) -> "LatencyDigest":
        out = cls(relative_error=float(d.get("re", DEFAULT_RELATIVE_ERROR)))
        out.zero_count = int(d.get("zero", 0))
        out.count = int(d.get("count", 0))
        out.sum = float(d.get("sum", 0.0))
        mn = d.get("min")
        out.min = math.inf if mn is None else float(mn)
        out.max = float(d.get("max", 0.0))
        out.buckets = {int(k): int(v) for k, v in (d.get("buckets") or {}).items()}
        return out


class WindowedDigest:
    """Rolling view over a stream: a ring of per-interval digests plus a
    cumulative all-time digest.

    ``snapshot()`` merges the live intervals — "the last ~window_s seconds"
    — which is what quantile *gauges* should report (an all-time p99 never
    recovers from one bad minute). ``total`` stays monotonic, which is what
    Prometheus *histogram* export needs."""

    def __init__(
        self,
        relative_error: float = DEFAULT_RELATIVE_ERROR,
        window_s: float = 60.0,
        slices: int = 6,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.relative_error = relative_error
        self.window_s = window_s
        self.slices = max(slices, 1)
        self._slice_s = window_s / self.slices
        self._clock = clock
        self._ring: deque = deque(
            [LatencyDigest(relative_error) for _ in range(self.slices)], maxlen=self.slices
        )
        self._slice_start = clock()
        self.total = LatencyDigest(relative_error)

    def _rotate(self, now: float) -> None:
        elapsed = now - self._slice_start
        if elapsed < self._slice_s:
            return
        steps = min(int(elapsed / self._slice_s), self.slices)
        for _ in range(steps):
            self._ring.append(LatencyDigest(self.relative_error))
        self._slice_start = now

    def observe(self, value: float) -> None:
        now = self._clock()
        self._rotate(now)
        self._ring[-1].observe(value)
        self.total.observe(value)

    def snapshot(self) -> LatencyDigest:
        self._rotate(self._clock())
        out = LatencyDigest(self.relative_error)
        for d in self._ring:
            out.merge(d)
        return out

    def to_wire(self) -> dict:
        """{"window": ..., "total": ...} — the window snapshot feeds fleet
        quantile gauges, the cumulative digest feeds the monotone Prometheus
        histogram export."""
        return {"window": self.snapshot().to_wire(), "total": self.total.to_wire()}


class Telemetry:
    """A named set of windowed digests — one per latency stream (ttft, tpot,
    itl, queue_wait, per-phase step durations, ...). Owned by a scheduler /
    mocker / frontend; exported through the stats scrape as one nested
    ``digests`` dict."""

    def __init__(
        self,
        relative_error: float = DEFAULT_RELATIVE_ERROR,
        window_s: float = 60.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.relative_error = relative_error
        self.window_s = window_s
        self._clock = clock
        self._digests: Dict[str, WindowedDigest] = {}
        # Digest creation can race (scheduler thread vs event loop scrape);
        # observes on an existing digest are GIL-atomic enough for
        # monitoring data.
        self._lock = threading.Lock()

    def digest(self, name: str) -> WindowedDigest:
        d = self._digests.get(name)
        if d is None:
            with self._lock:
                d = self._digests.setdefault(
                    name,
                    WindowedDigest(self.relative_error, self.window_s, clock=self._clock),
                )
        return d

    def observe(self, name: str, value: float) -> None:
        self.digest(name).observe(value)

    def names(self) -> List[str]:
        return sorted(self._digests)

    def to_wire(self) -> Dict[str, dict]:
        return {name: d.to_wire() for name, d in list(self._digests.items())}

    def summary(self, qs: Sequence[float] = (0.5, 0.9, 0.99)) -> Dict[str, dict]:
        """Human-oriented snapshot (the /debug/state digest block)."""
        out = {}
        for name, d in list(self._digests.items()):
            snap = d.snapshot()
            out[name] = {
                "count": d.total.count,
                "window_count": snap.count,
                **{f"p{int(q * 100)}": round(snap.quantile(q), 6) for q in qs},
                "mean": round(snap.mean, 6),
                "max": round(snap.max, 6),
            }
        return out


# --- SLO / goodput accounting -----------------------------------------------

class SloConfig:
    """Per-request latency targets. ``None`` disables judging a phase."""

    __slots__ = ("ttft_ms", "tpot_ms")

    def __init__(self, ttft_ms: Optional[float] = None, tpot_ms: Optional[float] = None):
        self.ttft_ms = ttft_ms
        self.tpot_ms = tpot_ms

    @property
    def enabled(self) -> bool:
        return self.ttft_ms is not None or self.tpot_ms is not None


class SloJudge:
    """Judges each finished request against the SLO targets and keeps the
    goodput account: requests (and their tokens) that met EVERY configured
    target. Counters are monotonic; the per-second gauges are computed over
    a short rolling window so they read as live rates."""

    def __init__(self, config: SloConfig, clock: Callable[[], float] = time.monotonic,
                 rate_window_s: float = 30.0):
        self.config = config
        self._clock = clock
        self.rate_window_s = rate_window_s
        self.attained = {"ttft": 0, "tpot": 0}
        self.violated = {"ttft": 0, "tpot": 0}
        self.goodput_requests_total = 0
        self.goodput_tokens_total = 0
        self.requests_total = 0
        self._recent: deque = deque()  # (ts, good_requests, good_tokens)

    def judge(self, ttft_s: Optional[float], tpot_s: Optional[float], n_tokens: int) -> bool:
        """Returns True when the request attained every configured target.
        A phase with no measurement (e.g. single-token request has no TPOT)
        is not judged."""
        if not self.config.enabled:
            return True
        self.requests_total += 1
        good = True
        if self.config.ttft_ms is not None and ttft_s is not None:
            if ttft_s * 1000.0 <= self.config.ttft_ms:
                self.attained["ttft"] += 1
            else:
                self.violated["ttft"] += 1
                good = False
        if self.config.tpot_ms is not None and tpot_s is not None:
            if tpot_s * 1000.0 <= self.config.tpot_ms:
                self.attained["tpot"] += 1
            else:
                self.violated["tpot"] += 1
                good = False
        if good:
            self.goodput_requests_total += 1
            self.goodput_tokens_total += n_tokens
            self._recent.append((self._clock(), 1, n_tokens))
        else:
            self._recent.append((self._clock(), 0, 0))
        return good

    def _trim(self) -> None:
        horizon = self._clock() - self.rate_window_s
        while self._recent and self._recent[0][0] < horizon:
            self._recent.popleft()

    def goodput_rates(self) -> Tuple[float, float]:
        """(SLO-attained req/s, tok/s) over the rolling window."""
        self._trim()
        if not self._recent:
            return 0.0, 0.0
        span = max(self._clock() - self._recent[0][0], 1e-6)
        reqs = sum(r for _, r, _ in self._recent)
        toks = sum(t for _, _, t in self._recent)
        return reqs / span, toks / span

    def attainment(self) -> float:
        """Fraction of judged phase checks that attained, 1.0 with no data."""
        a = sum(self.attained.values())
        v = sum(self.violated.values())
        return a / (a + v) if (a + v) else 1.0

    def to_stats(self) -> dict:
        """Flat keys for the worker stats scrape (COUNTER_KEYS names)."""
        req_s, tok_s = self.goodput_rates()
        return {
            "slo_ttft_attained_total": self.attained["ttft"],
            "slo_ttft_violated_total": self.violated["ttft"],
            "slo_tpot_attained_total": self.attained["tpot"],
            "slo_tpot_violated_total": self.violated["tpot"],
            "goodput_requests_total": self.goodput_requests_total,
            "goodput_tokens_total": self.goodput_tokens_total,
            "slo_attainment": round(self.attainment(), 6),
            "goodput_req_per_s": round(req_s, 6),
            "goodput_tok_per_s": round(tok_s, 6),
        }


# --- stall watchdog ----------------------------------------------------------

class StallWatchdog:
    """Detects a wedged step loop: work is queued but the engine has not
    completed a step for ``stall_after_s``. Evaluated lazily at probe time
    (``check()``) — no background thread, deterministic under a
    monkeypatched clock — and called from the stats scrape and the health
    endpoint, both of which poll anyway.

    ``probe`` returns ``(has_work, last_step_ts)`` where ``last_step_ts``
    is the clock time the last engine step completed (None = no step yet;
    the reference point is then the watchdog's own start)."""

    def __init__(
        self,
        probe: Callable[[], Tuple[bool, Optional[float]]],
        stall_after_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.probe = probe
        self.stall_after_s = stall_after_s
        self._clock = clock
        self._start_ts = clock()
        # check() is called from every poller (stats scrape on the event
        # loop, the health server's thread, bench loops): the transition
        # edge must fire its counter exactly once.
        self._check_lock = threading.Lock()
        self.stalled = False  # guarded-by: _check_lock
        self.stalls_total = 0  # guarded-by: _check_lock

    def last_step_age_s(self) -> float:
        _, last = self.probe()
        ref = self._start_ts if last is None else last
        return max(self._clock() - ref, 0.0)

    def check(self) -> bool:
        """Re-evaluate; returns the current stalled state. Fires the log +
        counter only on the not-stalled → stalled transition."""
        has_work, last = self.probe()
        ref = self._start_ts if last is None else last
        now_stalled = bool(has_work) and (self._clock() - ref) > self.stall_after_s
        with self._check_lock:
            if now_stalled and not self.stalled:
                self.stalls_total += 1
                logger.error(
                    "engine_stalled: step loop has not advanced for %.1fs with work queued",
                    self._clock() - ref,
                )
            self.stalled = now_stalled
        return now_stalled

    def to_stats(self) -> dict:
        stalled = self.check()
        return {
            "engine_stalled": 1.0 if stalled else 0.0,
            "engine_stalls_total": self.stalls_total,
            "last_step_age_s": round(self.last_step_age_s(), 3),
        }


# --- Prometheus export --------------------------------------------------------

# Fixed bounds for the native-histogram re-export of merged digests: latency
# scales from sub-ms engine steps to minute-long requests. (Digest buckets
# are re-attributed at their midpoints; with α=1% the attribution error is
# far below the bound spacing.)
DIGEST_HISTOGRAM_BOUNDS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

DIGEST_QUANTILES: Tuple[float, ...] = (0.5, 0.9, 0.99)


class DigestCollector:
    """prometheus_client custom collector rendering a set of digests as
    native histogram families (from the cumulative digests — monotone, so
    PromQL ``histogram_quantile``/``rate`` behave) plus quantile gauges
    (from the windowed snapshots — live percentiles without PromQL math).

    Families: ``<prefix><name>_seconds`` (histogram) and
    ``<prefix><name>_seconds_quantile{quantile="0.5|0.9|0.99"}`` (gauge)."""

    def __init__(self, prefix: str, registry=None, telemetry: Optional[Telemetry] = None):
        self.prefix = prefix
        # name -> (window LatencyDigest, total LatencyDigest)
        self._digests: Dict[str, Tuple[LatencyDigest, LatencyDigest]] = {}
        self._lock = threading.Lock()
        # Live mode: read digests straight from a local Telemetry at collect
        # time (the frontend's own e2e digests); otherwise update() /
        # update_from_wire() push merged fleet digests (the aggregator).
        self._telemetry = telemetry
        if registry is not None:
            registry.register(self)

    def update(self, merged: Dict[str, Tuple[LatencyDigest, LatencyDigest]]) -> None:
        """Replace the exported set with freshly merged (window, total)
        digest pairs."""
        with self._lock:
            self._digests = dict(merged)

    def update_from_wire(self, per_worker: Iterable[Dict[str, dict]]) -> None:
        """Merge per-worker ``Telemetry.to_wire()`` payloads into fleet
        digests and export them."""
        merged: Dict[str, Tuple[LatencyDigest, LatencyDigest]] = {}
        for wires in per_worker:
            for name, pair in (wires or {}).items():
                try:
                    win = LatencyDigest.from_wire(pair["window"])
                    tot = LatencyDigest.from_wire(pair["total"])
                except (KeyError, TypeError, ValueError):
                    continue
                if name in merged:
                    merged[name][0].merge(win)
                    merged[name][1].merge(tot)
                else:
                    merged[name] = (win, tot)
        self.update(merged)

    def collect(self):
        from prometheus_client.core import GaugeMetricFamily, HistogramMetricFamily

        if self._telemetry is not None:
            digests = {
                name: (self._telemetry.digest(name).snapshot(), self._telemetry.digest(name).total)
                for name in self._telemetry.names()
            }
        else:
            with self._lock:
                digests = dict(self._digests)
        for name, (window, total) in sorted(digests.items()):
            full = f"{self.prefix}{name}_seconds"
            cum, count = total.histogram(DIGEST_HISTOGRAM_BOUNDS)
            hist = HistogramMetricFamily(
                full, f"fleet-merged {name} latency digest (cumulative)",
            )
            hist.add_metric(
                [],
                buckets=[(str(b), float(c)) for b, c in zip(DIGEST_HISTOGRAM_BOUNDS, cum)]
                + [("+Inf", count)],
                sum_value=total.sum,
            )
            yield hist
            g = GaugeMetricFamily(
                f"{full}_quantile",
                f"fleet-merged {name} quantiles over the rolling window",
                labels=["quantile"],
            )
            for q in DIGEST_QUANTILES:
                g.add_metric([str(q)], window.quantile(q))
            yield g

    def describe(self):
        # Unchecked collector: families vary with the digest set.
        return []
