"""Distributed runtime: the TPU-native equivalent of the reference's
``lib/runtime`` (Rust, ~30k LoC — SURVEY.md §2a).

Key exports:
- :class:`Runtime` / :class:`DistributedRuntime` — process + cluster handles.
- ``Namespace`` → ``Component`` → ``Endpoint`` hierarchy with instance
  discovery via a watched key-value store (the etcd role).
- :class:`AsyncEngine` protocol and :class:`Context` (request id, cancellation,
  tracing) — ref: lib/runtime/src/engine.rs:201, pipeline/context.rs.
- :class:`PushRouter` — client-side routing (round-robin / random / direct /
  KV) — ref: lib/runtime/src/pipeline/network/egress/push_router.rs:33.
"""

from dynamo_tpu.runtime.engine import (
    AsyncEngine,
    Context,
    EngineStream,
    annotated,
)
from dynamo_tpu.runtime.runtime import Runtime
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.component import Namespace, Component, Endpoint, Instance
from dynamo_tpu.runtime.push_router import PushRouter, RouterMode
from dynamo_tpu.runtime.decorators import dynamo_worker, dynamo_endpoint

__all__ = [
    "AsyncEngine",
    "Context",
    "EngineStream",
    "annotated",
    "Runtime",
    "DistributedRuntime",
    "Namespace",
    "Component",
    "Endpoint",
    "Instance",
    "PushRouter",
    "RouterMode",
    "dynamo_worker",
    "dynamo_endpoint",
]
