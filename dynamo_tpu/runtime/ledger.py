"""Per-tenant capacity accounting: request bills → bounded tenant ledger.

The observability plane so far (digests PR 6, step cost model PR 6/7,
measured device windows PR 15) aggregates fleet-wide: it can say *that*
TTFT p99 regressed, not *who* consumed the capacity. This module is the
attribution substrate — the scheduler charges each request's queue time,
device time, FLOPs, output tokens, and KV block-seconds into a
:class:`RequestBill`, and per-worker bills roll into a
:class:`TenantLedger` whose memory is bounded regardless of tenant
cardinality:

- a :class:`SpaceSaving` top-K heavy-hitter sketch per billed dimension
  (device-seconds, KV block-seconds, queue-seconds) — the classic
  Metwally/Agrawal/El Abbadi stream-summary with weighted updates:
  estimates over-count by at most ``total/k``, the sketch is mergeable
  across workers, and ties break deterministically (lexicographically
  smaller tenant wins a rank tie, lexicographically smallest min-count
  entry is evicted) so two workers seeing the same stream agree;
- per-tenant windowed :class:`~dynamo_tpu.runtime.telemetry.LatencyDigest`
  TTFT/TPOT streams and SLO attained/violated counters, kept ONLY for
  tenants currently tracked by the device-seconds sketch (evicted tenant →
  digests dropped), so the per-tenant telemetry footprint is O(top_k);
- exact fleet totals per dimension, so the aggregator can conserve mass:
  fleet total − Σ top-K = the ``other`` bucket, and per-tenant families
  always sum to the true total.

``TenantLedger.to_wire()`` rides the worker stats scrape (nested under
``tenant_ledger``, like ``digests``); :class:`TenantFleet` on the
aggregator side merges the per-worker wires into fleet-true top-K
families. ``attribute()`` powers ``tools/autopsy.py --tenant``.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from dynamo_tpu.runtime.telemetry import SloConfig, WindowedDigest

ANON_TENANT = "anon"
DEFAULT_TOP_K = 16


# ---------------------------------------------------------------------------
# SpaceSaving heavy-hitter sketch
# ---------------------------------------------------------------------------


class SpaceSaving:
    """Weighted SpaceSaving stream summary over string keys.

    Tracks at most ``k`` keys. ``offer(key, w)`` either bumps a tracked
    key, fills a free slot, or evicts the minimum-count entry and adopts
    its count as the new key's error floor. Invariants (tested in
    tests/test_ledger.py):

    - ``estimate(key) ≥ true(key)`` for every key (over-estimate only);
    - ``estimate(key) − true(key) ≤ error(key) ≤ total/k``;
    - any key with ``true(key) > total/k`` is guaranteed tracked.

    Determinism: eviction picks the (count, key) lexicographic minimum;
    ``items()`` ranks by (−count, key) — equal counts rank the smaller
    key first — so independent replicas of the same stream agree exactly.
    """

    __slots__ = ("k", "total", "_items")

    def __init__(self, k: int = DEFAULT_TOP_K):
        if k < 1:
            raise ValueError(f"SpaceSaving k must be ≥ 1, got {k}")
        self.k = int(k)
        self.total = 0.0
        # key -> [count, error]
        self._items: Dict[str, List[float]] = {}

    def __len__(self) -> int:
        return len(self._items)

    def __contains__(self, key: str) -> bool:
        return key in self._items

    def offer(self, key: str, weight: float = 1.0) -> None:
        if weight <= 0.0:
            return
        self.total += weight
        slot = self._items.get(key)
        if slot is not None:
            slot[0] += weight
            return
        if len(self._items) < self.k:
            self._items[key] = [weight, 0.0]
            return
        victim = min(self._items, key=lambda t: (self._items[t][0], t))
        vcount = self._items.pop(victim)[0]
        self._items[key] = [vcount + weight, vcount]

    def estimate(self, key: str) -> float:
        slot = self._items.get(key)
        return slot[0] if slot is not None else 0.0

    def error(self, key: str) -> float:
        slot = self._items.get(key)
        return slot[1] if slot is not None else 0.0

    def min_count(self) -> float:
        """The eviction floor: an untracked key's true count is ≤ this."""
        if len(self._items) < self.k:
            return 0.0
        return min(c for c, _ in self._items.values())

    def items(self) -> List[Tuple[str, float, float]]:
        """[(key, count, error)] ranked by (−count, key) — deterministic."""
        return sorted(
            ((key, c, e) for key, (c, e) in self._items.items()),
            key=lambda t: (-t[1], t[0]),
        )

    def merge(self, other: "SpaceSaving") -> "SpaceSaving":
        """Merge another sketch in place (union of keys, counts summed;
        a key absent from one sketch contributes that sketch's eviction
        floor to both count and error — the over-estimate property and
        the summed ``total/k`` bound survive the merge), then trim back
        to k entries by the deterministic rank order."""
        floor_self = self.min_count()
        floor_other = other.min_count()
        merged: Dict[str, List[float]] = {}
        for key, (c, e) in self._items.items():
            oc = other._items.get(key)
            if oc is not None:
                merged[key] = [c + oc[0], e + oc[1]]
            else:
                merged[key] = [c + floor_other, e + floor_other]
        for key, (c, e) in other._items.items():
            if key not in merged:
                merged[key] = [c + floor_self, e + floor_self]
        kept = sorted(merged.items(), key=lambda t: (-t[1][0], t[0]))[: self.k]
        self._items = {key: slot for key, slot in kept}
        self.total += other.total
        return self

    def to_wire(self) -> dict:
        return {
            "k": self.k,
            "total": self.total,
            "items": [[key, c, e] for key, c, e in self.items()],
        }

    @classmethod
    def from_wire(cls, d: dict) -> "SpaceSaving":
        s = cls(int(d.get("k") or DEFAULT_TOP_K))
        s.total = float(d.get("total") or 0.0)
        for key, c, e in d.get("items") or []:
            s._items[str(key)] = [float(c), float(e)]
        return s


# ---------------------------------------------------------------------------
# Request bill
# ---------------------------------------------------------------------------


@dataclass
class RequestBill:
    """One finished (or timed-out / migrated-away / cancelled) request's
    capacity account, emitted by the scheduler at its finish choke point.
    Device-seconds are the request's pro-rated share of each step's wall
    time (marginal-roofline weights from the step cost model, scaled by
    the measured/modeled ratio when the continuous profiler has a live
    window); on a migration/disagg leg each scheduler bills only the
    device time IT spent, so multi-leg requests sum without
    double-billing."""

    tenant: str = ANON_TENANT
    request_id: str = ""
    queue_s: float = 0.0
    prefill_device_s: float = 0.0
    decode_device_s: float = 0.0
    flops: float = 0.0
    output_tokens: int = 0
    kv_block_s: float = 0.0
    finish_reason: str = "stop"
    ttft_s: Optional[float] = None
    tpot_s: Optional[float] = None

    @property
    def device_s(self) -> float:
        return self.prefill_device_s + self.decode_device_s


# ---------------------------------------------------------------------------
# Per-worker tenant ledger
# ---------------------------------------------------------------------------

_SLO_PHASES = ("ttft", "tpot")


@dataclass
class _TenantSlo:
    attained: Dict[str, int] = field(default_factory=lambda: {p: 0 for p in _SLO_PHASES})
    violated: Dict[str, int] = field(default_factory=lambda: {p: 0 for p in _SLO_PHASES})


class TenantLedger:
    """Bounded-memory per-tenant accounting for one worker.

    ``record(bill)`` is called from the scheduler thread at request
    finish; ``to_wire()``/``to_stats()`` from the stats scrape (event
    loop) — a lock covers the sketch/digest mutations."""

    def __init__(
        self,
        top_k: int = DEFAULT_TOP_K,
        slo: Optional[SloConfig] = None,
        window_s: float = 60.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.top_k = int(top_k)
        self.slo = slo or SloConfig()
        self.window_s = window_s
        self._clock = clock
        self._lock = threading.Lock()
        self.device_s = SpaceSaving(self.top_k)
        self.kv_block_s = SpaceSaving(self.top_k)
        self.queue_s = SpaceSaving(self.top_k)
        # Per-tenant telemetry exists only for tenants the device-seconds
        # sketch currently tracks — bounded at O(top_k) regardless of
        # tenant cardinality.
        self._digests: Dict[str, Dict[str, WindowedDigest]] = {}
        self._slo: Dict[str, _TenantSlo] = {}
        # Exact totals (conservation anchors for the `other` bucket).
        self.totals: Dict[str, float] = {
            "device_seconds": 0.0,
            "prefill_device_seconds": 0.0,
            "decode_device_seconds": 0.0,
            "kv_block_seconds": 0.0,
            "queue_seconds": 0.0,
            "flops": 0.0,
            "output_tokens": 0.0,
            "slo_attained": 0.0,
            "slo_violated": 0.0,
        }
        self.bills_total = 0

    def record(self, bill: RequestBill) -> None:
        tenant = bill.tenant or ANON_TENANT
        with self._lock:
            self.bills_total += 1
            t = self.totals
            t["device_seconds"] += bill.device_s
            t["prefill_device_seconds"] += bill.prefill_device_s
            t["decode_device_seconds"] += bill.decode_device_s
            t["kv_block_seconds"] += bill.kv_block_s
            t["queue_seconds"] += bill.queue_s
            t["flops"] += bill.flops
            t["output_tokens"] += bill.output_tokens
            self.device_s.offer(tenant, bill.device_s)
            self.kv_block_s.offer(tenant, bill.kv_block_s)
            self.queue_s.offer(tenant, bill.queue_s)
            if tenant in self.device_s:
                self._observe_tracked(tenant, bill)
            self._evict_untracked()

    def _observe_tracked(self, tenant: str, bill: RequestBill) -> None:
        dig = self._digests.get(tenant)
        if dig is None:
            dig = self._digests[tenant] = {
                p: WindowedDigest(window_s=self.window_s, clock=self._clock)
                for p in _SLO_PHASES
            }
            self._slo[tenant] = _TenantSlo()
        slo = self._slo[tenant]
        judged = bill.finish_reason in ("stop", "length")
        if bill.ttft_s is not None:
            dig["ttft"].observe(bill.ttft_s)
            if judged and self.slo.ttft_ms is not None:
                ok = bill.ttft_s * 1000.0 <= self.slo.ttft_ms
                self._count_slo(slo, "ttft", ok)
        if bill.tpot_s is not None:
            dig["tpot"].observe(bill.tpot_s)
            if judged and self.slo.tpot_ms is not None:
                ok = bill.tpot_s * 1000.0 <= self.slo.tpot_ms
                self._count_slo(slo, "tpot", ok)

    def _count_slo(self, slo: _TenantSlo, phase: str, ok: bool) -> None:
        if ok:
            slo.attained[phase] += 1
            self.totals["slo_attained"] += 1
        else:
            slo.violated[phase] += 1
            self.totals["slo_violated"] += 1

    def _evict_untracked(self) -> None:
        """Drop digests/SLO state for tenants the device sketch evicted —
        this is what keeps the telemetry footprint bounded."""
        if len(self._digests) <= self.top_k:
            return
        for tenant in [t for t in self._digests if t not in self.device_s]:
            self._digests.pop(tenant, None)
            self._slo.pop(tenant, None)

    # --- export ------------------------------------------------------------

    def to_wire(self) -> dict:
        """The nested stats-scrape payload the aggregator merges."""
        with self._lock:
            return {
                "top_k": self.top_k,
                "bills": self.bills_total,
                "totals": dict(self.totals),
                "sketches": {
                    "device_seconds": self.device_s.to_wire(),
                    "kv_block_seconds": self.kv_block_s.to_wire(),
                    "queue_seconds": self.queue_s.to_wire(),
                },
                "slo": {
                    tenant: {
                        "attained": dict(s.attained),
                        "violated": dict(s.violated),
                    }
                    for tenant, s in self._slo.items()
                },
                "digests": {
                    tenant: {p: d.to_wire() for p, d in dig.items()}
                    for tenant, dig in self._digests.items()
                },
            }

    def to_stats(self) -> dict:
        """Flat unlabeled worker-plane keys (registered in the aggregator
        key lists, pinned by the Grafana Tenants row). The labeled
        per-tenant families are aggregator-side only — built from the
        merged sketch wire, not from these."""
        with self._lock:
            return {
                "tenant_billed_device_seconds_total": self.totals["device_seconds"],
                "tenant_billed_kv_block_seconds_total": self.totals["kv_block_seconds"],
                "tenant_billed_queue_seconds_total": self.totals["queue_seconds"],
                "tenant_billed_output_tokens_total": self.totals["output_tokens"],
                "tenant_bills_total": self.bills_total,
                "tenant_slo_attained_total": self.totals["slo_attained"],
                "tenant_slo_violated_total": self.totals["slo_violated"],
                "tenant_tracked": float(len(self.device_s)),
            }

    def snapshot(self) -> dict:
        """Incident-bundle evidence: ranked shares per dimension, so
        ``autopsy --tenant`` can attribute a spike without the raw
        sketches."""
        wire = self.to_wire()
        return attribute(wire)


# ---------------------------------------------------------------------------
# Fleet-side merge (aggregator) + attribution (autopsy)
# ---------------------------------------------------------------------------

_DIMENSIONS = ("device_seconds", "kv_block_seconds", "queue_seconds")


class TenantFleet:
    """Aggregator-side: merge per-worker ledger wires into fleet-true
    top-K sketches + exact fleet totals. Stateless across scrapes — the
    caller feeds it every worker's latest wire each time and diffs the
    resulting cumulative counts itself."""

    def __init__(self, top_k: Optional[int] = None):
        self.top_k = top_k

    def merge(self, wires: Iterable[dict]) -> dict:
        wires = [w for w in wires if w]
        if not wires:
            return {}
        k = self.top_k or max(int(w.get("top_k") or DEFAULT_TOP_K) for w in wires)
        sketches = {dim: SpaceSaving(k) for dim in _DIMENSIONS}
        totals: Dict[str, float] = {}
        slo: Dict[str, Dict[str, Dict[str, int]]] = {}
        bills = 0
        for w in wires:
            bills += int(w.get("bills") or 0)
            for key, val in (w.get("totals") or {}).items():
                totals[key] = totals.get(key, 0.0) + float(val)
            for dim in _DIMENSIONS:
                sw = (w.get("sketches") or {}).get(dim)
                if sw:
                    sketches[dim].merge(SpaceSaving.from_wire(sw))
            for tenant, counts in (w.get("slo") or {}).items():
                dst = slo.setdefault(
                    tenant,
                    {"attained": {p: 0 for p in _SLO_PHASES},
                     "violated": {p: 0 for p in _SLO_PHASES}},
                )
                for kind in ("attained", "violated"):
                    for phase, n in (counts.get(kind) or {}).items():
                        dst[kind][phase] = dst[kind].get(phase, 0) + int(n)
        return {
            "top_k": k,
            "bills": bills,
            "totals": totals,
            "sketches": {dim: s.to_wire() for dim, s in sketches.items()},
            "slo": slo,
        }


def attribute(wire: dict) -> dict:
    """Rank tenants by share per billed dimension. Input is a ledger (or
    fleet-merged) wire; output is what autopsy renders:

        {"device_seconds": {"total": 12.3,
                            "tenants": [{"tenant": "x", "value": 10.3,
                                         "error": 0.0, "share": 0.84}, ...],
                            "other": 2.0, "other_share": 0.16}, ...}

    ``other`` = exact total − Σ tracked estimates, floored at 0 (sketch
    estimates over-count by ≤ total/k, so the floor absorbs the bias and
    shares stay in [0, 1])."""
    out: dict = {"bills": int(wire.get("bills") or 0)}
    totals = wire.get("totals") or {}
    for dim in _DIMENSIONS:
        sw = (wire.get("sketches") or {}).get(dim)
        total = float(totals.get(dim) or 0.0)
        tenants = []
        tracked_sum = 0.0
        if sw:
            for tenant, count, err in SpaceSaving.from_wire(sw).items():
                tracked_sum += count
                tenants.append({
                    "tenant": tenant,
                    "value": count,
                    "error": err,
                    "share": (count / total) if total > 0 else 0.0,
                })
        other = max(0.0, total - tracked_sum)
        out[dim] = {
            "total": total,
            "tenants": tenants,
            "other": other,
            "other_share": (other / total) if total > 0 else 0.0,
        }
    out["slo"] = wire.get("slo") or {}
    return out
