"""Endpoint client with live instance discovery.

Ref: lib/runtime/src/component/client.rs:40-285 — ``Client`` with
``InstanceSource::{Static, Dynamic(watch)}``. Dynamic discovery watches the
instance prefix in the KV store; lease expiry of a dead worker deletes its key
and the watch prunes it from the routing set within one watch delivery.
"""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional

import msgpack

from dynamo_tpu.runtime.component import Endpoint, Instance
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.transports.kvstore import EventType

logger = get_logger(__name__)


class Client:
    """Tracks live instances of one endpoint."""

    def __init__(self, endpoint: Endpoint):
        self.endpoint = endpoint
        self.drt = endpoint.drt
        self.instances: Dict[int, Instance] = {}
        self._static = False
        self._watch = None
        self._watch_task: Optional[asyncio.Task] = None
        self._changed = asyncio.Event()

    async def start(self, static_instances: Optional[List[Instance]] = None) -> None:
        if static_instances is not None:
            self._static = True
            self.instances = {i.instance_id: i for i in static_instances}
            return
        snapshot, self._watch = await self.drt.store.get_and_watch_prefix(self.endpoint.instance_prefix)
        for entry in snapshot:
            inst = Instance.from_json(entry.value)
            self.instances[inst.instance_id] = inst
        self._watch_task = asyncio.get_running_loop().create_task(self._watch_loop())

    async def _watch_loop(self) -> None:
        async for ev in self._watch:
            if ev.type == EventType.PUT and ev.value is not None:
                inst = Instance.from_json(ev.value)
                self.instances[inst.instance_id] = inst
            elif ev.type == EventType.DELETE:
                # key: instances/{ns}/{comp}/{ep}:{lease:x}
                try:
                    lease_hex = ev.key.rsplit(":", 1)[1]
                    self.instances.pop(int(lease_hex, 16), None)
                except (IndexError, ValueError):
                    pass
            self._changed.set()
            self._changed = asyncio.Event()

    def instance_ids(self) -> List[int]:
        return sorted(self.instances)

    async def wait_for_instances(self, min_count: int = 1, timeout: float = 30.0) -> List[Instance]:
        deadline = asyncio.get_running_loop().time() + timeout
        while len(self.instances) < min_count:
            remaining = deadline - asyncio.get_running_loop().time()
            if remaining <= 0:
                raise TimeoutError(
                    f"endpoint {self.endpoint.path}: {len(self.instances)}/{min_count} instances after {timeout}s"
                )
            changed = self._changed
            try:
                await asyncio.wait_for(changed.wait(), min(remaining, 0.5))
            except asyncio.TimeoutError:
                pass
        return [self.instances[i] for i in sorted(self.instances)]

    async def scrape_stats(self, timeout: float = 2.0) -> Dict[int, dict]:
        """Request/reply stats scrape of every live instance
        (ref: component.rs:280-334)."""
        out: Dict[int, dict] = {}

        async def one(inst: Instance):
            try:
                msg = await self.drt.bus.request(inst.stats_subject, b"{}", timeout=timeout)
                out[inst.instance_id] = msgpack.unpackb(msg.data, raw=False)
            except asyncio.TimeoutError:
                pass

        await asyncio.gather(*(one(i) for i in list(self.instances.values())))
        return out

    async def close(self) -> None:
        if self._watch is not None:
            await self._watch.cancel()
        if self._watch_task is not None:
            self._watch_task.cancel()
            try:
                await self._watch_task
            except asyncio.CancelledError:
                pass
