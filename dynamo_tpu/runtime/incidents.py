"""Incident autopsy plane: anomaly-triggered black-box capture.

The repo *records* everything — traces (PR 2), mergeable latency digests +
SLO/goodput accounting (PR 6), statically-checked metrics (PR 8) — but the
evidence of an incident (the recent-step ring, the trace ring, thread
stacks, digest windows) evaporates unless someone was already watching.
This module closes that gap with three host-side pieces:

- ``AnomalyDetector`` — watches the signals the stats scrape already
  carries: ``WindowedDigest`` quantile jumps vs a trailing baseline
  (TTFT / TPOT / queue-wait p99), SLO-violation-rate steps,
  ``compiles_after_warmup_total`` increments, stall-watchdog transitions,
  and ``decode_host_gap`` regressions. Evaluated lazily at scrape/probe
  time (the ``StallWatchdog`` pattern: no background thread, deterministic
  under a monkeypatched clock), debounced per reason.
- ``IncidentRecorder`` — writes a self-contained JSON bundle per incident
  (``debug_state()``, the flight recorder's recent-step ring, the tracer's
  in-memory trace ring, telemetry digest snapshots, thread stacks, engine
  config, the triggering signal and its baseline) with a global
  rate limit and an LRU retention cap, so a flapping detector cannot fill
  a disk or bury the first — usually most informative — capture.
- ``IncidentPlane`` — ties detector + recorder + the capture probes
  together behind two calls: ``observe(stats)`` on every stats scrape and
  ``to_stats()`` merged into the scrape result (``incidents_*_total``
  per-reason counters, ``incident_last_age_s``), so incidents flow
  stats → aggregator → Grafana like every other signal.

Everything here is plain host Python on the scrape path — zero device
dispatches, no hot-path work — and rides inside the observability bench's
≤2% budget (asserted with the full plane armed).

``tools/autopsy.py`` consumes the bundles: it joins the trace ring, step
ring, and digest snapshots into a "why was this slow" attribution report.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.telemetry import LatencyDigest

logger = get_logger(__name__)

BUNDLE_SCHEMA = "dynamo-incident-v1"

# The closed reason set: each is a per-reason counter on the stats wire
# (``incidents_<reason>_total`` — registered in metrics_aggregator
# COUNTER_KEYS and pinned by the Grafana "Incidents" row).
REASONS = (
    "ttft_p99",
    "tpot_p99",
    "queue_wait_p99",
    "slo_violation",
    "post_warmup_compile",
    "engine_stall",
    "host_gap",
    # Fleet-level: the live instance set shrank between scrapes (a worker
    # crashed or its lease lapsed). Fired by planes that observe
    # ``worker_instance_count`` — the aggregator's fleet plane — never by a
    # worker about itself; the key still exports as 0 on workers so the
    # metric family is uniform.
    "worker_lost",
)

# Which digest stream feeds each quantile-jump signal.
_QUANTILE_SIGNALS: Tuple[Tuple[str, str], ...] = (
    ("ttft", "ttft_p99"),
    ("tpot", "tpot_p99"),
    ("queue_wait", "queue_wait_p99"),
)

INCIDENT_DIR_ENV = "DYN_INCIDENT_DIR"


@dataclass
class DetectorConfig:
    """Thresholds for the anomaly rules. Defaults are deliberately blunt —
    the detector's job is catching order-of-magnitude regressions worth a
    black-box capture, not sub-10% drift (dashboards own that)."""

    # Quantile jump: window p99 must exceed jump_factor × trailing baseline
    # AND beat it by min_abs_s (absolute floor so microsecond-scale noise
    # on near-zero baselines cannot fire).
    jump_factor: float = 3.0
    min_abs_s: float = 0.005
    # Window sample count below which a quantile is not judged (a p99 of 2
    # samples is noise).
    min_window_count: int = 8
    # Checks absorbed into the EMA baseline before a signal arms.
    baseline_checks: int = 3
    ema_alpha: float = 0.3
    # SLO violation-rate step: fraction of newly judged phase checks that
    # violated since the previous check.
    violation_rate: float = 0.5
    min_judged: int = 4
    # Decode host-gap regression: mean gap over the scrape delta vs its
    # trailing baseline.
    gap_factor: float = 3.0
    min_gap_events: int = 32
    min_gap_abs_s: float = 0.0005
    # A reason that fired cannot re-fire within this window: a persistent
    # anomaly produces ONE capture, not one per scrape.
    debounce_s: float = 60.0


class AnomalyDetector:
    """Pure function of successive stats snapshots + a clock.

    ``update(stats)`` consumes one worker-scrape-shaped stats dict (the
    exact dict ``TpuEngine.stats_handler`` / the mocker build) and returns
    the list of reasons that fired this check, post-debounce. All state
    lives here, keyed off deltas between checks, so a monkeypatched clock
    plus a synthetic stats stream reproduces exact (reason, fire-count)
    sequences — the determinism the tests pin.
    """

    def __init__(self, config: Optional[DetectorConfig] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.config = config or DetectorConfig()
        self._clock = clock
        # update() is called from whichever thread polls stats (event loop,
        # health server, bench loop): one lock serializes the whole check.
        self._lock = threading.Lock()
        self.checks_total = 0  # guarded-by: _lock
        self.fired_total = 0  # guarded-by: _lock
        # Per-quantile-signal baseline state: {reason: {"baseline", "checks"}}.
        self._qstate: Dict[str, dict] = {}  # guarded-by: _lock
        self._gap_baseline: Optional[float] = None  # guarded-by: _lock
        self._gap_checks = 0  # guarded-by: _lock
        # Counter snapshots from the previous check (delta signals).
        self._last: Dict[str, float] = {}  # guarded-by: _lock
        self._last_fire: Dict[str, float] = {}  # guarded-by: _lock
        # Last evaluated values + the baselines they were judged against —
        # embedded in bundles so the autopsy can rank signals by ratio.
        self.last_values: Dict[str, float] = {}  # guarded-by: _lock
        self.baselines: Dict[str, float] = {}  # guarded-by: _lock

    # --- helpers ------------------------------------------------------------
    @staticmethod
    def _window_digest(stats: dict, name: str) -> Optional[LatencyDigest]:
        wire = (stats.get("digests") or {}).get(name)
        if not isinstance(wire, dict) or "window" not in wire:
            return None
        try:
            return LatencyDigest.from_wire(wire["window"])
        except (TypeError, ValueError, KeyError):
            return None

    def _debounced(self, reason: str, now: float) -> bool:
        last = self._last_fire.get(reason)
        return last is not None and (now - last) < self.config.debounce_s

    def _fire(self, reason: str, now: float, fired: List[str]) -> None:
        if self._debounced(reason, now):
            return
        self._last_fire[reason] = now
        self.fired_total += 1
        fired.append(reason)

    # --- the check ----------------------------------------------------------
    def update(self, stats: dict) -> List[str]:
        """Evaluate every rule against one stats snapshot; returns the
        reasons that fired (post-debounce), in REASONS order."""
        cfg = self.config
        with self._lock:
            now = self._clock()
            self.checks_total += 1
            fired: List[str] = []

            # (1) Digest quantile jumps vs trailing EMA baselines.
            for digest_name, reason in _QUANTILE_SIGNALS:
                d = self._window_digest(stats, digest_name)
                if d is None or d.count < cfg.min_window_count:
                    continue
                p99 = d.quantile(0.99)
                st = self._qstate.setdefault(reason, {"baseline": None, "checks": 0})
                self.last_values[reason] = p99
                if st["baseline"] is not None:
                    self.baselines[reason] = st["baseline"]
                armed = st["baseline"] is not None and st["checks"] >= cfg.baseline_checks
                anomalous = (
                    armed
                    and p99 > cfg.jump_factor * st["baseline"]
                    and (p99 - st["baseline"]) > cfg.min_abs_s
                )
                if anomalous:
                    # The spike is NOT absorbed into the baseline — a
                    # sustained regression keeps reading as anomalous (and
                    # keeps being debounced) instead of becoming the new
                    # normal within a few checks.
                    self._fire(reason, now, fired)
                else:
                    st["baseline"] = (
                        p99 if st["baseline"] is None
                        else cfg.ema_alpha * p99 + (1.0 - cfg.ema_alpha) * st["baseline"]
                    )
                    st["checks"] += 1

            # (2) SLO violation-rate step over the scrape delta.
            viol = float(stats.get("slo_ttft_violated_total", 0)) + float(
                stats.get("slo_tpot_violated_total", 0)
            )
            att = float(stats.get("slo_ttft_attained_total", 0)) + float(
                stats.get("slo_tpot_attained_total", 0)
            )
            pv, pa = self._last.get("violated"), self._last.get("attained")
            if pv is not None:
                dv, da = max(viol - pv, 0.0), max(att - pa, 0.0)
                judged = dv + da
                if judged >= cfg.min_judged:
                    rate = dv / judged
                    self.last_values["slo_violation"] = rate
                    self.baselines["slo_violation"] = cfg.violation_rate
                    if rate >= cfg.violation_rate:
                        self._fire("slo_violation", now, fired)
            self._last["violated"], self._last["attained"] = viol, att

            # (3) XLA compiled mid-traffic (any increment fires).
            compiles = stats.get("compiles_after_warmup_total")
            if compiles is not None:
                compiles = float(compiles)
                prev = self._last.get("compiles")
                self.last_values["post_warmup_compile"] = compiles
                if prev is not None and compiles > prev:
                    self._fire("post_warmup_compile", now, fired)
                self._last["compiles"] = compiles

            # (4) Stall-watchdog transition (not-stalled → stalled).
            stalled = float(stats.get("engine_stalled", 0.0))
            if stalled and not self._last.get("stalled", 0.0):
                self.last_values["engine_stall"] = stalled
                self._fire("engine_stall", now, fired)
            self._last["stalled"] = stalled

            # (5b) Instance-set shrink: a worker vanished between scrapes
            # (crash / lease lapse). Any shrink fires — scale-down should
            # drain first (worker_drains_total moves instead).
            n_inst = stats.get("worker_instance_count")
            if n_inst is not None:
                n_inst = float(n_inst)
                prev_inst = self._last.get("instances")
                self.last_values["worker_lost"] = n_inst
                if prev_inst is not None and n_inst < prev_inst:
                    self.baselines["worker_lost"] = prev_inst
                    self._fire("worker_lost", now, fired)
                self._last["instances"] = n_inst

            # (5) Decode host-gap regression: mean gap over the delta.
            ev = stats.get("decode_host_gap_events_total")
            s = stats.get("decode_host_gap_seconds_total")
            if ev is not None and s is not None:
                ev, s = float(ev), float(s)
                pe, ps = self._last.get("gap_events"), self._last.get("gap_seconds")
                if pe is not None and (ev - pe) >= cfg.min_gap_events:
                    mean = max(s - ps, 0.0) / (ev - pe)
                    self.last_values["host_gap"] = mean
                    if self._gap_baseline is not None:
                        self.baselines["host_gap"] = self._gap_baseline
                    armed = (
                        self._gap_baseline is not None
                        and self._gap_checks >= cfg.baseline_checks
                    )
                    if (
                        armed
                        and mean > cfg.gap_factor * self._gap_baseline
                        and (mean - self._gap_baseline) > cfg.min_gap_abs_s
                    ):
                        self._fire("host_gap", now, fired)
                    else:
                        self._gap_baseline = (
                            mean if self._gap_baseline is None
                            else cfg.ema_alpha * mean + (1.0 - cfg.ema_alpha) * self._gap_baseline
                        )
                        self._gap_checks += 1
                    self._last["gap_events"], self._last["gap_seconds"] = ev, s
                elif pe is None:
                    self._last["gap_events"], self._last["gap_seconds"] = ev, s

            return fired

    def snapshot(self) -> dict:
        """Detector state for bundle embedding / /debug/state: the values
        each signal last read and the baselines they were judged against —
        the evidence the autopsy ranks attribution candidates with."""
        with self._lock:
            return {
                "checks_total": self.checks_total,
                "fired_total": self.fired_total,
                "last_values": dict(self.last_values),
                "baselines": dict(self.baselines),
                "last_fire_age_s": {
                    r: round(self._clock() - t, 3) for r, t in self._last_fire.items()
                },
            }


# --- global evidence probes ---------------------------------------------------
# Components that hold incident-relevant state but no IncidentPlane of their
# own (the router's routing-decision ring, for one) register a probe here;
# every bundle captured in this process attaches each probe's snapshot under
# ``evidence.<name>``. Registration is last-writer-wins per name, so a
# rebuilt router simply replaces its predecessor's probe.
_EVIDENCE_PROBES: Dict[str, Callable[[], dict]] = {}
_EVIDENCE_LOCK = threading.Lock()


def register_evidence_probe(name: str, probe: Callable[[], dict]) -> None:
    with _EVIDENCE_LOCK:
        _EVIDENCE_PROBES[name] = probe


def unregister_evidence_probe(name: str) -> None:
    with _EVIDENCE_LOCK:
        _EVIDENCE_PROBES.pop(name, None)


def collect_evidence() -> Dict[str, dict]:
    with _EVIDENCE_LOCK:
        probes = dict(_EVIDENCE_PROBES)
    out: Dict[str, dict] = {}
    for name, probe in probes.items():
        try:
            out[name] = probe()
        except Exception as e:  # noqa: BLE001 — a broken probe must not lose the bundle
            out[name] = {"probe_error": f"{type(e).__name__}: {e}"}
    return out


def dump_thread_stacks() -> Dict[str, List[str]]:
    """Python stacks of every live thread (the /debug/stacks payload,
    callable without a server): the first question when the step loop
    wedges is "where is it stuck"."""
    import sys
    import traceback

    names = {t.ident: t.name for t in threading.enumerate()}
    out: Dict[str, List[str]] = {}
    for tid, frame in sys._current_frames().items():
        out[f"{names.get(tid, '?')}-{tid}"] = traceback.format_stack(frame)
    return out


@dataclass
class IncidentConfig:
    """Capture knobs (worker CLI: --incident-dir/--incident-keep/
    --profile-on-incident; ``DYN_INCIDENT_DIR`` is the env default)."""

    dir: Optional[str] = None  # None = detect + count, never write bundles
    keep: int = 16  # LRU retention cap on bundle files
    min_interval_s: float = 30.0  # global floor between any two captures
    profile_on_incident: bool = False
    profile_seconds: float = 2.0
    detector: DetectorConfig = field(default_factory=DetectorConfig)


class IncidentRecorder:
    """Writes (and retains) incident bundles. One bundle is ONE JSON file —
    self-contained by design: it can be attached to a CI run, mailed
    around, and fed to ``tools/autopsy.py`` with no sidecar files."""

    def __init__(self, dir: Optional[str] = None, keep: int = 16,
                 min_interval_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        self.dir = dir
        self.keep = max(int(keep), 1)
        self.min_interval_s = min_interval_s
        self._clock = clock
        self._lock = threading.Lock()
        self.captures_total = 0  # guarded-by: _lock
        self.rate_limited_total = 0  # guarded-by: _lock
        self.by_reason: Dict[str, int] = {r: 0 for r in REASONS}  # guarded-by: _lock
        self.last_capture_ts: Optional[float] = None  # guarded-by: _lock
        self.last_capture: Optional[dict] = None  # guarded-by: _lock
        self._bundles: List[dict] = []  # guarded-by: _lock  (retained manifests)

    def capture(self, reason: str, detail: dict, parts: dict) -> Optional[str]:
        """Record one incident. Returns the bundle path (None when capture
        was rate-limited or no directory is configured — the counters still
        advance so the scrape reflects every detected incident)."""
        with self._lock:
            now = self._clock()
            if (
                self.last_capture_ts is not None
                and (now - self.last_capture_ts) < self.min_interval_s
            ):
                self.rate_limited_total += 1
                logger.warning(
                    "incident %s rate-limited (last capture %.1fs ago < %.1fs floor)",
                    reason, now - self.last_capture_ts, self.min_interval_s,
                )
                return None
            self.last_capture_ts = now
            self.captures_total += 1
            self.by_reason[reason] = self.by_reason.get(reason, 0) + 1
            seq = self.captures_total
        wall_ts = time.time()
        summary = {"reason": reason, "ts": wall_ts, "detail": detail, "path": None,
                   "status": "counted"}
        if self.dir is not None:
            bundle = {
                "schema": BUNDLE_SCHEMA,
                "reason": reason,
                "ts": wall_ts,
                "detail": detail,
                **parts,
            }
            try:
                os.makedirs(self.dir, exist_ok=True)
                path = os.path.join(self.dir, f"incident_{seq:04d}_{reason}.json")
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(bundle, f, default=str)
                os.replace(tmp, path)  # readers never see a torn bundle
                summary["path"] = path
                summary["status"] = "written"
            except OSError as e:
                summary["status"] = f"error: {e}"
        logger.error("incident captured: reason=%s detail=%s bundle=%s",
                     reason, detail, summary["path"])
        with self._lock:
            self.last_capture = summary
            self._bundles.append(
                {k: summary[k] for k in ("reason", "ts", "path", "status")}
            )
            evicted = self._bundles[: -self.keep]
            self._bundles = self._bundles[-self.keep:]
        for old in evicted:
            if old.get("path"):
                try:
                    os.remove(old["path"])
                except OSError:
                    pass
        return summary["path"]

    def list(self) -> List[dict]:
        """Manifests of the retained bundles, oldest first."""
        with self._lock:
            return [dict(b) for b in self._bundles]

    def to_stats(self) -> dict:
        """Flat worker-scrape keys (COUNTER_KEYS / GAUGE_KEYS names)."""
        with self._lock:
            out: dict = {"incidents_total": self.captures_total}
            for reason in REASONS:
                out[f"incidents_{reason}_total"] = self.by_reason.get(reason, 0)
            out["incident_last_age_s"] = (
                round(self._clock() - self.last_capture_ts, 3)
                if self.last_capture_ts is not None
                else -1.0
            )
            return out


class IncidentPlane:
    """Detector + recorder + capture probes behind the two calls a stats
    handler makes: ``observe(stats)`` then merge ``to_stats()``.

    Probes are pulled lazily at capture time, never per check:

    - ``state_probe`` → ``debug_state()`` (sequences, block pool, digest
      summary, recent-step timeline)
    - ``flight_probe`` → the flight recorder's step-ring snapshot
    - ``config_probe`` → engine/scheduler configuration
    - the process tracer's ring and every thread's Python stack ride along
      unconditionally.
    """

    def __init__(
        self,
        config: Optional[IncidentConfig] = None,
        *,
        state_probe: Optional[Callable[[], dict]] = None,
        flight_probe: Optional[Callable[[], dict]] = None,
        config_probe: Optional[Callable[[], dict]] = None,
        profiler=None,  # runtime.profiling.DeviceProfiler
        clock: Callable[[], float] = time.monotonic,
    ):
        self.config = config or IncidentConfig()
        self.detector = AnomalyDetector(self.config.detector, clock=clock)
        self.recorder = IncidentRecorder(
            dir=self.config.dir, keep=self.config.keep,
            min_interval_s=self.config.min_interval_s, clock=clock,
        )
        self.state_probe = state_probe
        self.flight_probe = flight_probe
        self.config_probe = config_probe
        self.profiler = profiler

    def _build_parts(self, stats: dict) -> dict:
        from dynamo_tpu.runtime.tracing import get_tracer

        def probe(fn):
            if fn is None:
                return None
            try:
                return fn()
            except Exception as e:  # noqa: BLE001 — a broken probe must not lose the bundle
                return {"probe_error": f"{type(e).__name__}: {e}"}

        return {
            "stats": stats,
            "debug_state": probe(self.state_probe),
            "flight": probe(self.flight_probe),
            "config": probe(self.config_probe),
            "detector": self.detector.snapshot(),
            "trace_ring": get_tracer().ring_records(),
            "thread_stacks": dump_thread_stacks(),
            # Cross-component evidence (e.g. the router's routing-decision
            # ring: what was being sent where just before a worker_lost).
            "evidence": collect_evidence(),
        }

    def observe(self, stats: dict) -> List[str]:
        """One detector check against one stats snapshot; captures a bundle
        per fired reason (subject to the recorder's global rate limit — a
        multi-signal anomaly produces ONE bundle, whose detector snapshot
        still carries every signal's evidence)."""
        fired = self.detector.update(stats)
        for reason in fired:
            detail = {
                "value": self.detector.last_values.get(reason),
                "baseline": self.detector.baselines.get(reason),
            }
            path = self.recorder.capture(reason, detail, self._build_parts(stats))
            if (
                path is not None
                and self.config.profile_on_incident
                and self.profiler is not None
            ):
                # Short device profile attached next to the bundle,
                # captured off-thread so the scrape path never blocks on
                # the profiler's sleep window.
                self.profiler.capture_background(
                    self.config.profile_seconds,
                    label=os.path.splitext(os.path.basename(path))[0],
                )
        return fired

    def to_stats(self) -> dict:
        out = self.recorder.to_stats()
        out["profiler_captures_total"] = (
            self.profiler.captures_total if self.profiler is not None else 0
        )
        # Capture-path collisions (incident vs continuous vs HTTP): each one
        # used to be a silent drop; now every contender either queues or is
        # refused WITH this counter ticking.
        out["profiler_capture_conflicts_total"] = (
            self.profiler.capture_conflicts_total if self.profiler is not None else 0
        )
        return out

    def debug_info(self) -> dict:
        """The /debug/state "incidents" block: retained bundle list, last
        capture status, detector evidence."""
        return {
            "bundles": self.recorder.list(),
            "last_capture": self.recorder.last_capture,
            "rate_limited_total": self.recorder.rate_limited_total,
            "detector": self.detector.snapshot(),
            "profiler": (
                self.profiler.status() if self.profiler is not None else None
            ),
        }
