"""Process-local runtime: cancellation hierarchy + graceful shutdown tracking.

Ref: lib/runtime/src/{runtime.rs:1-166, lib.rs:67 (Runtime)} and
utils/graceful_shutdown.rs:1-81. The reference builds on tokio runtimes and a
cancellation-token tree; here the asyncio event loop is the substrate and we
keep the same observable semantics: a root CancellationToken whose children
are cancelled with it, and a shutdown tracker that waits for in-flight
endpoint handlers to drain.
"""

from __future__ import annotations

import asyncio
import contextlib
import signal
from typing import Optional, Set

from dynamo_tpu.runtime.config import Config
from dynamo_tpu.runtime.logging import get_logger, init_logging

logger = get_logger(__name__)


class CancellationToken:
    """Hierarchical cancellation (tokio CancellationToken equivalent)."""

    def __init__(self, parent: Optional["CancellationToken"] = None):
        self._event = asyncio.Event()
        self._children: Set["CancellationToken"] = set()
        self._parent = parent
        if parent is not None:
            parent._children.add(self)
            if parent.is_cancelled():
                self._event.set()

    def child_token(self) -> "CancellationToken":
        return CancellationToken(self)

    def cancel(self) -> None:
        if not self._event.is_set():
            self._event.set()
            for c in list(self._children):
                c.cancel()

    def is_cancelled(self) -> bool:
        return self._event.is_set()

    async def cancelled(self) -> None:
        await self._event.wait()

    def drop(self) -> None:
        if self._parent is not None:
            self._parent._children.discard(self)


class GracefulShutdownTracker:
    """Counts in-flight endpoint handlers; shutdown waits for zero
    (ref: utils/graceful_shutdown.rs)."""

    def __init__(self):
        self._count = 0
        self._zero = asyncio.Event()
        self._zero.set()

    def enter(self) -> None:
        self._count += 1
        self._zero.clear()

    def exit(self) -> None:
        self._count -= 1
        if self._count <= 0:
            self._count = 0
            self._zero.set()

    @property
    def in_flight(self) -> int:
        return self._count

    async def wait_drained(self, timeout: Optional[float] = None) -> bool:
        try:
            await asyncio.wait_for(self._zero.wait(), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    @contextlib.contextmanager
    def track(self):
        self.enter()
        try:
            yield
        finally:
            self.exit()


class Runtime:
    """Process handle: config, root cancellation token, shutdown tracking
    (ref: lib.rs:67)."""

    def __init__(self, config: Optional[Config] = None):
        init_logging()
        self.config = config or Config.from_env()
        self.cancellation = CancellationToken()
        self.shutdown_tracker = GracefulShutdownTracker()
        self._background: Set[asyncio.Task] = set()
        self._shutdown_started = False

    def child_token(self) -> CancellationToken:
        return self.cancellation.child_token()

    def spawn(self, coro, name: Optional[str] = None) -> asyncio.Task:
        """Track a background task; cancelled at shutdown."""
        task = asyncio.get_running_loop().create_task(coro, name=name)
        self._background.add(task)
        task.add_done_callback(self._background.discard)
        return task

    def install_signal_handlers(self) -> None:
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            with contextlib.suppress(NotImplementedError, RuntimeError):
                loop.add_signal_handler(sig, self.trigger_shutdown)

    def trigger_shutdown(self) -> None:
        if not self._shutdown_started:
            logger.info("shutdown triggered")
            self._shutdown_started = True
            self.cancellation.cancel()

    async def shutdown(self, drain_timeout: Optional[float] = None) -> None:
        """Cancel, drain in-flight handlers, stop background tasks."""
        self.trigger_shutdown()
        timeout = drain_timeout if drain_timeout is not None else self.config.runtime.shutdown_timeout_s
        drained = await self.shutdown_tracker.wait_drained(timeout)
        if not drained:
            logger.warning("graceful drain timed out with %d in-flight", self.shutdown_tracker.in_flight)
        for task in list(self._background):
            task.cancel()
        if self._background:
            await asyncio.gather(*self._background, return_exceptions=True)
        self._background.clear()
