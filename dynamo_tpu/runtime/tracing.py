"""Distributed request tracing: spans, events, JSONL + Chrome-trace export.

Ref: lib/runtime/src/logging.rs (W3C ``traceparent`` + OTLP span export) and
lib/llm/src/perf.rs / recorder.rs (timestamped streams, background JSONL
writer). The reference exports OTLP; here spans land in a JSONL file a
developer can grep, feed to ``tools/trace_view.py``, or convert to the
Chrome ``chrome://tracing`` / Perfetto format.

Design constraints (why this is not just the asyncio Recorder from
``llm/perf.py``):

- **Emitters live on both sides of the thread boundary.** The scheduler
  emits from the engine's step thread (``asyncio.to_thread``); the HTTP
  service and ingress loops emit from the event loop. Export therefore
  rides a ``queue.SimpleQueue`` drained by a daemon writer thread —
  ``emit`` never blocks and never touches the event loop.
- **One trace across processes.** Sampling is a deterministic function of
  the trace id, so the frontend, worker, and scheduler independently reach
  the same keep/drop decision for a request without coordination.
- **Zero overhead when off.** ``tracer.enabled`` is a plain attribute;
  every call site guards on it (or on the per-sequence ``trace`` tuple),
  so the disabled path is one branch.
- **A black box survives export being off.** The tracer keeps the last
  ``ring_size`` records in an in-memory ring even when no trace file is
  configured: when an incident fires, the bundle captures the ring — the
  trace evidence for "what was the engine doing right before this" no
  longer depends on someone having been tailing a file.
- **Tail-based keep for SLO violators.** With ``tail=True``, traces that
  lose the deterministic head-sampling coin flip still record into the
  ring (flagged unexported); ``promote(trace_id)`` exports a trace's
  buffered records after the fact — the frontend calls it when a request
  violates its SLO, so violating requests keep their full span set at any
  sampling rate. Promotion is per-process (each process promotes its own
  ring); cross-process spans of an unsampled trace additionally survive
  through incident bundles, which carry the ring verbatim.

Span ids follow W3C trace-context: 32-hex trace ids, 16-hex span ids
(``runtime/logging.py`` TraceParent is the wire carrier).
"""

from __future__ import annotations

import json
import os
import queue
import secrets
import threading
import time
import zlib
from collections import deque
from typing import Any, Dict, Iterable, List, Optional

from dynamo_tpu.runtime.logging import TraceParent, get_logger

logger = get_logger(__name__)

TRACE_FILE_ENV = "DYN_TRACE_FILE"
TRACE_SAMPLE_ENV = "DYN_TRACE_SAMPLE"
TRACE_RING_ENV = "DYN_TRACE_RING"
TRACE_TAIL_ENV = "DYN_TRACE_TAIL"

# Default in-memory ring depth once tracing is configured (0 disables).
DEFAULT_RING_SIZE = 256


class Span:
    """An in-flight span. ``end()`` (or the ``with`` block) emits it."""

    __slots__ = ("tracer", "name", "service", "trace_id", "span_id", "parent_id",
                 "start_ns", "attrs", "events", "export", "_done")

    def __init__(self, tracer: "Tracer", name: str, service: str, trace_id: str,
                 parent_id: Optional[str], attrs: Dict[str, Any], export: bool = True):
        self.tracer = tracer
        self.name = name
        self.service = service
        self.trace_id = trace_id
        self.span_id = secrets.token_hex(8)
        self.parent_id = parent_id
        self.start_ns = time.time_ns()
        self.attrs = attrs
        self.events: List[dict] = []
        # False = ring-only (tail mode, trace not head-sampled): the record
        # stays promotable until it ages out of the ring.
        self.export = export
        self._done = False

    def event(self, name: str, **attrs: Any) -> None:
        """Instant event attached to this span's timeline."""
        self.events.append({"name": name, "ts": time.time_ns() / 1e9, **attrs})

    def set(self, **attrs: Any) -> None:
        self.attrs.update(attrs)

    def end(self) -> None:
        if self._done:
            return
        self._done = True
        rec = {
            "kind": "span",
            "name": self.name,
            "service": self.service,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "ts": self.start_ns / 1e9,
            "dur_s": (time.time_ns() - self.start_ns) / 1e9,
        }
        if self.attrs:
            rec["attrs"] = self.attrs
        if self.events:
            rec["events"] = self.events
        self.tracer._put(rec, export=self.export)

    def child_traceparent(self) -> TraceParent:
        """Wire carrier for downstream hops: same trace, this span as parent."""
        return TraceParent(trace_id=self.trace_id, parent_id=self.span_id)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None:
            self.attrs.setdefault("error", f"{type(exc).__name__}: {exc}")
        self.end()


class _NullSpan:
    """Span stand-in when the trace is not sampled: every op is a no-op."""

    __slots__ = ()

    def event(self, name: str, **attrs: Any) -> None:
        pass

    def set(self, **attrs: Any) -> None:
        pass

    def end(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        pass


NULL_SPAN = _NullSpan()


class Tracer:
    """Process tracer: sampling decision + non-blocking JSONL export.

    ``emit``/``Span.end`` enqueue records on a thread-safe queue; a daemon
    writer thread batches them to disk, so neither the event loop nor the
    engine step thread ever waits on file IO (the perf.py Recorder
    pattern, portable across the thread boundary)."""

    def __init__(self, path: Optional[str] = None, sample: float = 1.0,
                 service: str = "dynamo", ring_size: int = 0, tail: bool = False):
        self.path = path
        self.sample = sample
        self.service = service
        self.ring_size = max(int(ring_size), 0)
        # Tail-based keep: record unsampled traces into the ring so they can
        # be promoted to the export after the fact (SLO violations).
        self.tail = bool(tail) and self.ring_size > 0
        # Ring-only tracing (path=None, ring_size>0) is a valid enabled
        # state: the black box records without any file export configured.
        self.enabled = (path is not None or self.ring_size > 0) and sample > 0.0
        self.events_written = 0
        # Ring entries are mutable {"rec": ..., "exported": bool} cells so
        # promote() can mark what it already shipped (no double-export).
        self._ring: "deque[dict]" = deque(maxlen=self.ring_size or 1)
        self._queue: "queue.SimpleQueue[Optional[dict]]" = queue.SimpleQueue()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    # --- sampling -----------------------------------------------------------
    def sampled(self, trace_id: str) -> bool:
        """Deterministic head sampling keyed on the trace id: every process
        in the request's path reaches the same decision, so one request is
        either fully traced everywhere or not at all."""
        if not self.enabled:
            return False
        if self.sample >= 1.0:
            return True
        # crc32 over the whole id: stable across processes/runs (unlike
        # hash()) and uniform even for low-entropy ids.
        frac = (zlib.crc32(trace_id.encode()) & 0xFFFFFFFF) / 0xFFFFFFFF
        return frac < self.sample

    def record_allowed(self, trace_id: str) -> bool:
        """Should this trace produce records at all? Head-sampled traces
        export; in tail mode unsampled traces still record into the ring
        (promotable later)."""
        if not self.enabled:
            return False
        return self.tail or self.sampled(trace_id)

    # --- span / event API ---------------------------------------------------
    def span(self, name: str, trace_id: str, parent_id: Optional[str] = None,
             service: Optional[str] = None, **attrs: Any):
        if not self.record_allowed(trace_id):
            return NULL_SPAN
        return Span(self, name, service or self.service, trace_id, parent_id,
                    attrs, export=self.sampled(trace_id))

    def span_from(self, name: str, tp: TraceParent, **attrs: Any):
        """Span continuing a wire TraceParent (its parent_id is the remote
        caller's span)."""
        return self.span(name, tp.trace_id, parent_id=tp.parent_id, **attrs)

    def event(self, name: str, trace_id: str, parent_id: Optional[str] = None,
              service: Optional[str] = None, **attrs: Any) -> None:
        """Instant (zero-duration) event in a trace."""
        if not self.record_allowed(trace_id):
            return
        rec = {
            "kind": "event",
            "name": name,
            "service": service or self.service,
            "trace_id": trace_id,
            "parent_id": parent_id,
            "ts": time.time_ns() / 1e9,
        }
        if attrs:
            rec["attrs"] = attrs
        self._put(rec, export=self.sampled(trace_id))

    # --- ring / tail promotion ----------------------------------------------
    def ring_records(self) -> List[dict]:
        """Snapshot of the in-memory ring, oldest first (incident bundles
        embed this — the per-process trace black box)."""
        return [cell["rec"] for cell in list(self._ring)]

    def promote(self, trace_id: str) -> int:
        """Export every still-buffered (unexported) record of ``trace_id``
        from the ring — the tail-sampling keep decision. Returns how many
        records were promoted. A no-op without a trace file (the ring alone
        already retains them for incident bundles)."""
        n = 0
        for cell in list(self._ring):
            if cell["exported"] or cell["rec"].get("trace_id") != trace_id:
                continue
            cell["exported"] = True
            n += 1
            if self.path is not None:
                self._queue.put(cell["rec"])
        if n and self.path is not None:
            self._ensure_writer()
        return n

    # --- export plumbing ----------------------------------------------------
    def _put(self, rec: dict, export: bool = True) -> None:
        if self.ring_size:
            self._ring.append({"rec": rec, "exported": export})
        if not export or self.path is None:
            return
        self._queue.put(rec)
        self._ensure_writer()

    def _ensure_writer(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._writer, name="trace-writer", daemon=True
                )
                self._thread.start()

    def _writer(self) -> None:
        with open(self.path, "a") as f:
            while True:
                item = self._queue.get()
                if item is None:
                    return
                batch = [item]
                # Batch whatever is already queued into one write+flush.
                while True:
                    try:
                        nxt = self._queue.get_nowait()
                    except queue.Empty:
                        break
                    if nxt is None:
                        self._drain(f, batch)
                        return
                    batch.append(nxt)
                self._drain(f, batch)

    def _drain(self, f, batch: List[dict]) -> None:
        for rec in batch:
            f.write(json.dumps(rec) + "\n")
        f.flush()
        self.events_written += len(batch)

    def flush(self, timeout: float = 5.0) -> None:
        """Stop the writer after draining everything queued so far. The next
        emit restarts it — safe to call between requests or at exit."""
        if self._thread is None or not self._thread.is_alive():
            return
        self._queue.put(None)
        self._thread.join(timeout)
        self._thread = None

    def close(self) -> None:
        self.flush()
        self.enabled = False


# --- process-global tracer ---------------------------------------------------

_TRACER = Tracer(path=None, sample=0.0)


def configure_tracing(path: Optional[str] = None, sample: Optional[float] = None,
                      service: Optional[str] = None, ring_size: Optional[int] = None,
                      tail: Optional[bool] = None) -> Tracer:
    """(Re)configure the process tracer. Falls back to ``DYN_TRACE_FILE`` /
    ``DYN_TRACE_SAMPLE`` / ``DYN_TRACE_RING`` / ``DYN_TRACE_TAIL`` env (the
    knobs worker/frontend CLIs expose). The ring defaults ON
    (``DEFAULT_RING_SIZE`` records) so every configured process keeps a
    trace black box for incident bundles even with no trace file."""
    global _TRACER
    if path is None:
        path = os.environ.get(TRACE_FILE_ENV) or None
    if sample is None:
        try:
            sample = float(os.environ.get(TRACE_SAMPLE_ENV, "1.0"))
        except ValueError:
            sample = 1.0
    if ring_size is None:
        try:
            ring_size = int(os.environ.get(TRACE_RING_ENV, str(DEFAULT_RING_SIZE)))
        except ValueError:
            ring_size = DEFAULT_RING_SIZE
    if tail is None:
        tail = os.environ.get(TRACE_TAIL_ENV, "").strip().lower() in ("1", "true", "yes", "on")
    _TRACER.flush()
    _TRACER = Tracer(path=path, sample=sample, service=service or _TRACER.service,
                     ring_size=ring_size, tail=tail)
    return _TRACER


def get_tracer() -> Tracer:
    return _TRACER


# --- readers / exporters -----------------------------------------------------


def read_trace_file(path: str) -> List[dict]:
    """Parse a JSONL trace file, skipping malformed lines (a crash mid-write
    must not make the whole file unreadable)."""
    out: List[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
    return out


def chrome_trace(records: Iterable[dict]) -> dict:
    """Convert span/event records to the Chrome trace-event format (loadable
    in chrome://tracing and Perfetto). Services map to pids; each trace id
    gets its own tid lane so concurrent requests don't interleave."""
    services: Dict[str, int] = {}
    lanes: Dict[str, int] = {}
    events: List[dict] = []

    def pid(service: str) -> int:
        if service not in services:
            services[service] = len(services) + 1
            events.append({
                "ph": "M", "pid": services[service], "name": "process_name",
                "args": {"name": service},
            })
        return services[service]

    def tid(trace_id: str) -> int:
        if trace_id not in lanes:
            lanes[trace_id] = len(lanes) + 1
        return lanes[trace_id]

    for rec in records:
        if rec.get("kind") not in ("span", "event"):
            continue
        base = {
            "pid": pid(rec.get("service") or "dynamo"),
            "tid": tid(rec.get("trace_id") or "?"),
            "name": rec.get("name") or "?",
            "ts": float(rec.get("ts") or 0.0) * 1e6,  # µs
            "args": {
                "trace_id": rec.get("trace_id"),
                "span_id": rec.get("span_id"),
                "parent_id": rec.get("parent_id"),
                **(rec.get("attrs") or {}),
            },
        }
        if rec["kind"] == "span":
            events.append({**base, "ph": "X", "dur": float(rec.get("dur_s") or 0.0) * 1e6})
            for ev in rec.get("events") or []:
                events.append({
                    "ph": "i", "s": "t",
                    "pid": base["pid"], "tid": base["tid"],
                    "name": ev.get("name") or "?",
                    "ts": float(ev.get("ts") or 0.0) * 1e6,
                    "args": {k: v for k, v in ev.items() if k not in ("name", "ts")},
                })
        else:
            events.append({**base, "ph": "i", "s": "t"})
    return {"traceEvents": events, "displayTimeUnit": "ms"}
