"""DistributedRuntime: the cluster handle.

Ref: lib/runtime/src/{lib.rs:243-272, distributed.rs:42-170} — owns the etcd +
NATS clients (here: KvStore + PubSub), a lazily-started TCP response-plane
server, the component registry, metrics registries, and SystemHealth.

Backends:
- ``detached()``      — in-memory store+bus: single-process deployments, tests.
- ``from_settings()`` — honours ``DYN_CONTROL_PLANE`` env: ``mem`` or ``tcp``
  (the built-in control-plane server, ``python -m dynamo_tpu.control_plane``).
"""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional

from dynamo_tpu.runtime.component import Namespace, ServeHandle
from dynamo_tpu.runtime.config import Config
from dynamo_tpu.runtime.engine import AsyncEngine
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.runtime import Runtime
from dynamo_tpu.runtime.transports.kvstore import KvStore, Lease, MemKvStore
from dynamo_tpu.runtime.transports.pubsub import MemPubSub, PubSub
from dynamo_tpu.runtime.transports.tcp import TcpStreamServer

logger = get_logger(__name__)


class DistributedRuntime:
    def __init__(
        self,
        runtime: Optional[Runtime] = None,
        store: Optional[KvStore] = None,
        bus: Optional[PubSub] = None,
        *,
        advertise_host: str = "127.0.0.1",
    ):
        self.runtime = runtime or Runtime()
        self.config: Config = self.runtime.config
        self.store = store if store is not None else MemKvStore()
        self.bus = bus if bus is not None else MemPubSub()
        self._tcp_server = TcpStreamServer(advertise_host=advertise_host)
        self._tcp_started = False
        # In-process engines by instance id — the local fast path registry.
        self.local_engines: Dict[int, AsyncEngine] = {}
        self.serve_handles: List[ServeHandle] = []
        self._closed = False

    # --- constructors -------------------------------------------------------
    @classmethod
    async def detached(cls, runtime: Optional[Runtime] = None) -> "DistributedRuntime":
        """Single-process runtime with in-memory control plane
        (ref: from_settings_without_discovery distributed.rs:161-170)."""
        drt = cls(runtime=runtime)
        await drt.start()
        return drt

    @classmethod
    async def from_settings(cls, runtime: Optional[Runtime] = None) -> "DistributedRuntime":
        runtime = runtime or Runtime()
        backend = runtime.config.control_plane.backend
        if backend == "mem":
            return await cls.detached(runtime)
        if backend == "tcp":
            from dynamo_tpu.runtime.transports.tcp_control import TcpKvStore, TcpPubSub, connect_control_plane

            conn = await connect_control_plane(runtime.config.control_plane.address)
            drt = cls(runtime=runtime, store=TcpKvStore(conn), bus=TcpPubSub(conn))
            await drt.start()
            return drt
        raise ValueError(f"unknown control plane backend: {backend}")

    async def start(self) -> None:
        if not self._tcp_started:
            await self._tcp_server.start()
            self._tcp_started = True

    # --- component model ----------------------------------------------------
    def namespace(self, name: Optional[str] = None) -> Namespace:
        return Namespace(self, name or self.config.namespace)

    def tcp_server_handle(self) -> TcpStreamServer:
        assert self._tcp_started, "DistributedRuntime not started"
        return self._tcp_server

    # --- leases -------------------------------------------------------------
    def spawn_lease_keepalive(self, lease: Lease) -> None:
        """Keep a lease alive at ttl/3 cadence until revoked
        (ref: transports/etcd/lease.rs keepalive loop)."""

        async def keepalive():
            from dynamo_tpu.runtime import faults

            interval = max(lease.ttl_s / 3.0, 0.1)
            try:
                while not lease.revoked:
                    await asyncio.sleep(interval)
                    if lease.revoked:
                        return
                    if faults.armed():
                        # Chaos plane: ``lease_drop`` skips renewals — the
                        # TTL keeps ticking, the lease expires, the
                        # instance key vanishes, and routers prune the
                        # worker within one watch delivery.
                        try:
                            await faults.afire("lease.keepalive", lease=f"{lease.id:x}")
                        except faults.InjectedFault:
                            continue
                    try:
                        await self.store.keep_alive(lease.id)
                    except Exception:
                        logger.warning("lease %x keepalive failed", lease.id)
                        return
            except asyncio.CancelledError:
                pass

        self.runtime.spawn(keepalive(), name=f"lease-keepalive-{lease.id:x}")

    # --- shutdown -----------------------------------------------------------
    async def shutdown(self) -> None:
        if self._closed:
            return
        self._closed = True
        for handle in list(self.serve_handles):
            try:
                await handle.stop()
            except Exception:
                logger.exception("error stopping endpoint %s", handle.instance.etcd_key)
        await self.runtime.shutdown()
        await self._tcp_server.close()
        await self.bus.close()
        await self.store.close()
