"""Logging + distributed tracing context.

TPU-native equivalent of the reference's tracing-subscriber setup and W3C
``traceparent`` propagation (ref: lib/runtime/src/logging.rs:1-1098 —
``TraceParent`` :179, ``DistributedTraceContext`` :138, JSONL mode via
``DYN_LOGGING_JSONL`` :305).

Trace context rides request headers (HTTP) and control-plane message headers so
a request can be followed frontend → router → worker across processes.
"""

from __future__ import annotations

import json
import logging
import os
import secrets
import sys
import time
from dataclasses import dataclass, field
from typing import Mapping, Optional

TRACEPARENT_HEADER = "traceparent"
TRACESTATE_HEADER = "tracestate"


@dataclass
class TraceParent:
    """W3C trace-context carrier (ref: logging.rs:179)."""

    version: int = 0
    trace_id: str = ""
    parent_id: str = ""
    flags: int = 1
    tracestate: Optional[str] = None

    @classmethod
    def new_root(cls) -> "TraceParent":
        return cls(trace_id=secrets.token_hex(16), parent_id=secrets.token_hex(8))

    @classmethod
    def from_header(cls, value: str, tracestate: Optional[str] = None) -> Optional["TraceParent"]:
        try:
            parts = value.strip().split("-")
            if len(parts) != 4:
                return None
            version, trace_id, parent_id, flags = parts
            if len(trace_id) != 32 or len(parent_id) != 16 or set(trace_id) == {"0"}:
                return None
            return cls(
                version=int(version, 16),
                trace_id=trace_id.lower(),
                parent_id=parent_id.lower(),
                flags=int(flags, 16),
                tracestate=tracestate,
            )
        except (ValueError, AttributeError):
            return None

    @classmethod
    def from_headers(cls, headers: Mapping[str, str]) -> Optional["TraceParent"]:
        lowered = {k.lower(): v for k, v in headers.items()}
        tp = lowered.get(TRACEPARENT_HEADER)
        if tp is None:
            return None
        return cls.from_header(tp, lowered.get(TRACESTATE_HEADER))

    def child(self) -> "TraceParent":
        """New span within the same trace."""
        return TraceParent(
            version=self.version,
            trace_id=self.trace_id,
            parent_id=secrets.token_hex(8),
            flags=self.flags,
            tracestate=self.tracestate,
        )

    def to_header(self) -> str:
        return f"{self.version:02x}-{self.trace_id}-{self.parent_id}-{self.flags:02x}"

    def to_headers(self) -> dict:
        h = {TRACEPARENT_HEADER: self.to_header()}
        if self.tracestate:
            h[TRACESTATE_HEADER] = self.tracestate
        return h


class JsonlFormatter(logging.Formatter):
    """One JSON object per line (ref: logging.rs JSONL mode)."""

    def format(self, record: logging.LogRecord) -> str:
        entry = {
            "ts": round(time.time(), 6),
            "level": record.levelname,
            "target": record.name,
            "message": record.getMessage(),
        }
        for k in ("trace_id", "span_id", "request_id", "component", "endpoint"):
            v = getattr(record, k, None)
            if v is not None:
                entry[k] = v
        if record.exc_info:
            entry["exception"] = self.formatException(record.exc_info)
        return json.dumps(entry, ensure_ascii=False)


_INITIALIZED = False


def init_logging(level: Optional[str] = None, jsonl: Optional[bool] = None) -> None:
    """Initialise process logging once (ref: logging.rs init :401).

    Env: ``DYN_LOG`` (level filter, like RUST_LOG), ``DYN_LOGGING_JSONL``.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    _INITIALIZED = True
    level = level or os.environ.get("DYN_LOG", "INFO")
    jsonl = jsonl if jsonl is not None else os.environ.get("DYN_LOGGING_JSONL", "").lower() in ("1", "true")
    handler = logging.StreamHandler(sys.stderr)
    if jsonl:
        handler.setFormatter(JsonlFormatter())
    else:
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname).1s %(name)s %(message)s", datefmt="%H:%M:%S")
        )
    root = logging.getLogger()
    root.handlers[:] = [handler]
    try:
        root.setLevel(level.upper())
    except ValueError:
        root.setLevel(logging.INFO)


def get_logger(name: str) -> logging.Logger:
    return logging.getLogger(name)
