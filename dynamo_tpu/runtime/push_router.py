"""Client-side request routing over live instances.

Ref: lib/runtime/src/pipeline/network/egress/push_router.rs:33-275
(``RouterMode`` :71 — round_robin :138 / random :159 / direct :179 / static
:197, busy-threshold gating via WorkerMonitor) and egress/addressed_router.rs
(two-part wire: publish request over pub/sub with TCP call-home info; response
frames return over TCP).

Failure lifecycle (this layer, not the Migration operator above it):

- **Retry budget** — ``NoInstancesError`` (empty instance set, e.g. during a
  rolling restart) is retried inside the router with jittered exponential
  backoff up to ``RetryPolicy.max_retries`` before surfacing. The old
  behavior surfaced immediately and the Migration operator spun on it with
  zero backoff.
- **Circuit breaker** — per-worker consecutive-failure tracking: a worker
  whose streams keep dying trips OPEN and is excluded from candidate
  selection for ``cooldown_s``; after cooldown one HALF-OPEN probe request
  is allowed through — success closes the circuit, failure re-opens it.
  State is lock-guarded: routes run on the event loop while stats scrapes
  read snapshots from other threads.
- **Prompt cancellation** — a watcher task publishes the cancel op the
  moment the request context stops, instead of waiting for the next frame
  to notice.

The KV-aware mode lives in ``dynamo_tpu.llm.kv_router`` and wraps this router
with a scheduler-chosen ``instance_id`` (the reference's KvPushRouter does the
same around PushRouter.direct).
"""

from __future__ import annotations

import asyncio
import collections
import enum
import random
import threading
import time
from dataclasses import dataclass
from typing import Any, AsyncIterator, Dict, Optional, Set

import msgpack

from dynamo_tpu.runtime.client import Client
from dynamo_tpu.runtime.engine import Annotated, Context, StreamDisconnect
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.tracing import get_tracer

logger = get_logger(__name__)


class RouterMode(enum.Enum):
    ROUND_ROBIN = "round-robin"
    RANDOM = "random"
    DIRECT = "direct"
    KV = "kv"


class NoInstancesError(Exception):
    pass


class WorkerMonitor:
    """Tracks per-worker busy state from published load metrics
    (ref: utils/worker_monitor.rs:34-190 — busy when kv-cache usage exceeds
    the threshold). Fed by ForwardPassMetrics via the metrics subscriber."""

    def __init__(self, busy_threshold: Optional[float] = None):
        self.busy_threshold = busy_threshold
        self._usage: dict[int, float] = {}

    def update(self, instance_id: int, kv_usage: float) -> None:
        self._usage[instance_id] = kv_usage

    def busy_instances(self) -> Set[int]:
        if self.busy_threshold is None:
            return set()
        return {i for i, u in self._usage.items() if u >= self.busy_threshold}


@dataclass
class RetryPolicy:
    """NoInstances retry budget with jittered exponential backoff. ``seed``
    pins the jitter for deterministic tests; production leaves it None."""

    max_retries: int = 3
    backoff_base_s: float = 0.05
    backoff_max_s: float = 2.0
    jitter: float = 0.5  # fraction of each backoff randomized away
    seed: Optional[int] = None

    def __post_init__(self):
        self._rng = random.Random(self.seed)

    def backoff_s(self, attempt: int) -> float:
        base = min(self.backoff_max_s, self.backoff_base_s * (2.0 ** attempt))
        return base * (1.0 - self.jitter * self._rng.random())


# Circuit states (exported in snapshots; the gauge value for circuit_open).
CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


class CircuitBreaker:
    """Per-worker consecutive-failure circuit.

    closed --(failures >= threshold)--> open --(cooldown)--> half_open
    half_open --(probe success)--> closed ; --(probe failure)--> open

    All state behind one lock: ``record_*`` fire from the routing path on
    the event loop while ``snapshot()`` serves stats scrapes from other
    threads (THR001 scope)."""

    def __init__(self, threshold: int = 3, cooldown_s: float = 5.0,
                 clock=time.monotonic, on_transition=None):
        self.threshold = max(int(threshold), 1)
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._on_transition = on_transition  # (instance_id, state) -> None
        self._lock = threading.Lock()
        # {instance_id: {"state", "failures", "opened_at", "probing"}}
        self._w: Dict[int, dict] = {}  # guarded-by: _lock
        self.trips_total = 0  # guarded-by: _lock

    def _entry(self, wid: int) -> dict:
        return self._w.setdefault(
            wid, {"state": CLOSED, "failures": 0, "opened_at": 0.0, "probing": False}
        )

    def _set_state(self, wid: int, e: dict, state: str) -> None:
        if e["state"] != state:
            e["state"] = state
            if self._on_transition is not None:
                self._on_transition(wid, state)

    def record_failure(self, wid: int) -> None:
        with self._lock:
            e = self._entry(wid)
            e["failures"] += 1
            e["probing"] = False
            if e["state"] == HALF_OPEN or e["failures"] >= self.threshold:
                if e["state"] != OPEN:
                    self.trips_total += 1
                    logger.warning(
                        "circuit OPEN for worker %x (%d consecutive failures)",
                        wid, e["failures"],
                    )
                self._set_state(wid, e, OPEN)
                e["opened_at"] = self._clock()

    def record_success(self, wid: int) -> None:
        with self._lock:
            e = self._entry(wid)
            if e["state"] != CLOSED:
                logger.info("circuit CLOSED for worker %x", wid)
            e["failures"] = 0
            e["probing"] = False
            self._set_state(wid, e, CLOSED)

    def blocked_instances(self) -> Set[int]:
        """Workers selection must skip right now. OPEN workers whose
        cooldown lapsed transition to HALF_OPEN here (and stop being
        blocked until a probe claims the slot)."""
        now = self._clock()
        with self._lock:
            out: Set[int] = set()
            for wid, e in self._w.items():
                if e["state"] == OPEN:
                    if now - e["opened_at"] >= self.cooldown_s:
                        self._set_state(wid, e, HALF_OPEN)
                    else:
                        out.add(wid)
                        continue
                if e["state"] == HALF_OPEN and e["probing"]:
                    out.add(wid)  # one probe at a time
            return out

    def note_dispatch(self, wid: int) -> None:
        """Selection chose this worker: a HALF_OPEN worker's dispatch is
        the probe — block further routes until it resolves."""
        with self._lock:
            e = self._w.get(wid)
            if e is not None and e["state"] == HALF_OPEN:
                e["probing"] = True

    def forget(self, wid: int) -> None:
        with self._lock:
            self._w.pop(wid, None)

    def state_of(self, wid: int) -> str:
        with self._lock:
            e = self._w.get(wid)
            return e["state"] if e is not None else CLOSED

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "trips_total": self.trips_total,
                "workers": {
                    f"{wid:x}": {"state": e["state"], "failures": e["failures"]}
                    for wid, e in self._w.items()
                },
            }


class PushRouter:
    """Routes requests to endpoint instances; returns the response stream."""

    def __init__(
        self,
        client: Client,
        mode: RouterMode = RouterMode.ROUND_ROBIN,
        *,
        monitor: Optional[WorkerMonitor] = None,
        retry: Optional[RetryPolicy] = None,
        breaker: Optional[CircuitBreaker] = None,
        metrics=None,  # optional MetricsRegistry: circuit_open{worker} gauges
    ):
        self.client = client
        self.drt = client.drt
        self.mode = mode
        self.monitor = monitor or WorkerMonitor()
        self._metrics = metrics
        self.retry = retry if retry is not None else RetryPolicy()
        self.breaker = breaker if breaker is not None else CircuitBreaker(
            on_transition=self._on_circuit_transition
        )
        self.retries_total = 0
        self._rr = 0
        # Routing-decision black box: the evidence an incident bundle wants
        # when a worker vanishes ("what was being sent where, just before").
        self.decisions: collections.deque = collections.deque(maxlen=64)
        from dynamo_tpu.runtime.incidents import register_evidence_probe

        register_evidence_probe(
            f"router:{client.endpoint.path}", self.routing_evidence
        )

    def _on_circuit_transition(self, wid: int, state: str) -> None:
        if self._metrics is not None:
            self._metrics.gauge(
                "circuit_open", "per-worker circuit state (1=open, 0.5=half-open)",
                worker=f"{wid:x}",
            ).set(1.0 if state == OPEN else (0.5 if state == HALF_OPEN else 0.0))

    def routing_evidence(self) -> dict:
        """Recent routing decisions + breaker state (incident bundles)."""
        return {
            "mode": self.mode.value,
            "endpoint": self.client.endpoint.path,
            "live_instances": [f"{i:x}" for i in self.client.instance_ids()],
            "recent_decisions": list(self.decisions),
            "breaker": self.breaker.snapshot(),
            "retries_total": self.retries_total,
        }

    # --- instance selection -------------------------------------------------
    def _candidates(self) -> list[int]:
        ids = self.client.instance_ids()
        if not ids:
            raise NoInstancesError(f"no instances for {self.client.endpoint.path}")
        busy = self.monitor.busy_instances()
        blocked = self.breaker.blocked_instances()
        free = [i for i in ids if i not in busy and i not in blocked]
        if free:
            return free
        unblocked = [i for i in ids if i not in blocked]
        # all busy ⇒ degrade to the unblocked set; all circuits open ⇒
        # degrade to the full set rather than fail (availability beats
        # breaker purity when there is nowhere else to send).
        return unblocked or ids

    def select(self, instance_id: Optional[int] = None) -> int:
        if instance_id is not None:
            if instance_id not in self.client.instances:
                raise NoInstancesError(f"instance {instance_id:x} not found for {self.client.endpoint.path}")
            return instance_id
        ids = self._candidates()
        if self.mode == RouterMode.RANDOM:
            return random.choice(ids)
        # default round-robin
        chosen = ids[self._rr % len(ids)]
        self._rr += 1
        return chosen

    async def _select_with_retry(self, instance_id: Optional[int]) -> int:
        """Selection behind the retry budget: an empty instance set gets
        jittered-backoff retries (rolling restart, watch latency) before
        NoInstancesError surfaces. Direct selects (explicit instance_id)
        don't retry — the caller pinned a worker that is gone."""
        attempt = 0
        while True:
            try:
                return self.select(instance_id)
            except NoInstancesError:
                if instance_id is not None or attempt >= self.retry.max_retries:
                    raise
                delay = self.retry.backoff_s(attempt)
                attempt += 1
                self.retries_total += 1
                logger.warning(
                    "no instances for %s; retry %d/%d in %.0f ms",
                    self.client.endpoint.path, attempt, self.retry.max_retries,
                    delay * 1000.0,
                )
                await asyncio.sleep(delay)

    # --- request paths ------------------------------------------------------
    async def generate(
        self,
        request: Any,
        context: Optional[Context] = None,
        *,
        instance_id: Optional[int] = None,
    ) -> AsyncIterator[Annotated]:
        """Push the request to a selected instance and yield response frames.

        Raises :class:`StreamDisconnect` if the stream drops mid-flight, which
        the Migration operator upstream turns into a replay on another worker.
        """
        ctx = context or Context()
        chosen = await self._select_with_retry(instance_id)
        instance = self.client.instances[chosen]
        self.breaker.note_dispatch(chosen)
        self.decisions.append({
            "ts": round(time.monotonic(), 3),
            "request_id": ctx.id,
            "instance": f"{chosen:x}",
            "mode": self.mode.value,
        })
        tp = ctx.traceparent
        if tp is not None:
            get_tracer().event(
                "route", tp.trace_id, parent_id=tp.parent_id, service="frontend",
                instance=f"{chosen:x}", endpoint=self.client.endpoint.path,
                mode=self.mode.value,
            )

        local = self.drt.local_engines.get(chosen)
        if local is not None:
            # In-process fast path: skip pub/sub + TCP entirely.
            try:
                async for item in self._generate_local(local, request, ctx):
                    yield item
            except StreamDisconnect:
                self.breaker.record_failure(chosen)
                raise
            self.breaker.record_success(chosen)
            return

        conn_info, pending = self.drt.tcp_server_handle().register()
        payload = msgpack.packb(
            {"request": request, "ctx": ctx.to_wire(), "conn": conn_info.to_dict()},
            use_bin_type=True,
        )
        await self.drt.bus.publish(instance.subject, payload)

        cancel_state = {"sent": False}

        async def publish_cancel() -> None:
            if cancel_state["sent"]:
                return
            cancel_state["sent"] = True
            # Two-level cancellation (ref: engine.rs AsyncEngineContext):
            # stop_generating → graceful "cancel" (the engine frees KV and
            # closes the stream with finish_reason=cancelled); kill → hard
            # "kill" (the handler abandons mid-stream).
            op = "kill" if ctx.is_killed() else "cancel"
            await self.drt.bus.publish(
                instance.control_subject,
                msgpack.packb({"op": op, "request_id": ctx.id}, use_bin_type=True),
            )

        async def cancel_on_stop() -> None:
            # Prompt propagation: a stopped context publishes the cancel op
            # immediately — the old path only noticed at the next frame,
            # which for a long prefill could be seconds away.
            await ctx.stopped()
            await publish_cancel()

        watcher = asyncio.get_running_loop().create_task(cancel_on_stop())
        try:
            async for frame in pending.frames():
                if ctx.is_stopped():
                    await publish_cancel()
                if frame.kind == "prologue":
                    continue
                if frame.kind == "data":
                    yield Annotated.from_wire(frame.header)
                elif frame.kind == "complete":
                    self.breaker.record_success(chosen)
                    return
                elif frame.kind == "error":
                    if frame.header.get("disconnect"):
                        # Abrupt socket death too: the TCP layer surfaces it
                        # as a synthesized disconnect error frame.
                        self.breaker.record_failure(chosen)
                        raise StreamDisconnect(frame.header.get("message", "disconnect"))
                    raise RuntimeError(frame.header.get("message", "engine error"))
        finally:
            watcher.cancel()
            self.drt.tcp_server_handle().unregister(conn_info.stream_id)

    async def _generate_local(self, engine, request, ctx) -> AsyncIterator[Annotated]:
        try:
            async for item in engine.generate(request, ctx):
                yield item if isinstance(item, Annotated) else Annotated(data=item)
        except ConnectionError as e:
            # In-process engines die with the same observable semantics as
            # the wire path: a StreamDisconnect the Migration operator can
            # replay (a raw ConnectionResetError would bubble to a 500).
            raise StreamDisconnect(str(e) or "engine connection failure") from e

    # convenience wrappers matching the reference's API surface
    async def round_robin(self, request, context=None):
        self.mode = RouterMode.ROUND_ROBIN
        return self.generate(request, context)

    async def random(self, request, context=None):
        self.mode = RouterMode.RANDOM
        return self.generate(request, context)

    async def direct(self, request, instance_id: int, context=None):
        return self.generate(request, context, instance_id=instance_id)
