"""Client-side request routing over live instances.

Ref: lib/runtime/src/pipeline/network/egress/push_router.rs:33-275
(``RouterMode`` :71 — round_robin :138 / random :159 / direct :179 / static
:197, busy-threshold gating via WorkerMonitor) and egress/addressed_router.rs
(two-part wire: publish request over pub/sub with TCP call-home info; response
frames return over TCP).

The KV-aware mode lives in ``dynamo_tpu.llm.kv_router`` and wraps this router
with a scheduler-chosen ``instance_id`` (the reference's KvPushRouter does the
same around PushRouter.direct).
"""

from __future__ import annotations

import asyncio
import enum
import random
from typing import Any, AsyncIterator, Optional, Set

import msgpack

from dynamo_tpu.runtime.client import Client
from dynamo_tpu.runtime.engine import Annotated, Context, StreamDisconnect
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.tracing import get_tracer

logger = get_logger(__name__)


class RouterMode(enum.Enum):
    ROUND_ROBIN = "round-robin"
    RANDOM = "random"
    DIRECT = "direct"
    KV = "kv"


class NoInstancesError(Exception):
    pass


class WorkerMonitor:
    """Tracks per-worker busy state from published load metrics
    (ref: utils/worker_monitor.rs:34-190 — busy when kv-cache usage exceeds
    the threshold). Fed by ForwardPassMetrics via the metrics subscriber."""

    def __init__(self, busy_threshold: Optional[float] = None):
        self.busy_threshold = busy_threshold
        self._usage: dict[int, float] = {}

    def update(self, instance_id: int, kv_usage: float) -> None:
        self._usage[instance_id] = kv_usage

    def busy_instances(self) -> Set[int]:
        if self.busy_threshold is None:
            return set()
        return {i for i, u in self._usage.items() if u >= self.busy_threshold}


class PushRouter:
    """Routes requests to endpoint instances; returns the response stream."""

    def __init__(
        self,
        client: Client,
        mode: RouterMode = RouterMode.ROUND_ROBIN,
        *,
        monitor: Optional[WorkerMonitor] = None,
    ):
        self.client = client
        self.drt = client.drt
        self.mode = mode
        self.monitor = monitor or WorkerMonitor()
        self._rr = 0

    # --- instance selection -------------------------------------------------
    def _candidates(self) -> list[int]:
        ids = self.client.instance_ids()
        if not ids:
            raise NoInstancesError(f"no instances for {self.client.endpoint.path}")
        busy = self.monitor.busy_instances()
        free = [i for i in ids if i not in busy]
        return free or ids  # all busy ⇒ degrade to full set rather than fail

    def select(self, instance_id: Optional[int] = None) -> int:
        if instance_id is not None:
            if instance_id not in self.client.instances:
                raise NoInstancesError(f"instance {instance_id:x} not found for {self.client.endpoint.path}")
            return instance_id
        ids = self._candidates()
        if self.mode == RouterMode.RANDOM:
            return random.choice(ids)
        # default round-robin
        chosen = ids[self._rr % len(ids)]
        self._rr += 1
        return chosen

    # --- request paths ------------------------------------------------------
    async def generate(
        self,
        request: Any,
        context: Optional[Context] = None,
        *,
        instance_id: Optional[int] = None,
    ) -> AsyncIterator[Annotated]:
        """Push the request to a selected instance and yield response frames.

        Raises :class:`StreamDisconnect` if the stream drops mid-flight, which
        the Migration operator upstream turns into a replay on another worker.
        """
        ctx = context or Context()
        chosen = self.select(instance_id)
        instance = self.client.instances[chosen]
        tp = ctx.traceparent
        if tp is not None:
            get_tracer().event(
                "route", tp.trace_id, parent_id=tp.parent_id, service="frontend",
                instance=f"{chosen:x}", endpoint=self.client.endpoint.path,
                mode=self.mode.value,
            )

        local = self.drt.local_engines.get(chosen)
        if local is not None:
            # In-process fast path: skip pub/sub + TCP entirely.
            async for item in self._generate_local(local, request, ctx):
                yield item
            return

        conn_info, pending = self.drt.tcp_server_handle().register()
        payload = msgpack.packb(
            {"request": request, "ctx": ctx.to_wire(), "conn": conn_info.to_dict()},
            use_bin_type=True,
        )
        await self.drt.bus.publish(instance.subject, payload)

        cancelled_sent = False
        try:
            async for frame in pending.frames():
                if ctx.is_stopped() and not cancelled_sent:
                    cancelled_sent = True
                    await self.drt.bus.publish(
                        instance.control_subject,
                        msgpack.packb({"op": "cancel", "request_id": ctx.id}, use_bin_type=True),
                    )
                if frame.kind == "prologue":
                    continue
                if frame.kind == "data":
                    yield Annotated.from_wire(frame.header)
                elif frame.kind == "complete":
                    return
                elif frame.kind == "error":
                    if frame.header.get("disconnect"):
                        raise StreamDisconnect(frame.header.get("message", "disconnect"))
                    raise RuntimeError(frame.header.get("message", "engine error"))
        finally:
            self.drt.tcp_server_handle().unregister(conn_info.stream_id)

    async def _generate_local(self, engine, request, ctx) -> AsyncIterator[Annotated]:
        async for item in engine.generate(request, ctx):
            yield item if isinstance(item, Annotated) else Annotated(data=item)

    # convenience wrappers matching the reference's API surface
    async def round_robin(self, request, context=None):
        self.mode = RouterMode.ROUND_ROBIN
        return self.generate(request, context)

    async def random(self, request, context=None):
        self.mode = RouterMode.RANDOM
        return self.generate(request, context)

    async def direct(self, request, instance_id: int, context=None):
        return self.generate(request, context, instance_id=instance_id)
