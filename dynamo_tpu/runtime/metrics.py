"""Hierarchical metrics registries.

Ref: lib/runtime/src/metrics.rs:1-1679 (``MetricsRegistry`` trait :365) and
metrics/prometheus_names.rs — registries keyed by the component hierarchy
(drt → namespace → component → endpoint) with auto-attached labels, exported
in Prometheus text format by the system status server.

Built on ``prometheus_client`` with a thin hierarchy wrapper so metric names
and label sets match the reference's canonical scheme
(``dynamo_component_*`` / ``dynamo_frontend_*``).
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, Optional, Tuple

from prometheus_client import CollectorRegistry, Counter, Gauge, Histogram, generate_latest

# Canonical metric name prefixes (ref: prometheus_names.rs).
COMPONENT_PREFIX = "dynamo_component_"
FRONTEND_PREFIX = "dynamo_frontend_"


class MetricsRegistry:
    """A node in the metrics hierarchy. Children inherit labels."""

    def __init__(
        self,
        registry: Optional[CollectorRegistry] = None,
        labels: Optional[Dict[str, str]] = None,
        prefix: str = COMPONENT_PREFIX,
    ):
        self.registry = registry or CollectorRegistry()
        self.labels = dict(labels or {})
        self.prefix = prefix
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def child(self, **labels: str) -> "MetricsRegistry":
        merged = {**self.labels, **labels}
        return MetricsRegistry(self.registry, merged, self.prefix)

    def _full_name(self, name: str) -> str:
        return name if name.startswith("dynamo_") else f"{self.prefix}{name}"

    def _get_or_create(self, kind, name: str, documentation: str, extra_labels: Iterable[str] = (), **kwargs):
        full = self._full_name(name)
        label_names = tuple(sorted(self.labels)) + tuple(extra_labels)
        key = f"{full}|{','.join(label_names)}"
        with self._lock:
            metric = self._metrics.get(key)
            if metric is None:
                try:
                    metric = kind(full, documentation, labelnames=label_names, registry=self.registry, **kwargs)
                except ValueError:
                    # Already registered on the shared registry by a sibling
                    # node — reuse the collector, but ONLY if its label set
                    # matches. Silently reusing a collector with different
                    # labels made ``.labels(**values)`` blow up far from the
                    # misdeclaring call site (or, worse, record under the
                    # wrong series).
                    metric = self.registry._names_to_collectors.get(full)  # type: ignore[attr-defined]
                    if metric is None:
                        raise
                    existing = tuple(getattr(metric, "_labelnames", ()))
                    if tuple(sorted(existing)) != tuple(sorted(label_names)):
                        raise ValueError(
                            f"metric {full!r} already registered with labels "
                            f"{sorted(existing)}, requested {sorted(label_names)}; "
                            "sibling registries must declare identical label sets "
                            "for a shared metric name"
                        )
                self._metrics[key] = metric
        return metric

    def _labelled(self, metric, extra: Dict[str, str]):
        values = {**self.labels, **extra}
        return metric.labels(**values) if values else metric

    def counter(self, name: str, documentation: str = "", **extra_labels: str):
        m = self._get_or_create(Counter, name, documentation, extra_labels=sorted(extra_labels))
        return self._labelled(m, extra_labels)

    def gauge(self, name: str, documentation: str = "", **extra_labels: str):
        m = self._get_or_create(Gauge, name, documentation, extra_labels=sorted(extra_labels))
        return self._labelled(m, extra_labels)

    def histogram(
        self,
        name: str,
        documentation: str = "",
        buckets: Optional[Tuple[float, ...]] = None,
        **extra_labels: str,
    ):
        kwargs = {"buckets": buckets} if buckets else {}
        m = self._get_or_create(Histogram, name, documentation, extra_labels=sorted(extra_labels), **kwargs)
        return self._labelled(m, extra_labels)

    def render(self) -> bytes:
        """Prometheus text exposition."""
        return generate_latest(self.registry)


# Latency histogram buckets tuned for LLM serving (TTFT ms-scale, ITL ms-scale)
# — ref: http/service/metrics.rs histogram buckets.
TTFT_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)
ITL_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0)
DURATION_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0)
# Mixed-step composition: prefill tokens riding one engine step (the
# scheduler's prefill-bucket rungs — see SchedulerConfig.mixed_prefill_budget).
# Workers export the raw counters (mixed_steps_total / mixed_prefill_tokens_
# total / mixed_decode_tokens_total via stats → metrics_aggregator gauges);
# these buckets are for per-step composition histograms in dashboards and
# bench.py's mixed-batch section.
MIXED_PREFILL_TOKEN_BUCKETS = (0.0, 16.0, 32.0, 64.0, 128.0, 256.0, 512.0, 1024.0, 2048.0)
