"""Chaos plane: seeded, deterministic fault injection for the demo stack.

The robustness features in this tree — migration replay, TTL leases,
cancellation, drain — only matter if something can *provoke* the failures
they claim to survive. This module is that something: a process-global
``FaultInjector`` armed with a scenario (a list of :class:`FaultSpec`) that
fires at named **sites** planted on the real serving paths:

=====================  =====================================================
site                   semantics (kinds it honors)
=====================  =====================================================
``worker.frame``       per response frame on the worker's TCP call-home
                       (``_PushEndpoint._handle``): ``stream_drop`` severs
                       the socket without a final frame (the client observes
                       a genuine StreamDisconnect and migrates), ``hang``
                       sleeps ``delay_s`` once, ``slow`` sleeps per frame.
``worker.step``        per simulated engine step (mocker ``_sim_loop``):
                       ``crash`` kills the engine loop — every in-flight
                       stream drops abruptly, like a process death; ``hang``
                       wedges the loop for ``delay_s``; ``slow`` stretches
                       every subsequent step by ``factor``.
``bus.publish``        the control-plane pub/sub hop: ``partition`` drops
                       the message, ``delay`` sleeps ``delay_s`` first.
``lease.keepalive``    the worker's lease heartbeat: ``lease_drop`` skips
                       renewals — the lease expires, the instance key
                       vanishes, routers prune the worker.
``stats.reply``        the stats-scrape request/reply: ``stats_blackout``
                       swallows the reply (the scraper times out).
=====================  =====================================================

Sites are **counted deterministically**: each ``fire()`` increments the
site's pass counter, and a spec matches pass numbers via ``after``/``every``
/``count`` — so a fixed scenario against a fixed workload produces the exact
same injection sequence every run (two runs ⇒ identical ``injector.log``).
The only randomness is the opt-in ``probability`` field, drawn from the
injector's seeded RNG — still reproducible under a fixed seed.

Every injection is recorded three ways: the ``log`` list (tests assert exact
sequences), a ``fault`` trace event into the tracer ring (incident bundles
capture it), and ``faults_injected_total`` / ``faults_<kind>_total``
counters merged into the worker stats scrape (→ aggregator → Grafana).

Arming is explicit and off by default: ``arm(FaultInjector(...))``,
``--fault-scenario`` on the worker/frontend CLIs, or ``DYN_FAULTS`` (inline
JSON or ``@/path/to/scenario.json``) for subprocess demo stacks. The
unarmed fast path is one module-global ``is None`` check (``armed()``), so
serving code pays nothing when chaos is off — and an armed-but-idle
injector (no matching specs) costs one dict lookup per planted site, inside
the observability bench's ≤2% budget.
"""

from __future__ import annotations

import asyncio
import json
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from dynamo_tpu.runtime.logging import get_logger

logger = get_logger(__name__)

FAULTS_ENV = "DYN_FAULTS"

# The closed kind set: each is a per-kind counter on the stats wire
# (``faults_<kind>_total`` — registered in metrics_aggregator COUNTER_KEYS
# and pinned by the Grafana "Chaos" panel).
KINDS = (
    "crash",
    "hang",
    "stream_drop",
    "delay",
    "partition",
    "lease_drop",
    "stats_blackout",
    "slow",
)

SITES = (
    "worker.frame",
    "worker.step",
    "bus.publish",
    "lease.keepalive",
    "stats.reply",
)

# Kinds whose firing RAISES at the site (the others sleep or signal).
_RAISING = frozenset({"crash", "stream_drop", "partition", "lease_drop", "stats_blackout"})


class InjectedFault(Exception):
    """A deliberately injected failure. Sites either let it propagate as a
    crash or catch it to enact the kind's semantics (drop a socket, skip a
    keepalive). Carries the spec so handlers can branch on ``kind``."""

    def __init__(self, spec: "FaultSpec", attrs: Dict[str, Any]):
        super().__init__(f"injected {spec.kind} at {spec.site}")
        self.kind = spec.kind
        self.spec = spec
        self.attrs = attrs


@dataclass
class FaultSpec:
    """One injection rule. Pass-count triggers (``after``/``every``/
    ``count``) are deterministic; ``probability`` draws from the injector's
    seeded RNG. ``match`` constrains site attributes (equality; values are
    compared as strings so instance ids can be given in hex)."""

    site: str
    kind: str
    after: int = 0  # skip the first N passes through the site
    every: int = 1  # then fire on every Nth eligible pass
    count: int = 1  # total firings (0 = unlimited)
    match: Dict[str, Any] = field(default_factory=dict)
    delay_s: float = 0.0  # hang/delay/slow sleep
    factor: float = 1.0  # slow: step-time multiplier (mocker)
    probability: float = 1.0  # <1.0: seeded coin flip per eligible pass
    # runtime state
    fired: int = 0
    seen: int = 0  # eligible passes observed (post-match)

    def __post_init__(self):
        if self.site not in SITES:
            raise ValueError(f"unknown fault site {self.site!r} (sites: {SITES})")
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} (kinds: {KINDS})")

    @classmethod
    def from_dict(cls, d: dict) -> "FaultSpec":
        allowed = {"site", "kind", "after", "every", "count", "match",
                   "delay_s", "factor", "probability"}
        unknown = set(d) - allowed
        if unknown:
            raise ValueError(f"unknown fault spec fields: {sorted(unknown)}")
        return cls(**d)

    def matches(self, attrs: Dict[str, Any]) -> bool:
        for k, want in self.match.items():
            if k.endswith("_prefix"):
                # e.g. {"subject_prefix": "rq."} partitions only the
                # request-push plane, leaving stats/control alive.
                have = attrs.get(k[: -len("_prefix")])
                if have is None or not str(have).startswith(str(want)):
                    return False
                continue
            have = attrs.get(k)
            if have is None or str(have) != str(want):
                return False
        return True


class FaultInjector:
    """Deterministic scenario evaluator. Thread-safe: sites fire from the
    event loop, the scheduler thread, and scrape threads alike."""

    def __init__(self, scenario: Optional[List] = None, *, seed: int = 0):
        specs: List[FaultSpec] = []
        for s in scenario or []:
            specs.append(s if isinstance(s, FaultSpec) else FaultSpec.from_dict(s))
        self.seed = seed
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._by_site: Dict[str, List[FaultSpec]] = {}
        for s in specs:
            self._by_site.setdefault(s.site, []).append(s)
        self.specs = specs
        self.passes: Dict[str, int] = {}  # guarded-by: _lock
        self.injected_total = 0  # guarded-by: _lock
        self.by_kind: Dict[str, int] = {k: 0 for k in KINDS}  # guarded-by: _lock
        # The injection record tests assert on: (n, site, kind, attrs).
        self.log: List[dict] = []  # guarded-by: _lock

    # --- evaluation ---------------------------------------------------------
    def check(self, site: str, **attrs: Any) -> Optional[FaultSpec]:
        """Count one pass through ``site`` and return the spec that fires,
        if any (first match wins; a pass feeds every spec's counters so
        later specs stay deterministic regardless of earlier ones)."""
        specs = self._by_site.get(site)
        with self._lock:
            n = self.passes.get(site, 0) + 1
            self.passes[site] = n
            if not specs:
                return None
            hit: Optional[FaultSpec] = None
            for s in specs:
                if s.count and s.fired >= s.count:
                    continue
                if not s.matches(attrs):
                    continue
                s.seen += 1
                if s.seen <= s.after:
                    continue
                if (s.seen - s.after - 1) % max(s.every, 1) != 0:
                    continue
                if s.probability < 1.0 and self._rng.random() >= s.probability:
                    continue
                if hit is None:
                    hit = s
            if hit is None:
                return None
            hit.fired += 1
            self.injected_total += 1
            self.by_kind[hit.kind] = self.by_kind.get(hit.kind, 0) + 1
            record = {
                "n": self.injected_total,
                "site": site,
                "kind": hit.kind,
                "pass": n,
                "attrs": {k: str(v) for k, v in attrs.items()},
            }
            self.log.append(record)
        logger.warning("fault injected: %s %s (pass %d) attrs=%s",
                       hit.kind, site, n, record["attrs"])
        self._trace(record)
        return hit

    @staticmethod
    def _trace(record: dict) -> None:
        # Into the tracer (ring + export when enabled): incident bundles and
        # trace_view timelines show the injection inline with the request
        # lifecycle it perturbed.
        from dynamo_tpu.runtime.tracing import get_tracer

        tracer = get_tracer()
        if not tracer.enabled:
            return
        trace_id = str(record["attrs"].get("trace_id") or "0" * 32)
        tracer.event(
            "fault", trace_id, service="chaos",
            site=record["site"], kind=record["kind"], n=record["n"],
            **{k: v for k, v in record["attrs"].items() if k != "trace_id"},
        )

    # --- stats --------------------------------------------------------------
    def to_stats(self) -> dict:
        """Worker-scrape counter keys (COUNTER_KEYS names)."""
        with self._lock:
            out = {"faults_injected_total": self.injected_total}
            for kind in KINDS:
                out[f"faults_{kind}_total"] = self.by_kind.get(kind, 0)
            return out

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "seed": self.seed,
                "injected_total": self.injected_total,
                "by_kind": {k: v for k, v in self.by_kind.items() if v},
                "log": [dict(r) for r in self.log],
            }


# --- process-global arming ---------------------------------------------------
_INJECTOR: Optional[FaultInjector] = None


def arm(injector: Optional[FaultInjector]) -> Optional[FaultInjector]:
    """Install (or, with None, remove) the process-global injector."""
    global _INJECTOR
    _INJECTOR = injector
    if injector is not None:
        logger.warning("chaos plane ARMED: %d spec(s), seed=%d",
                       len(injector.specs), injector.seed)
    return injector


def disarm() -> None:
    arm(None)


def get_injector() -> Optional[FaultInjector]:
    return _INJECTOR


def armed() -> bool:
    """The unarmed fast path: call sites guard every planted site with this
    one module-global check, so chaos-off serving pays a single ``is None``."""
    return _INJECTOR is not None


def arm_from_spec(spec: str, *, seed: int = 0) -> FaultInjector:
    """Arm from inline JSON, or ``@/path`` to a JSON file. The JSON is
    either a list of spec dicts or ``{"seed": int, "faults": [...]}``."""
    if spec.startswith("@"):
        with open(spec[1:]) as f:
            data = json.load(f)
    else:
        data = json.loads(spec)
    if isinstance(data, dict):
        seed = int(data.get("seed", seed))
        scenario = data.get("faults") or []
    else:
        scenario = data
    return arm(FaultInjector(scenario, seed=seed))


def maybe_arm_from_env() -> Optional[FaultInjector]:
    """CLI entrypoints call this so subprocess demo stacks can be armed via
    ``DYN_FAULTS`` without new flags on every binary."""
    spec = os.environ.get(FAULTS_ENV)
    if not spec:
        return None
    return arm_from_spec(spec)


# --- site helpers -------------------------------------------------------------
def fire(site: str, **attrs: Any) -> Optional[FaultSpec]:
    """Synchronous site: raises :class:`InjectedFault` for raising kinds,
    sleeps for ``hang``/``slow``/``delay``, returns the spec (callers that
    need the ``factor``/``delay_s`` knobs read it). No-op when unarmed."""
    inj = _INJECTOR
    if inj is None:
        return None
    spec = inj.check(site, **attrs)
    if spec is None:
        return None
    if spec.kind in _RAISING:
        raise InjectedFault(spec, attrs)
    if spec.kind in ("hang", "delay", "slow") and spec.delay_s > 0:
        time.sleep(spec.delay_s)
    return spec


async def afire(site: str, **attrs: Any) -> Optional[FaultSpec]:
    """Async site: like :func:`fire` but sleeps without blocking the loop."""
    inj = _INJECTOR
    if inj is None:
        return None
    spec = inj.check(site, **attrs)
    if spec is None:
        return None
    if spec.kind in _RAISING:
        raise InjectedFault(spec, attrs)
    if spec.kind in ("hang", "delay", "slow") and spec.delay_s > 0:
        await asyncio.sleep(spec.delay_s)
    return spec


def stats() -> dict:
    """Injected-fault counters for a stats_handler to merge; {} when
    unarmed (the keys only appear on chaos-armed workers)."""
    inj = _INJECTOR
    return inj.to_stats() if inj is not None else {}
