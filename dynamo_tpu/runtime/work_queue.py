"""Durable competing-consumer work queue (the NATS JetStream work-queue role).

Ref: the reference's ``NatsQueue`` (lib/bindings/python src/dynamo/_core.pyi:894
— enqueue_task/dequeue_task over a JetStream work-queue stream), used by the
trtllm backend's prefill-first disaggregation path to hand prefill work to
whichever prefill worker pulls it next.

Design on this runtime's primitives (no new transport surface):
- Items live in a durable ``Stream`` (sequence-numbered, replayable).
- A claim is an atomic create-only KV key ``wq/{name}/claim/{seq}`` bound to
  the consumer's lease: two consumers can never claim the same item, and a
  dead consumer's claim evaporates with its lease so the item is redelivered.
- Consumers without a lease get a claim *deadline* instead (``claim_ttl_s``,
  stored in the claim value): a consumer that crashes between claim and ack
  only delays redelivery until the deadline passes — items are never
  orphaned either way. The deadline is the *writer's* wall clock read by
  other hosts, so ``claim_ttl_s`` must be generous relative to inter-host
  clock skew (default 60 s ≫ NTP skew); a thief re-checks the done marker
  after winning a stolen claim, which narrows (but cannot fully close,
  absent CAS) the window where a slow-but-alive claimant's late ack races
  the steal — the queue is at-least-once, consumers must be idempotent.
- Ack writes ``wq/{name}/done/{seq}`` (unleased — completion survives the
  worker) and drops the claim; fully-acked prefixes are purged from the
  stream opportunistically.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass
from typing import Optional

from dynamo_tpu.runtime.transports.kvstore import KeyExists, KvStore
from dynamo_tpu.runtime.transports.pubsub import PubSub, Stream

_POLL_S = 0.05


@dataclass
class QueueItem:
    seq: int
    data: bytes
    _queue: "WorkQueue"

    async def ack(self) -> None:
        await self._queue._ack(self.seq)


class WorkQueue:
    """Competing-consumer queue: many producers, many consumers, each item
    delivered to exactly one live consumer (redelivered if that consumer's
    lease dies before ack)."""

    def __init__(
        self,
        store: KvStore,
        bus: PubSub,
        name: str,
        lease_id: Optional[int] = None,
        claim_ttl_s: float = 60.0,
    ):
        self.store = store
        self.bus = bus
        self.name = name
        self.lease_id = lease_id
        # Deadline for lease-less claims: a crashed consumer's claim is
        # reclaimable after this long. Leased claims expire with the lease.
        self.claim_ttl_s = claim_ttl_s
        self._stream: Optional[Stream] = None
        self._cursor = 1  # lowest seq that might still be claimable

    async def _ensure_stream(self) -> Stream:
        if self._stream is None:
            self._stream = await self.bus.stream(f"wq_{self.name}")
        return self._stream

    def _claim_key(self, seq: int) -> str:
        return f"wq/{self.name}/claim/{seq:020d}"

    def _done_key(self, seq: int) -> str:
        return f"wq/{self.name}/done/{seq:020d}"

    async def enqueue(self, payload: bytes) -> int:
        stream = await self._ensure_stream()
        return await stream.publish(self.name, payload)

    async def depth(self) -> int:
        """Items neither acked nor currently claimed (i.e. available)."""
        stream = await self._ensure_stream()
        done = {e.key for e in await self.store.get_prefix(f"wq/{self.name}/done/")}
        claimed = {e.key for e in await self.store.get_prefix(f"wq/{self.name}/claim/")}
        n = 0
        for msg in await stream.fetch(stream.first_seq):
            if self._done_key(msg.seq) not in done and self._claim_key(msg.seq) not in claimed:
                n += 1
        return n

    async def dequeue(self, timeout: Optional[float] = None) -> Optional[QueueItem]:
        """Claim the next available item, waiting up to ``timeout`` (forever
        if None). Returns None on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        stream = await self._ensure_stream()
        while True:
            item = await self._try_claim(stream)
            if item is not None:
                return item
            if deadline is not None and time.monotonic() >= deadline:
                return None
            # New items arrive via publish; reclaimable items via lease
            # expiry — both are cheap to poll at this cadence.
            await asyncio.sleep(_POLL_S)

    async def _try_claim(self, stream: Stream) -> Optional[QueueItem]:
        batch = await stream.fetch(max(self._cursor, stream.first_seq))
        advance = True
        now = time.time()
        for msg in batch:
            if await self.store.get(self._done_key(msg.seq)) is not None:
                if advance:
                    self._cursor = msg.seq + 1
                continue
            existing = await self.store.get(self._claim_key(msg.seq))
            stole = existing is not None
            if existing is not None:
                # Lease-less claims carry a deadline; expired ⇒ the claimant
                # died between claim and ack — steal it. (Delete + create_only
                # races resolve atomically: one thief wins, others KeyExists.)
                try:
                    expired = existing.value and float(existing.value) < now
                except ValueError:
                    expired = False
                if not expired:
                    advance = False  # live claim by a peer; may still come back
                    continue
                await self.store.delete(self._claim_key(msg.seq))
            claim_val = b"" if self.lease_id is not None else str(now + self.claim_ttl_s).encode()
            try:
                await self.store.put(
                    self._claim_key(msg.seq), claim_val, lease_id=self.lease_id, create_only=True
                )
            except KeyExists:
                advance = False
                continue
            # On a steal, re-check done AFTER winning the claim: the previous
            # claimant may have acked between our done-check and the
            # delete/re-claim above. This narrows the duplicate window; it
            # cannot close it (an alive-but-slow claimant can still ack after
            # this check — at-least-once semantics, see class docstring).
            # Fresh claims skip the round-trip.
            if stole and await self.store.get(self._done_key(msg.seq)) is not None:
                await self.store.delete(self._claim_key(msg.seq))
                if advance:
                    self._cursor = msg.seq + 1
                continue
            return QueueItem(seq=msg.seq, data=msg.data, _queue=self)
        return None

    async def _ack(self, seq: int) -> None:
        await self.store.put(self._done_key(seq), b"")
        await self.store.delete(self._claim_key(seq))
        await self._maybe_purge()

    async def _maybe_purge(self) -> None:
        """Purge the longest fully-acked prefix from the stream and drop its
        done-markers, bounding state growth."""
        stream = await self._ensure_stream()
        upto = 0
        for msg in await stream.fetch(stream.first_seq):
            if await self.store.get(self._done_key(msg.seq)) is None:
                break
            upto = msg.seq
        if upto:
            await stream.purge(upto)
            for e in await self.store.get_prefix(f"wq/{self.name}/done/"):
                if int(e.key.rsplit("/", 1)[1]) <= upto:
                    await self.store.delete(e.key)
