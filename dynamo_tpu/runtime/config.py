"""Layered runtime configuration from environment variables.

TPU-native equivalent of the reference's figment-based config
(ref: lib/runtime/src/config.rs:66-180 — env ``DYN_RUNTIME_*``,
``DYN_SYSTEM_*``, ``DYN_WORKER_*``). We keep the same env-var surface so
operator tooling translates directly.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field, fields


def _env_bool(name: str, default: bool) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    return int(v) if v is not None else default


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    return float(v) if v is not None else default


def _env_str(name: str, default: str) -> str:
    return os.environ.get(name, default)


@dataclass
class RuntimeConfig:
    """Process-level runtime knobs (ref: config.rs RuntimeConfig)."""

    # Worker thread pool sizing (maps to asyncio executor workers here).
    num_worker_threads: int = field(default_factory=lambda: _env_int("DYN_RUNTIME_NUM_WORKER_THREADS", 4))
    max_blocking_threads: int = field(default_factory=lambda: _env_int("DYN_RUNTIME_MAX_BLOCKING_THREADS", 16))
    # Graceful-shutdown drain timeout in seconds.
    shutdown_timeout_s: float = field(default_factory=lambda: _env_float("DYN_RUNTIME_SHUTDOWN_TIMEOUT", 30.0))


@dataclass
class SystemConfig:
    """System status server config (ref: config.rs:85-123 DYN_SYSTEM_*)."""

    enabled: bool = field(default_factory=lambda: _env_bool("DYN_SYSTEM_ENABLED", False))
    port: int = field(default_factory=lambda: _env_int("DYN_SYSTEM_PORT", 0))
    host: str = field(default_factory=lambda: _env_str("DYN_SYSTEM_HOST", "0.0.0.0"))
    # When true, /health reflects per-endpoint health rather than process liveness
    # (ref: DYN_SYSTEM_USE_ENDPOINT_HEALTH_STATUS config.rs:112).
    use_endpoint_health_status: bool = field(
        default_factory=lambda: _env_bool("DYN_SYSTEM_USE_ENDPOINT_HEALTH_STATUS", False)
    )
    starting_health_status: str = field(default_factory=lambda: _env_str("DYN_SYSTEM_STARTING_HEALTH_STATUS", "notready"))


@dataclass
class ControlPlaneConfig:
    """Where the control plane (KV store + pubsub — the etcd/NATS role) lives.

    ``mem`` — in-process (single-process deployments and tests).
    ``tcp`` — the built-in control-plane server (``python -m dynamo_tpu.control_plane``).
    """

    backend: str = field(default_factory=lambda: _env_str("DYN_CONTROL_PLANE", "mem"))
    address: str = field(default_factory=lambda: _env_str("DYN_CONTROL_PLANE_ADDRESS", "127.0.0.1:6650"))
    lease_ttl_s: float = field(default_factory=lambda: _env_float("DYN_CONTROL_PLANE_LEASE_TTL", 10.0))


@dataclass
class Config:
    runtime: RuntimeConfig = field(default_factory=RuntimeConfig)
    system: SystemConfig = field(default_factory=SystemConfig)
    control_plane: ControlPlaneConfig = field(default_factory=ControlPlaneConfig)
    namespace: str = field(default_factory=lambda: _env_str("DYN_NAMESPACE", "dynamo"))

    @classmethod
    def from_env(cls) -> "Config":
        return cls()


def config_overview(cfg: Config) -> dict:
    """Flatten a Config to a dict for logging/diagnostics."""
    out: dict = {}
    for f in fields(cfg):
        v = getattr(cfg, f.name)
        if hasattr(v, "__dataclass_fields__"):
            out[f.name] = {g.name: getattr(v, g.name) for g in fields(v)}
        else:
            out[f.name] = v
    return out
