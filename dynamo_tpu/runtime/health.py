"""System status server: /health, /live, /metrics.

Ref: lib/runtime/src/system_status_server.rs:20-705 (axum server) and
SystemHealth in lib.rs:81-174 — endpoint-level health states, configured via
``DYN_SYSTEM_*`` (config.rs:85-123).
"""

from __future__ import annotations

import asyncio
import json
import sys
import threading
import traceback
from typing import Callable, Dict, Optional

from aiohttp import web

from dynamo_tpu.runtime.config import SystemConfig
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.metrics import MetricsRegistry

logger = get_logger(__name__)

HEALTHY = "ready"
UNHEALTHY = "notready"


class SystemHealth:
    """Tracks process + per-endpoint health (ref: lib.rs:81-174).

    ``attach_engine`` adds engine liveness to readiness: the probe returns
    the watchdog/flight stats (``engine_stalled``, ``last_step_age_s``,
    ``compiles_after_warmup_total``); a stalled engine reports notready
    even while the process itself is up — exactly the state where routing
    more traffic at the worker makes things worse."""

    def __init__(self, starting_status: str = UNHEALTHY, use_endpoint_health: bool = False):
        self.system_status = starting_status
        self.use_endpoint_health = use_endpoint_health
        self.endpoints: Dict[str, str] = {}
        self._engine_probe: Optional[Callable[[], dict]] = None

    def set_system_ready(self) -> None:
        self.system_status = HEALTHY

    def set_endpoint_health(self, endpoint_path: str, status: str) -> None:
        self.endpoints[endpoint_path] = status

    def remove_endpoint(self, endpoint_path: str) -> None:
        self.endpoints.pop(endpoint_path, None)

    def attach_engine(self, probe: Callable[[], dict]) -> None:
        """``probe()`` → dict with ``engine_stalled`` (0/1) plus any extra
        liveness fields to surface on /health."""
        self._engine_probe = probe

    def _engine_state(self) -> Optional[dict]:
        if self._engine_probe is None:
            return None
        try:
            return self._engine_probe()
        except Exception as e:  # noqa: BLE001 — health must answer regardless
            return {"engine_stalled": 1.0, "probe_error": str(e)}

    def is_healthy(self) -> bool:
        engine = self._engine_state()
        if engine is not None and engine.get("engine_stalled"):
            return False
        if self.use_endpoint_health:
            return bool(self.endpoints) and all(s == HEALTHY for s in self.endpoints.values())
        return self.system_status == HEALTHY

    def snapshot(self) -> dict:
        out = {
            "status": HEALTHY if self.is_healthy() else UNHEALTHY,
            "system": self.system_status,
            "endpoints": dict(self.endpoints),
        }
        engine = self._engine_state()
        if engine is not None:
            out["engine"] = engine
        return out


class SystemStatusServer:
    def __init__(
        self,
        health: SystemHealth,
        metrics: Optional[MetricsRegistry] = None,
        config: Optional[SystemConfig] = None,
        state_probe: Optional[Callable[[], dict]] = None,
        profiler=None,  # runtime.profiling.DeviceProfiler
        drain_cb: Optional[Callable[[], "asyncio.Future"]] = None,
    ):
        self.health = health
        self.metrics = metrics
        self.config = config or SystemConfig()
        # POST /drain → the worker's drain lifecycle (deregister, stop
        # admitting, finish-or-migrate in-flight, exit). Idempotent.
        self.drain_cb = drain_cb
        self._draining = False
        # Live introspection source for /debug/state (e.g.
        # TpuEngine.debug_state): running/waiting sequences, block pool,
        # digest snapshots, the recent step timeline.
        self.state_probe = state_probe
        # On-demand profiling: POST /debug/profile?seconds=N captures a
        # jax.profiler device trace (kind=host runs the stdlib stack
        # sampler instead) against the LIVE worker — no restart, no
        # pre-armed tracing.
        self.profiler = profiler
        self._runner: Optional[web.AppRunner] = None
        self.port: Optional[int] = None

    async def start(self) -> None:
        app = web.Application()
        app.router.add_get("/health", self._health)
        app.router.add_get("/live", self._live)
        app.router.add_get("/metrics", self._metrics)
        app.router.add_get("/debug/state", self._debug_state)
        app.router.add_get("/debug/stacks", self._debug_stacks)
        app.router.add_post("/debug/profile", self._debug_profile)
        app.router.add_post("/drain", self._drain)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.config.host, self.config.port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]  # type: ignore[union-attr]
        logger.info("system status server on %s:%d", self.config.host, self.port)

    async def _health(self, request: web.Request) -> web.Response:
        snap = self.health.snapshot()
        status = 200 if snap["status"] == HEALTHY else 503
        return web.Response(status=status, text=json.dumps(snap), content_type="application/json")

    async def _live(self, request: web.Request) -> web.Response:
        return web.Response(status=200, text=json.dumps({"status": "live"}), content_type="application/json")

    async def _metrics(self, request: web.Request) -> web.Response:
        body = self.metrics.render() if self.metrics is not None else b""
        return web.Response(status=200, body=body, content_type="text/plain")

    async def _debug_state(self, request: web.Request) -> web.Response:
        """Live engine introspection: the "what is the engine doing RIGHT
        NOW" dump for incident debugging — no scrape interval, no
        aggregation delay."""
        if self.state_probe is None:
            return web.Response(
                status=404,
                text=json.dumps({"error": "no state probe attached"}),
                content_type="application/json",
            )
        try:
            state = self.state_probe()
        except Exception as e:  # noqa: BLE001 — debug surface must not 500-loop
            state = {"error": f"{type(e).__name__}: {e}"}
        return web.Response(
            status=200, text=json.dumps(state, default=str), content_type="application/json"
        )

    async def _debug_profile(self, request: web.Request) -> web.Response:
        """On-demand profile window against the live process.

        ``POST /debug/profile?seconds=N[&kind=device|host]`` — ``device``
        (default) runs a programmatic jax.profiler capture and returns the
        artifact path; ``host`` runs the stdlib stack sampler and returns
        the aggregated frame report (where is host time going, by scheduler
        code path). Both run in a thread so the event loop keeps serving
        health probes during the window."""
        try:
            seconds = float(request.query.get("seconds", "2"))
        except ValueError:
            return web.Response(
                status=400,
                text=json.dumps({"error": "seconds must be a number"}),
                content_type="application/json",
            )
        if not 0 < seconds <= 60:
            return web.Response(
                status=400,
                text=json.dumps({"error": "seconds must be in (0, 60]"}),
                content_type="application/json",
            )
        kind = request.query.get("kind", "device")
        if kind == "host":
            from dynamo_tpu.runtime.profiling import HostStackSampler

            report = await asyncio.to_thread(HostStackSampler().sample_for, seconds)
            return web.Response(
                status=200, text=json.dumps({"kind": "host", **report}),
                content_type="application/json",
            )
        if self.profiler is None:
            return web.Response(
                status=404,
                text=json.dumps({"error": "no device profiler attached"}),
                content_type="application/json",
            )
        result = await asyncio.to_thread(self.profiler.capture, seconds, "http")
        status = 200 if result.get("status") == "ok" else 409 if result.get("status") == "busy" else 500
        return web.Response(
            status=status, text=json.dumps({"kind": "device", **result}),
            content_type="application/json",
        )

    async def _drain(self, request: web.Request) -> web.Response:
        """``POST /drain`` — begin the worker's drain lifecycle: deregister
        from discovery, stop admitting, finish (or migrate) in-flight work
        within shutdown_timeout_s, then exit. The planner's scale-down
        primitive; SIGTERM takes the same path. Answers 202 immediately —
        the drain runs in the background while /health flips notready."""
        if self.drain_cb is None:
            return web.Response(
                status=404,
                text=json.dumps({"error": "no drain hook attached"}),
                content_type="application/json",
            )
        already = self._draining
        self._draining = True
        self.health.system_status = UNHEALTHY  # steer probes away immediately
        if not already:
            asyncio.get_running_loop().create_task(self.drain_cb())
        return web.Response(
            status=202,
            text=json.dumps({"status": "draining", "already_draining": already}),
            content_type="application/json",
        )

    async def _debug_stacks(self, request: web.Request) -> web.Response:
        """Python stacks of every thread — the first question when the step
        loop wedges (is it blocked in a dispatch? a lock? the allocator?)."""
        names = {t.ident: t.name for t in threading.enumerate()}
        stacks = {}
        for tid, frame in sys._current_frames().items():
            stacks[f"{names.get(tid, '?')}-{tid}"] = traceback.format_stack(frame)
        return web.Response(
            status=200, text=json.dumps(stacks), content_type="application/json"
        )

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
