"""System status server: /health, /live, /metrics.

Ref: lib/runtime/src/system_status_server.rs:20-705 (axum server) and
SystemHealth in lib.rs:81-174 — endpoint-level health states, configured via
``DYN_SYSTEM_*`` (config.rs:85-123).
"""

from __future__ import annotations

import asyncio
import json
from typing import Dict, Optional

from aiohttp import web

from dynamo_tpu.runtime.config import SystemConfig
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.metrics import MetricsRegistry

logger = get_logger(__name__)

HEALTHY = "ready"
UNHEALTHY = "notready"


class SystemHealth:
    """Tracks process + per-endpoint health (ref: lib.rs:81-174)."""

    def __init__(self, starting_status: str = UNHEALTHY, use_endpoint_health: bool = False):
        self.system_status = starting_status
        self.use_endpoint_health = use_endpoint_health
        self.endpoints: Dict[str, str] = {}

    def set_system_ready(self) -> None:
        self.system_status = HEALTHY

    def set_endpoint_health(self, endpoint_path: str, status: str) -> None:
        self.endpoints[endpoint_path] = status

    def remove_endpoint(self, endpoint_path: str) -> None:
        self.endpoints.pop(endpoint_path, None)

    def is_healthy(self) -> bool:
        if self.use_endpoint_health:
            return bool(self.endpoints) and all(s == HEALTHY for s in self.endpoints.values())
        return self.system_status == HEALTHY

    def snapshot(self) -> dict:
        return {
            "status": HEALTHY if self.is_healthy() else UNHEALTHY,
            "system": self.system_status,
            "endpoints": dict(self.endpoints),
        }


class SystemStatusServer:
    def __init__(
        self,
        health: SystemHealth,
        metrics: Optional[MetricsRegistry] = None,
        config: Optional[SystemConfig] = None,
    ):
        self.health = health
        self.metrics = metrics
        self.config = config or SystemConfig()
        self._runner: Optional[web.AppRunner] = None
        self.port: Optional[int] = None

    async def start(self) -> None:
        app = web.Application()
        app.router.add_get("/health", self._health)
        app.router.add_get("/live", self._live)
        app.router.add_get("/metrics", self._metrics)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.config.host, self.config.port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]  # type: ignore[union-attr]
        logger.info("system status server on %s:%d", self.config.host, self.port)

    async def _health(self, request: web.Request) -> web.Response:
        snap = self.health.snapshot()
        status = 200 if snap["status"] == HEALTHY else 503
        return web.Response(status=status, text=json.dumps(snap), content_type="application/json")

    async def _live(self, request: web.Request) -> web.Response:
        return web.Response(status=200, text=json.dumps({"status": "live"}), content_type="application/json")

    async def _metrics(self, request: web.Request) -> web.Response:
        body = self.metrics.render() if self.metrics is not None else b""
        return web.Response(status=200, body=body, content_type="text/plain")

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
