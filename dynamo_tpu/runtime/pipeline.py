"""Typed operator pipeline: composable request/response transform stages.

Ref: lib/runtime/src/{pipeline.rs:31-58, pipeline/nodes.rs:1-339} — the
SingleIn/ManyOut node graph (ServiceFrontend → Operator… → ServiceBackend)
used to assemble frontend → preprocessor → backend → migration → router →
engine chains (entrypoint/input/common.rs:226 build_routed_pipeline).

An :class:`Operator` transforms the request on the way down and the response
stream on the way up; ``link`` folds operators around a terminal engine,
producing a single composed :class:`AsyncEngine`.
"""

from __future__ import annotations

from typing import Any, AsyncIterator, Sequence

from dynamo_tpu.runtime.engine import AsyncEngine, Context


class Operator:
    """A bidirectional pipeline stage."""

    async def transform_request(self, request: Any, context: Context) -> Any:
        return request

    def transform_response(
        self, stream: AsyncIterator[Any], request: Any, context: Context
    ) -> AsyncIterator[Any]:
        return stream

    def attach(self, downstream: AsyncEngine) -> AsyncEngine:
        return _OperatorEngine(self, downstream)


class _OperatorEngine:
    def __init__(self, op: Operator, downstream: AsyncEngine):
        self.op = op
        self.downstream = downstream

    async def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        transformed = await self.op.transform_request(request, context)
        stream = self.downstream.generate(transformed, context)
        async for item in self.op.transform_response(stream, transformed, context):
            yield item


def link(operators: Sequence[Operator], engine: AsyncEngine) -> AsyncEngine:
    """Fold operators around the terminal engine: the first operator sees the
    original request first and the final response stream last."""
    composed = engine
    for op in reversed(list(operators)):
        composed = op.attach(composed)
    return composed
